"""ECBackend — the erasure-coded PG data path, batched for TPU.

Rebuild of the reference's EC read/write/recovery dataflow (ref:
src/osd/ECBackend.{h,cc} + ECCommon.{h,cc} — submit_transaction write
fan-out, RMWPipeline::start_rmw read-modify-write of partial stripes,
objects_read_and_reconstruct degraded read, RecoveryOp/
continue_recovery_op streaming recovery; ECTransaction::
generate_transactions for the per-shard store writes; per-shard HashInfo
bookkeeping ref: src/osd/ECUtil.{h,cc}).

TPU-first reshaping (SURVEY.md §2.7 P1-P4): where the reference fans
one object's sub-ops out over the network and recovers objects under a
semaphore one RecoveryOp at a time, here the unit of work is a BATCH of
objects — writes encode (B, k, chunk) in one device launch, recovery
gathers surviving shards for B objects into (B, k, chunk) device
arrays, runs ONE batched decode, and scatters the rebuilt shards back.
The per-shard stores are MemStore instances standing in for OSDs, so
the whole pipeline runs hermetically (the reference's
many-daemons-one-box trick, in-process).

Stripe geometry is POOL-WIDE and fixed (ref: pool stripe_unit →
ECUtil::stripe_info_t): every object is laid out round-robin in stripes
of k * chunk_size logical bytes, so objects span multiple stripes and a
partial overwrite touches only the stripes covering its byte range.
That makes the reference's read-modify-write pipeline meaningful here:
`write_ranges` reads the pre-image of just the touched stripe window
from the data shards (reconstructing the window from survivors when
shards are down), overlays the new bytes, re-encodes the window in one
batched launch, and emits per-shard sub-range writes.

Object placement: shard slot s of an object lands on the OSD in slot s
of the PG's acting set and carries the coder's chunk id s; the coder's
get_chunk_mapping() names which slots carry data vs parity (identity
for RS, interleaved for LRC). A lost OSD means one lost shard per
object, which is exactly the recovery workload metric #2 in BASELINE.md
measures (objects/s).
"""

from __future__ import annotations

import functools as _functools
import struct as _struct
import threading as _threading
from dataclasses import dataclass, field

import numpy as np

from ..ec.interface import ErasureCode
from ..ec.registry import factory
from ..utils.perf_counters import PerfCountersBuilder
from ..utils.tracing import span
from .memstore import MemStore, Transaction
from .pgbackend import HINFO_KEY, PGBackend, shard_cid  # noqa: F401
from .repairplan import plan_read, plan_repair
from .stripe import HashInfo, StripeInfo, as_flat_u8


def ec_perf_counters():
    """The EC data-path counter schema (logger "ec"). A daemon builds
    ONE instance and shares it across every PG backend it primaries
    (per-PG loggers would explode the metric space); standalone
    harnesses (recovery_bench) read the backend's own default."""
    return (PerfCountersBuilder("ec")
            .add_u64_counter("encode_launches",
                             "generic encode device launches")
            .add_u64_counter("fused_write_launches",
                             "fused encode+crc single launches")
            .add_u64_counter("host_encode_launches",
                             "write-path encodes served by the native "
                             "SSE codec + hardware crc32c (CPU "
                             "backend only — bit-identical to the "
                             "fused device launch)")
            .add_u64_counter("decode_launches",
                             "read-path decode launches")
            .add_u64_counter("recover_launches",
                             "fused recovery launches")
            .add_u64_counter("program_cache_hits",
                             "compiled-program cache hits")
            .add_u64_counter("program_cache_misses",
                             "compiled-program cache compiles")
            .add_u64_counter("encode_bytes", "logical bytes encoded")
            .add_u64_counter("decode_bytes", "logical bytes decoded")
            .add_u64_counter("recovered_objects",
                             "objects rebuilt by recovery")
            .add_u64_counter("recovered_bytes",
                             "shard bytes rebuilt by recovery")
            .add_u64_counter("hinfo_failures",
                             "helper chunks failing hinfo verify")
            .add_u64_counter("read_eio",
                             "read-path chunk crc mismatches")
            .add_u64_counter("planner_local_plans",
                             "repairs planned inside one LRC local "
                             "group (repair-locality planner)")
            .add_u64_counter("planner_subchunk_plans",
                             "repairs planned as Clay/MSR sub-chunk "
                             "range reads")
            .add_u64_counter("planner_cost_plans",
                             "cost-ranked helper selections (SHEC "
                             "windows / MDS cheapest-k)")
            .add_u64_counter("planner_full_plans",
                             "plans laddered to a full/multi-loss "
                             "decode (locality broken or multi-loss)")
            .add_u64_counter("recover_wire_bytes",
                             "helper bytes pulled for recovery (the "
                             "repair-bytes-on-wire numerator)")
            .add_time_avg("encode_time", "write-path encode wall time",
                          hist=True)
            .add_time_avg("decode_time", "read-path decode wall time",
                          hist=True)
            .add_time_avg("recover_stage_time",
                          "recovery host staging (producer thread)")
            .add_time_avg("recover_launch_time",
                          "recovery launch enqueue + async D2H start",
                          hist=True)
            .add_time_avg("recover_fetch_time",
                          "blocking remainder of the D2H fetch "
                          "(overlap eats the rest)")
            .add_time_avg("recover_writeback_time",
                          "rebuilt-shard writeback fan-out")
            .add_u64_counter("rmw_ops",
                             "partial-stripe overwrites served by the "
                             "parity-delta fast path")
            .add_u64_counter("rmw_delta_launches",
                             "fused delta-encode launches (device or "
                             "native host)")
            .add_u64_counter("rmw_wire_bytes",
                             "journal + delta payload bytes shipped "
                             "to participating shards (the RMW "
                             "amplification numerator)")
            .add_u64_counter("rmw_preread_bytes",
                             "pre-image bytes read for delta "
                             "construction (zero on the append path)")
            .add_u64_counter("rmw_fetch_waves",
                             "combined RMW prepare-fetch waves (one "
                             "per delta group: hinfo attrs + pre-"
                             "image ranges gathered in a single "
                             "overlapped round trip)")
            .add_u64_counter("rmw_fetch_frames",
                             "prepare-fetch frames issued (one per "
                             "participant shard per wave — the 1+m "
                             "sequential getattrs + per-span reads "
                             "these replaced counted 1 frame each)")
            .add_u64_counter("rmw_shard_ios",
                             "participating shards per RMW op, summed "
                             "(the shard-IO amplification counter: "
                             "1 data + m parity on the fast path)")
            .add_u64_counter("rmw_full_fallbacks",
                             "RMW jobs laddered to the full-stripe "
                             "path (degraded/stale stripe, stripe-"
                             "spanning or overlapping writes)")
            .add_u64_counter("rmw_append_fast",
                             "delta jobs whose pre-image was pure "
                             "padding (appends: no read phase at all)")
            .add_u64_counter("journal_entries",
                             "stripe-journal intents logged")
            .add_u64_counter("journal_replay_forward",
                             "journaled RMWs rolled forward on replay")
            .add_u64_counter("journal_replay_rollback",
                             "journaled RMWs rolled back on replay")
            .add_u64_counter("write_wire_bytes",
                             "full-path shard write bytes shipped "
                             "(the full-stripe amplification "
                             "numerator the RMW ratio divides by)")
            .add_u64_counter("stream_launches",
                             "StreamingCodec tile launches")
            .add_u64_counter("stream_bytes",
                             "bytes streamed through tiled encode")
            .add_time_avg("stream_drain_time",
                          "StreamingCodec blocking drain remainder")
            .create_perf_counters())


@dataclass
class ShardSet:
    """The 'cluster': one ObjectStore per OSD id. `store_factory` picks
    the backend — MemStore (default) or a persistent TinStore keyed by
    osd id (the store_test.cc parameterization, applied to the whole
    cluster sim)."""
    stores: dict[int, MemStore] = field(default_factory=dict)
    store_factory: "callable | None" = None

    def osd(self, osd_id: int) -> MemStore:
        if osd_id not in self.stores:
            self.stores[osd_id] = (self.store_factory(osd_id)
                                   if self.store_factory else MemStore())
        return self.stores[osd_id]


class ECBackend(PGBackend):
    """One PG's EC backend over a set of per-OSD stores."""

    def __init__(self, profile: dict | str, pg: str, acting: list[int],
                 cluster: ShardSet | None = None,
                 chunk_size: int | None = None,
                 perf=None, ensure_collections: bool = True):
        # data-path counters: the owning daemon passes its shared "ec"
        # logger; a bare backend (benches, unit tests) gets its own
        self.perf = perf if perf is not None else ec_perf_counters()
        self.coder: ErasureCode = factory(profile)
        self.k = self.coder.get_data_chunk_count()
        self.m = self.coder.get_coding_chunk_count()
        self.min_live = self.k  # EC pool min_size gate
        if len(acting) != self.k + self.m:
            raise ValueError(
                f"acting set size {len(acting)} != k+m={self.k + self.m}")
        # chunk mapping (ref: ErasureCodeInterface::get_chunk_mapping):
        # shard slot s holds the coder's chunk id s, and mapping[j]
        # names the slot carrying DENSE row j (encode_chunks' k data
        # rows then m parity rows). Identity for RS; LRC interleaves
        # data and local/global parity positions.
        self.chunk_mapping = [int(p) for p in
                              self.coder.get_chunk_mapping()]
        if sorted(self.chunk_mapping) != list(range(self.k + self.m)):
            raise ValueError(
                f"chunk mapping {self.chunk_mapping} is not a "
                f"permutation of 0..{self.k + self.m - 1}")
        self.data_slots = self.chunk_mapping[:self.k]
        self._perm = np.asarray(self.chunk_mapping)
        self._identity_mapping = \
            self.chunk_mapping == list(range(self.k + self.m))
        # pool-wide stripe geometry; round the requested chunk size up
        # through the coder's own alignment rule (clay needs sub-chunk
        # multiples, everything needs CHUNK_ALIGNMENT)
        requested = chunk_size or self.coder.get_chunk_size(0) or 4096
        cs = self.coder.get_chunk_size(requested * self.k)
        self.sinfo = StripeInfo(self.k, cs)
        self._init_common(pg, acting, cluster or ShardSet(),
                          ensure_collections=ensure_collections)
        self._fused_cache: dict = {}
        # partial-stripe RMW state: per-PG stripe-journal sequence
        # (replay re-anchors it past every seq seen on disk) and the
        # crash hook the phase-boundary tests drive (None in prod)
        self._rmw_seq = 0
        self._rmw_crash_hook = None
        # read-path EIO accounting (verify-on-read mismatches + the
        # in-place rewrites they triggered)
        self.eio_stats = {"read_eio": 0, "repaired": 0}

    # -- helpers ------------------------------------------------------------

    def _shard_len(self, object_size: int) -> int:
        return self.sinfo.object_size_to_shard_size(object_size)

    def _slots_from_dense(self, dense: np.ndarray) -> np.ndarray:
        """(B, n, L) dense rows (k data then m parity, encode order)
        -> per-slot rows: slot chunk_mapping[j] carries dense row j."""
        if self._identity_mapping:
            return dense
        out = np.empty_like(dense)
        out[:, self._perm] = dense
        return out

    _expected_shard_len = _shard_len  # shallow-scrub size rule

    # hinfo CRCs use the shared batched-launch helper
    _batched_hinfo_crcs = staticmethod(PGBackend._batched_crcs)

    @staticmethod
    @_functools.lru_cache(maxsize=256)
    def _fused_write_fn(matrix_bytes: bytes, m: int, k: int, impl: str,
                        sl: int, bucket: int):
        """Process-wide cache (like rs_kernels._make_jitted): every
        PG backend with the same coder geometry shares ONE compiled
        program per (shard len, batch bucket) — a per-backend cache
        would recompile the identical HLO once per PG per daemon."""
        import jax
        import jax.numpy as jnp

        from ..csum.kernels import crc32c_blocks
        from ..ops.rs_kernels import make_encoder
        matrix = np.frombuffer(matrix_bytes,
                               dtype=np.uint8).reshape(m, k)
        enc = make_encoder(matrix, impl, bucket_batch=False)
        n = m + k

        def fused(d):                # (bucket, k, sl) u8
            parity = enc(d)          # (bucket, m, sl)
            rows = jnp.concatenate([d, parity], axis=1)
            crcs = crc32c_blocks(rows.reshape(bucket * n, sl),
                                 init=0xFFFFFFFF,
                                 xorout=0).reshape(bucket, n)
            return parity, crcs
        return jax.jit(fused)

    def _encode_shards_with_crcs(self, data_shards: np.ndarray,
                                 sl: int) -> tuple[np.ndarray,
                                                   np.ndarray]:
        """(B, k, sl) data rows -> (slot-ordered (B, n, sl) shards,
        slot-ordered (B, n) hinfo CRCs). For static-matrix coders the
        encode AND both CRC sets run as ONE fused, B-bucketed device
        launch with a single host fetch — the write path's r01 shape
        dispatched encode + CRC as separate launches with host
        round-trips between (the wire tier pays that per client op).
        Other coders take the generic two-launch path."""
        from ..ec.rs import ReedSolomon
        B = data_shards.shape[0]
        if isinstance(self.coder, ReedSolomon) \
                and _host_crc_available():
            # host-encode mode (the r10 host-integrity precedent, on
            # the WRITE path): on the CPU backend the native SSE RS
            # codec + hardware crc32c beat the XLA launch ~4x at wire
            # batch sizes, and the bytes are BIT-IDENTICAL (same
            # coding matrix, ec_create_with_matrix; parity pinned by
            # tests/test_sharded_osd.py). On a real accelerator the
            # device encode is nearly free and this path stays off.
            mat = np.ascontiguousarray(self.coder.matrix,
                                       dtype=np.uint8)
            handle = _host_encoder_handle(mat.tobytes(), self.k,
                                          self.m)
            if handle is not None:
                from .. import native as _native
                import ctypes as _ctypes
                self.perf.inc_many(
                    (("host_encode_launches", 1),
                     ("encode_bytes", int(data_shards.size))))
                with span("ecbackend.write.encode",
                          counters=self.perf, key="encode_time"):
                    data_c = np.ascontiguousarray(data_shards)
                    parity = np.zeros((B, self.m, sl), np.uint8)
                    rc = _native.lib().ec_encode(
                        handle,
                        data_c.ctypes.data_as(_ctypes.c_char_p),
                        parity.ctypes.data_as(_ctypes.c_char_p),
                        sl, B)
                    if rc == 0:
                        dense = np.concatenate([data_shards, parity],
                                               axis=1)
                        dense_crcs = _native.native_crc32c_rows(
                            0xFFFFFFFF,
                            np.ascontiguousarray(dense).reshape(
                                B * self.n, sl)).reshape(B, self.n)
                        shards = self._slots_from_dense(dense)
                        if self._identity_mapping:
                            return shards, dense_crcs
                        crcs = np.empty_like(dense_crcs)
                        crcs[:, self._perm] = dense_crcs
                        return shards, crcs
                # rc != 0: fall through to the fused device launch
        if isinstance(self.coder, ReedSolomon):
            import jax
            from ..ops.rs_kernels import pow2_bucket
            bucket = pow2_bucket(B)
            mat = np.ascontiguousarray(self.coder.matrix,
                                       dtype=np.uint8)
            ci0 = self._fused_write_fn.cache_info()
            fn = self._fused_write_fn(mat.tobytes(), self.m, self.k,
                                      self.coder.impl, sl, bucket)
            ci1 = self._fused_write_fn.cache_info()
            self.perf.inc_many(
                (("fused_write_launches", 1),
                 ("encode_bytes", int(data_shards.size)),
                 ("program_cache_hits", ci1.hits - ci0.hits),
                 ("program_cache_misses", ci1.misses - ci0.misses)))
            padded = data_shards
            if bucket != B:
                padded = np.zeros((bucket,) + data_shards.shape[1:],
                                  dtype=np.uint8)
                padded[:B] = data_shards
            with span("ecbackend.write.encode", counters=self.perf,
                      key="encode_time"):
                parity_d, crcs_d = fn(padded)
                parity, dense_crcs = jax.device_get((parity_d, crcs_d))
            dense = np.concatenate(
                [data_shards, np.asarray(parity)[:B]], axis=1)
            dense_crcs = np.asarray(dense_crcs)[:B]
            shards = self._slots_from_dense(dense)
            if self._identity_mapping:
                return shards, dense_crcs
            crcs = np.empty_like(dense_crcs)
            crcs[:, self._perm] = dense_crcs
            return shards, crcs
        self.perf.inc_many((("encode_launches", 1),
                            ("encode_bytes", int(data_shards.size))))
        with span("ecbackend.write.encode", counters=self.perf,
                  key="encode_time"):
            parity = np.asarray(self.coder.encode_chunks(data_shards))
        shards = self._slots_from_dense(
            np.concatenate([data_shards, parity], axis=1))
        crcs = self._batched_hinfo_crcs(
            shards.reshape(-1, sl)).reshape(B, self.n)
        return shards, crcs

    def _write_empty(self, name: str, live: list[int] | None = None) -> None:
        hinfo = HashInfo(1, 0, [0xFFFFFFFF])
        self.object_sizes[name] = 0
        live = live if live is not None else list(range(self.n))
        for shard in live:
            t = (Transaction()
                 .write(shard_cid(self.pg, shard), name, 0, b"")
                 .truncate(shard_cid(self.pg, shard), name, 0)
                 .setattr(shard_cid(self.pg, shard), name,
                          HINFO_KEY, hinfo.to_bytes()))
            self._store(shard).queue_transaction(t)
        self._log_write(name, live)

    # -- write path (submit_transaction, full-object) ------------------------

    def write_objects(self, objects: dict[str, bytes | np.ndarray],
                      dead_osds: set[int] | None = None,
                      shard_txn_extra=None) -> None:
        """Full-object writes, batched: encode every equal-length group
        in one device launch, then scatter per-shard store transactions
        (the role of ECTransaction::generate_transactions). Shards on
        dead OSDs are skipped and fall behind in the PG log.

        shard_txn_extra: optional factory, called once per fan-out
        wave with the wave's object names, AFTER the PG log reflects
        the wave's writes; returns fn(shard, txn) that appends extra
        ops to each shard's transaction. The wire tier rides the PG metadata persist on it
        (the pg-log-entries-inside-the-transaction discipline, ref:
        ECTransaction carrying log entries to every shard) so a client
        write costs ONE fan-out instead of two. With the hook in use
        the log append happens before the fan-out; a failed wave then
        leaves log entries no shard applied, which the caller's
        degraded retry simply supersedes (cursors only advance on the
        entries the retry wave ships)."""
        live = self._live_slots(dead_osds)
        self._check_min_size(live)
        by_len: dict[int, list[tuple[str, np.ndarray]]] = {}
        for name, data in objects.items():
            arr = as_flat_u8(data)
            by_len.setdefault(len(arr), []).append((name, arr))
        for olen, group in by_len.items():
            if olen == 0:
                for name, _ in group:
                    self._write_empty(name, live)
                if shard_txn_extra is not None:
                    add = shard_txn_extra([n for n, _ in group])
                    txns = []
                    for shard in live:
                        t = Transaction()
                        add(shard, t)
                        txns.append((shard, t))
                    self._fanout_txns(txns)
                continue
            batch = np.stack([a for _, a in group])
            sl = self._shard_len(olen)
            data_shards = self.sinfo.object_to_shards(batch)  # (B, k, sl)
            shards, crcs = self._encode_shards_with_crcs(data_shards,
                                                         sl)
            for name, _ in group:
                self.object_sizes[name] = olen
            add = None
            if shard_txn_extra is not None:
                # log FIRST so the extra ops (the metadata persist)
                # see the post-write history; see the docstring for
                # why a failed wave cannot wedge the cursors
                for name, _ in group:
                    self._log_write(name, live)
                add = shard_txn_extra([n for n, _ in group])
            # ONE combined transaction per shard for the whole batch
            # (the sub-op fan-out unit; on the wire tier this is one
            # MStoreOp frame per shard instead of one per object —
            # the batched analog of MOSDECSubOpWrite carrying the
            # whole RMW plan), fanned out pipelined: all shards'
            # frames hit the wire before any ack is awaited
            txns = []
            for shard in live:
                cid = shard_cid(self.pg, shard)
                t = Transaction()
                for bi, (name, arr) in enumerate(group):
                    hinfo = HashInfo(1, sl, [int(crcs[bi, shard])])
                    # truncate clears any stale tail from a previous,
                    # larger version of the object
                    t.write(cid, name, 0, shards[bi, shard, :]) \
                     .truncate(cid, name, sl) \
                     .setattr(cid, name, HINFO_KEY, hinfo.to_bytes())
                if add is not None:
                    add(shard, t)
                txns.append((shard, t))
            self.perf.inc("write_wire_bytes", len(group) * len(live) * sl)
            self._fanout_txns(txns)
            if shard_txn_extra is None:
                for name, _ in group:
                    self._log_write(name, live)

    # -- write path (RMW partial-stripe) -------------------------------------

    # write_at (the single-range RMW entry; ref: ECCommon::RMWPipeline::
    # start_rmw) is inherited from PGBackend and lands in write_ranges

    def _read_data_window(self, names: list[str], c0: int, clen: int,
                          dead: set[int],
                          old_slens: list[int]) -> np.ndarray:
        """Pre-image data-shard window (B, k, clen) for the RMW read
        phase, reconstructing down data shards from survivors (the
        degraded-write case). Reads past a shard's end zero-fill, which
        matches the zero-padding layout rule.

        old_slens: each object's current shard length — vector codes
        (clay) must decode at the OLD length because their sub-chunk
        geometry depends on chunk length; zero-extended chunks would
        decode to garbage."""
        B = len(names)
        avail = self._fresh_for(
            names, [s for s in range(self.n) if self.acting[s] not in dead])
        lost_data = [s for s in self.data_slots if s not in avail]

        def read_window(s: int, nm: str, off: int, ln: int) -> np.ndarray:
            buf = np.zeros(ln, dtype=np.uint8)
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            if st.exists(cid, nm):
                got = st.read(cid, nm, off, ln)
                buf[:len(got)] = got
            return buf

        # window rows are DENSE data order (row j <-> slot
        # data_slots[j]) so shards_to_object can consume it directly
        dense_of = {s: j for j, s in enumerate(self.data_slots)}
        window = np.zeros((B, self.k, clen), dtype=np.uint8)
        for j, s in enumerate(self.data_slots):
            if s in lost_data:
                continue
            for bi, nm in enumerate(names):
                window[bi, j] = read_window(s, nm, c0, clen)
        if not lost_data:
            return window
        helpers = sorted(self.coder.minimum_to_decode(lost_data, avail))
        if getattr(self.coder, "positionwise", True):
            # surviving data helpers are already in `window`; only read
            # parity helpers from the stores
            stacks = {s: window[:, dense_of[s]] if s in dense_of else
                      np.stack([read_window(s, nm, c0, clen)
                                for nm in names])
                      for s in helpers}
            rec = self.coder.decode_chunks(lost_data, stacks)
            for s in lost_data:
                window[:, dense_of[s]] = np.asarray(rec[s])
        else:
            # decode whole chunks at each object's OLD shard length
            # (the non-positionwise path always uses c0 == 0 windows)
            by_old: dict[int, list[int]] = {}
            for bi, sl in enumerate(old_slens):
                if sl:
                    by_old.setdefault(sl, []).append(bi)
            for sl, idxs in by_old.items():
                stacks = {s: np.stack([read_window(s, names[bi], 0, sl)
                                       for bi in idxs])
                          for s in helpers}
                rec = self.coder.decode_chunks(lost_data, stacks)
                ln = min(sl, clen)
                for s in lost_data:
                    window[idxs, dense_of[s], :ln] = \
                        np.asarray(rec[s])[:, :ln]
        return window

    def write_ranges(self, ops: list[tuple[str, int, bytes | np.ndarray]],
                     dead_osds: set[int] | None = None) -> None:
        """Batched RMW dispatcher: every (name, offset, bytes) op goes
        to the PARITY-DELTA fast path when the stripe is clean (all
        shards live + caught up, write within one stripe, touched data
        columns < k) — only the touched data shard(s) plus the m
        parity shards move on the wire, crash-consistent through the
        per-PG stripe journal — and ladders to the full-stripe RMW
        (`_write_ranges_full`, the pre-r16 path) otherwise: degraded
        or stale stripes, object creation, stripe-spanning or
        overlapping writes, vector-code geometry changes."""
        dead = dead_osds or set()
        delta_jobs, full_ops = self._partition_rmw(ops, dead)
        if delta_jobs:
            self._write_ranges_delta(delta_jobs)
        if full_ops:
            self.perf.inc("rmw_full_fallbacks",
                          len({n for n, _o, _d in full_ops}))
            self._write_ranges_full(full_ops, dead_osds)

    def _write_ranges_full(self,
                           ops: list[tuple[str, int, bytes | np.ndarray]],
                           dead_osds: set[int] | None = None) -> None:
        """Full-stripe RMW: read the touched stripe window, overlay,
        re-encode, and emit per-shard sub-range writes + hinfo
        updates. Encode launches are batched across objects whose
        windows have equal chunk length. Handles every case the delta
        path refuses (degraded pre-image reconstruction included)."""
        dead = dead_osds or set()
        k, si = self.k, self.sinfo
        live = [s for s in range(self.n) if self.acting[s] not in dead]
        self._check_min_size(live)

        # merge ops per object into one covering window
        per_obj: dict[str, list[tuple[int, np.ndarray]]] = {}
        for name, offset, data in ops:
            if offset < 0:
                raise ValueError(f"negative offset {offset}")
            per_obj.setdefault(name, []).append(
                (int(offset), as_flat_u8(data)))

        jobs = []  # (name, writes, old_slen, new_size, s0, clen)
        for name, writes in per_obj.items():
            old_size = self.object_sizes.get(name, 0)
            writes = [(off, a) for off, a in writes if len(a)]
            if not writes:
                # zero-length writes don't extend; just ensure existence
                if name not in self.object_sizes:
                    self._write_empty(name, live)
                continue
            hi = max(off + len(a) for off, a in writes)
            new_size = max(old_size, hi)
            lo = min(off for off, a in writes)
            if not getattr(self.coder, "positionwise", True):
                # vector codes (clay) couple bytes across the whole
                # chunk: windows are not independently encodable, so
                # fall back to a whole-object RMW
                lo, hi = 0, new_size
            s0, slen = si.offset_len_to_stripe_bounds(lo, hi - lo)
            jobs.append((name, writes, self._shard_len(old_size),
                         new_size, s0, slen // k))

        by_clen: dict[int, list[tuple]] = {}
        for job in jobs:
            by_clen.setdefault(job[-1], []).append(job)

        for clen, group in by_clen.items():
            names = [j[0] for j in group]
            old_slens = [j[2] for j in group]
            c0s = {j[4] // k for j in group}
            if len(c0s) == 1:
                window = self._read_data_window(names, c0s.pop(), clen,
                                                dead, old_slens)
            else:
                # mixed chunk offsets in one length group: read per job
                window = np.stack([
                    self._read_data_window([j[0]], j[4] // k, clen, dead,
                                           [j[2]])[0]
                    for j in group])
            # overlay new bytes in logical space
            logical = si.shards_to_object(window)  # (B, slen)
            for bi, (name, writes, _, _, s0, _) in enumerate(group):
                for off, arr in writes:
                    logical[bi, off - s0:off - s0 + len(arr)] = arr
            dshards = si.object_to_shards(logical)       # (B, k, clen)
            parity = np.asarray(self.coder.encode_chunks(dshards))
            shards = self._slots_from_dense(
                np.concatenate([dshards, parity], axis=1))  # (B, n, clen)

            # apply sub-range writes + recompute full-shard hinfo on the
            # LIVE shards only (down shards are rebuilt by recovery;
            # touching their stores would resurrect destroyed OSD ids).
            # Cumulative-CRC hinfo is append-only in the reference; an
            # overwrite invalidates it, so the RMW path recomputes the
            # full-shard CRC — batched per equal shard length.
            new_full: dict[int, list[np.ndarray]] = {}  # nsl -> full bytes
            slots: dict[int, list[tuple[int, int]]] = {}  # nsl -> (bi, s)
            for bi, (name, writes, _, new_size, s0, _) in enumerate(group):
                nsl = self._shard_len(new_size)
                c0 = s0 // k
                for s in live:
                    st = self._store(s)
                    cid = shard_cid(self.pg, s)
                    old = st.read(cid, name) if st.exists(cid, name) \
                        else np.zeros(0, dtype=np.uint8)
                    full = np.zeros(nsl, dtype=np.uint8)
                    full[:min(len(old), nsl)] = old[:nsl]
                    full[c0:c0 + clen] = shards[bi, s]
                    new_full.setdefault(nsl, []).append(full)
                    slots.setdefault(nsl, []).append((bi, s))
            crc_of: dict[tuple[int, int], int] = {}
            for nsl, fulls in new_full.items():
                crcs = self._batched_hinfo_crcs(np.stack(fulls))
                for (bi, s), c in zip(slots[nsl], crcs):
                    crc_of[(bi, s)] = int(c)
            # one combined txn per live shard for the whole group,
            # fanned out pipelined (matches the full-write path)
            shard_txns = {s: Transaction() for s in live}
            for bi, (name, writes, _, new_size, s0, _) in enumerate(group):
                nsl = self._shard_len(new_size)
                c0 = s0 // k
                for s in live:
                    hinfo = HashInfo(1, nsl, [crc_of[(bi, s)]])
                    shard_txns[s].write(shard_cid(self.pg, s), name, c0,
                                        shards[bi, s]) \
                        .setattr(shard_cid(self.pg, s), name,
                                 HINFO_KEY, hinfo.to_bytes())
            self.perf.inc("write_wire_bytes",
                          len(group) * len(live) * clen)
            self._fanout_txns(list(shard_txns.items()))
            for bi, (name, writes, _, new_size, s0, _) in enumerate(group):
                self.object_sizes[name] = new_size
                self._log_write(name, live)

    # -- write path (parity-delta fast path + stripe journal) ----------------
    #
    # The small-overwrite/append data path (ROADMAP item 3; the
    # online-EC measurement arxiv 1709.05365 shows write amplification
    # dominating this workload): delta_j = G[j,i] (x) (new_i ^ old_i)
    # folded into each parity shard, so only the touched data shard(s)
    # plus m parity shards move — not k+m. Crash consistency comes
    # from a per-PG stripe journal (intent logged durably on every
    # participating shard BEFORE any in-place XOR; an applied shard
    # atomically bumps its watermark and drops the entry), replayed by
    # stripe_journal_replay: SIGKILL anywhere leaves the stripe
    # bit-exact with either the old or the new bytes, never torn.

    JOURNAL_OBJ = "__stripe_journal__"
    _J_APPLIED = b"applied"

    @staticmethod
    def _jkey(seq: int) -> bytes:
        return b"e%016x" % seq

    @staticmethod
    def _encode_jentry(seq: int, name: str, slot: int,
                       participants, new_size: int, osl: int, nsl: int,
                       a: int, delta: bytes, new_crc: int,
                       version: int) -> bytes:
        from ..utils.encoding import Encoder
        e = Encoder()
        e.u32(1)                        # entry codec version
        e.u64(seq).string(name).u32(slot)
        e.list([int(p) for p in participants], Encoder.u32)
        e.u64(new_size).u64(osl).u64(nsl)
        e.u64(a).blob(delta)
        e.u32(new_crc)
        e.u64(version)                  # the PG-log version this RMW
        #                                 creates: replay drops entries
        #                                 a later write superseded
        return e.bytes()

    @staticmethod
    def _decode_jentry(raw: bytes) -> dict:
        from ..utils.encoding import Decoder
        d = Decoder(raw)
        v = d.u32()
        if v != 1:
            raise ValueError(f"stripe-journal entry version {v}")
        return {"seq": d.u64(), "name": d.string(), "slot": d.u32(),
                "participants": d.list(Decoder.u32),
                "new_size": d.u64(), "osl": d.u64(), "nsl": d.u64(),
                "a": d.u64(), "delta": d.blob(), "new_crc": d.u32(),
                "version": d.u64()}

    def _partition_rmw(self, ops, dead: set[int]):
        """Split a write_ranges op list into delta-eligible jobs and
        the ops the full path must carry. One job per object (ops
        merged); a job is delta-eligible when the stripe is CLEAN
        (every slot live and caught up — a delta against a stale or
        reconstructed pre-image would fold garbage into parity, so
        degraded stripes refuse and ladder down), the object exists,
        the merged writes don't overlap or span a full stripe, fewer
        than k data columns are touched, and (vector codes) the shard
        length doesn't change under the sub-chunk geometry."""
        k, si = self.k, self.sinfo
        per_obj: dict[str, list[tuple[int, np.ndarray]]] = {}
        order: list[str] = []
        raw: dict[str, list[tuple]] = {}
        for name, offset, data in ops:
            if offset < 0:
                raise ValueError(f"negative offset {offset}")
            if name not in per_obj:
                order.append(name)
            per_obj.setdefault(name, []).append(
                (int(offset), as_flat_u8(data)))
            raw.setdefault(name, []).append((name, offset, data))
        all_live = len(self._live_slots(dead)) == self.n
        jobs, full_ops = [], []
        for name in order:
            writes = [(o, a) for o, a in per_obj[name] if len(a)]
            old_size = self.object_sizes.get(name, 0)
            job = None
            if writes and all_live and old_size > 0:
                job = self._delta_job(name, writes, old_size)
            if job is not None \
                    and len(self._fresh_for([name],
                                            list(range(self.n)))) \
                    == self.n:
                jobs.append(job)
            else:
                full_ops.extend(raw[name])
        return jobs, full_ops

    def _delta_job(self, name: str, writes, old_size: int):
        """Geometry of one delta-eligible overwrite, or None. A job is
        (name, writes, old_size, new_size, osl, nsl, touched, spans,
        a, b): `spans` are per-write (col, chunk_off, len, log_off)
        chunk sub-ranges, (a, b) the common shard-offset window the
        delta rows are positioned in."""
        si, k = self.sinfo, self.k
        sw = si.stripe_width
        lo = min(o for o, _a in writes)
        hi = max(o + len(a) for o, a in writes)
        if hi - lo >= sw or lo >= old_size + sw:
            return None     # stripe-spanning, or a hole of untouched
        #                     stripes past the tail: full path
        # overlap check: delta composition is XOR — overlapping writes
        # in one wave would double-fold
        ivs = sorted((o, o + len(a)) for o, a in writes)
        for (s1, e1), (s2, _e2) in zip(ivs, ivs[1:]):
            if s2 < e1:
                return None
        new_size = max(old_size, hi)
        osl = self._shard_len(old_size)
        nsl = self._shard_len(new_size)
        spans = []
        touched: set[int] = set()
        for off, arr in writes:
            at = off
            end = off + len(arr)
            while at < end:
                stripe, rem = divmod(at, sw)
                col = rem // si.chunk_size
                in_chunk = rem % si.chunk_size
                ln = min(end - at, si.chunk_size - in_chunk)
                spans.append((col, stripe * si.chunk_size + in_chunk,
                              ln, at))
                touched.add(col)
                at += ln
        if len(touched) >= k:
            return None     # every data shard moves anyway
        if not getattr(self.coder, "positionwise", True):
            if nsl != osl:
                return None     # sub-chunk geometry changes with
            #                     length: ladder to full re-encode
            a, b = 0, osl       # byte positions couple: the delta
            #                     window is the whole chunk
        else:
            a = min(c0 for _col, c0, _ln, _lo in spans)
            b = max(c0 + ln for _col, c0, ln, _lo in spans)
        return (name, writes, old_size, new_size, osl, nsl,
                tuple(sorted(touched)), spans, a, b)

    @staticmethod
    @_functools.lru_cache(maxsize=256)
    def _fused_delta_fn(matrix_bytes: bytes, m: int, t: int, impl: str,
                        wl: int, bucket: int):
        """Process-wide fused delta-encode program (the r10 recovery-
        program sharing rule): every PG backend whose coder exposes
        the same delta_program_key shares ONE compiled program per
        (window len, batch bucket). delta rows (bucket, t, wl) ->
        (parity deltas (bucket, m, wl), zero-seed CRCs of all t+m
        rows) in a single launch — the CRCs feed the incremental
        hinfo update."""
        import jax
        import jax.numpy as jnp

        from ..csum.kernels import crc32c_blocks
        from ..ops.rs_kernels import make_encoder
        D = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(m, t)
        enc = make_encoder(D, impl, bucket_batch=False)
        n = m + t

        def fused(d):                   # (bucket, t, wl) u8
            parity = enc(d)             # (bucket, m, wl)
            rows = jnp.concatenate([d, parity], axis=1)
            crcs = crc32c_blocks(rows.reshape(bucket * n, wl),
                                 init=0, xorout=0).reshape(bucket, n)
            return parity, crcs
        return jax.jit(fused)

    def _delta_parity_crcs(self, touched: tuple, deltas: np.ndarray
                           ) -> tuple[np.ndarray, np.ndarray]:
        """(B, t, wl) data deltas -> ((B, m, wl) parity deltas,
        (B, t+m) zero-seed CRCs of data+parity delta rows). Static-
        matrix coders take the native host codec (CPU backend, the
        r13 host-encode mode) or the fused device program; the rest
        (bitmatrix, clay) go through parity_delta's generic
        XOR-linear encode."""
        B, t, wl = deltas.shape
        D = self.coder.delta_matrix(touched)
        if D is not None and _host_crc_available():
            handle = _host_encoder_handle(
                np.ascontiguousarray(D, np.uint8).tobytes(), t, self.m)
            if handle is not None:
                from .. import native as _native
                import ctypes as _ctypes
                data_c = np.ascontiguousarray(deltas)
                parity = np.zeros((B, self.m, wl), np.uint8)
                rc = _native.lib().ec_encode(
                    handle,
                    data_c.ctypes.data_as(_ctypes.c_char_p),
                    parity.ctypes.data_as(_ctypes.c_char_p), wl, B)
                if rc == 0:
                    self.perf.inc("rmw_delta_launches")
                    rows = np.concatenate([deltas, parity], axis=1)
                    crcs = _native.native_crc32c_rows(
                        0, np.ascontiguousarray(rows).reshape(
                            B * (t + self.m), wl)).reshape(
                                B, t + self.m)
                    return parity, crcs
        if D is not None:
            import jax

            from ..ops.rs_kernels import pow2_bucket
            bucket = pow2_bucket(B)
            ci0 = self._fused_delta_fn.cache_info()
            fn = self._fused_delta_fn(
                np.ascontiguousarray(D, np.uint8).tobytes(), self.m,
                t, getattr(self.coder, "impl", None) or "mxu", wl,
                bucket)
            ci1 = self._fused_delta_fn.cache_info()
            self.perf.inc_many(
                (("rmw_delta_launches", 1),
                 ("program_cache_hits", ci1.hits - ci0.hits),
                 ("program_cache_misses", ci1.misses - ci0.misses)))
            padded = deltas
            if bucket != B:
                padded = np.zeros((bucket, t, wl), np.uint8)
                padded[:B] = deltas
            parity_d, crcs_d = fn(padded)
            parity, crcs = jax.device_get((parity_d, crcs_d))
            return (np.asarray(parity)[:B], np.asarray(crcs)[:B])
        self.perf.inc("rmw_delta_launches")
        parity = self.coder.parity_delta(touched, deltas)
        rows = np.concatenate([deltas, parity], axis=1)
        crcs = _rows_crc0(rows.reshape(B * (t + self.m), wl)).reshape(
            B, t + self.m)
        return parity, crcs

    def _shard_old_crcs(self, name: str, slots) -> dict[int, int] | None:
        """Current hinfo CRC per slot, or None when any slot's stored
        hinfo is absent/odd (the delta path then refuses the job —
        an incremental update against a wrong base would stamp a
        corrupt CRC that verifies forever)."""
        osl = self._shard_len(self.object_sizes[name])
        out: dict[int, int] = {}
        for s in slots:
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            try:
                hinfo = HashInfo.from_bytes(st.getattr(cid, name,
                                                       HINFO_KEY))
            except KeyError:
                return None
            if hinfo.total_chunk_size != osl:
                return None
            out[s] = hinfo.get_chunk_hash(0)
        return out

    def _rmw_participants(self, touched: tuple, osl: int,
                          nsl: int) -> list[int]:
        """Participant shard slots of one delta job: the touched data
        columns + every parity slot; growth (nsl != osl) adds the
        rest with payload-free entries (zero-extension + hinfo
        shift). ONE derivation shared by the prepare fetch and the
        commit fan-out so the two cannot drift."""
        parts = ([self.data_slots[c] for c in touched]
                 + [self.chunk_mapping[self.k + j]
                    for j in range(self.m)])
        if nsl != osl:
            parts = parts + [s for s in range(self.n)
                             if s not in set(parts)]
        return parts

    def _rmw_prefetch(self, touched: tuple, group):
        """One pipelined wave of combined (hinfo attr + pre-image
        sub-range) fetches for a whole delta group — r16's prepare
        phase paid 1+m tiny sequential getattrs plus one read RTT per
        touched span per job; this pays ONE overlapped frame per
        participant shard for the group (RemoteStore.rmw_fetch_submit;
        in-process stores take the direct path, same accounting).

        Returns (old_crcs, prereads) aligned with `group`:
        old_crcs[i] = {slot: stored hinfo crc} or None when any
        participant's hinfo refuses the incremental base (the job
        then reroutes through the full path, exactly like
        _shard_old_crcs); prereads[i] = {(slot, off, len): bytes}
        for every touched sub-range below the old tail."""
        per_slot: dict[int, list[tuple[str, list]]] = {}
        parts_of: list[list[int]] = []
        ranges_of: list[dict[int, list]] = []
        for job in group:
            name, _w, old_size, _ns, osl, nsl, _t, spans, _a, _b = job
            parts = self._rmw_participants(touched, osl, nsl)
            parts_of.append(parts)
            need: dict[int, list] = {}
            for col, c0, ln, lo in spans:
                if lo < old_size:
                    need.setdefault(self.data_slots[col],
                                    []).append((c0, ln))
            ranges_of.append(need)
            for s in parts:
                per_slot.setdefault(s, []).append(
                    (name, need.get(s, [])))
        handles: list[tuple[int, object]] = []
        results: dict[int, list] = {}
        for s, items in sorted(per_slot.items()):
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            sub = getattr(st, "rmw_fetch_submit", None)
            if sub is not None:
                handles.append((s, sub(cid, HINFO_KEY, items)))
                continue
            out = []
            for name, ranges in items:
                try:
                    attr, ok = st.getattr(cid, name, HINFO_KEY), True
                except KeyError:
                    attr, ok = b"", False
                out.append((ok, attr,
                            [np.asarray(st.read(cid, name, off, ln),
                                        np.uint8).tobytes()
                             for off, ln in ranges]))
            results[s] = out
        for s, h in handles:
            results[s] = h.result()
        self.perf.inc_many((("rmw_fetch_waves", 1),
                            ("rmw_fetch_frames", len(per_slot))))
        cursor = {s: 0 for s in per_slot}
        old_crcs: list[dict | None] = []
        prereads: list[dict] = []
        for ji, job in enumerate(group):
            osl = job[4]
            crcs: dict[int, int] | None = {}
            pre: dict = {}
            for s in parts_of[ji]:
                ok, attr, rows = results[s][cursor[s]]
                cursor[s] += 1
                if crcs is not None:
                    hinfo = None
                    if ok:
                        try:
                            hinfo = HashInfo.from_bytes(attr)
                        except Exception:   # noqa: BLE001 — odd
                            hinfo = None    # stored attr: refuse
                    if hinfo is None or hinfo.total_chunk_size != osl:
                        crcs = None
                    else:
                        crcs[s] = hinfo.get_chunk_hash(0)
                for (off, ln), blob in zip(ranges_of[ji].get(s, []),
                                           rows):
                    pre[(s, off, ln)] = np.frombuffer(blob, np.uint8)
            old_crcs.append(crcs)
            prereads.append(pre)
        return old_crcs, prereads

    def _write_ranges_delta(self, jobs) -> None:
        """Execute delta-eligible RMW jobs: build the delta rows
        (reading only the touched sub-ranges' pre-image — none at all
        for appends into padding), one fused delta-encode launch per
        (touched-columns, window) group, then the journaled two-phase
        shard update. Jobs whose stored hinfo refuses the incremental
        update reroute through the full path."""
        by_shape: dict[tuple, list] = {}
        for job in jobs:
            _n, _w, _os, _ns, _osl, _nsl, touched, _sp, a, b = job
            by_shape.setdefault((touched, b - a), []).append(job)
        for (touched, wl), group in by_shape.items():
            self._delta_group(touched, wl, group)

    def _delta_group(self, touched: tuple, wl: int, group) -> None:
        t = len(touched)
        col_of = {c: i for i, c in enumerate(touched)}
        parity_slots = [self.chunk_mapping[self.k + j]
                        for j in range(self.m)]
        B = len(group)
        deltas = np.zeros((B, t, wl), np.uint8)
        append_fast = 0
        preread = 0
        # r17: ONE overlapped prepare-fetch wave for the whole group —
        # hinfo attrs (the incremental-update base _delta_commit
        # verifies) and pre-image sub-ranges arrive together, one
        # frame per participant shard instead of 1+m sequential
        # getattrs + a read RTT per span per job
        old_crcs, prereads = self._rmw_prefetch(touched, group)
        for bi, job in enumerate(group):
            name, writes, old_size, _ns, osl, _nsl, _t, spans, a, _b \
                = job
            pure_append = all(lo >= old_size
                              for _c, _c0, _ln, lo in spans)
            for col, c0, ln, lo in spans:
                off, arr = next((o, w) for o, w in writes
                                if o <= lo and lo + ln <= o + len(w))
                newb = arr[lo - off:lo - off + ln]
                row = deltas[bi, col_of[col]]
                if lo >= old_size:
                    # append into padding: the pre-image is zeros by
                    # the layout rule — no read phase
                    row[c0 - a:c0 - a + ln] = newb
                    continue
                got = prereads[bi][(self.data_slots[col], c0, ln)]
                oldb = np.zeros(ln, np.uint8)
                oldb[:len(got)] = got
                preread += ln
                row[c0 - a:c0 - a + ln] = np.asarray(newb) ^ oldb
            if pure_append:
                append_fast += 1
        parity, crcs = self._delta_parity_crcs(touched, deltas)
        self.perf.inc_many((("rmw_preread_bytes", preread),
                            ("rmw_append_fast", append_fast)))
        self._delta_commit(touched, wl, group, deltas, parity, crcs,
                           parity_slots, old_crcs=old_crcs)

    def _delta_commit(self, touched: tuple, wl: int, group,
                      deltas, parity, crcs, parity_slots,
                      old_crcs: list | None = None) -> None:
        """The journaled two-phase shard update of one delta batch:
        intent entries (delta payload + new hinfo) durably on every
        participating shard, then the atomic per-shard apply (XOR +
        hinfo + watermark bump + entry drop in ONE transaction).
        `old_crcs` carries the prefetched per-job hinfo bases from
        _rmw_prefetch (None entries reroute through the full path);
        absent, the per-job sync getattr loop serves (bare-backend
        callers)."""
        t = len(touched)
        hook = self._rmw_crash_hook
        # per job: rows per slot, new crcs per slot, participants
        waves = []       # (job, seq, {slot: (row|None, new_crc)})
        wire = 0
        shard_prep: dict[int, Transaction] = {}
        shard_apply: dict[int, Transaction] = {}
        max_seq_of: dict[int, int] = {}
        keys_of: dict[int, list[bytes]] = {}
        for bi, job in enumerate(group):
            name, _w, _os, new_size, osl, nsl, _t, _sp, a, b = job
            # growth touches every shard (zero-extension + hinfo
            # shift) — the others ride payload-free entries
            parts = self._rmw_participants(touched, osl, nsl)
            old = old_crcs[bi] if old_crcs is not None \
                else self._shard_old_crcs(name, parts)
            if old is None:
                # stored hinfo refuses the incremental base: reroute
                # this job through the full path (rare — e.g. a
                # legacy object written before hinfo discipline)
                self.perf.inc("rmw_full_fallbacks")
                self._write_ranges_full(
                    [(name, o, w) for o, w in job[1]], None)
                continue
            self._rmw_seq += 1
            seq = self._rmw_seq
            # the PG-log version this job will create (jobs log in
            # wave order right after the apply fan-out)
            pred_version = self.pg_log.head + len(waves) + 1
            plan: dict[int, tuple] = {}
            for ti, c in enumerate(touched):
                s = self.data_slots[c]
                crc0 = int(crcs[bi, ti])
                plan[s] = (deltas[bi, ti], crc0)
            for j, s in enumerate(parity_slots):
                plan[s] = (parity[bi, j], int(crcs[bi, t + j]))
            for s in parts:
                row, crc0 = plan.get(s, (None, None))
                if crc0 is None:
                    new_crc = _crc_shift(old[s], nsl - osl)
                else:
                    new_crc = (_crc_shift(old[s], nsl - osl)
                               ^ _crc_shift(crc0, nsl - b))
                delta_b = b"" if row is None else row.tobytes()
                entry = self._encode_jentry(
                    seq, name, s, parts, new_size, osl, nsl, a,
                    delta_b, new_crc, pred_version)
                cid = shard_cid(self.pg, s)
                shard_prep.setdefault(s, Transaction()).omap_set(
                    cid, self.JOURNAL_OBJ,
                    {self._jkey(seq): entry})
                at = shard_apply.setdefault(s, Transaction())
                if row is not None:
                    at.xor(cid, name, a, row)
                if nsl != osl:
                    at.truncate(cid, name, nsl)
                at.setattr(cid, name, HINFO_KEY,
                           HashInfo(1, nsl, [new_crc]).to_bytes())
                max_seq_of[s] = max(max_seq_of.get(s, 0), seq)
                keys_of.setdefault(s, []).append(self._jkey(seq))
                wire += len(entry) + len(delta_b)
            waves.append((job, seq, plan, parts))
        if not waves:
            return
        for s, at in shard_apply.items():
            cid = shard_cid(self.pg, s)
            at.omap_set(cid, self.JOURNAL_OBJ,
                        {self._J_APPLIED:
                         _struct.pack("<Q", max_seq_of[s])})
            at.omap_rmkeys(cid, self.JOURNAL_OBJ, keys_of[s])
        try:
            if hook is not None:
                hook("before_prepare")
                # sequential fan-outs under the hook so the crash
                # matrix can land BETWEEN shards (a pipelined wave
                # has no observable mid-point)
                for idx, (s, pt) in enumerate(
                        sorted(shard_prep.items())):
                    self._store(s).queue_transaction(pt)
                    if idx == 0:
                        hook("mid_prepare")
            else:
                self._fanout_txns(list(shard_prep.items()))
            self.perf.inc("journal_entries",
                          sum(len(v) for v in keys_of.values()))
            if hook is not None:
                hook("after_prepare")
                for idx, (s, at) in enumerate(
                        sorted(shard_apply.items())):
                    self._store(s).queue_transaction(at)
                    if idx == 0:
                        hook("mid_apply")
            else:
                self._fanout_txns(list(shard_apply.items()))
            if hook is not None:
                hook("after_apply")
        except (ConnectionError, OSError):
            # a participant died mid-wave: best-effort drop of the
            # wave's intents on every reachable shard (an applied
            # shard holds none — rmkeys no-ops). The caller's
            # degraded retry then rewrites the window through the
            # full path, and the superseded-version guard makes any
            # entry this cleanup missed a replay no-op.
            for s, keys in keys_of.items():
                try:
                    self._store(s).queue_transaction(
                        Transaction().omap_rmkeys(
                            shard_cid(self.pg, s),
                            self.JOURNAL_OBJ, keys))
                except (ConnectionError, OSError, KeyError):
                    pass
            raise
        live = list(range(self.n))
        ios = 0
        for job, _seq, _plan, parts in waves:
            name = job[0]
            self.object_sizes[name] = job[3]
            self._log_write(name, live)
            ios += len(parts)
        self.perf.inc_many((("rmw_ops", len(waves)),
                            ("rmw_shard_ios", ios),
                            ("rmw_wire_bytes", wire)))

    def stripe_journal_replay(self, dead_osds: set[int] | None = None
                              ) -> dict:
        """Replay the per-PG stripe journal after a crash/remount
        (ref: the PGLog-driven divergent-entry resolution, applied to
        RMW intents). Decision per pending seq: roll FORWARD when any
        live participant already applied it (its watermark proves the
        prepare phase completed everywhere) or when every live
        participant still holds the intent (prepare complete, crash
        before any apply — forward and backward are both consistent;
        forward matches the ack the client may have seen); roll BACK
        otherwise (prepare incomplete: applying would tear the
        stripe). Apply is idempotent — an applied shard holds no
        entry and is never re-XORed. Returns {forward, rolled_back,
        entries}."""
        dead = dead_osds or set()
        live = self._live_slots(dead)
        live_set = set(live)
        pending: dict[int, dict[int, dict]] = {}
        watermark: dict[int, int] = {}
        # the existence probe fans out PIPELINED (one overlapped round
        # trip, not n sequential ones — restores run this on every
        # reconcile and most PGs have no journal at all)
        probes: list[tuple[int, object]] = []
        sync_exists: dict[int, bool] = {}
        for s in list(live):
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            sub = getattr(st, "exists_submit", None)
            try:
                if sub is not None:
                    probes.append((s, sub(cid, self.JOURNAL_OBJ)))
                else:
                    sync_exists[s] = st.exists(cid, self.JOURNAL_OBJ)
            except (ConnectionError, OSError, KeyError):
                live_set.discard(s)
        for s, h in probes:
            try:
                sync_exists[s] = bool(h.result()[0])
            except (ConnectionError, OSError, KeyError):
                # an unreachable-but-not-yet-marked shard: scan
                # around it like a dead one (its intents settle on
                # the next restore's replay)
                live_set.discard(s)
        for s in list(live):
            if not sync_exists.get(s, False):
                continue
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            try:
                page = st.omap_iter(cid, self.JOURNAL_OBJ)
            except (ConnectionError, OSError, KeyError):
                live_set.discard(s)
                continue
            for key, val in page:
                if key == self._J_APPLIED:
                    watermark[s] = _struct.unpack("<Q", val)[0]
                elif key.startswith(b"e"):
                    ent = self._decode_jentry(val)
                    pending.setdefault(ent["seq"], {})[s] = ent
        forward = rolled_back = 0
        for seq in sorted(pending):
            holders = pending[seq]
            ent0 = next(iter(holders.values()))
            parts = [p for p in ent0["participants"] if p in live_set]
            applied_any = any(watermark.get(p, -1) >= seq
                              for p in parts)
            all_logged = all(p in holders for p in parts)
            name = ent0["name"]
            # superseded entries (a later write — e.g. the degraded
            # full-path retry of this very RMW — already bumped the
            # object's version) must never re-fold their delta
            roll = (applied_any or all_logged) \
                and name in self.object_sizes \
                and ent0["version"] > self.object_versions.get(name, 0)
            for s, ent in holders.items():
                st = self._store(s)
                cid = shard_cid(self.pg, s)
                txn = Transaction()
                if roll:
                    if ent["delta"]:
                        txn.xor(cid, name, ent["a"], np.frombuffer(
                            ent["delta"], np.uint8))
                    if ent["nsl"] != ent["osl"]:
                        txn.truncate(cid, name, ent["nsl"])
                    txn.setattr(cid, name, HINFO_KEY, HashInfo(
                        1, ent["nsl"], [ent["new_crc"]]).to_bytes())
                    txn.omap_set(cid, self.JOURNAL_OBJ,
                                 {self._J_APPLIED:
                                  _struct.pack("<Q", max(
                                      watermark.get(s, 0), seq))})
                    watermark[s] = max(watermark.get(s, 0), seq)
                txn.omap_rmkeys(cid, self.JOURNAL_OBJ,
                                [self._jkey(seq)])
                st.queue_transaction(txn)
            if roll:
                forward += 1
                self.object_sizes[name] = max(
                    self.object_sizes.get(name, 0), ent0["new_size"])
            else:
                rolled_back += 1
        self._rmw_seq = max([self._rmw_seq] + list(pending)
                            + list(watermark.values()))
        self.perf.inc_many((("journal_replay_forward", forward),
                            ("journal_replay_rollback", rolled_back)))
        return {"forward": forward, "rolled_back": rolled_back,
                "entries": sum(len(h) for h in pending.values())}

    # -- read path -----------------------------------------------------------

    # read_object is inherited; read_objects is the batched
    # objects_read_and_reconstruct analog

    def read_objects(self, names: list[str],
                     dead_osds: set[int] | None = None,
                     verify: bool = True,
                     repair: bool = True,
                     helper_costs: dict[int, int] | None = None
                     ) -> dict[str, np.ndarray]:
        """Batched reads with BlueStore-style verify-on-read: every
        chunk consumed is CRC-checked against its stored hinfo in one
        batched launch (ref: BlueStore::_verify_csum on every read);
        a mismatch is the EIO path — the read transparently re-decodes
        from other shards AND repairs the rotten chunk in place (ref:
        the read-error recovery qa/standalone/erasure-code/
        test-erasure-eio.sh exercises). repair=False keeps the
        re-decode but skips the writeback — the read-only contract of
        a degraded-read view served by a non-primary.

        Degraded reads gather through the repair-locality planner
        (plan_read): an LRC single-shard loss pulls its local group
        instead of any-k, and `helper_costs` (slot -> cost) biases
        which survivors serve (the daemon's complaint/latency
        memory)."""
        dead = dead_osds or set()
        alive = [s for s in range(self.n)
                 if self.acting[s] not in dead]
        want = list(self.data_slots)
        out: dict[str, np.ndarray] = {}
        # batched like recovery: stack equal-shard-length groups and
        # decode each group in ONE launch
        by_len: dict[int, list[str]] = {}
        for name in names:
            if self.object_sizes[name] == 0:
                out[name] = np.zeros(0, dtype=np.uint8)
                continue
            by_len.setdefault(self._shard_len(self.object_sizes[name]),
                              []).append(name)
        for sl, group in by_len.items():
            # a shard that missed any of this group's writes is stale
            # for it and must not serve (it replays on rejoin)
            avail = self._fresh_for(group, alive)
            while True:
                # the planner raises when the survivors can't cover
                # `want` — the caller's retry boundary
                need_set, family = plan_read(self.coder, want, avail,
                                             costs=helper_costs)
                if family != "direct":
                    self._count_plan(family)
                need = sorted(need_set)
                stacks, missing = {}, None
                for s in need:
                    try:
                        stacks[s] = np.stack(
                            [self._store(s).read(shard_cid(self.pg, s),
                                                 n) for n in group])
                    except KeyError:
                        # cursor says fresh but the store lacks the
                        # object: a repointed slot whose rebuild has
                        # not landed this object yet (recovery in
                        # flight) — plan around it like a stale shard
                        missing = s
                        break
                if missing is None:
                    break
                avail.remove(missing)
            bad: dict[str, set[int]] = {}
            if verify:
                rows = np.concatenate([stacks[s] for s in need])
                crcs = self._batched_crcs(rows).reshape(
                    len(need), len(group))
                for si, s in enumerate(need):
                    st = self._store(s)
                    cid = shard_cid(self.pg, s)
                    for bi, nm in enumerate(group):
                        hinfo = HashInfo.from_bytes(
                            st.getattr(cid, nm, HINFO_KEY))
                        if int(crcs[si, bi]) != hinfo.get_chunk_hash(0):
                            bad.setdefault(nm, set()).add(s)
            clean_group = [n for n in group if n not in bad]
            if clean_group:
                idx = [group.index(n) for n in clean_group]
                sub = {s: stacks[s][idx] for s in need}
                self.perf.inc_many(
                    (("decode_launches", 1),
                     ("decode_bytes",
                      len(clean_group) * len(need) * sl)))
                with span("ecbackend.read.decode", counters=self.perf,
                          key="decode_time"):
                    rec = self.coder.decode(want, sub)
                shards = np.stack([rec[s] for s in self.data_slots],
                                  axis=1)
                objs = self.sinfo.shards_to_object(shards)
                for oi, name in enumerate(clean_group):
                    out[name] = objs[oi, :self.object_sizes[name]]
            for name, bad_set in bad.items():
                self.eio_stats["read_eio"] += len(bad_set)
                self.perf.inc("read_eio", len(bad_set))
                out[name] = self._read_eio(name, sl, avail, bad_set,
                                           repair=repair)
        return out

    def _read_eio(self, name: str, sl: int, avail: list[int],
                  bad: set[int], repair: bool = True) -> np.ndarray:
        """One object's EIO path: decode around the rotten shards,
        return the bytes, and repair the rot in place.

        Substitute shards are CRC-VERIFIED before they feed the decode:
        an unverified substitute with its own rot would hand the client
        corrupt bytes and then durably launder them — the repair would
        rewrite the flagged shard from corrupt data under a freshly
        matching CRC that no future scrub could catch."""
        want = list(self.data_slots)
        bad = set(bad)
        while True:
            ok_shards = [s for s in avail if s not in bad]
            need = sorted(plan_read(self.coder, want, ok_shards)[0])
            stacks = {}
            newly_bad = False
            for s in need:
                st = self._store(s)
                cid = shard_cid(self.pg, s)
                try:
                    chunk = st.read(cid, name)
                    hinfo = HashInfo.from_bytes(st.getattr(cid, name,
                                                           HINFO_KEY))
                except KeyError:
                    # repointed slot mid-rebuild (no bytes/hinfo yet):
                    # plan around it, exactly like rot
                    bad.add(s)
                    newly_bad = True
                    break
                crc = int(self._batched_crcs(chunk[None, :])[0])
                if crc != hinfo.get_chunk_hash(0):
                    self.eio_stats["read_eio"] += 1
                    bad.add(s)
                    newly_bad = True
                    break
                stacks[s] = chunk[None, :]
            if newly_bad:
                continue  # re-plan without the newly found rot
            rec = self.coder.decode(want, stacks)
            shards = np.stack([rec[s] for s in self.data_slots], axis=1)
            obj = self.sinfo.shards_to_object(shards)[0]
            if repair:
                self._repair_shards(name, obj, sorted(bad), sl)
            return obj[:self.object_sizes[name]]

    def _repair_shards(self, name: str, logical: np.ndarray,
                       slots: list[int], sl: int) -> None:
        """Rewrite specific shards of one object from its logical bytes
        (the read-error / `ceph pg repair` writeback)."""
        dshards = self.sinfo.object_to_shards(logical[None, :])
        parity = np.asarray(self.coder.encode_chunks(dshards))
        full = self._slots_from_dense(
            np.concatenate([dshards, parity], axis=1))[0]  # (n, sl)
        crcs = self._batched_hinfo_crcs(full[slots])
        for ci, s in enumerate(slots):
            hinfo = HashInfo(1, sl, [int(crcs[ci])])
            t = (Transaction()
                 .write(shard_cid(self.pg, s), name, 0, full[s])
                 .truncate(shard_cid(self.pg, s), name, sl)
                 .setattr(shard_cid(self.pg, s), name,
                          HINFO_KEY, hinfo.to_bytes()))
            self._store(s).queue_transaction(t)
            self.eio_stats["repaired"] += 1

    def repair_pg(self, dead_osds: set[int] | None = None) -> dict:
        """`ceph pg repair` analog: deep-scrub, then rewrite every
        inconsistent shard from the surviving majority (ref:
        PrimaryLogPG repair path driven by the scrubber's
        authoritative-copy decision)."""
        dead = dead_osds or set()
        rep = self.deep_scrub(dead_osds=dead)
        alive = [s for s in range(self.n)
                 if self.acting[s] not in dead]
        alive_set = set(alive)
        by_name: dict[str, list[int]] = {}
        skipped = 0
        for name, slot in rep["inconsistent"]:
            # never write to a dead slot (repairing it would resurrect
            # a destroyed OSD's store; recovery rebuilds it instead),
            # and a deleted object's leftover is delete-replay's job
            if slot not in alive_set or name not in self.object_sizes:
                skipped += 1
                continue
            by_name.setdefault(name, []).append(slot)
        repaired = 0
        for name, slots in sorted(by_name.items()):
            sl = self._shard_len(self.object_sizes[name])
            obj = self._read_eio(name, sl,
                                 self._fresh_for([name], alive),
                                 set(slots))
            del obj  # _read_eio already repaired in place
            repaired += len(slots)
        return {"checked": rep["checked"], "repaired": repaired,
                "objects": len(by_name), "skipped": skipped,
                "strays_removed": self._remove_strays(dead)}

    # -- recovery (the objects/s metric) -------------------------------------

    def _count_plan(self, family: str) -> None:
        """Fold a planner decision into the declared counters."""
        key = {"lrc_local": "planner_local_plans",
               "clay_planes": "planner_subchunk_plans",
               "shec_cost": "planner_cost_plans",
               "mds": "planner_cost_plans"}.get(family,
                                                "planner_full_plans")
        self.perf.inc(key)

    def plan_recovery(self, lost_shards: list[int],
                      replacement_osds: dict[int, int] | None = None,
                      verify_hinfo: bool = True,
                      names: list[str] | None = None,
                      helper_exclude: set[int] | None = None,
                      helper_costs: dict[int, int] | None = None
                      ) -> "_RecoveryPlan":
        """Open one PG's recovery intent: validate the plan, point the
        lost slots at their replacement OSDs, replay deletes and empty
        objects immediately, and return the rebuild work (names grouped
        by shard length) for a RecoveryRunner to execute — possibly
        FUSED with other PGs' plans into shared decode launches (the
        cross-PG batch formation the per-PG reconcile round lacked).
        Raises ValueError before any mutation when the plan is
        impossible (insufficient live helpers), exactly like the old
        monolithic recover_shards.

        Helper selection goes through the repair-locality planner
        (repairplan.plan_repair): LRC single-loss reads one local
        group, Clay single-loss reads only the repair planes (the
        runner ships sub-chunk ranges), SHEC/RS rank by the optional
        per-helper `helper_costs` (slot -> cost; the daemon feeds its
        complaint memory + peer-latency EWMAs)."""
        lost = sorted(set(lost_shards))
        if len(lost) > self.m:
            raise ValueError(f"{len(lost)} lost shards exceeds m={self.m}")
        excluded = helper_exclude or set()
        full_plan = names is None
        names = sorted(self.object_sizes) if names is None \
            else sorted(set(names))
        provided = set(names)
        # helpers must be caught up for everything being REBUILT — a
        # stale survivor would decode old bytes into the new shard.
        # Validate the plan BEFORE mutating acting, so an impossible
        # recovery (insufficient live helpers) leaves no partial state.
        # A deletes-only replay needs no helper data at all.
        rebuild = [n for n in names if n in self.object_sizes]
        survivors: list[int] = []
        helper: list[int] = []
        repair = None
        if rebuild:
            survivors = self._fresh_for(
                rebuild, [s for s in range(self.n)
                          if s not in lost and s not in excluded])
            repair = plan_repair(self.coder, lost, survivors,
                                 costs=helper_costs)
            helper = sorted(repair.helpers)
            self._count_plan(repair.family)
        repl = replacement_osds or {}
        for s in lost:
            new_osd = repl.get(s, self.acting[s])
            self.acting[s] = new_osd
            t = Transaction().create_collection(shard_cid(self.pg, s))
            self.cluster.osd(new_osd).queue_transaction(t)
        plan = _RecoveryPlan(self, lost, helper, survivors,
                             verify_hinfo, full_plan, provided)
        plan.repair = repair
        # names whose last log entry was a DELETE replay as removals
        names = self._replay_deletes(lost, names)

        for name in names:
            if self.object_sizes[name] == 0:
                hinfo = HashInfo(1, 0, [0xFFFFFFFF])
                for s in lost:
                    # truncate clears a stale pre-failure chunk (the
                    # object may have shrunk to empty while this shard
                    # was down)
                    t = (Transaction()
                         .write(shard_cid(self.pg, s), name, 0, b"")
                         .truncate(shard_cid(self.pg, s), name, 0)
                         .setattr(shard_cid(self.pg, s), name,
                                  HINFO_KEY, hinfo.to_bytes()))
                    self._store(s).queue_transaction(t)
                plan.counters["objects"] += 1
                continue
            plan.names_by_len.setdefault(
                self._shard_len(self.object_sizes[name]),
                []).append(name)
        plan.remaining = {n for g in plan.names_by_len.values()
                          for n in g}
        if plan.names_by_len:
            if repair is not None and repair.planes is not None:
                # sub-chunk wire reads: stage only the repair planes
                # and decode through the range program — the helper
                # bytes on the wire drop to wire_fraction of a full
                # pull (beta/q^t for Clay)
                fn = self.coder.range_batch_decoder(lost, helper)
                if fn is not None:
                    plan.dec_fn = fn
                    plan.group_key = self.coder. \
                        range_decode_program_key(lost, helper)
                    plan.range_planes = repair.planes
                    plan.sub_count = repair.sub_chunk_count
            if plan.dec_fn is None:
                plan.dec_fn = self.coder.batch_decoder(lost, helper)
                if plan.dec_fn is not None:
                    key = self.coder.decode_program_key(lost, helper)
                    # id()-keyed fallbacks stay in the BACKEND's cache
                    # (a process-wide id key could alias a dead object)
                    plan.group_key = key if key is not None else None
        return plan

    def recover_shards(self, lost_shards: list[int],
                       replacement_osds: dict[int, int] | None = None,
                       batch: int = 128,
                       verify_hinfo: bool = True,
                       names: list[str] | None = None,
                       helper_exclude: set[int] | None = None,
                       helper_costs: dict[int, int] | None = None) -> dict:
        """Rebuild every object's lost shard(s): the RecoveryOp loop,
        batched AND pipelined. Returns counters {objects, bytes,
        hinfo_failures}. One-plan convenience over plan_recovery +
        RecoveryRunner — the cross-PG reconcile pass feeds MANY plans
        to one runner instead.

        Dataflow (ref: ECBackend::continue_recovery_op streaming, P5):
        for codecs with a static decode matrix (batch_decoder), each
        sub-batch is ONE fused device launch (decode + helper XOR-fold;
        integrity rides the fold — see RecoveryRunner); launches are
        enqueued asynchronously with copy_to_host_async, so results
        stream back one batch behind (double buffering). Codecs
        without a static matrix take the generic decode_chunks path,
        still batched per launch.

        lost_shards: shard slots whose OSD died.
        replacement_osds: slot -> new OSD id (defaults to reusing the
        slot's OSD id, i.e. re-created store after replacement).
        names: restrict recovery to these objects — the PG-log
        delta-replay path (a revived shard rebuilds only what it
        missed; ref: PGLog-driven recovery vs backfill).
        helper_exclude: shard slots that must not serve helper reads
        (other still-down OSDs during a partial rejoin).
        """
        plan = self.plan_recovery(lost_shards, replacement_osds,
                                  verify_hinfo, names, helper_exclude,
                                  helper_costs=helper_costs)
        RecoveryRunner([plan], batch=batch, perf=self.perf).run()
        return plan.counters

    def _recover_fallback(self, lost: list[int], survivors: list[int],
                          bad_pairs: dict[str, set[int]],
                          subgroup: list[str], rebuilt_all: np.ndarray,
                          counters: dict) -> None:
        """Re-decode objects whose helper reads failed hinfo, batched by
        identical bad-shard set (one decode launch per distinct set
        instead of the r01 per-object loop)."""
        by_bad: dict[tuple[int, ...], list[str]] = {}
        for name, bad in bad_pairs.items():
            by_bad.setdefault(tuple(sorted(bad)), []).append(name)
        for bad, names_ in by_bad.items():
            alt = [s for s in survivors if s not in bad]
            alt_need = sorted(self.coder.minimum_to_decode(lost, alt))
            stacks = {s: np.stack([self._store(s).read(
                shard_cid(self.pg, s), n) for n in names_])
                for s in alt_need}
            alt_rec = self.coder.decode_chunks(lost, stacks)
            for li, s in enumerate(lost):
                rec_s = np.asarray(alt_rec[s])
                for ni, name in enumerate(names_):
                    rebuilt_all[subgroup.index(name), li] = rec_s[ni]

    def _writeback_rebuilt(self, lost: list[int], subgroup: list[str],
                           rebuilt_all: np.ndarray, crcs: np.ndarray,
                           sl: int, counters: dict,
                           window: "RecoveryRunner | None" = None) -> None:
        # ONE combined txn per replacement shard for the whole batch
        # (the write-path fan-out unit), pipelined across shards — at
        # the wire tier this is len(lost) overlapped MStoreOp frames
        # per batch instead of len(lost) * B sequential ones. With a
        # `window`, the push rides the runner's byte-budgeted in-flight
        # window instead: frames of LATER batches go out before these
        # acks return (acks are collected as the budget fills and at
        # finish()), the recovery analog of the client op window.
        txns = []
        for li, s in enumerate(lost):
            cid = shard_cid(self.pg, s)
            t = Transaction()
            for bi, name in enumerate(subgroup):
                chunk = rebuilt_all[bi, li]
                hinfo = HashInfo(1, sl, [int(crcs[bi, li])])
                t.write(cid, name, 0, chunk) \
                 .truncate(cid, name, sl) \
                 .setattr(cid, name, HINFO_KEY, hinfo.to_bytes())
                counters["bytes"] += int(chunk.size)
            txns.append((s, t))
        if window is None:
            self._fanout_txns(txns)
        else:
            window.push_txns(self, txns, len(subgroup) * sl)
        counters["objects"] += len(subgroup)

    def _count_recovery(self, counters: dict) -> None:
        self.perf.inc_many(
            (("recovered_objects", counters["objects"]),
             ("recovered_bytes", counters["bytes"]),
             ("hinfo_failures", counters["hinfo_failures"])))

    # -- deep scrub ----------------------------------------------------------

    def _scrub_journal(self, live_slots: list[int]) -> dict:
        """Journal-aware deep scrub (r17): audit pending
        __stripe_journal__ intents instead of skipping the collection
        with the other "__" internals. Per live slot, every entry must
        decode (codec version 1), agree with its omap key, name this
        slot as a participant, fit its own geometry (the delta payload
        inside the new shard length), and sit ABOVE the slot's applied
        watermark (an entry at-or-below the watermark was applied but
        never dropped — the apply txn is atomic, so that's store
        corruption, not lag). Intents a later write superseded are
        counted stale — inert by the replay's version guard, not
        corrupt. Findings stay OUT of the `inconsistent` list: a
        pending intent is crash-recovery state, and auto_repair's
        decode-rebuild must never chew on the journal object."""
        pending = stale = 0
        bad: list[tuple[int, str]] = []      # (slot, why)
        for s in live_slots:
            store = self._store(s)
            cid = shard_cid(self.pg, s)
            try:
                if not store.exists(cid, self.JOURNAL_OBJ):
                    continue
                page = store.omap_iter(cid, self.JOURNAL_OBJ)
            except (ConnectionError, OSError, KeyError):
                continue                      # unreachable: lag excuse
            watermark = None
            entries: list[tuple[bytes, bytes]] = []
            for key, val in page:
                if key == self._J_APPLIED:
                    if len(val) == 8:
                        watermark = _struct.unpack("<Q", val)[0]
                    else:
                        bad.append((s, "watermark not 8 bytes"))
                elif key.startswith(b"e"):
                    entries.append((key, val))
                else:
                    bad.append((s, f"unknown journal key {key!r}"))
            for key, val in entries:
                try:
                    ent = self._decode_jentry(val)
                except Exception as e:   # noqa: BLE001 — ANY decode
                    # failure is the corruption this audit exists for
                    bad.append((s, f"undecodable intent {key!r}: "
                                   f"{type(e).__name__}"))
                    continue
                if self._jkey(ent["seq"]) != key:
                    bad.append((s, f"intent seq {ent['seq']} "
                                   f"disagrees with key {key!r}"))
                    continue
                if s not in ent["participants"]:
                    bad.append((s, f"intent seq {ent['seq']} does "
                                   f"not name slot {s} a participant"))
                    continue
                if ent["delta"] and \
                        ent["a"] + len(ent["delta"]) > ent["nsl"]:
                    bad.append((s, f"intent seq {ent['seq']} delta "
                                   f"overruns shard length "
                                   f"{ent['nsl']}"))
                    continue
                if watermark is not None and ent["seq"] <= watermark:
                    bad.append((s, f"intent seq {ent['seq']} at or "
                                   f"below applied watermark "
                                   f"{watermark} (apply is atomic "
                                   f"with the entry drop)"))
                    continue
                if ent["version"] <= self.object_versions.get(
                        ent["name"], 0):
                    stale += 1                # superseded: inert
                else:
                    pending += 1              # legitimate in-flight
        return {"journal_pending": pending, "journal_stale": stale,
                "journal_bad": bad}

    def deep_scrub(self, dead_osds: set[int] | None = None) -> dict:
        """Read every LIVE shard of every object, verify stored hinfo
        CRCs (the be_deep_scrub bulk-checksum audit), batched per
        shard. Dead slots are skipped — even touching their stores
        would resurrect destroyed OSD ids. The per-PG stripe journal
        is audited too (see _scrub_journal) instead of skipped."""
        from ..csum.kernels import crc32c_blocks
        dead = dead_osds or set()
        bad: list[tuple[str, int]] = []
        checked = 0
        for s in range(self.n):
            if self.acting[s] in dead:
                continue
            store = self._store(s)
            cid = shard_cid(self.pg, s)
            # a shard behind on an object's last write (or holding a
            # not-yet-replayed delete's leftover) is lagging, not
            # corrupt — same staleness excuse the replicated scrub and
            # shallow scrub apply
            # "__"-prefixed objects are PG-internal bookkeeping (e.g.
            # the standalone tier's __pg_meta__ omap blob): no hinfo,
            # not client data — the scrub audits client objects only
            names = [n for n in store.list_objects(cid)
                     if not n.startswith("__")
                     and n in self.object_sizes
                     and self.shard_applied[s]
                     >= self.object_versions.get(n, 0)]
            by_len: dict[int, list[str]] = {}
            for n in names:
                by_len.setdefault(store.stat(cid, n), []).append(n)
            for ln, group in by_len.items():
                blocks = np.stack([store.read(cid, n) for n in group])
                crcs = np.asarray(crc32c_blocks(blocks, init=0xFFFFFFFF,
                                                xorout=0))
                for bi, n in enumerate(group):
                    hinfo = HashInfo.from_bytes(store.getattr(cid, n,
                                                              HINFO_KEY))
                    checked += 1
                    if hinfo.get_chunk_hash(0) != int(crcs[bi]):
                        bad.append((n, s))
        rep = {"checked": checked, "inconsistent": bad}
        rep.update(self._scrub_journal(
            [s for s in range(self.n) if self.acting[s] not in dead]))
        return rep


# -- cross-PG recovery engine -------------------------------------------------

_RECOVER_PROGRAMS: dict = {}
_RECOVER_PROGRAMS_LOCK = _threading.Lock()

#: one shard-fetch frame's byte budget (readv chunks larger batches so
#: a single source OSD never serializes a multi-MiB frame per pull)
RECOVERY_FETCH_BYTES = 8 << 20


@_functools.lru_cache(maxsize=64)
def _host_encoder_handle(matrix_bytes: bytes, k: int, m: int):
    """Process-wide native RS encoder per coding matrix (the same
    sharing rule as the fused-program cache). Handles live for the
    process — ec_destroy never runs, matching the program caches."""
    try:
        from .. import native
        h = native.lib().ec_create_with_matrix(k, m, matrix_bytes)
        return h or None
    except Exception:   # noqa: BLE001 — no native lib: device path
        return None


@_functools.lru_cache(maxsize=1)
def _host_crc_available() -> bool:
    """Host-integrity mode: on the CPU backend with the native SSE4.2
    crc32c built, checksums run ~20x faster as host instructions than
    as gather-bound XLA programs — the device then runs DECODE ONLY
    (plus the helper XOR-fold) and integrity moves off the launch.
    On a real accelerator the device checksum is nearly free and the
    host would serialize, so this stays device-side there."""
    import jax
    if jax.default_backend() != "cpu":
        return False
    try:
        from .. import native
        return native.ready() and native.crc32c_hw()
    except Exception:   # noqa: BLE001 — any native trouble = no mode
        return False


@_functools.lru_cache(maxsize=4096)
def _shift_cols(nbytes: int) -> tuple:
    """Packed GF(2) column constants of the CRC32C shift-by-nbytes
    matrix (cached: the RMW path shifts through the same tail
    distances over and over)."""
    from ..csum.reference import matrix_cols_u32, shift_matrix
    return tuple(int(c) for c in matrix_cols_u32(shift_matrix(nbytes)))


def _crc_shift(reg: int, nbytes: int) -> int:
    """Advance a raw CRC32C register through nbytes zero bytes — the
    O(1) building block of the incremental hinfo update (CRC32C is
    GF(2)-linear in the message AND the seed, so
    crc(new_row) = shift^{tail}(crc(old_row)) ^ shift^{tail'}(crc0(delta)))."""
    if nbytes == 0 or reg == 0:
        return int(reg)
    cols = _shift_cols(int(nbytes))
    out = 0
    for b in range(32):
        if (reg >> b) & 1:
            out ^= cols[b]
    return out


def _rows_crc0(rows: np.ndarray) -> np.ndarray:
    """(N, L) byte rows -> (N,) ZERO-seed crc32c (the delta-row
    convention: a zero seed composes under XOR and position shifts);
    native SSE4.2 when built, batched device launch otherwise."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    if _host_crc_available():
        from .. import native
        return np.asarray(native.native_crc32c_rows(0, rows),
                          dtype=np.uint32)
    from ..csum.kernels import crc32c_blocks
    from ..ops.rs_kernels import run_bucketed
    return np.asarray(run_bucketed(
        lambda b: crc32c_blocks(b, init=0, xorout=0), rows),
        dtype=np.uint32)


def _rows_crc32c(rows: np.ndarray) -> np.ndarray:
    """(B, L) byte rows -> (B,) raw crc32c (seed -1, the HashInfo
    convention); native SSE4.2 when built, batched device launch
    otherwise."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    if _host_crc_available():
        from .. import native
        return native.native_crc32c_rows(0xFFFFFFFF, rows)
    return np.asarray(PGBackend._batched_crcs(rows), dtype=np.uint32)


def readv_ranges_host(store, cid: str, names: list[str], length: int,
                      ranges, attr_key: str | None
                      ) -> tuple[np.ndarray, np.ndarray | None,
                                 list[int]]:
    """Serve a ranged shard pull from a LOCAL store — the source half
    of the sub-chunk wire read (ref: ErasureCodeClay's
    minimum_to_decode sub-chunk ranges riding the ECSubRead).

    Per object: verify the FULL stored row against its hinfo when
    `attr_key` is given (rot detection stays at the source — the
    receiver never sees the whole row, so the r10 whole-row fold can't
    cover it), slice the planned `ranges`, and crc32c the shipped
    bytes (range-level integrity the receiver's fold verify consumes;
    CRC32C is GF(2)-linear at any row length, so H range rows still
    verify with ONE fold CRC).

    Returns (rows (B, rl) uint8, range CRCs (B,) uint32 | None,
    indices of rows whose FULL shard failed its hinfo — their range
    bytes ship anyway and the receiver plans around them)."""
    ranges = [(int(o), int(ln)) for o, ln in ranges]
    rl = sum(ln for _o, ln in ranges)
    B = len(names)
    rows = np.empty((B, rl), dtype=np.uint8)
    bad: list[int] = []
    if attr_key is not None:
        full = np.empty((B, length), dtype=np.uint8)
        for i, name in enumerate(names):
            arr = store.read(cid, name)
            if len(arr) != length:
                # a stale/partial shard must fail LOUDLY — zero-
                # filling would hand the decoder garbage (the readv
                # contract)
                raise ValueError(
                    f"readv_ranges: {name!r} is {len(arr)} bytes, "
                    f"expected {length}")
            full[i] = arr
        crcs = _rows_crc32c(full)
        for i, name in enumerate(names):
            hinfo = HashInfo.from_bytes(
                store.getattr(cid, name, attr_key))
            if int(crcs[i]) != hinfo.get_chunk_hash(0):
                bad.append(i)
        at = 0
        for off, ln in ranges:
            rows[:, at:at + ln] = full[:, off:off + ln]
            at += ln
        range_crcs = _rows_crc32c(rows)
        return rows, range_crcs, bad
    for i, name in enumerate(names):
        at = 0
        for off, ln in ranges:
            got = store.read(cid, name, off, ln)
            if len(got) != ln:
                raise ValueError(
                    f"readv_ranges: {name!r} range ({off},{ln}) "
                    f"returned {len(got)} bytes")
            rows[i, at:at + ln] = got
            at += ln
    return rows, None, bad


@_functools.lru_cache(maxsize=256)
def _fold_seed_const(sl: int) -> int:
    """shift^{sl}(0xFFFFFFFF): the seed contribution inside a raw
    hinfo CRC of an sl-byte row (crc_{-1}(m) = crc_0(m) ^ K)."""
    from ..csum.reference import apply_shift
    return int(apply_shift(0xFFFFFFFF, sl))


def _expected_fold_crcs(exp: np.ndarray, sl: int) -> np.ndarray:
    """Expected raw CRC of the XOR-fold of H helper rows, from their
    expected per-row hinfo CRCs. CRC32C is GF(2)-linear in the
    message: crc_0(r0 ^ .. ^ rH) = XOR_i crc_0(r_i), and the -1 seed
    adds the constant K = shift^{sl}(-1) per row — so H rows verify
    with ONE data-pass checksum instead of H (arxiv 2108.02692's
    aggregation idea applied to the verify pass; a corruption pair
    that XOR-cancels would need a 2^-32 collision AND two rotten
    helpers in one object)."""
    K = np.uint32(_fold_seed_const(sl))
    folded = np.bitwise_xor.reduce(exp.astype(np.uint32) ^ K, axis=1)
    return folded ^ K


def _build_recover_program(dec_fn, verify: bool, host_crc: bool):
    """ONE jitted device program per (decode program, verify, mode) —
    process-wide when the coder exposes a decode_program_key, so every
    PG backend with the same geometry shares ONE compiled program (the
    r09 tree compiled it once per PG per daemon).

    host_crc mode: fn(stack) -> (rebuilt[, helper-fold]); checksums run
    on the host (native SSE4.2). Device mode: fn(stack, expfold) ->
    (rebuilt, rebuilt-CRCs, fold-ok) all device-resident."""
    import jax
    import jax.numpy as jnp

    if host_crc:
        def fused(stack):              # (B, H, sl) u8
            rebuilt = dec_fn(stack)    # (B, E, sl)
            if verify:
                fold = jnp.bitwise_xor.reduce(stack, axis=1)
                return rebuilt, fold
            return (rebuilt,)
        return jax.jit(fused)

    from ..csum.kernels import crc32c_blocks

    def fused(stack, expfold):         # (B, H, rl) u8, (B,) u32
        B, H, L = stack.shape
        rebuilt = dec_fn(stack)        # (B, E, sl) — sl may exceed
        E = rebuilt.shape[1]           # the staged rl (range plans
        out_len = rebuilt.shape[2]     # ship sub-chunks, rebuild
        #                                whole rows)
        rcrc = crc32c_blocks(rebuilt.reshape(B * E, out_len),
                             init=0xFFFFFFFF,
                             xorout=0).reshape(B, E)
        if verify:
            fold = jnp.bitwise_xor.reduce(stack, axis=1)
            fcrc = crc32c_blocks(fold, init=0xFFFFFFFF, xorout=0)
            ok = fcrc == expfold
        else:
            ok = jnp.ones((B,), dtype=bool)
        return rebuilt, rcrc, ok
    return jax.jit(fused)


class _RecoveryPlan:
    """One PG's recovery intent (opened by ECBackend.plan_recovery):
    the rebuild name groups plus everything a RecoveryRunner needs to
    stage, verify, write back, and finally mark the slots caught up.
    `remaining` shrinks as batches land — a wire-tier round that dies
    mid-way re-plans exactly the leftover names."""

    __slots__ = ("be", "lost", "helper", "survivors", "verify",
                 "full_plan", "provided", "counters", "names_by_len",
                 "dec_fn", "group_key", "remaining", "done",
                 "repair", "range_planes", "sub_count")

    def __init__(self, be, lost, helper, survivors, verify, full_plan,
                 provided):
        self.be = be
        self.lost = list(lost)
        self.helper = list(helper)
        self.survivors = list(survivors)
        self.verify = verify
        self.full_plan = full_plan
        self.provided = provided
        self.counters = {"objects": 0, "bytes": 0, "hinfo_failures": 0}
        self.names_by_len: dict[int, list[str]] = {}
        self.dec_fn = None
        self.group_key = None
        self.remaining: set[str] = set()
        self.done = False
        # repair-locality planner outputs: the RepairPlan that chose
        # the helpers, plus the sub-chunk range shape when the wire
        # ships less than full rows (range_planes None = full rows)
        self.repair = None
        self.range_planes: tuple[int, ...] | None = None
        self.sub_count = 1

    def row_ranges(self, sl: int):
        """(row bytes shipped per helper, coalesced (off, len) ranges
        or None) at shard length `sl` — the wire shape of one staged
        helper row."""
        if self.range_planes is None:
            return sl, None
        from .repairplan import coalesce_ranges
        s = sl // self.sub_count
        return (len(self.range_planes) * s,
                coalesce_ranges((z * s, s) for z in self.range_planes))

    def finish(self) -> None:
        """Count the work done; advance applied cursors only when every
        planned name landed (a partial round must not defeat the
        staleness gate — the retry covers the rest)."""
        if self.done:
            return
        self.done = True
        if not self.remaining:
            self.be._mark_caught_up(self.lost, self.full_plan,
                                    self.provided)
        self.be._count_recovery(self.counters)


class RecoveryRunner:
    """Cross-PG fused recovery: executes MANY plans as one pipeline of
    fused decode batches (ref: ECBackend::continue_recovery_op, but the
    unit of admission is a BATCH drawn from every primaried PG, not one
    RecoveryOp of one PG).

    Batch formation: fused plans group by (decode-program key, shard
    length) — PGs sharing a geometry and loss pattern FILL shared
    batches, so the round costs one launch per batch instead of one
    per PG; mixed-geometry plans (different k/m, different loss slots)
    ride the same pipeline side by side with their own programs. The
    batch dim is pow2-bucketed like the write path (ragged tails would
    compile one program per size).

    Pipelining: launches dispatch async with copy_to_host_async, one
    batch ahead (results stream back under the next batch's staging);
    shard fetches submit per (PG, helper shard) and overlap across
    source OSDs (windowed PULL); writeback acks collect behind a byte
    budget (windowed PUSH). step() advances one batch at a time so the
    wire tier's mClock worker can interleave client ops between grants.

    Consistency under interleaved client ops (wire tier): the lost
    slots were repointed at plan time, so every client mutation after
    that reaches the recovering store directly; staging skips names
    whose size-class changed, and writeback skips names whose version
    moved since their stage — a skipped name needs nothing from us and
    a write of the OLD decode would resurrect overwritten (or deleted)
    bytes under a fresh CRC."""

    def __init__(self, plans, batch: int = 128, perf=None,
                 push_window_ops: int = 0, push_window_bytes: int = 0,
                 host_crc: bool | None = None):
        self.plans = [p for p in plans if p is not None]
        self.perf = perf if perf is not None else (
            self.plans[0].be.perf if self.plans else ec_perf_counters())
        self.batch = max(1, int(batch))
        self._host_crc = (_host_crc_available() if host_crc is None
                          else bool(host_crc))
        self._push_ops_cap = int(push_window_ops)
        self._push_bytes_cap = int(push_window_bytes)
        self._push: list = []        # (handle, nbytes) in-flight acks
        self._push_bytes = 0
        self.stats = {"batches": 0, "fused_batches": 0,
                      "generic_batches": 0, "cross_pg_batches": 0,
                      "range_batches": 0, "helper_bytes_on_wire": 0,
                      "push_stalls": 0, "push_max_inflight_bytes": 0,
                      "skipped_stale": 0,
                      "host_crc": self._host_crc}
        self._batches: list = []
        groups: dict = {}
        order: list = []
        for plan in self.plans:
            for sl, names in sorted(plan.names_by_len.items()):
                if plan.dec_fn is None:
                    for i in range(0, len(names), self.batch):
                        self._batches.append(
                            ("generic", plan, sl,
                             names[i:i + self.batch]))
                    continue
                key = (plan.group_key
                       if plan.group_key is not None
                       else ("inst", id(plan.be), tuple(plan.lost),
                             tuple(plan.helper)),
                       sl, plan.verify)
                if key not in groups:
                    groups[key] = []
                    order.append(key)
                groups[key].extend((plan, n) for n in names)
        for key in order:
            pairs = groups[key]
            for i in range(0, len(pairs), self.batch):
                sub = pairs[i:i + self.batch]
                self._batches.append(("fused", sub[0][0], key[1], sub))
        self._bi = 0
        self._pending: list = []
        self._stage_bufs: dict = {}

    # -- pacing hooks (the mClock worker's inputs) -------------------------

    def pending(self) -> int:
        return (len(self._batches) - self._bi) + len(self._pending)

    def next_cost(self) -> int:
        """Bytes the next step will move — the mClock cost input
        (range plans cost their PLANNED wire bytes, not full rows)."""
        if self._bi < len(self._batches):
            kind, plan, sl, payload = self._batches[self._bi]
            rl, _ranges = plan.row_ranges(sl)
            return max(1, len(plan.helper)) * rl * len(payload)
        if self._pending:
            sl, pairs = self._pending[0][0], self._pending[0][2]
            return sl * len(pairs)
        return 1

    def next_helper_osds(self) -> list[int]:
        """Distinct source OSD ids the NEXT batch pulls helper rows
        from — the key set the r17 per-failure-domain repair budgets
        bucket next_cost()'s bytes by. Empty when the pipeline is
        drained or the next step is a completion (no new reads)."""
        if self._bi >= len(self._batches):
            return []
        kind, plan, _sl, payload = self._batches[self._bi]
        plans = [plan] if kind == "generic" else \
            list({id(p): p for p, _n in payload}.values())
        out: set[int] = set()
        for p in plans:
            for h in p.helper:
                out.add(int(p.be.acting[h]))
        return sorted(out)

    # -- pipeline ----------------------------------------------------------

    def step(self) -> bool:
        """One pipeline advance: launch the next batch (completing the
        oldest first when the pipeline is full) or drain one pending
        completion. Returns True while work remains."""
        if self._bi < len(self._batches):
            kind, plan, sl, payload = self._batches[self._bi]
            self._bi += 1
            if kind == "generic":
                self._run_generic(plan, sl, payload)
            else:
                self._launch(sl, payload)
                if len(self._pending) >= 2:
                    self._complete(self._pending.pop(0))
        elif self._pending:
            self._complete(self._pending.pop(0))
        else:
            return False
        return self._bi < len(self._batches) or bool(self._pending)

    def run(self) -> None:
        while self.step():
            pass
        self.finish()

    def finish(self) -> None:
        """Drain the pipeline and the push window, then settle every
        plan (cursor advance + counter fold)."""
        while self._pending:
            self._complete(self._pending.pop(0))
        self._drain_push(0, 0)
        for plan in self.plans:
            plan.finish()

    # -- windowed push ------------------------------------------------------

    def push_txns(self, be, txns, nbytes: int) -> None:
        """Submit writeback transactions into the in-flight window:
        transmit now, collect acks only when the byte/op budget fills
        (and at finish) — later batches' frames overlap these acks."""
        for shard, t in txns:
            st = be._store(shard)
            submit = getattr(st, "queue_transaction_async", None)
            if submit is None:
                st.queue_transaction(t)
                continue
            if self._push_ops_cap or self._push_bytes_cap:
                stalled = self._drain_push(
                    (self._push_ops_cap - 1) if self._push_ops_cap
                    else None,
                    (self._push_bytes_cap - nbytes)
                    if self._push_bytes_cap else None)
                if stalled:
                    self.stats["push_stalls"] += stalled
            self._push.append((submit(t), nbytes))
            self._push_bytes += nbytes
            self.stats["push_max_inflight_bytes"] = max(
                self.stats["push_max_inflight_bytes"], self._push_bytes)
        if not (self._push_ops_cap or self._push_bytes_cap):
            # no window configured: keep the synchronous durability
            # point (every shard acked before the next batch) — the
            # frames still all hit the wire before any ack is awaited
            self._drain_push(0, 0)

    def _drain_push(self, max_ops: int | None,
                    max_bytes: int | None) -> int:
        drained = 0
        while self._push and (
                (max_ops is not None and len(self._push) > max_ops)
                or (max_bytes is not None
                    and self._push_bytes > max(0, max_bytes))):
            h, nb = self._push.pop(0)
            self._push_bytes -= nb
            drained += 1
            h.result()
        return drained

    # -- fused path ---------------------------------------------------------

    def _program(self, plan):
        key = plan.group_key
        if key is None:
            # no shareable identity: cache on the owning backend (the
            # pre-r10 behavior, minus the per-(sl) duplication)
            ckey = ("r10", id(plan.dec_fn), plan.verify, self._host_crc)
            fn = plan.be._fused_cache.get(ckey)
            if fn is None:
                self.perf.inc("program_cache_misses")
                fn = _build_recover_program(plan.dec_fn, plan.verify,
                                            self._host_crc)
                plan.be._fused_cache[ckey] = fn
            else:
                self.perf.inc("program_cache_hits")
            return fn
        ckey = (key, plan.verify, self._host_crc)
        with _RECOVER_PROGRAMS_LOCK:
            fn = _RECOVER_PROGRAMS.get(ckey)
            if fn is None:
                self.perf.inc("program_cache_misses")
                fn = _build_recover_program(plan.dec_fn, plan.verify,
                                            self._host_crc)
                _RECOVER_PROGRAMS[ckey] = fn
            else:
                self.perf.inc("program_cache_hits")
        return fn

    def _stage_buffer(self, bucket: int, H: int, sl: int) -> np.ndarray:
        # ring of 2 reusable buffers per shape: with a depth-2 pipeline
        # the transfer of batch i completed at dispatch, so buffer
        # i % 2 is free by the time batch i+2 stages (a fresh 100+ MiB
        # np.empty per batch pays page-fault cost every launch)
        key = (bucket, H, sl, self.stats["batches"] % 2)
        buf = self._stage_bufs.get(key)
        if buf is None:
            buf = np.zeros((bucket, H, sl), dtype=np.uint8)
            self._stage_bufs[key] = buf
        return buf

    def _launch(self, sl: int, pairs) -> None:
        import jax

        from ..ops.rs_kernels import pow2_bucket
        proto = pairs[0][0]
        helper = proto.helper
        H = len(helper)
        # the group key pins every plan in the batch to one program,
        # hence one (H, range shape) — rl is the staged row width
        # (full shard, or the planned sub-chunk ranges only)
        rl, _ranges = proto.row_ranges(sl)
        # stage-time revalidation (see class docstring)
        live: list[tuple] = []   # (plan, name, version-at-stage)
        for plan, name in pairs:
            size = plan.be.object_sizes.get(name)
            if size is None or plan.be._shard_len(size) != sl:
                plan.remaining.discard(name)
                self.stats["skipped_stale"] += 1
                continue
            live.append((plan, name,
                         plan.be.object_versions.get(name, 0)))
        if not live:
            return
        B = len(live)
        bucket = pow2_bucket(B)
        stack = self._stage_buffer(bucket, H, rl)
        exp = np.zeros((B, H), dtype=np.uint32)
        with span("ecbackend.recover.stage", counters=self.perf,
                  key="recover_stage_time"):
            pre_bad = self._stage(live, sl, rl, stack, exp,
                                  proto.verify)
        wire = B * H * rl
        self.stats["helper_bytes_on_wire"] += wire
        self.perf.inc("recover_wire_bytes", wire)
        if bucket != B:
            stack[B:] = 0
        program = self._program(proto)
        self.perf.inc("recover_launches")
        with span("ecbackend.recover.launch", counters=self.perf,
                  key="recover_launch_time"):
            if self._host_crc:
                handles = program(stack)
            else:
                expfold = np.zeros(bucket, dtype=np.uint32)
                if proto.verify:
                    expfold[:B] = _expected_fold_crcs(exp, rl)
                    # a padded all-zero row folds to zero bytes, whose
                    # raw CRC is just the seed shifted through rl zero
                    # bytes — match it so padding never "fails"
                    expfold[B:] = _fold_seed_const(rl)
                handles = program(stack, expfold)
            for h in handles:
                try:
                    h.copy_to_host_async()
                except AttributeError:
                    break   # non-jax handle (test stub)
        self._pending.append((sl, rl, live, handles, exp, pre_bad))
        self.stats["batches"] += 1
        self.stats["fused_batches"] += 1
        if proto.range_planes is not None:
            self.stats["range_batches"] += 1
        if len({id(p) for p, _, _ in live}) > 1:
            self.stats["cross_pg_batches"] += 1

    @staticmethod
    def _segments(live) -> list[tuple]:
        """Contiguous per-plan runs of a batch: (plan, row0, names)."""
        segs: list[tuple] = []
        for ri, (plan, name, _v) in enumerate(live):
            if not segs or segs[-1][0] is not plan:
                segs.append((plan, ri, []))
            segs[-1][2].append(name)
        return segs

    def _stage(self, live, sl: int, rl: int, stack: np.ndarray,
               exp: np.ndarray, verify: bool) -> dict[int, set[int]]:
        """Fill (B, H, rl) helper rows + expected fold inputs. Remote
        stores submit ONE readv frame per (PG, helper shard) — data
        AND integrity in the frame — all frames on the wire before any
        reply is collected (the windowed PULL: fetches from different
        source OSDs overlap instead of serializing per object).

        Full-row plans ship whole shards and `exp` carries the stored
        hinfo CRCs (the r10 whole-row fold). Range plans ship only the
        planned sub-chunk ranges; the SOURCE verifies each full shard
        against its hinfo (rot detection moves to the helper), `exp`
        carries the shipped ranges' CRCs, and rows whose full shard
        failed at the source come back in the returned
        {batch row: {helper slot}} map — the decode proceeds but those
        objects re-decode through the full-row fallback."""
        waits: list[tuple] = []
        pre_bad: dict[int, set[int]] = {}
        for plan, r0, names in self._segments(live):
            nb = len(names)
            _rl, ranges = plan.row_ranges(sl)
            for hi, s in enumerate(plan.helper):
                st = plan.be._store(s)
                cid = shard_cid(plan.be.pg, s)
                # chunk by the fetch byte budget so one source OSD
                # never serializes a giant frame
                per = max(1, RECOVERY_FETCH_BYTES // max(1, rl))
                if ranges is not None:
                    subr = getattr(st, "readv_ranges_submit", None)
                    for c0 in range(0, nb, per):
                        cnames = names[c0:c0 + per]
                        if subr is not None:
                            waits.append(
                                (subr(cid, cnames, sl, ranges,
                                      HINFO_KEY if verify else None),
                                 r0 + c0, hi, len(cnames), s))
                            continue
                        rows, crcs, bad = readv_ranges_host(
                            st, cid, cnames, sl, ranges,
                            HINFO_KEY if verify else None)
                        stack[r0 + c0:r0 + c0 + len(cnames), hi, :] \
                            = rows
                        if crcs is not None:
                            exp[r0 + c0:r0 + c0 + len(cnames), hi] \
                                = crcs
                        for b in bad:
                            pre_bad.setdefault(r0 + c0 + b,
                                               set()).add(s)
                    continue
                subv = getattr(st, "readv_submit", None)
                if subv is not None:
                    for c0 in range(0, nb, per):
                        cnames = names[c0:c0 + per]
                        waits.append(
                            (subv(cid, cnames, sl,
                                  HINFO_KEY if verify else None),
                             r0 + c0, hi, len(cnames), None))
                    continue
                out = stack[r0:r0 + nb, hi, :]
                rb = getattr(st, "read_batch", None)
                if rb is not None:
                    rb(cid, names, sl, out=out)
                else:
                    for bi, name in enumerate(names):
                        out[bi] = st.read(cid, name)
                if verify:
                    for bi, name in enumerate(names):
                        hb = st.getattr(cid, name, HINFO_KEY)
                        exp[r0 + bi, hi] = HashInfo.from_bytes(
                            hb).get_chunk_hash(0)
        for handle, r0, hi, nb, range_slot in waits:
            if range_slot is not None:
                data, crcs, bad = handle.result()
                rows = np.frombuffer(data, np.uint8)
                if rows.size != nb * rl:
                    raise ValueError(
                        f"readv_ranges: got {rows.size} bytes, "
                        f"expected {nb * rl}")
                stack[r0:r0 + nb, hi, :] = rows.reshape(nb, rl)
                if crcs is not None:
                    exp[r0:r0 + nb, hi] = crcs
                for b in bad:
                    pre_bad.setdefault(r0 + int(b),
                                       set()).add(range_slot)
                continue
            data, attrs = handle.result()
            rows = np.frombuffer(data, np.uint8)
            if rows.size != nb * sl:
                raise ValueError(
                    f"readv: got {rows.size} bytes, expected {nb * sl}")
            stack[r0:r0 + nb, hi, :] = rows.reshape(nb, sl)
            if attrs is not None:
                for bi, hb in enumerate(attrs):
                    exp[r0 + bi, hi] = HashInfo.from_bytes(
                        hb).get_chunk_hash(0)
        return pre_bad

    def _locate_bad_helpers(self, plan, name: str, bi: int,
                            exp: np.ndarray) -> set[int]:
        """Fold CRC mismatched for one object: re-read its helper rows
        and checksum each to find the rotten shard(s) — the rare path
        pays the per-row pass the common path no longer does. For
        range plans `exp` holds the SHIPPED ranges' CRCs (not hinfo),
        so the re-read compares full rows against the stored hinfo
        instead — same verdict, different oracle."""
        bad: set[int] = set()
        for hi, s in enumerate(plan.helper):
            st = plan.be._store(s)
            cid = shard_cid(plan.be.pg, s)
            chunk = st.read(cid, name)
            if self._host_crc:
                from .. import native
                crc = int(native.native_crc32c(0xFFFFFFFF, chunk))
            else:
                crc = int(PGBackend._batched_crcs(chunk[None, :])[0])
            if plan.range_planes is not None:
                want = HashInfo.from_bytes(
                    st.getattr(cid, name, HINFO_KEY)).get_chunk_hash(0)
            else:
                want = int(exp[bi, hi])
            if crc != want:
                bad.add(s)
        return bad

    def _complete(self, entry) -> None:
        import jax
        sl, rl, live, handles, exp, pre_bad = entry
        B = len(live)
        proto = live[0][0]
        with span("ecbackend.recover.fetch", counters=self.perf,
                  key="recover_fetch_time"):
            got = jax.device_get(handles)
        if self._host_crc:
            rebuilt = np.asarray(got[0])[:B]
            E = rebuilt.shape[1]
            from .. import native
            rcrc = native.native_crc32c_rows(
                0xFFFFFFFF, rebuilt.reshape(B * E, sl)).reshape(B, E)
            if proto.verify:
                fold = np.asarray(got[1])[:B]
                ok = (native.native_crc32c_rows(0xFFFFFFFF, fold)
                      == _expected_fold_crcs(exp, rl))
            else:
                ok = np.ones(B, dtype=bool)
        else:
            rebuilt = np.asarray(got[0])[:B]
            rcrc = np.asarray(got[1])[:B]
            ok = np.asarray(got[2])[:B]
        # rebuilt may be a read-only device_get view; the fallback and
        # the bucket slice both want a private copy
        rebuilt = np.array(rebuilt)
        rcrc = np.array(rcrc)
        bad_by_plan: dict[int, dict[str, set[int]]] = {}
        # source-flagged rot (range plans: the helper's full shard
        # failed its hinfo before slicing — the fold can't see it
        # because the range CRC covers the rotten bytes as shipped)
        for bi, bads in (pre_bad or {}).items():
            plan, name, _v = live[bi]
            plan.counters["hinfo_failures"] += len(bads)
            bad_by_plan.setdefault(id(plan), {})[name] = set(bads)
        if proto.verify and not ok.all():
            for bi in np.nonzero(~ok)[0]:
                plan, name, _v = live[bi]
                if name in bad_by_plan.get(id(plan), {}):
                    continue    # already flagged at the source
                bad = self._locate_bad_helpers(plan, name, int(bi), exp)
                if bad:
                    plan.counters["hinfo_failures"] += len(bad)
                    bad_by_plan.setdefault(id(plan), {})[name] = bad
        with span("ecbackend.recover.writeback", counters=self.perf,
                  key="recover_writeback_time"):
            for plan, r0, names in self._segments(live):
                nb = len(names)
                seg_rebuilt = rebuilt[r0:r0 + nb]
                seg_crcs = rcrc[r0:r0 + nb]
                bad_pairs = bad_by_plan.get(id(plan), {})
                if bad_pairs:
                    plan.be._recover_fallback(
                        plan.lost, plan.survivors, bad_pairs, names,
                        seg_rebuilt, plan.counters)
                    idxs = sorted(names.index(n) for n in bad_pairs)
                    fix = plan.be._batched_hinfo_crcs(
                        seg_rebuilt[idxs].reshape(-1, sl)).reshape(
                            len(idxs), len(plan.lost))
                    seg_crcs[idxs] = fix
                # writeback-time revalidation: a name whose version
                # moved since its stage already holds fresher bytes on
                # the recovering slot — writing the stale decode would
                # resurrect them under a matching CRC
                keep = [i for i in range(nb)
                        if plan.be.object_versions.get(names[i], 0)
                        == live[r0 + i][2]
                        and names[i] in plan.be.object_sizes]
                if len(keep) != nb:
                    self.stats["skipped_stale"] += nb - len(keep)
                if keep:
                    plan.be._writeback_rebuilt(
                        plan.lost, [names[i] for i in keep],
                        seg_rebuilt[keep], seg_crcs[keep], sl,
                        plan.counters, window=self)
                plan.remaining.difference_update(names)

    # -- generic path (codecs without a static decode plan) ----------------

    def _run_generic(self, plan, sl: int, names: list[str]) -> None:
        be = plan.be
        live = [n for n in names
                if be.object_sizes.get(n) is not None
                and be._shard_len(be.object_sizes[n]) == sl]
        if len(live) != len(names):
            # stale-skipped names need nothing from us (their mutation
            # already reached the repointed slot) but must still leave
            # the remaining set or the plan never settles
            self.stats["skipped_stale"] += len(names) - len(live)
            plan.remaining.difference_update(
                set(names) - set(live))
        names = live
        if not names:
            return
        self.perf.inc("recover_launches")
        self.stats["batches"] += 1
        self.stats["generic_batches"] += 1
        wire = len(plan.helper) * sl * len(names)
        self.stats["helper_bytes_on_wire"] += wire
        self.perf.inc("recover_wire_bytes", wire)
        stacks = {s: np.stack([be._store(s).read(
            shard_cid(be.pg, s), n) for n in names])
            for s in plan.helper}
        bad_pairs: dict[str, set[int]] = {}
        if plan.verify:
            for s in plan.helper:
                crcs_s = be._batched_hinfo_crcs(stacks[s])
                for bi, name in enumerate(names):
                    hb = be._store(s).getattr(
                        shard_cid(be.pg, s), name, HINFO_KEY)
                    if HashInfo.from_bytes(hb).get_chunk_hash(0) \
                            != int(crcs_s[bi]):
                        plan.counters["hinfo_failures"] += 1
                        bad_pairs.setdefault(name, set()).add(s)
        rec = be.coder.decode_chunks(plan.lost, stacks)
        rebuilt_all = np.stack(
            [np.asarray(rec[s]) for s in plan.lost], axis=1)
        if bad_pairs:
            be._recover_fallback(plan.lost, plan.survivors, bad_pairs,
                                 names, rebuilt_all, plan.counters)
        crcs = be._batched_hinfo_crcs(
            rebuilt_all.reshape(-1, sl)).reshape(len(names),
                                                 len(plan.lost))
        be._writeback_rebuilt(plan.lost, names, rebuilt_all, crcs, sl,
                              plan.counters, window=self)
        plan.remaining.difference_update(names)
