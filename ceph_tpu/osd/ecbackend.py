"""ECBackend — the erasure-coded PG data path, batched for TPU.

Rebuild of the reference's EC read/write/recovery dataflow (ref:
src/osd/ECBackend.{h,cc} + ECCommon.{h,cc} — submit_transaction write
fan-out, objects_read_and_reconstruct degraded read,
RecoveryOp/continue_recovery_op streaming recovery;
ECTransaction::generate_transactions for the per-shard store writes;
per-shard HashInfo bookkeeping ref: src/osd/ECUtil.{h,cc}).

TPU-first reshaping (SURVEY.md §2.7 P1-P4): where the reference fans
one object's sub-ops out over the network and recovers objects under a
semaphore one RecoveryOp at a time, here the unit of work is a BATCH of
objects — writes encode (B, k, chunk) in one device launch, recovery
gathers surviving shards for B objects into (B, k, chunk) device
arrays, runs ONE batched decode, and scatters the rebuilt shards back.
The per-shard stores are MemStore instances standing in for OSDs, so
the whole pipeline runs hermetically (the reference's
many-daemons-one-box trick, in-process).

Object placement: shard i of an object lands on the OSD in slot i of
the PG's acting set (the chunk->shard identity mapping); a lost OSD
means one lost shard per object, which is exactly the recovery
workload metric #2 in BASELINE.md measures (objects/s).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..ec.interface import ErasureCode
from ..ec.registry import factory
from .memstore import MemStore, Transaction
from .stripe import HashInfo, StripeInfo

HINFO_KEY = "hinfo_key"  # same xattr name role as the reference


@dataclass
class ShardSet:
    """The 'cluster': one MemStore per OSD id."""
    stores: dict[int, MemStore] = field(default_factory=dict)

    def osd(self, osd_id: int) -> MemStore:
        if osd_id not in self.stores:
            self.stores[osd_id] = MemStore()
        return self.stores[osd_id]


def shard_cid(pg: str, shard: int) -> str:
    """Collection name of one PG shard (role of spg_t's shard id)."""
    return f"{pg}s{shard}"


class ECBackend:
    """One PG's EC backend over a set of per-OSD stores."""

    def __init__(self, profile: dict | str, pg: str, acting: list[int],
                 cluster: ShardSet | None = None,
                 chunk_size: int | None = None):
        self.coder: ErasureCode = factory(profile)
        self.k = self.coder.get_data_chunk_count()
        self.m = self.coder.get_coding_chunk_count()
        self.n = self.k + self.m
        if len(acting) != self.n:
            raise ValueError(f"acting set size {len(acting)} != k+m={self.n}")
        self.pg = pg
        self.acting = list(acting)
        if self.coder.get_chunk_mapping() != list(range(self.n)):
            raise ValueError("non-identity chunk mappings not supported "
                             "by this backend yet")
        self.cluster = cluster or ShardSet()
        cs = chunk_size or self.coder.get_chunk_size(0) or 4096
        self.sinfo = StripeInfo(self.k, cs)
        # one collection per shard on its OSD
        for shard, osd in enumerate(self.acting):
            t = Transaction().create_collection(shard_cid(pg, shard))
            self.cluster.osd(osd).queue_transaction(t)
        self.object_sizes: dict[str, int] = {}  # the PG log's size info

    # -- helpers ------------------------------------------------------------

    def _store(self, shard: int) -> MemStore:
        return self.cluster.osd(self.acting[shard])

    def _chunk_len(self, object_size: int) -> int:
        padded = self.coder.get_chunk_size(
            self.sinfo.logical_to_next_stripe_offset(object_size))
        return max(padded, self.sinfo.chunk_size)

    @staticmethod
    def _batched_hinfo_crcs(chunks: np.ndarray) -> np.ndarray:
        """One device launch for all shards' hinfo CRCs (raw register,
        seed -1 — the HashInfo convention)."""
        from ..csum.kernels import crc32c_blocks
        return np.asarray(crc32c_blocks(chunks, init=0xFFFFFFFF, xorout=0))

    # -- write path (submit_transaction) ------------------------------------

    def write_objects(self, objects: dict[str, bytes | np.ndarray]) -> None:
        """Full-object writes, batched: encode every equal-length group
        in one device launch, then scatter per-shard store transactions
        (the role of ECTransaction::generate_transactions)."""
        by_len: dict[int, list[tuple[str, np.ndarray]]] = {}
        for name, data in objects.items():
            arr = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
                data, (bytes, bytearray, memoryview)) else np.asarray(
                    data, np.uint8)
            by_len.setdefault(len(arr), []).append((name, arr))
        for olen, group in by_len.items():
            if olen == 0:
                # zero-length objects: empty shards, hinfo over 0 bytes
                hinfo = HashInfo(1, 0, [0xFFFFFFFF])
                for name, _ in group:
                    self.object_sizes[name] = 0
                    for shard in range(self.n):
                        t = (Transaction()
                             .write(shard_cid(self.pg, shard), name, 0, b"")
                             .truncate(shard_cid(self.pg, shard), name, 0)
                             .setattr(shard_cid(self.pg, shard), name,
                                      HINFO_KEY, hinfo.to_bytes()))
                        self._store(shard).queue_transaction(t)
                continue
            batch = np.stack([a for _, a in group])
            cl = self._chunk_len(olen)
            # object_to_shards pads to the stripe boundary (= k*cl here,
            # since cl is derived from olen) and splits to data shards
            sin = StripeInfo(self.k, cl)
            data_shards = sin.object_to_shards(batch)    # (B, k, cl)
            parity = np.asarray(self.coder.encode_chunks(data_shards))
            shards = np.concatenate([data_shards, parity], axis=1)
            crcs = self._batched_hinfo_crcs(shards.reshape(-1, cl))
            crcs = crcs.reshape(len(group), self.n)
            for bi, (name, arr) in enumerate(group):
                self.object_sizes[name] = olen
                for shard in range(self.n):
                    chunk = shards[bi, shard, :]
                    hinfo = HashInfo(1, cl, [int(crcs[bi, shard])])
                    # truncate clears any stale tail from a previous,
                    # larger version of the object
                    t = (Transaction()
                         .write(shard_cid(self.pg, shard), name, 0, chunk)
                         .truncate(shard_cid(self.pg, shard), name, cl)
                         .setattr(shard_cid(self.pg, shard), name,
                                  HINFO_KEY, hinfo.to_bytes()))
                    self._store(shard).queue_transaction(t)

    # -- read path -----------------------------------------------------------

    def read_object(self, name: str,
                    dead_osds: set[int] | None = None) -> np.ndarray:
        """Read one object, reconstructing if shards are unavailable
        (objects_read_and_reconstruct)."""
        return self.read_objects([name], dead_osds)[name]

    def read_objects(self, names: list[str],
                     dead_osds: set[int] | None = None) -> dict[str, np.ndarray]:
        dead = dead_osds or set()
        avail = [s for s in range(self.n)
                 if self.acting[s] not in dead]
        want = list(range(self.k))
        need = sorted(self.coder.minimum_to_decode(want, avail))
        out: dict[str, np.ndarray] = {}
        # batched like recovery: stack equal-chunk-length groups and
        # decode each group in ONE launch
        by_len: dict[int, list[str]] = {}
        for name in names:
            if self.object_sizes[name] == 0:
                out[name] = np.zeros(0, dtype=np.uint8)
                continue
            by_len.setdefault(self._chunk_len(self.object_sizes[name]),
                              []).append(name)
        for cl, group in by_len.items():
            stacks = {s: np.stack([self._store(s).read(shard_cid(self.pg, s),
                                                       n) for n in group])
                      for s in need}
            rec = self.coder.decode(want, stacks)
            shards = np.stack([rec[i] for i in range(self.k)], axis=1)
            objs = StripeInfo(self.k, cl).shards_to_object(shards)  # (B, k*cl)
            for bi, name in enumerate(group):
                out[name] = objs[bi, :self.object_sizes[name]]
        return out

    # -- recovery (the objects/s metric) -------------------------------------

    def recover_shards(self, lost_shards: list[int],
                       replacement_osds: dict[int, int] | None = None,
                       batch: int = 128,
                       verify_hinfo: bool = True) -> dict:
        """Rebuild every object's lost shard(s): the RecoveryOp loop,
        batched. Returns counters {objects, bytes, hinfo_failures}.

        lost_shards: shard slots whose OSD died.
        replacement_osds: slot -> new OSD id (defaults to reusing the
        slot's OSD id, i.e. re-created store after replacement).
        """
        lost = sorted(set(lost_shards))
        if len(lost) > self.m:
            raise ValueError(f"{len(lost)} lost shards exceeds m={self.m}")
        repl = replacement_osds or {}
        for s in lost:
            new_osd = repl.get(s, self.acting[s])
            self.acting[s] = new_osd
            t = Transaction().create_collection(shard_cid(self.pg, s))
            self.cluster.osd(new_osd).queue_transaction(t)

        survivors = [s for s in range(self.n) if s not in lost]
        helper = sorted(self.coder.minimum_to_decode(lost, survivors))
        names = sorted(self.object_sizes)
        counters = {"objects": 0, "bytes": 0, "hinfo_failures": 0}
        for i in range(0, len(names), batch):
            group = names[i:i + batch]
            # batched gather: (B, |helper|, chunk) — stride the reads by
            # equal chunk length groups
            by_len: dict[int, list[str]] = {}
            for name in group:
                if self.object_sizes[name] == 0:
                    # nothing to decode: re-create the empty shard
                    hinfo = HashInfo(1, 0, [0xFFFFFFFF])
                    for s in lost:
                        t = (Transaction()
                             .write(shard_cid(self.pg, s), name, 0, b"")
                             .setattr(shard_cid(self.pg, s), name,
                                      HINFO_KEY, hinfo.to_bytes()))
                        self._store(s).queue_transaction(t)
                    counters["objects"] += 1
                    continue
                cl = self._chunk_len(self.object_sizes[name])
                by_len.setdefault(cl, []).append(name)
            for cl, subgroup in by_len.items():
                stacks = {
                    s: np.stack([self._store(s).read(shard_cid(self.pg, s), n)
                                 for n in subgroup])
                    for s in helper}
                bad_pairs: dict[str, set[int]] = {}  # object -> bad shards
                if verify_hinfo:
                    # reject corrupt helper reads BEFORE decoding from
                    # them (the reference checks hinfo on every EC read);
                    # affected objects re-decode from alternate helpers
                    for s in helper:
                        crcs = self._batched_hinfo_crcs(stacks[s])
                        for bi, name in enumerate(subgroup):
                            hb = self._store(s).getattr(
                                shard_cid(self.pg, s), name, HINFO_KEY)
                            if HashInfo.from_bytes(hb).get_chunk_hash(0) \
                                    != int(crcs[bi]):
                                counters["hinfo_failures"] += 1
                                bad_pairs.setdefault(name, set()).add(s)
                rec = self.coder.decode_chunks(lost, stacks)  # {slot: (B, cl)}
                rebuilt_all = np.stack([np.asarray(rec[s]) for s in lost],
                                       axis=1)  # (B, |lost|, cl)
                for name, bad in bad_pairs.items():
                    bi = subgroup.index(name)
                    alt = [s for s in survivors if s not in bad]
                    alt_need = sorted(self.coder.minimum_to_decode(lost, alt))
                    chunks = {s: self._store(s).read(shard_cid(self.pg, s),
                                                     name)
                              for s in alt_need}
                    alt_rec = self.coder.decode_chunks(lost, chunks)
                    for li, s in enumerate(lost):
                        rebuilt_all[bi, li] = np.asarray(alt_rec[s])
                crcs = self._batched_hinfo_crcs(
                    rebuilt_all.reshape(-1, cl)).reshape(len(subgroup),
                                                         len(lost))
                for li, s in enumerate(lost):
                    for bi, name in enumerate(subgroup):
                        chunk = rebuilt_all[bi, li]
                        hinfo = HashInfo(1, cl, [int(crcs[bi, li])])
                        t = (Transaction()
                             .write(shard_cid(self.pg, s), name, 0, chunk)
                             .truncate(shard_cid(self.pg, s), name, cl)
                             .setattr(shard_cid(self.pg, s), name,
                                      HINFO_KEY, hinfo.to_bytes()))
                        self._store(s).queue_transaction(t)
                        counters["bytes"] += int(chunk.size)
                counters["objects"] += len(subgroup)
        return counters

    # -- deep scrub ----------------------------------------------------------

    def deep_scrub(self) -> dict:
        """Read every shard of every object, verify stored hinfo CRCs
        (the be_deep_scrub bulk-checksum audit), batched per shard."""
        from ..csum.kernels import crc32c_blocks
        bad: list[tuple[str, int]] = []
        checked = 0
        for s in range(self.n):
            store = self._store(s)
            cid = shard_cid(self.pg, s)
            names = store.list_objects(cid)
            by_len: dict[int, list[str]] = {}
            for n in names:
                by_len.setdefault(store.stat(cid, n), []).append(n)
            for ln, group in by_len.items():
                blocks = np.stack([store.read(cid, n) for n in group])
                crcs = np.asarray(crc32c_blocks(blocks, init=0xFFFFFFFF,
                                                xorout=0))
                for bi, n in enumerate(group):
                    hinfo = HashInfo.from_bytes(store.getattr(cid, n,
                                                              HINFO_KEY))
                    checked += 1
                    if hinfo.get_chunk_hash(0) != int(crcs[bi]):
                        bad.append((n, s))
        return {"checked": checked, "inconsistent": bad}
