"""PeeringState — the GetInfo/GetLog/GetMissing consensus pass.

Rebuild of the reference's peering machine (ref: src/osd/
PeeringState.{h,cc} — a boost::statechart whose load-bearing phases
are: GetInfo (query every up shard for its pg_info_t: last_update,
log bounds), GetLog (pick the authoritative log holder via
find_best_info and pull its log), GetMissing (diff every shard's
last_update against the authoritative log into per-shard missing
sets), then choose_acting/Activate — after which missing objects are
recovered log-first, and shards whose gap predates the log tail are
backfilled instead).

Mapped onto this repo's primitives: each PGBackend already carries the
authoritative in-memory log (`pg_log`) and a per-shard applied cursor
(`shard_applied` — the last_update analog), so peering here is a PURE
FUNCTION over (backend, liveness): it produces the per-shard missing
plan and the PG's resulting state. SimCluster drives it on every map
change / revive and executes the plan through recover_shards; the
state lands in `health()` exactly like `ceph pg stat` strings.

States (the reference's pg_state_t names):
  active+clean        every slot alive and caught up
  active+degraded     >= min_size fresh shards, but some slot down or
                      behind (recovery pending/possible)
  active+backfilling  a slot is receiving a full copy (pg_temp serves)
  peering             healthy enough to activate, but the primary's
                      up_thru is not yet recorded for this interval —
                      the WaitUpThru phase: I/O stays parked until the
                      monitors commit it (ref: PeeringState WaitUpThru
                      + adjust_need_up_thru)
  down                not enough live shards to serve I/O at all
  incomplete          live shards exist, but fewer than min_size of
                      them reach the newest write — recent data is
                      unserviceable until a fresher shard returns

up_thru (ref: osd_info_t::up_thru): the map-recorded proof horizon of
an OSD's activity. Peering consults it in two directions:

* FORWARD (WaitUpThru): before this interval's primary serves I/O,
  its up_thru must reach the interval's start epoch — else a write
  could land in an interval the rest of the cluster can later prove
  nothing about. `peer(..., interval_start=, up_thru=)` classifies
  that window as "peering" with `needs_up_thru=True`; the caller asks
  the monitors to record it and re-peers on the committed map.
* BACKWARD (maybe_went_rw): a PAST interval whose primary never got
  up_thru recorded at its start epoch provably never went active, so
  no write can exist from it — peering neither waits on nor trusts
  its members (`interval_maybe_went_rw`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

BACKFILL = "backfill"  # plan marker: log trimmed past cursor


def interval_maybe_went_rw(interval_start: int,
                           primary_up_thru: int) -> bool:
    """Could an interval that began at map epoch `interval_start`
    have served writes? Only if its primary's up_thru was recorded
    at/past that epoch — otherwise the primary died (or never asked
    the monitors) before the PG could go active, so the interval
    provably carries no writes and need not be waited on or trusted
    (ref: PastIntervals::check_new_interval's maybe_went_rw)."""
    return int(primary_up_thru) >= int(interval_start)


@dataclass
class ShardInfo:
    """One GetInfo reply (pg_info_t slice)."""
    slot: int
    osd: int
    alive: bool
    applied: int          # last_update analog


@dataclass
class PeeringResult:
    state: str                      # pg_state string
    auth_version: int               # newest version any live shard has
    head: int                       # the log's newest version
    infos: list[ShardInfo]
    # live-but-behind slots -> list of object names to replay, or
    # BACKFILL when the log has been trimmed past their cursor
    missing: dict[int, list[str] | str] = field(default_factory=dict)
    # the WaitUpThru signal: the PG would be active, but the primary's
    # up_thru has not reached this interval's start epoch yet — the
    # caller must get it recorded by the monitors first
    needs_up_thru: bool = False

    @property
    def serviceable(self) -> bool:
        return self.state not in ("down", "incomplete") \
            and not self.state.startswith("peering")


def peer(backend, alive_osds, backfilling: bool = False,
         compute_missing: bool = True, interval_start: int = 0,
         up_thru: int | None = None) -> PeeringResult:
    """Run the GetInfo -> GetLog -> GetMissing phases for one PG.

    backend: a PGBackend (holds acting, pg_log, shard_applied).
    alive_osds: container with `alive_osds[osd]` truthy when the OSD
    process answers (the heartbeat view).
    backfilling: the cluster's flag that this PG has an in-flight
    pg_temp-protected copy.
    compute_missing: False skips the GetMissing log walk (classify-only
    mode for per-op serviceability gates and health polls — the state
    depends only on cursor counts, and walking a 10k-entry log per
    client op would be pure waste).
    interval_start/up_thru: the current interval's start epoch and the
    primary's map-recorded up_thru; when up_thru lags the interval
    start, a PG that would otherwise go active is held in "peering"
    (the WaitUpThru phase) with needs_up_thru=True. up_thru=None keeps
    the pre-up_thru behavior (callers that don't track intervals).
    """
    head = backend.pg_log.head

    # -- GetInfo: per-slot infos; dead shards don't reply; an unfilled
    # CRUSH slot (hole sentinel CRUSH_ITEM_NONE = 0x7FFFFFFF, or any
    # id outside the OSD table) has nobody to ask -> undersized PG
    from ..crush.map import CRUSH_ITEM_NONE
    n_osds = len(alive_osds)

    def hole(osd: int) -> bool:
        return osd == CRUSH_ITEM_NONE or not (0 <= osd < n_osds)

    infos = [ShardInfo(slot, osd,
                       not hole(osd) and bool(alive_osds[osd]),
                       backend.shard_applied[slot])
             for slot, osd in enumerate(backend.acting)]
    live = [i for i in infos if i.alive]
    undersized = any(hole(i.osd) for i in infos)

    # -- GetLog: the authoritative version reachable from live shards ------
    auth_version = max((i.applied for i in live), default=0)

    # -- GetMissing: per live shard, what it must replay -------------------
    behind = [i for i in live if i.applied < head]
    missing: dict[int, list[str] | str] = {}
    if compute_missing:
        for i in behind:
            names = backend.pg_log.missing_since(i.applied)
            missing[i.slot] = BACKFILL if names is None else names

    # -- classify (choose_acting/Activate outcome) -------------------------
    # distinct OSDs, mirroring the min_size gate: two slots on one
    # disk are one failure domain
    live_osds = {i.osd for i in live}
    fresh_osds = {i.osd for i in live if i.applied >= head}
    min_live = backend.min_live
    needs_up_thru = False
    if len(live_osds) < min_live:
        state = "down"
    elif len(fresh_osds) < min_live:
        # enough processes, but not enough of them have the newest
        # writes: I/O on recent objects would be wrong/unrecoverable
        state = "incomplete"
    elif up_thru is not None and up_thru < interval_start:
        # WaitUpThru: the data is there, but the primary may not serve
        # a single write until the monitors have recorded its up_thru
        # for this interval — or a later peering could not prove
        # whether this interval went rw (ref: adjust_need_up_thru)
        state = "peering"
        needs_up_thru = True
    elif backfilling:
        state = "active+backfilling"
    elif behind or len(live) < len(infos):
        state = "active+degraded"
    else:
        state = "active+clean"
    if undersized and state.startswith("active"):
        state += "+undersized"
    return PeeringResult(state, auth_version, head, infos, missing,
                         needs_up_thru=needs_up_thru)
