"""PeeringState — the GetInfo/GetLog/GetMissing consensus pass.

Rebuild of the reference's peering machine (ref: src/osd/
PeeringState.{h,cc} — a boost::statechart whose load-bearing phases
are: GetInfo (query every up shard for its pg_info_t: last_update,
log bounds), GetLog (pick the authoritative log holder via
find_best_info and pull its log), GetMissing (diff every shard's
last_update against the authoritative log into per-shard missing
sets), then choose_acting/Activate — after which missing objects are
recovered log-first, and shards whose gap predates the log tail are
backfilled instead).

Mapped onto this repo's primitives: each PGBackend already carries the
authoritative in-memory log (`pg_log`) and a per-shard applied cursor
(`shard_applied` — the last_update analog), so peering here is a PURE
FUNCTION over (backend, liveness): it produces the per-shard missing
plan and the PG's resulting state. SimCluster drives it on every map
change / revive and executes the plan through recover_shards; the
state lands in `health()` exactly like `ceph pg stat` strings.

States (the reference's pg_state_t names):
  active+clean        every slot alive and caught up
  active+degraded     >= min_size fresh shards, but some slot down or
                      behind (recovery pending/possible)
  active+backfilling  a slot is receiving a full copy (pg_temp serves)
  down                not enough live shards to serve I/O at all
  incomplete          live shards exist, but fewer than min_size of
                      them reach the newest write — recent data is
                      unserviceable until a fresher shard returns
"""

from __future__ import annotations

from dataclasses import dataclass, field

BACKFILL = "backfill"  # plan marker: log trimmed past cursor


@dataclass
class ShardInfo:
    """One GetInfo reply (pg_info_t slice)."""
    slot: int
    osd: int
    alive: bool
    applied: int          # last_update analog


@dataclass
class PeeringResult:
    state: str                      # pg_state string
    auth_version: int               # newest version any live shard has
    head: int                       # the log's newest version
    infos: list[ShardInfo]
    # live-but-behind slots -> list of object names to replay, or
    # BACKFILL when the log has been trimmed past their cursor
    missing: dict[int, list[str] | str] = field(default_factory=dict)

    @property
    def serviceable(self) -> bool:
        return self.state not in ("down", "incomplete")


def peer(backend, alive_osds, backfilling: bool = False,
         compute_missing: bool = True) -> PeeringResult:
    """Run the GetInfo -> GetLog -> GetMissing phases for one PG.

    backend: a PGBackend (holds acting, pg_log, shard_applied).
    alive_osds: container with `alive_osds[osd]` truthy when the OSD
    process answers (the heartbeat view).
    backfilling: the cluster's flag that this PG has an in-flight
    pg_temp-protected copy.
    compute_missing: False skips the GetMissing log walk (classify-only
    mode for per-op serviceability gates and health polls — the state
    depends only on cursor counts, and walking a 10k-entry log per
    client op would be pure waste).
    """
    head = backend.pg_log.head

    # -- GetInfo: per-slot infos; dead shards don't reply; an unfilled
    # CRUSH slot (hole sentinel CRUSH_ITEM_NONE = 0x7FFFFFFF, or any
    # id outside the OSD table) has nobody to ask -> undersized PG
    from ..crush.map import CRUSH_ITEM_NONE
    n_osds = len(alive_osds)

    def hole(osd: int) -> bool:
        return osd == CRUSH_ITEM_NONE or not (0 <= osd < n_osds)

    infos = [ShardInfo(slot, osd,
                       not hole(osd) and bool(alive_osds[osd]),
                       backend.shard_applied[slot])
             for slot, osd in enumerate(backend.acting)]
    live = [i for i in infos if i.alive]
    undersized = any(hole(i.osd) for i in infos)

    # -- GetLog: the authoritative version reachable from live shards ------
    auth_version = max((i.applied for i in live), default=0)

    # -- GetMissing: per live shard, what it must replay -------------------
    behind = [i for i in live if i.applied < head]
    missing: dict[int, list[str] | str] = {}
    if compute_missing:
        for i in behind:
            names = backend.pg_log.missing_since(i.applied)
            missing[i.slot] = BACKFILL if names is None else names

    # -- classify (choose_acting/Activate outcome) -------------------------
    # distinct OSDs, mirroring the min_size gate: two slots on one
    # disk are one failure domain
    live_osds = {i.osd for i in live}
    fresh_osds = {i.osd for i in live if i.applied >= head}
    min_live = backend.min_live
    if len(live_osds) < min_live:
        state = "down"
    elif len(fresh_osds) < min_live:
        # enough processes, but not enough of them have the newest
        # writes: I/O on recent objects would be wrong/unrecoverable
        state = "incomplete"
    elif backfilling:
        state = "active+backfilling"
    elif behind or len(live) < len(infos):
        state = "active+degraded"
    else:
        state = "active+clean"
    if undersized and state.startswith("active"):
        state += "+undersized"
    return PeeringResult(state, auth_version, head, infos, missing)
