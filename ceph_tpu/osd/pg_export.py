"""PG export/import — offline checkpoint of a placement group.

Rebuild of the reference's disaster-recovery tool semantics (ref:
src/tools/ceph_objectstore_tool.cc — `--op export` walks a PG's
objects/attrs/log into a portable file, `--op import` replays it into
another OSD; SURVEY §5 checkpoint/resume names this as the offline
half of durability). Mapped onto this framework:

* export reads the PG's LOGICAL objects through the backend (so a
  degraded PG exports fine — reconstruction is the read path), plus
  the PG log bounds and per-object versions;
* import replays the objects through the target cluster's client
  write path, which re-places and re-encodes them under the TARGET
  pool's profile — an EC k=4,m=2 export imports cleanly into a
  replicated or k=8,m=3 cluster (the reference requires same-profile
  imports; re-encoding through the framework's own codec removes that
  restriction and is the TPU-native choice: bytes are the contract,
  not shard layout).

File format: utils.encoding versioned section (v1): pg id, pool
profile string, log head/tail, objects [(name, version, data)].
"""

from __future__ import annotations

import numpy as np

from ..utils.encoding import Decoder, Encoder

MAGIC = 0x70676578  # "pgex"


def export_pg(cluster, ps: int, path: str) -> dict:
    """Write one PG's logical state to `path`; returns a summary.
    Works on degraded PGs — reads reconstruct from survivors."""
    be = cluster.pgs[ps]
    dead = cluster._dead_osds()
    names = be.list_pg_objects()
    data = be.read_objects(names, dead_osds=dead) if names else {}
    e = Encoder()
    e.u32(MAGIC)
    e.start(1, 1)
    e.string(be.pg)
    e.string(str(cluster.profile))
    e.u64(be.pg_log.head).u64(be.pg_log.tail)
    e.u32(len(names))
    for n in names:
        e.string(n)
        e.u64(be.object_versions.get(n, 0))
        e.blob(np.asarray(data[n], np.uint8).tobytes())
    e.finish()
    blob = e.bytes()
    with open(path, "wb") as f:
        f.write(blob)
    return {"pg": be.pg, "objects": len(names),
            "bytes": sum(int(np.asarray(d).size)
                         for d in data.values()),
            "file_bytes": len(blob)}


def read_export(path: str) -> dict:
    with open(path, "rb") as f:
        d = Decoder(f.read())
    if d.u32() != MAGIC:
        raise ValueError(f"{path}: not a pg export")
    d.start(1)
    out = {"pg": d.string(), "profile": d.string(),
           "log_head": d.u64(), "log_tail": d.u64()}
    objs = {}
    for _ in range(d.u32()):
        name = d.string()
        _version = d.u64()
        objs[name] = np.frombuffer(d.blob(), dtype=np.uint8)
    d.finish()
    out["objects"] = objs
    return out


def import_objects(cluster, path: str,
                   overwrite: bool = False) -> dict:
    """Replay an export into `cluster` through its client write path
    (re-placed by ITS map, re-encoded by ITS pool profile). Refuses to
    clobber existing objects unless overwrite=True (the reference
    refuses to import over an existing PG)."""
    exp = read_export(path)
    if not overwrite:
        # placement is deterministic by name: an object can only live
        # in its located PG
        existing = [n for n in exp["objects"]
                    if n in cluster.pgs[
                        cluster.locate(n)].object_sizes]
        if existing:
            raise FileExistsError(
                f"{len(existing)} object(s) already exist "
                f"(e.g. {existing[0]!r}); pass overwrite=True")
    if exp["objects"]:
        cluster.write(exp["objects"])
    return {"pg": exp["pg"], "objects": len(exp["objects"]),
            "source_profile": exp["profile"]}
