"""Object classes — server-side methods executed at the object.

Rebuild of the reference's cls plugin system (ref: src/osd/
ClassHandler.cc loading cls_*.so; objclass API src/objclass/
objclass.h — cls_cxx_read/write/map_get_val/...; dispatched from
PrimaryLogPG::do_osd_ops CEPH_OSD_OP_CALL). A class method runs AT the
object's primary with transactional access to the object's data and a
KV plane, so read-modify-write logic executes without a client round
trip per step.

TPU-first framing: classes are pure-Python callables registered in a
table (the dlopen role is already covered by native/'s EC plugin ABI);
the DATA they touch still moves through the normal client path, so EC
encode fan-out, snapshots' COW, and PG logging all apply to cls
writes exactly as to client writes.

Built-ins mirror the reference's most-used classes:
* `lock`   — advisory object locks (ref: src/cls/lock/cls_lock.cc):
  lock/unlock/break_lock/get_info, exclusive or shared, owner+cookie.
* `refcount` — get/put/read a reference count; the object removes
  itself when the count drops to zero (ref: src/cls/refcount).
* `version` — bump/read a monotonically increasing object version
  (ref: src/cls/version).

Method I/O is bytes->bytes with JSON envelopes (auditable in tests;
the reference uses its own encodings — an implementation detail, not
behavior)."""

from __future__ import annotations

import json

_CLS: dict[tuple[str, str], object] = {}


def register_cls(cls: str, method: str):
    """Decorator: register fn(handle, input_bytes) -> bytes."""
    def deco(fn):
        key = (cls, method)
        if key in _CLS and _CLS[key] is not fn:
            raise ValueError(f"cls method {cls}.{method} already "
                             f"registered")
        _CLS[key] = fn
        return fn
    return deco


class ClsHandle:
    """What a class method sees: the one object it was invoked on
    (cls_cxx_* surface). Data ops route through the cluster's client
    path; `kv` is the object's key-value plane (cls map ops)."""

    def __init__(self, cluster, name: str):
        self._c = cluster
        self.name = name

    def exists(self) -> bool:
        ps = self._c.locate(self.name)
        return self.name in self._c.pgs[ps].object_sizes

    def stat(self) -> int:
        ps = self._c.locate(self.name)
        return self._c.pgs[ps].stat_object(self.name)

    def read(self) -> bytes:
        return bytes(self._c.read(self.name))

    def write_full(self, data: bytes) -> None:
        self._c.write({self.name: data})

    def remove(self) -> None:
        self._c.remove(self.name)
        self._c.obj_kv.pop(self.name, None)

    @property
    def kv(self) -> dict:
        return self._c.obj_kv.setdefault(self.name, {})


class ClsError(RuntimeError):
    """A class method refused the operation (the -EBUSY/-ENOENT style
    error return of the reference's cls methods)."""


def cls_call(cluster, name: str, cls: str, method: str,
             inp: bytes = b"") -> bytes:
    fn = _CLS.get((cls, method))
    if fn is None:
        raise KeyError(f"no object class method {cls}.{method}")
    return fn(ClsHandle(cluster, name), inp)


# -- built-in: advisory locks (cls_lock) -------------------------------------

def _lock_state(h: ClsHandle) -> dict:
    return h.kv.setdefault("lock", {"type": None, "holders": {}})


@register_cls("lock", "lock")
def _lock_lock(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp or b"{}")
    owner = req.get("owner", "")
    ltype = req.get("type", "exclusive")
    if ltype not in ("exclusive", "shared"):
        raise ClsError(f"bad lock type {ltype!r}")
    st = _lock_state(h)
    if st["holders"]:
        if owner in st["holders"]:
            if ltype != st["type"]:
                # upgrades/downgrades are not silent no-ops — the
                # caller would believe it holds the new type (the
                # reference cls_lock returns -EBUSY here too)
                raise ClsError("EBUSY: lock upgrade not supported")
            return b"{}"             # re-entrant for the same owner
        if st["type"] == "exclusive" or ltype == "exclusive":
            raise ClsError("EBUSY: lock held")
    st["type"] = ltype
    st["holders"][owner] = {"since": "held"}
    return b"{}"


@register_cls("lock", "unlock")
def _lock_unlock(h: ClsHandle, inp: bytes) -> bytes:
    owner = json.loads(inp or b"{}").get("owner", "")
    st = _lock_state(h)
    if owner not in st["holders"]:
        raise ClsError("ENOENT: not a lock holder")
    del st["holders"][owner]
    if not st["holders"]:
        st["type"] = None
    return b"{}"


@register_cls("lock", "break_lock")
def _lock_break(h: ClsHandle, inp: bytes) -> bytes:
    """Forcibly evict another client's lock (the recovery path an
    operator uses when a lock holder died)."""
    owner = json.loads(inp or b"{}").get("owner", "")
    st = _lock_state(h)
    st["holders"].pop(owner, None)
    if not st["holders"]:
        st["type"] = None
    return b"{}"


@register_cls("lock", "get_info")
def _lock_info(h: ClsHandle, inp: bytes) -> bytes:
    st = _lock_state(h)
    return json.dumps({"type": st["type"],
                       "holders": sorted(st["holders"])}).encode()


# -- built-in: refcount ------------------------------------------------------

@register_cls("refcount", "get")
def _ref_get(h: ClsHandle, inp: bytes) -> bytes:
    h.kv["refs"] = h.kv.get("refs", 0) + 1
    return json.dumps({"refs": h.kv["refs"]}).encode()


@register_cls("refcount", "put")
def _ref_put(h: ClsHandle, inp: bytes) -> bytes:
    refs = h.kv.get("refs", 0) - 1
    if refs < 0:
        raise ClsError("EINVAL: refcount underflow")
    h.kv["refs"] = refs
    if refs == 0:
        h.remove()                   # last ref drops the object
    return json.dumps({"refs": refs}).encode()


@register_cls("refcount", "read")
def _ref_read(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps({"refs": h.kv.get("refs", 0)}).encode()


# -- built-in: version -------------------------------------------------------

@register_cls("version", "bump")
def _ver_bump(h: ClsHandle, inp: bytes) -> bytes:
    h.kv["ver"] = h.kv.get("ver", 0) + 1
    return json.dumps({"ver": h.kv["ver"]}).encode()


@register_cls("version", "read")
def _ver_read(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps({"ver": h.kv.get("ver", 0)}).encode()
