"""Cache tiering — a writeback cache pool overlaying a base pool.

Rebuild of the reference's cache-tier machinery (ref:
src/osd/PrimaryLogPG.cc maybe_handle_cache_detail — proxy vs promote
decision on a cache miss; agent_work / agent_maybe_flush /
agent_maybe_evict — the tiering agent draining dirty objects and
evicting cold clean ones against target_max_bytes ratios; whiteout
objects carrying deletes down to the base tier; HitSet recency
tracking. Operator surface ref: src/mon/OSDMonitor.cc `osd tier add /
cache-mode / set-overlay` and the pool's cache_target_dirty_ratio /
cache_target_full_ratio options).

TPU-first reshaping: the reference's agent visits objects one at a
time through PrimaryLogPG ops; here flush IS the batched write path —
the agent collects the coldest dirty objects and hands the whole set
to the base pool's `write()` in one call, so a flush of B objects is
ONE batched EC encode launch (SURVEY §2.7 P2), and eviction is a
single batched remove. Promotion likewise rides the cache pool's
batched write.

Scope (matching SURVEY §2.3's "context beyond the EC slice" marker):
writeback mode only (the reference's readonly/readproxy/forward modes
are degenerate cases of the same plumbing), full-object granularity,
one overlay per base pool.
"""

from __future__ import annotations

import numpy as np

from ..utils.perf_counters import PerfCountersBuilder
from .stripe import as_flat_u8


class CacheTier:
    """Writeback overlay: clients address THIS object (the librados
    IoCtx keeps talking to the base pool name; the overlay redirect is
    the reference's `osd tier set-overlay`)."""

    def __init__(self, base, cache,
                 target_max_bytes: int = 64 << 20,
                 dirty_ratio: float = 0.4,
                 full_ratio: float = 0.8,
                 promote_after_hits: int = 2,
                 hit_set_period: float = 60.0):
        if not (0.0 < dirty_ratio <= full_ratio <= 1.0):
            raise ValueError("need 0 < dirty_ratio <= full_ratio <= 1")
        self.base = base
        self.cache = cache
        self.target_max_bytes = int(target_max_bytes)
        self.dirty_ratio = float(dirty_ratio)
        self.full_ratio = float(full_ratio)
        self.promote_after_hits = int(promote_after_hits)
        self.hit_set_period = float(hit_set_period)
        # per-object cache state: dirty bit + last-touch tick + size.
        # A WHITEOUT is a cache entry recording a delete that has not
        # reached the base yet (ref: object_info_t FLAG_WHITEOUT).
        self._dirty: dict[str, bool] = {}
        self._size: dict[str, int] = {}
        self._touch: dict[str, int] = {}
        self._whiteout: set[str] = set()
        self._tick = 0
        # running byte counters: the agent runs per write and must
        # not pay an O(objects) dict scan each time
        self._cache_bytes = 0
        self._dirty_bytes = 0
        # HitSet: miss counters over a sliding period (ref: HitSet
        # bloom persistence — a dict stands in; decayed wholesale each
        # period so one-shot scans never promote)
        self._hits: dict[str, int] = {}
        self._hits_age = 0
        b = PerfCountersBuilder("cache_tier")
        for c in ("hit", "miss", "promote", "proxy_read", "flush",
                  "evict", "whiteout"):
            b.add_u64_counter(f"tier_{c}")
        self.perf = b.create_perf_counters()

    # -- client surface ------------------------------------------------------

    def write(self, objects: dict[str, bytes | np.ndarray]) -> None:
        """Writeback: land in the CACHE pool only, mark dirty; the
        agent flushes to base later (the client ack does not wait for
        the base tier — that is the point of writeback mode)."""
        self._tick += 1
        payload = {}
        for name, data in objects.items():
            arr = as_flat_u8(data)
            payload[name] = arr
            self._account(name, int(arr.size), dirty=True)
            self._touch[name] = self._tick
            self._whiteout.discard(name)
        self.cache.write(payload)
        self._agent()

    def read(self, name: str) -> np.ndarray:
        self._tick += 1
        if name in self._whiteout:
            raise KeyError(f"no object {name!r}")
        if name in self._size:
            self.perf.inc("tier_hit")
            self._touch[name] = self._tick
            return self.cache.read(name)
        self.perf.inc("tier_miss")
        self._decay_hits()
        hits = self._hits[name] = self._hits.get(name, 0) + 1
        data = np.asarray(self.base.read(name))   # miss: KeyError here
        if hits >= self.promote_after_hits:
            # PROMOTE: copy into the cache pool, clean (the bytes
            # also live in base; ref: promote_object)
            self.perf.inc("tier_promote")
            self.cache.write({name: data})
            self._account(name, int(data.size), dirty=False)
            self._touch[name] = self._tick
            # reset recency: an evicted-then-missed object must earn
            # promotion again, not bounce straight back in (churn)
            self._hits.pop(name, None)
            self._agent()
        else:
            # below the promotion threshold: serve THROUGH the tier
            # without caching (ref: do_proxy_read)
            self.perf.inc("tier_proxy_read")
        return data

    def remove(self, names: list[str] | str) -> None:
        """Delete through the tier: drop cached bytes, and leave a
        WHITEOUT when the base still holds the object so the delete
        propagates on flush instead of resurrecting on the next
        miss."""
        self._tick += 1
        names = [names] if isinstance(names, str) else list(names)
        # validate the WHOLE batch before mutating anything (the
        # recover_shards convention): a bad name mid-batch must not
        # leave a half-applied delete the retry then trips over
        for name in dict.fromkeys(names):
            if name in self._whiteout or (
                    name not in self._size
                    and not self._exists_in_base(name)):
                raise KeyError(f"no object {name!r}")
        for name in dict.fromkeys(names):
            if name in self._whiteout:
                # already logically deleted: delete must agree with
                # read (which raises) — and not double-count stats
                raise KeyError(f"no object {name!r}")
            in_cache = name in self._size
            in_base = self._exists_in_base(name)
            if not in_cache and not in_base:
                raise KeyError(f"no object {name!r}")
            if in_cache:
                self.cache.remove([name])
                self._forget(name)
            if in_base:
                self._whiteout.add(name)
                self.perf.inc("tier_whiteout")

    # -- the tiering agent ---------------------------------------------------

    @property
    def cache_bytes(self) -> int:
        return self._cache_bytes

    @property
    def dirty_bytes(self) -> int:
        return self._dirty_bytes

    def _account(self, name: str, size: int, dirty: bool) -> None:
        """Install/refresh one cache entry, keeping the running byte
        counters exact across overwrites and dirty transitions."""
        old_size = self._size.get(name)
        if old_size is not None:
            self._cache_bytes -= old_size
            if self._dirty.get(name):
                self._dirty_bytes -= old_size
        self._size[name] = size
        self._dirty[name] = dirty
        self._cache_bytes += size
        if dirty:
            self._dirty_bytes += size

    def _agent(self) -> None:
        """agent_work: flush the coldest dirty objects when dirty
        bytes exceed the dirty ratio; evict the coldest clean ones
        when total bytes exceed the full ratio. Both run as ONE
        batched operation against the pools."""
        dirty_target = int(self.target_max_bytes * self.dirty_ratio)
        if self.dirty_bytes > dirty_target:
            over = self.dirty_bytes - dirty_target
            self.flush(self._coldest(over, dirty=True))
        full_target = int(self.target_max_bytes * self.full_ratio)
        if self.cache_bytes > full_target:
            over = self.cache_bytes - full_target
            victims = self._coldest(over, dirty=False)
            if victims:
                self.evict(victims)

    def _coldest(self, over_bytes: int, dirty: bool) -> list[str]:
        pool = sorted((n for n in self._size
                       if bool(self._dirty.get(n)) == dirty),
                      key=lambda n: self._touch[n])
        out, acc = [], 0
        for n in pool:
            if acc >= over_bytes:
                break
            out.append(n)
            acc += self._size[n]
        return out

    def flush(self, names: list[str] | None = None) -> int:
        """Write dirty objects down to base (one batched base write)
        and apply pending whiteouts (one batched base remove)."""
        if names is None:
            names = [n for n in self._size if self._dirty.get(n)]
        names = [n for n in names if self._dirty.get(n)]
        if names:
            batch = {n: self.cache.read(n) for n in names}
            self.base.write(batch)
            for n in names:
                self._dirty[n] = False
                self._dirty_bytes -= self._size[n]
            self.perf.inc("tier_flush", len(names))
        if self._whiteout:
            # invariant: whiteouts are only created for names verified
            # in base, and only this tier deletes from base — no
            # re-probe needed
            self.base.remove(sorted(self._whiteout))
            self._whiteout.clear()
        return len(names)

    def evict(self, names: list[str]) -> int:
        """Drop CLEAN cached copies (bytes remain in base)."""
        victims = [n for n in names
                   if n in self._size and not self._dirty.get(n)]
        if victims:
            self.cache.remove(victims)
            for n in victims:
                self._forget(n)
            self.perf.inc("tier_evict", len(victims))
        return len(victims)

    def flush_evict_all(self) -> None:
        """`rados cache-flush-evict-all` — drain the tier completely
        (the decommission path before `osd tier remove-overlay`)."""
        self.flush()
        self.evict([n for n in list(self._size)
                    if not self._dirty.get(n)])

    # -- helpers -------------------------------------------------------------

    def _forget(self, name: str) -> None:
        sz = self._size.pop(name, None)
        if sz is not None:
            self._cache_bytes -= sz
            if self._dirty.get(name):
                self._dirty_bytes -= sz
        self._dirty.pop(name, None)
        self._touch.pop(name, None)

    def _exists_in_base(self, name: str) -> bool:
        # metadata-only probe: a full base.read() would decode a whole
        # EC stripe just to test existence
        locate = getattr(self.base, "locate", None)
        pgs = getattr(self.base, "pgs", None)
        if locate is not None and pgs is not None:
            return name in pgs[locate(name)].object_sizes
        try:
            self.base.read(name)
            return True
        except KeyError:
            return False

    def _decay_hits(self) -> None:
        self._hits_age += 1
        if self._hits_age >= self.hit_set_period:
            self._hits.clear()
            self._hits_age = 0

    def stats(self) -> dict:
        return {
            "cache_bytes": self.cache_bytes,
            "dirty_bytes": self.dirty_bytes,
            "objects": len(self._size),
            "whiteouts": len(self._whiteout),
            **{k: int(v) for k, v in self.perf.dump().items()
               if k.startswith("tier_")},
        }
