"""mClock op scheduler — QoS-tagged dequeue for the OSD op path.

Rebuild of the reference's scheduler (ref: src/osd/scheduler/
mClockScheduler.{h,cc}, which wraps the dmclock library's
PullPriorityQueue; op classes ref: src/osd/scheduler/OpSchedulerItem.h —
client, background_recovery, background_best_effort, scrub...). The
algorithm is the published mClock/dmClock tagging scheme:

Each class has (reservation ρ, weight w, limit λ) in ops-per-second.
Every enqueued op gets three tags from its class state:

    R = max(now, R_prev + cost/ρ)     (reservation spacing)
    L = max(now, L_prev + cost/λ)     (limit spacing)
    P = max(now, P_prev) + cost/w     (proportional-share spacing)

Dequeue at time `now`:
 1. constraint phase: among classes whose head R-tag <= now, pick the
    smallest R-tag (reservations are met first, in tag order);
 2. weight phase: otherwise, among classes whose head L-tag <= now,
    pick the smallest P-tag (spare capacity split by weight);
 3. else idle (every class is limit-bound).

The scheduler is clock-agnostic: `dequeue(now)` takes the caller's
time, so SimCluster drives it with virtual time and real daemons could
drive it with wall time. Weight tags use a per-class "virtual start"
bumped to now on idle->busy transitions so an idle class doesn't bank
credit forever (dmclock's idle-adjustment).

Classes are DYNAMIC: beyond the fixed op-class split (client /
background_recovery / scrub ...), the wire OSD registers one class per
client entity ("tenant:<entity>", see OSDDaemon._client_class) via
ensure_class(), each with its own (ρ, w, λ) resolved from the
osd_mclock_scheduler_tenant_* config — the per-client dmclock deployment
shape from the mClock paper, so one heavy tenant (or its hedged
duplicates) competes under its own tags instead of riding the shared
client class. Idle tenant classes cost one tag comparison per dequeue
and are not garbage-collected (tenant counts here are tens, not
millions).

TPU relevance: the scheduler is the admission layer that decides WHICH
batch the device runs next (client encode vs recovery decode vs scrub
CRC); keeping it cost-aware keeps recovery from starving client
latency, the exact failure mode mClock exists to prevent in the
reference OSD.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass


@dataclass(frozen=True)
class ClientProfile:
    """(ρ, w, λ) in ops/s; λ == 0 means unlimited (no limit phase)."""
    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0

    def __post_init__(self):
        if self.reservation < 0 or self.weight <= 0 or self.limit < 0:
            raise ValueError(f"bad profile {self}")
        if self.limit and self.reservation > self.limit:
            raise ValueError(f"reservation {self.reservation} > limit "
                             f"{self.limit}")


# the reference's built-in profile split (high_client_ops-ish defaults):
# clients get a guaranteed floor and most of the weight; recovery gets a
# floor but a ceiling too; scrub/best-effort scavenge spare capacity
DEFAULT_PROFILES = {
    "client": ClientProfile(reservation=50.0, weight=10.0, limit=0.0),
    "background_recovery": ClientProfile(reservation=25.0, weight=5.0,
                                         limit=100.0),
    "background_best_effort": ClientProfile(reservation=0.0, weight=2.0,
                                            limit=0.0),
    "scrub": ClientProfile(reservation=0.0, weight=1.0, limit=50.0),
}


def parse_profile(spec: str) -> ClientProfile:
    """'res,wgt,lim' -> ClientProfile (ops/s-space; lim 0 = unlimited).
    The value grammar of the osd_mclock_scheduler_tenant_default
    option."""
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) != 3:
        raise ValueError(f"bad profile spec {spec!r} "
                         f"(want 'res,wgt,lim')")
    res, wgt, lim = (float(p) for p in parts)
    return ClientProfile(reservation=res, weight=wgt, limit=lim)


def parse_profile_table(spec: str) -> dict[str, ClientProfile]:
    """'entityA=r,w,l;entityB=r,w,l' -> per-tenant profile table (the
    osd_mclock_scheduler_tenant_profiles grammar). Empty items are
    skipped so trailing ';' is legal."""
    out: dict[str, ClientProfile] = {}
    for item in str(spec).split(";"):
        item = item.strip()
        if not item:
            continue
        ent, eq, prof = item.partition("=")
        if not eq or not ent.strip():
            raise ValueError(f"bad tenant profile item {item!r} "
                             f"(want 'entity=res,wgt,lim')")
        out[ent.strip()] = parse_profile(prof)
    return out


class TokenBucket:
    """Clock-agnostic token bucket (rate units/s, burst capacity).
    `take(cost, now)` returns 0.0 when the tokens were granted, else
    the seconds until `cost` tokens will exist — the caller defers
    that long instead of busy-polling. Like the mClock tags, `now` is
    the caller's clock, so SimCluster/scale_sim drive it in virtual
    time and the wire tier in wall time."""

    __slots__ = ("rate", "burst", "tokens", "stamp", "granted",
                 "throttled")

    def __init__(self, rate: float, burst: float,
                 now: float = 0.0):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate {rate} / burst {burst} must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)      # start full: the first burst
        #                                 after an idle period is free
        self.stamp = float(now)
        self.granted = 0.0
        self.throttled = 0

    def _refill(self, now: float) -> None:
        if now > self.stamp:
            self.tokens = min(self.burst,
                              self.tokens + (now - self.stamp)
                              * self.rate)
        self.stamp = max(self.stamp, now)

    def take(self, cost: float, now: float) -> float:
        """Grant `cost` tokens (0.0) or the wait until they refill.
        Costs above the burst still clear — the bucket goes negative
        ONCE and the debt repays at `rate` (one oversized recovery
        batch must throttle the NEXT grant, not deadlock forever)."""
        self._refill(now)
        if self.tokens >= cost or self.tokens >= self.burst:
            self.tokens -= cost
            self.granted += cost
            return 0.0
        self.throttled += 1
        return (cost - self.tokens) / self.rate

    def retune(self, rate: float, burst: float) -> None:
        """Live budget change: tokens clamp into the new burst."""
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate {rate} / burst {burst} must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = min(self.tokens, self.burst)

    def dump(self) -> dict:
        return {"rate": self.rate, "burst": self.burst,
                "tokens": round(self.tokens, 1),
                "granted": round(self.granted, 1),
                "throttled": self.throttled}


class DomainBudgets:
    """Per-failure-domain repair bandwidth budgets: one TokenBucket
    per CRUSH domain (rack by default), created lazily on first grant.
    Buckets are INDEPENDENT — domain A draining to zero never delays a
    grant whose helpers live in domain B (the starvation-freedom
    property the repair-policy tests pin). Rate/burst re-resolve on
    every request so a committed `config set
    osd_repair_domain_budget_mbps` retunes live buckets in place."""

    def __init__(self):
        self._buckets: dict = {}

    def request(self, domain_bytes: "dict[object, float]", rate: float,
                burst: float, now: float) -> float:
        """Draw `domain_bytes[d]` bytes from every involved domain's
        bucket. Returns 0.0 when every domain granted, else the
        longest wait among the refusing domains — and REFUNDS the
        domains that did grant (an all-or-nothing draw, so a
        two-domain pull cannot leak tokens it never used)."""
        taken: list[tuple[TokenBucket, float]] = []
        wait = 0.0
        for dom, nbytes in domain_bytes.items():
            b = self._buckets.get(dom)
            if b is None:
                b = self._buckets[dom] = TokenBucket(rate, burst,
                                                     now=now)
            elif b.rate != rate or b.burst != burst:
                b.retune(rate, burst)
            w = b.take(float(nbytes), now)
            if w > 0.0:
                wait = max(wait, w)
            else:
                taken.append((b, float(nbytes)))
        if wait > 0.0:
            for b, nbytes in taken:
                b.tokens = min(b.burst, b.tokens + nbytes)
                b.granted -= nbytes
        return wait

    def dump(self) -> dict:
        return {str(d): b.dump()
                for d, b in sorted(self._buckets.items(),
                                   key=lambda kv: str(kv[0]))}


class _ClassQueue:
    __slots__ = ("profile", "items", "r_prev", "l_prev", "p_prev",
                 "busy", "served", "served_cost", "throttled")

    def __init__(self, profile: ClientProfile):
        self.profile = profile
        self.items: list = []       # heap of (seq, item, cost) FIFO
        self.r_prev = 0.0
        self.l_prev = 0.0
        self.p_prev = 0.0
        self.busy = False
        self.served = 0             # ops granted (occupancy dumps)
        self.served_cost = 0.0      # cost units granted
        self.throttled = 0          # dequeue passes skipped limit-bound


class MClockScheduler:
    def __init__(self, profiles: dict[str, ClientProfile] | None = None):
        self._classes: dict[str, _ClassQueue] = {}
        for name, prof in (profiles or DEFAULT_PROFILES).items():
            self._classes[name] = _ClassQueue(prof)
        self._seq = itertools.count()
        self._len = 0

    def add_class(self, name: str, profile: ClientProfile) -> None:
        if name in self._classes:
            raise ValueError(f"class {name!r} exists")
        self._classes[name] = _ClassQueue(profile)

    def ensure_class(self, name: str, profile: ClientProfile) -> None:
        """Create-or-retune: the dynamic per-tenant registration path
        (first op from a new client entity creates its class; a config
        change retunes it in place, queued ops keep their order)."""
        q = self._classes.get(name)
        if q is None:
            self._classes[name] = _ClassQueue(profile)
        elif q.profile != profile:
            self.set_profile(name, profile)

    def class_names(self) -> list[str]:
        return list(self._classes)

    def remove_if(self, cls: str, pred) -> int:
        """Drop queued ops of `cls` matching pred(item) — cancelled
        work must not burn the class's limit budget as no-ops. Returns
        the count removed."""
        q = self._classes[cls]
        keep = [e for e in q.items if not pred(e[1])]
        removed = len(q.items) - len(keep)
        if removed:
            heapq.heapify(keep)
            q.items = keep
            self._len -= removed
        return removed

    def set_profile(self, name: str, profile: ClientProfile) -> None:
        """Runtime QoS change (the reference's `ceph config set
        osd_mclock_*` path); queued ops keep their order, tags restart
        from the next dequeue."""
        q = self._classes[name]
        q.profile = profile
        q.busy = False

    def __len__(self) -> int:
        return self._len

    def enqueue(self, cls: str, item, cost: float = 1.0) -> None:
        """cost is in 'op units' — callers scale it by bytes/ops so one
        huge recovery batch doesn't count like one tiny client op (the
        reference scales cost by osd_mclock_cost_per_byte)."""
        if cost <= 0:
            raise ValueError(f"cost {cost} <= 0")
        q = self._classes[cls]  # KeyError for unknown class is correct
        heapq.heappush(q.items, (next(self._seq), item, cost))
        self._len += 1

    def _head_tags(self, q: _ClassQueue, now: float):
        """Tags the head op WOULD get if dequeued at `now`."""
        _, _, cost = q.items[0]
        p = q.profile
        if not q.busy:
            # idle->busy: tags restart from now — no banked credit, and
            # no arrival penalty (dmclock assigns the first request
            # R = max(now, ...) = now)
            r_tag = now if p.reservation else float("inf")
            l_tag = now
            p_tag = now + cost / p.weight
        else:
            # R spaces from the PREVIOUS TAG, not from now: under
            # backlog dmclock's arrival-time tags degenerate to pure
            # spacing, so a late-served reservation keeps its credit
            # and catches up (no drift). Idle credit is still dropped
            # by the busy flag above.
            r_tag = (q.r_prev + cost / p.reservation
                     if p.reservation else float("inf"))
            # L spaces purely too: a drain at one discrete virtual
            # time instant may serve the whole λ*dt allotment of the
            # elapsed window (SimCluster pumps once per tick step)
            l_tag = (q.l_prev + cost / p.limit if p.limit else now)
            p_tag = max(now, q.p_prev) + cost / p.weight
        return r_tag, l_tag, p_tag

    def dequeue(self, now: float):
        """Returns (class_name, item) or None when idle/limit-bound."""
        best_r = best_w = None
        for name, q in self._classes.items():
            if not q.items:
                q.busy = False
                continue
            r_tag, l_tag, p_tag = self._head_tags(q, now)
            if r_tag > now and l_tag > now:
                # head has queued work but its limit tag is in the
                # future: this pass the class is LIMIT-BOUND. Count it —
                # the per-tenant throttle attribution dump_mclock and
                # the workload engine surface (which tenant mClock is
                # actually holding back, not just who is slow).
                q.throttled += 1
                continue
            if r_tag <= now and (best_r is None or r_tag < best_r[0]):
                best_r = (r_tag, name, l_tag, p_tag)
            if l_tag <= now and (best_w is None or p_tag < best_w[0]):
                best_w = (p_tag, name, r_tag, l_tag)
        if best_r is not None:
            r_tag, name, l_tag, p_tag = best_r
        elif best_w is not None:
            p_tag, name, r_tag, l_tag = best_w
        else:
            return None
        q = self._classes[name]
        _, item, cost = heapq.heappop(q.items)
        q.r_prev, q.l_prev, q.p_prev = r_tag, l_tag, p_tag
        q.busy = True
        q.served += 1
        q.served_cost += cost
        self._len -= 1
        return name, item

    def next_eligible(self, now: float) -> float | None:
        """Earliest future time a queued head becomes servable, or None
        when the queue is empty (lets a wall-clock pump sleep precisely
        instead of polling while every class is limit-bound)."""
        best = None
        for q in self._classes.values():
            if not q.items:
                continue
            r_tag, l_tag, _ = self._head_tags(q, now)
            t = min(r_tag, l_tag)
            if t <= now:
                return now
            if best is None or t < best:
                best = t
        return best

    def dump(self) -> dict:
        """Per-class occupancy + grant counters (the `dump_mclock`
        admin view; recovery_bench emits this next to perf deltas)."""
        # snapshot the table: tenant classes appear dynamically from
        # dispatch threads while admin/bench threads dump
        return {name: {"queued": len(q.items),
                       "served": q.served,
                       "served_cost": round(q.served_cost, 3),
                       "throttled": q.throttled,
                       "profile": {"reservation": q.profile.reservation,
                                   "weight": q.profile.weight,
                                   "limit": q.profile.limit}}
                for name, q in list(self._classes.items())}

    def drain(self, now: float, budget: int | None = None) -> list:
        """Dequeue until idle/limit-bound (or budget ops); the per-tick
        pump SimCluster uses."""
        out = []
        while budget is None or len(out) < budget:
            got = self.dequeue(now)
            if got is None:
                break
            out.append(got)
        return out
