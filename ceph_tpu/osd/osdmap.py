"""OSDMap — the epoch-versioned cluster map: object -> PG -> OSDs.

Rebuild of the reference's placement layer above CRUSH (ref:
src/osd/OSDMap.{h,cc} — object_locator_to_pg, raw_pg_to_pps via
ceph_stable_mod, _pg_to_raw_osds, pg_to_up_acting_osds with
pg_temp/primary_temp overrides; pool model ref: pg_pool_t in
src/osd/osd_types.h; string hash ref: src/common/ceph_hash.cc
ceph_str_hash_rjenkins).

TPU-first shape: the per-PG scalar path exists for parity/debugging,
but the real API is the batched one — `pgs_to_up(pool, ps_array)`
pushes the whole PG population through the vectorized CRUSH mapper in
one device launch; sparse pg_temp/primary_temp overrides are applied
host-side after (they are rare, transient backfill state).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..crush.hash import hash32_2
from ..crush.map import CRUSH_ITEM_NONE, CrushMap
from ..crush.mapper import VectorMapper
from ..crush.oracle import OracleMapper


def ceph_stable_mod(x: int | np.ndarray, b: int, bmask: int):
    """Stable modulo: doubling b reshuffles only the new half of the
    space (what makes pg_num growth cheap)."""
    lo = x & bmask
    return np.where(lo < b, lo, x & (bmask >> 1)) if isinstance(
        x, np.ndarray) else (lo if lo < b else x & (bmask >> 1))


def pg_num_mask(pg_num: int) -> int:
    """Smallest 2^n-1 >= pg_num-1 (the reference's calc_pg_masks)."""
    if pg_num < 1:
        raise ValueError("pg_num must be >= 1")
    return (1 << (pg_num - 1).bit_length()) - 1


def str_hash_rjenkins(s: bytes | str) -> int:
    """Bob Jenkins' lookup2 string hash, the object-name hash (role of
    ceph_str_hash_rjenkins). Shares the mixing round with crush.hash."""
    if isinstance(s, str):
        s = s.encode()
    M = 0xFFFFFFFF

    def mix(a, b, c):
        from ..crush.hash import _mix
        with np.errstate(over="ignore"):
            a, b, c = _mix(np.uint32(a), np.uint32(b), np.uint32(c))
        return int(a), int(b), int(c)

    a = b = 0x9E3779B9
    c = 0
    n = len(s)
    i = 0
    while n - i >= 12:
        a = (a + int.from_bytes(s[i:i + 4], "little")) & M
        b = (b + int.from_bytes(s[i + 4:i + 8], "little")) & M
        c = (c + int.from_bytes(s[i + 8:i + 12], "little")) & M
        a, b, c = mix(a, b, c)
        i += 12
    c = (c + n) & M
    tail = s[i:]
    for idx, shift in ((10, 24), (9, 16), (8, 8)):
        if len(tail) > idx:
            c = (c + (tail[idx] << shift)) & M
    for idx, shift in ((7, 24), (6, 16), (5, 8), (4, 0)):
        if len(tail) > idx:
            b = (b + (tail[idx] << shift)) & M
    for idx, shift in ((3, 24), (2, 16), (1, 8), (0, 0)):
        if len(tail) > idx:
            a = (a + (tail[idx] << shift)) & M
    a, b, c = mix(a, b, c)
    return c


#: per-OSD fullness ladder states carried on the map (r21 capacity
#: plane; ref: osd_state NEARFULL/BACKFILLFULL/FULL in osd_types.h).
#: Absent from osd_full_state == 0 == plenty of room.
FULL_NONE = 0
FULL_NEARFULL = 1
FULL_BACKFILLFULL = 2
FULL_FULL = 3
FULL_STATE_NAMES = {FULL_NEARFULL: "nearfull",
                    FULL_BACKFILLFULL: "backfillfull",
                    FULL_FULL: "full"}


@dataclass
class PGPool:
    """pg_pool_t equivalent: placement parameters of one pool."""
    pool_id: int
    pg_num: int
    size: int                      # replicas / k+m
    min_size: int
    crush_rule: int
    is_erasure: bool = False
    pgp_num: int | None = None
    ec_profile: dict = field(default_factory=dict)
    # pool snapshots (ref: pg_pool_t::snap_seq/snaps — monitor-owned,
    # distributed to OSDs/clients inside the map): sid -> snap name
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)
    # pool quotas (ref: pg_pool_t::quota_max_bytes/quota_max_objects):
    # the leader compares MgrReport pool aggregates against these and
    # flips the pool's FULL flag on the map; 0 = unlimited
    quota_max_bytes: int = 0
    quota_max_objects: int = 0

    def __post_init__(self):
        if self.pgp_num is None:
            self.pgp_num = self.pg_num
        self.pg_mask = pg_num_mask(self.pg_num)
        self.pgp_mask = pg_num_mask(self.pgp_num)

    def raw_pg_to_pps(self, ps: int | np.ndarray):
        """Placement seed: stable-mod onto pgp_num then mix with the
        pool id (the HASHPSPOOL behavior, the modern default)."""
        m = ceph_stable_mod(ps, self.pgp_num, self.pgp_mask)
        if isinstance(ps, np.ndarray):
            return np.asarray(hash32_2(m.astype(np.uint32),
                                       np.uint32(self.pool_id)))
        return int(hash32_2(np.uint32(m), np.uint32(self.pool_id)))


def _encode_pool(en, p: "PGPool") -> None:
    # v2 appends snap_seq + snaps, v3 quotas; compat 1 (old readers
    # skip the tail via the section length)
    en.start(3, 1)
    en.i32(p.pool_id).u32(p.pg_num).u32(p.size).u32(p.min_size)
    en.i32(p.crush_rule).boolean(p.is_erasure).u32(p.pgp_num)
    en.mapping(p.ec_profile, lambda e2, k: e2.string(k),
               lambda e2, v: e2.string(str(v)))
    en.u64(p.snap_seq)
    en.mapping(p.snaps, lambda e2, k: e2.u64(k),
               lambda e2, v: e2.string(v))
    en.u64(p.quota_max_bytes)
    en.u64(p.quota_max_objects)
    en.finish()


def _decode_pool(dd) -> "PGPool":
    pv = dd.start(3)
    p = PGPool(dd.i32(), dd.u32(), dd.u32(), dd.u32(), dd.i32(),
               dd.boolean(), dd.u32(),
               dd.mapping(lambda e2: e2.string(),
                          lambda e2: e2.string()))
    if pv >= 2:
        p.snap_seq = dd.u64()
        p.snaps = dd.mapping(lambda e2: e2.u64(),
                             lambda e2: e2.string())
    if pv >= 3:
        p.quota_max_bytes = dd.u64()
        p.quota_max_objects = dd.u64()
    dd.finish()
    return p


class OSDMap:
    """Cluster map: CRUSH topology + pools + per-OSD runtime state."""

    def __init__(self, crush: CrushMap, epoch: int = 1):
        self.crush = crush
        self.epoch = epoch
        self.pools: dict[int, PGPool] = {}
        n = crush.n_devices
        self.osd_weight = np.full(n, 0x10000, dtype=np.int32)  # in/out 16.16
        self.osd_up = np.ones(n, dtype=bool)
        # per-OSD up_thru (ref: osd_info_t::up_thru, recorded by
        # OSDMonitor on MOSDAlive): the newest epoch through which the
        # monitors have PROOF the OSD was up and serving. A primary
        # must get its up_thru recorded at (or past) its interval's
        # start epoch before the PG may go active — so peering can
        # later decide whether a past interval could possibly have
        # served writes (maybe_went_rw) without asking its dead
        # members (ref: PastIntervals::check_new_interval).
        self.osd_up_thru = np.zeros(n, dtype=np.int64)
        self.pg_temp: dict[tuple[int, int], list[int]] = {}
        self.primary_temp: dict[tuple[int, int], int] = {}
        # balancer overrides (ref: OSDMap pg_upmap_items + _apply_upmap)
        self.pg_upmap_items: dict[tuple[int, int],
                                  list[tuple[int, int]]] = {}
        # centralized config KV (role of the ConfigMonitor store, ref:
        # src/mon/ConfigMonitor.cc — `ceph config set` lands here).
        # Re-design: rather than a second PaxosService, the KV rides
        # the same replicated value the monitors already run Paxos
        # over; daemons apply it at their config system's "mon" layer
        # on every map commit (defaults < file < mon < override).
        self.config_kv: dict[str, str] = {}
        # monitor membership (role of the MonMap, ref: src/mon/
        # MonMap.h + MonmapMonitor.cc). Re-design, same pattern as
        # config_kv: rather than a second PaxosService with its own
        # epoch, the member list rides the one replicated value the
        # monitors run Paxos over — membership changes ARE map
        # commits, so quorum math moves atomically with the commit
        # that changes it.
        self.mon_members: list[int] = [0, 1, 2]
        # OSDs an ADMINISTRATOR marked out (`ceph osd out`): sticky
        # across daemon restarts, unlike the failure path's auto-out
        # which a boot reverses (ref: osd_state AUTOOUT vs admin
        # weight changes in OSDMonitor)
        self.osd_admin_out: set[int] = set()
        # r21 capacity plane: per-OSD fullness ladder state (osd ->
        # FULL_NEARFULL/BACKFILLFULL/FULL; absent = fine), the
        # cluster-wide FULL flag (any device at mon_osd_full_ratio —
        # clients park writes), and per-pool FULL flags from quota
        # enforcement (ref: OSDMAP_FULL + pg_pool_t FLAG_FULL)
        self.osd_full_state: dict[int, int] = {}
        self.cluster_full: bool = False
        self.full_pools: set[int] = set()
        self._vm = VectorMapper(crush)
        self._om = OracleMapper(crush)

    # -- wire form (ref: OSDMap::encode/decode) -----------------------------

    def encode(self) -> bytes:
        """Versioned wire form: epoch, crush map, per-OSD runtime state,
        pools, temp overrides (ref: src/osd/OSDMap.cc encode)."""
        from ..utils.encoding import Encoder
        # v2 appends pg_upmap_items, v3 config_kv, v4 mon_members,
        # v5 osd_admin_out, v6 osd_up_thru, v7 the capacity plane
        # (osd_full_state + cluster_full + full_pools); compat stays 1
        # (an old reader skips the tail via the section length — the
        # ENCODE_START contract)
        e = Encoder().start(7, 1)
        e.u32(self.epoch)
        e.blob(self.crush.encode())
        e.list([int(w) for w in self.osd_weight],
               lambda en, w: en.i32(w))
        e.list([bool(u) for u in self.osd_up],
               lambda en, u: en.boolean(u))
        e.list([self.pools[k] for k in sorted(self.pools)], _encode_pool)
        e.mapping(self.pg_temp,
                  lambda en, k: en.i32(k[0]).u32(k[1]),
                  lambda en, v: en.list(v, lambda e2, o: e2.i32(o)))
        e.mapping(self.primary_temp,
                  lambda en, k: en.i32(k[0]).u32(k[1]),
                  lambda en, v: en.i32(v))
        e.mapping(self.pg_upmap_items,
                  lambda en, k: en.i32(k[0]).u32(k[1]),
                  lambda en, v: en.list(
                      v, lambda e2, ft: e2.i32(ft[0]).i32(ft[1])))
        e.mapping(self.config_kv, lambda en, k: en.string(k),
                  lambda en, v: en.string(v))
        e.list(self.mon_members, lambda e2, r: e2.i32(r))
        e.list(sorted(self.osd_admin_out), lambda e2, o: e2.i32(o))
        e.list([int(t) for t in self.osd_up_thru],
               lambda e2, t: e2.u64(t))
        e.mapping({int(o): int(s)
                   for o, s in sorted(self.osd_full_state.items())},
                  lambda e2, o: e2.i32(o), lambda e2, s: e2.u32(s))
        e.boolean(self.cluster_full)
        e.list(sorted(self.full_pools), lambda e2, p: e2.i32(p))
        return e.finish().bytes()

    @classmethod
    def decode(cls, data: bytes) -> "OSDMap":
        from ..utils.encoding import Decoder
        d = Decoder(data)
        v = d.start(7)
        epoch = d.u32()
        crush = CrushMap.decode(d.blob())
        m = cls(crush, epoch=epoch)
        weights = d.list(lambda dd: dd.i32())
        ups = d.list(lambda dd: dd.boolean())
        m.osd_weight = np.asarray(weights, dtype=np.int32)
        m.osd_up = np.asarray(ups, dtype=bool)
        for p in d.list(_decode_pool):
            m.pools[p.pool_id] = p
        m.pg_temp = d.mapping(lambda dd: (dd.i32(), dd.u32()),
                              lambda dd: dd.list(lambda e2: e2.i32()))
        m.primary_temp = d.mapping(lambda dd: (dd.i32(), dd.u32()),
                                   lambda dd: dd.i32())
        if v >= 2:
            m.pg_upmap_items = d.mapping(
                lambda dd: (dd.i32(), dd.u32()),
                lambda dd: dd.list(lambda e2: (e2.i32(), e2.i32())))
        if v >= 3:
            m.config_kv = d.mapping(lambda dd: dd.string(),
                                    lambda dd: dd.string())
        if v >= 4:
            m.mon_members = d.list(lambda dd: dd.i32())
        if v >= 5:
            m.osd_admin_out = set(d.list(lambda dd: dd.i32()))
        if v >= 6:
            m.osd_up_thru = np.asarray(d.list(lambda dd: dd.u64()),
                                       dtype=np.int64)
        if v >= 7:
            m.osd_full_state = d.mapping(lambda dd: dd.i32(),
                                         lambda dd: dd.u32())
            m.cluster_full = d.boolean()
            m.full_pools = set(d.list(lambda dd: dd.i32()))
        d.finish()
        return m

    # -- mutators (each bumps the epoch like an inc map) -------------------

    def _bump(self):
        self.epoch += 1
        self.__dict__.pop("_placement_cache", None)

    def add_pool(self, pool: PGPool) -> None:
        if pool.crush_rule not in self.crush.rules:
            raise ValueError(f"pool rule {pool.crush_rule} not in crush map")
        self.pools[pool.pool_id] = pool
        self._bump()

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False
        self.clean_pg_upmaps()
        self._bump()

    def mark_up(self, osd: int) -> None:
        self.osd_up[osd] = True
        self._bump()

    def record_up_thru(self, osd: int, epoch: int | None = None) -> None:
        """Record that `osd` was up through `epoch` (default: the
        current epoch) — the OSDMonitor's MOSDAlive handling (ref:
        OSDMonitor::prepare_alive -> osd_info_t::up_thru). Monotone
        and idempotent: a stale or duplicate request rebases to a
        no-op on the proposal pipe."""
        epoch = self.epoch if epoch is None else int(epoch)
        if not self.osd_up[osd] or self.osd_up_thru[osd] >= epoch:
            return
        self.osd_up_thru[osd] = epoch
        self._bump()

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0
        self.clean_pg_upmaps()
        self._bump()

    def config_set(self, key: str, value: str) -> None:
        """Centralized `ceph config set` (ref: ConfigMonitor::
        prepare_command): idempotent — an unchanged value does not
        bump the epoch, so a replayed/duplicate op rebases to a
        no-op on the monitors' proposal pipe."""
        value = str(value)
        if self.config_kv.get(key) == value:
            return
        self.config_kv[key] = value
        self._bump()

    def mon_join(self, rank: int) -> None:
        """Admit a monitor to the quorum (ref: MonmapMonitor handling
        MMonJoin). Idempotent: a duplicate rebases to a no-op."""
        if rank in self.mon_members:
            return
        self.mon_members = sorted(self.mon_members + [rank])
        self._bump()

    def mon_leave(self, rank: int) -> None:
        """Remove a monitor from the quorum (`ceph mon remove`) —
        idempotent like mon_join."""
        if rank not in self.mon_members:
            return
        self.mon_members = [r for r in self.mon_members if r != rank]
        self._bump()

    def config_rm(self, key: str) -> None:
        """Centralized `ceph config rm` — idempotent like config_set."""
        if key not in self.config_kv:
            return
        del self.config_kv[key]
        self._bump()

    def set_pg_upmap_items(self, pg: tuple[int, int],
                           items: list[tuple[int, int]]) -> None:
        """Balancer override: per-PG (from_osd, to_osd) redirects
        (ref: `ceph osd pg-upmap-items`). Empty list clears."""
        if items:
            self.pg_upmap_items[pg] = [(int(f), int(t)) for f, t in items]
        else:
            self.pg_upmap_items.pop(pg, None)
        self._bump()

    def set_pg_upmap_bulk(self, updates: dict) -> None:
        """Apply MANY per-PG upmap overrides as ONE map epoch — the
        shape a balancer round lands in the real cluster (one monitor
        commit carries the whole batch, not one epoch per PG). Empty
        item lists clear their entries."""
        if not updates:
            return
        for pg, items in updates.items():
            if items:
                self.pg_upmap_items[pg] = [(int(f), int(t))
                                           for f, t in items]
            else:
                self.pg_upmap_items.pop(pg, None)
        self._bump()

    def clean_pg_upmaps(self) -> None:
        """Drop upmap entries that can no longer be honored (ref:
        OSDMap::clean_pg_upmaps + OSDMonitor maybe_remove_pg_upmaps,
        run on map changes so stale balancer decisions never pin data
        to dead devices): a redirect dies when its target OSD is out
        OR down (a down target cannot serve the shard it pins), and a
        whole entry dies when its pool is gone or its ps outgrew the
        pool's pg space."""
        for pg, items in list(self.pg_upmap_items.items()):
            pool = self.pools.get(pg[0])
            if pool is None or pg[1] >= pool.pg_num:
                del self.pg_upmap_items[pg]
                continue
            kept = [(f, t) for f, t in items
                    if t < len(self.osd_weight)
                    and self.osd_weight[t] > 0 and self.osd_up[t]]
            if len(kept) != len(items):
                if kept:
                    self.pg_upmap_items[pg] = kept
                else:
                    del self.pg_upmap_items[pg]

    def remove_pool(self, pool_id: int) -> None:
        """Delete a pool and every per-PG override keyed to it (ref:
        OSDMonitor pool deletion -> OSDMap::Incremental old_pools).
        Idempotent: removing an absent pool is a no-op."""
        if pool_id not in self.pools:
            return
        del self.pools[pool_id]
        for d in (self.pg_temp, self.primary_temp, self.pg_upmap_items):
            for pg in [k for k in d if k[0] == pool_id]:
                del d[pg]
        self._bump()

    def mark_in(self, osd: int, weight: float = 1.0) -> None:
        self.osd_weight[osd] = int(weight * 0x10000)
        self._bump()

    def pool_mksnap(self, pool_id: int, name: str) -> None:
        """Take a named pool snapshot (ref: OSDMonitor pool mksnap ->
        pg_pool_t::add_snap). Idempotent by NAME so the same request
        queued on several monitors commits exactly one snap."""
        p = self.pools[pool_id]
        if name in p.snaps.values():
            return
        p.snap_seq += 1
        p.snaps[p.snap_seq] = name
        self._bump()

    def pool_rmsnap(self, pool_id: int, name: str) -> None:
        p = self.pools[pool_id]
        sids = [s for s, n in p.snaps.items() if n == name]
        if not sids:
            return
        for s in sids:
            del p.snaps[s]
        self._bump()

    def set_pg_temp(self, pg: tuple[int, int], acting: list[int]) -> None:
        if acting:
            self.pg_temp[pg] = list(acting)
        else:
            self.pg_temp.pop(pg, None)
        self._bump()

    def set_pg_num(self, pool_id: int, pg_num: int) -> None:
        """Grow a pool's pg_num (and pgp_num with it) — the map half of
        a PG split (ref: src/mon/OSDMonitor.cc pg_num handling). The
        stable_mod hash space makes this cheap: surviving parents keep
        their ps (stable_mod is the identity below the old pg_num), so
        only split-off children remap. Shrinking (PG merge) is not
        supported."""
        pool = self.pools[pool_id]
        if pg_num < pool.pg_num:
            raise ValueError(f"pg_num {pg_num} < current {pool.pg_num}: "
                             f"merges not supported")
        if pg_num == pool.pg_num:
            return
        pool.pg_num = pool.pgp_num = pg_num
        pool.pg_mask = pool.pgp_mask = pg_num_mask(pg_num)
        self._bump()

    def set_primary_temp(self, pg: tuple[int, int], osd: int | None) -> None:
        if osd is None:
            self.primary_temp.pop(pg, None)
        else:
            self.primary_temp[pg] = osd
        self._bump()

    # -- capacity plane (r21) -----------------------------------------------

    def full_state_of(self, osd: int) -> int:
        """Ladder state of one OSD (FULL_NONE when unlisted)."""
        return self.osd_full_state.get(int(osd), FULL_NONE)

    def set_full_states(self, osd_states: dict[int, int],
                        cluster_full: bool,
                        full_pools) -> None:
        """Commit the leader's evaluated ladder in ONE epoch (per-OSD
        states + cluster flag + quota-tripped pools). Idempotent: the
        closure rebases to a no-op when the committed map already
        carries the same evaluation — the ladder re-runs every leader
        tick and must not churn epochs."""
        osd_states = {int(o): int(s) for o, s in osd_states.items()
                      if int(s) != FULL_NONE}
        cluster_full = bool(cluster_full)
        full_pools = {int(p) for p in full_pools}
        if (osd_states == self.osd_full_state
                and cluster_full == self.cluster_full
                and full_pools == self.full_pools):
            return
        self.osd_full_state = osd_states
        self.cluster_full = cluster_full
        self.full_pools = full_pools
        self._bump()

    def set_pool_quota(self, pool_id: int, max_bytes: int,
                       max_objects: int) -> None:
        """`ceph osd pool set-quota` — idempotent like config_set."""
        p = self.pools[pool_id]
        max_bytes, max_objects = int(max_bytes), int(max_objects)
        if (p.quota_max_bytes, p.quota_max_objects) \
                == (max_bytes, max_objects):
            return
        p.quota_max_bytes = max_bytes
        p.quota_max_objects = max_objects
        self._bump()

    # -- object -> PG -------------------------------------------------------

    def object_to_pg(self, pool_id: int, name: bytes | str) -> tuple[int, int]:
        pool = self.pools[pool_id]
        ps = ceph_stable_mod(str_hash_rjenkins(name), pool.pg_num,
                             pool.pg_mask)
        return (pool_id, ps)

    # -- PG -> OSDs ---------------------------------------------------------

    def _raw_pg_to_osds(self, pool: PGPool, ps: int) -> list[int]:
        pps = pool.raw_pg_to_pps(ps)
        out = self._om.do_rule(pool.crush_rule, pps, self.osd_weight,
                               pool.size)
        return (out + [CRUSH_ITEM_NONE] * pool.size)[:pool.size]

    def _apply_upmap(self, pool_id: int, ps: int,
                     raw: list[int]) -> list[int]:
        """pg_upmap_items overrides (ref: OSDMap::_apply_upmap): each
        (from, to) pair redirects that OSD's slot for this PG — the
        balancer's fine-grained placement override."""
        items = self.pg_upmap_items.get((pool_id, ps))
        if not items:
            return raw
        out = list(raw)
        for frm, to in items:
            if to in out:
                continue  # a duplicate target would break slot sets
            for i, o in enumerate(out):
                if o == frm:
                    out[i] = to
                    break
        return out

    def _up_from_raw(self, raw: list[int]) -> list[int]:
        """raw -> up: down OSDs become NONE holes (EC keeps slot order;
        the reference filters in _raw_to_up_osds)."""
        return [o if (o != CRUSH_ITEM_NONE and o < len(self.osd_up)
                      and self.osd_up[o]) else CRUSH_ITEM_NONE for o in raw]

    @staticmethod
    def _primary_of(osds: list[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def pg_to_up_acting_osds(self, pool_id: int, ps: int):
        """Returns (up, up_primary, acting, acting_primary) — the full
        override pipeline: raw CRUSH -> drop down OSDs -> pg_temp /
        primary_temp. Memoized per epoch: placement is pure in the map
        state, and the wire tier recomputes it on every client op and
        daemon dispatch (the CRUSH walk dominated the plain-mode rados
        bench profile); any mutation clears the cache via _bump."""
        cache = self.__dict__.setdefault("_placement_cache", {})
        hit = cache.get((pool_id, ps))
        if hit is not None:
            return hit
        pool = self.pools[pool_id]
        raw = self._apply_upmap(pool_id, ps,
                                self._raw_pg_to_osds(pool, ps))
        up = self._up_from_raw(raw)
        up_primary = self._primary_of(up)
        acting = self.pg_temp.get((pool_id, ps), up)
        acting_primary = self.primary_temp.get((pool_id, ps),
                                               self._primary_of(acting))
        out = (up, up_primary, acting, acting_primary)
        cache[(pool_id, ps)] = out
        return out

    def pg_to_acting_osds(self, pool_id: int, ps: int) -> list[int]:
        return self.pg_to_up_acting_osds(pool_id, ps)[2]

    # -- batched PG -> OSDs (the TPU path) ----------------------------------

    def pgs_to_raw(self, pool_id: int, ps: np.ndarray | None = None):
        """Raw CRUSH output for ALL (or the given) PGs of a pool in one
        vectorized launch: NO upmap overlay, NO down-filtering — the
        balancer's ground truth (a down-but-in member still owns its
        slot, and failure-domain math must derive from it)."""
        pool = self.pools[pool_id]
        if ps is None:
            ps = np.arange(pool.pg_num, dtype=np.uint32)
        ps = np.asarray(ps, np.uint32)
        pps = pool.raw_pg_to_pps(ps)
        raw = np.asarray(self._vm.do_rule(pool.crush_rule, pps,
                                          self.osd_weight, pool.size))
        return raw[:, :pool.size].copy()

    def pgs_to_up(self, pool_id: int, ps: np.ndarray | None = None):
        """Map ALL (or the given) PGs of a pool in one vectorized launch.

        Returns (B, size) int32 UP sets with CRUSH_ITEM_NONE holes.
        Like the scalar path, pg_temp does NOT affect up — it only
        overrides acting (see pgs_to_acting).
        """
        pool = self.pools[pool_id]
        if ps is None:
            ps = np.arange(pool.pg_num, dtype=np.uint32)
        ps = np.asarray(ps, np.uint32)
        raw = self.pgs_to_raw(pool_id, ps)
        if self.pg_upmap_items:
            # sparse host-side overlay (like pg_temp in pgs_to_acting):
            # upmaps are rare relative to pg_num
            pos_of = {int(p): i for i, p in enumerate(ps)}
            for (pid, s), items in self.pg_upmap_items.items():
                if pid != pool_id or s not in pos_of:
                    continue
                raw[pos_of[s]] = self._apply_upmap(
                    pid, s, [int(o) for o in raw[pos_of[s]]])
        # down OSDs -> NONE
        down_lut = ~self.osd_up
        idx = np.clip(raw, 0, len(self.osd_up) - 1)
        is_down = np.where(raw >= 0, down_lut[idx], False)
        return np.where(is_down, np.int32(CRUSH_ITEM_NONE), raw)

    def pgs_to_acting(self, pool_id: int, ps: np.ndarray | None = None):
        """Batched acting sets: up overridden by the sparse pg_temp
        entries (host-side; backfill state is rare and transient)."""
        pool = self.pools[pool_id]
        if ps is None:
            ps = np.arange(pool.pg_num, dtype=np.uint32)
        ps = np.asarray(ps, np.uint32)
        acting = self.pgs_to_up(pool_id, ps).copy()
        for (pid, s), override in self.pg_temp.items():
            if pid == pool_id:
                hit = np.nonzero(ps == s)[0]
                if hit.size:
                    row = (list(override) + [CRUSH_ITEM_NONE] * pool.size)
                    acting[hit[0]] = row[:pool.size]
        return acting

    def pg_stats(self, pool_id: int):
        """Placement summary over the whole pool: per-OSD PG counts and
        degraded (holey) PG count — what `ceph osd df` surfaces."""
        up = self.pgs_to_up(pool_id)
        real = up[up != CRUSH_ITEM_NONE]
        counts = np.bincount(real, minlength=len(self.osd_up))
        degraded = int((up == CRUSH_ITEM_NONE).any(axis=1).sum())
        return {"pg_per_osd": counts, "degraded_pgs": degraded}

    # -- cloning / comparison ------------------------------------------------

    def shallow_clone(self) -> "OSDMap":
        """Structural copy sharing the (immutable-in-practice) CRUSH
        map and its compiled mappers: O(n_osds) array copies + dict
        copies, no re-decode. This is what an incremental apply
        mutates so readers holding the old map object never see a
        half-applied epoch."""
        c = object.__new__(OSDMap)
        c.crush = self.crush
        c.epoch = self.epoch
        c.pools = {
            pid: PGPool(p.pool_id, p.pg_num, p.size, p.min_size,
                        p.crush_rule, p.is_erasure, p.pgp_num,
                        dict(p.ec_profile), p.snap_seq, dict(p.snaps),
                        p.quota_max_bytes, p.quota_max_objects)
            for pid, p in self.pools.items()}
        c.osd_weight = self.osd_weight.copy()
        c.osd_up = self.osd_up.copy()
        c.osd_up_thru = self.osd_up_thru.copy()
        c.pg_temp = {k: list(v) for k, v in self.pg_temp.items()}
        c.primary_temp = dict(self.primary_temp)
        c.pg_upmap_items = {k: list(v)
                            for k, v in self.pg_upmap_items.items()}
        c.config_kv = dict(self.config_kv)
        c.mon_members = list(self.mon_members)
        c.osd_admin_out = set(self.osd_admin_out)
        c.osd_full_state = dict(self.osd_full_state)
        c.cluster_full = self.cluster_full
        c.full_pools = set(self.full_pools)
        c._vm = self._vm
        c._om = self._om
        return c


def same_state(a: "OSDMap", b: "OSDMap") -> bool:
    """Canonical (order-insensitive) equality of two maps — what the
    incremental-map property tests pin: a follower that applied the
    delta chain must be indistinguishable from the leader. Byte
    equality of encode() is NOT required (mapping sections ride dict
    insertion order, which legitimately differs across histories)."""
    if a.epoch != b.epoch or a.pools != b.pools:
        return False
    if a.osd_weight.tolist() != b.osd_weight.tolist() \
            or a.osd_up.tolist() != b.osd_up.tolist() \
            or a.osd_up_thru.tolist() != b.osd_up_thru.tolist():
        return False
    if a.pg_temp != b.pg_temp or a.primary_temp != b.primary_temp \
            or a.pg_upmap_items != b.pg_upmap_items:
        return False
    if a.config_kv != b.config_kv or a.mon_members != b.mon_members \
            or a.osd_admin_out != b.osd_admin_out:
        return False
    if a.osd_full_state != b.osd_full_state \
            or a.cluster_full != b.cluster_full \
            or a.full_pools != b.full_pools:
        return False
    return (a.crush is b.crush) or a.crush.encode() == b.crush.encode()


class Incremental:
    """OSDMap delta — the epoch-to-epoch wire unit (ref: src/osd/
    OSDMap.h OSDMap::Incremental — new_up_client/new_weight/new_state,
    new_pg_temp, new_pg_upmap_items, new_pools/old_pools, fullmap
    fallback; distributed by the monitors so map churn at 10k OSDs
    ships deltas instead of full maps).

    Construction is diff-based (`Incremental.diff(old, new)`): the
    monitors' mutate closures already produce the post-change map, so
    the delta is derived rather than accumulated — one code path no
    matter which mutator ran. A CRUSH topology change (rare: device
    add at the crush level) falls back to carrying the full map blob,
    exactly the reference's `fullmap` member.

    Erase sentinels: pg_temp/pg_upmap_items erase as empty lists,
    primary_temp as -1 — the same convention the mutators use.
    """

    def __init__(self, epoch: int, base_epoch: int):
        self.epoch = epoch
        self.base_epoch = base_epoch
        self.full_blob: bytes | None = None
        self.new_up: list[int] = []
        self.new_down: list[int] = []
        self.new_weights: dict[int, int] = {}
        self.new_up_thru: dict[int, int] = {}
        self.new_pools: list[PGPool] = []
        self.removed_pools: list[int] = []
        self.new_pg_temp: dict[tuple[int, int], list[int]] = {}
        self.new_primary_temp: dict[tuple[int, int], int] = {}
        self.new_pg_upmap_items: dict[tuple[int, int],
                                      list[tuple[int, int]]] = {}
        self.new_config: dict[str, str] = {}
        self.removed_config: list[str] = []
        self.new_mon_members: list[int] | None = None
        self.new_admin_out: list[int] | None = None
        # r21 capacity plane: full-replacement deltas (the state is
        # O(n_osds) at worst, and a partial merge could resurrect a
        # cleared flag) — presence-boolean encoded like mon_members
        self.new_full_state: dict[int, int] | None = None
        self.new_cluster_full: bool | None = None
        self.new_full_pools: list[int] | None = None

    # -- construction --------------------------------------------------------

    @classmethod
    def diff(cls, old: "OSDMap", new: "OSDMap") -> "Incremental":
        inc = cls(new.epoch, old.epoch)
        crush_same = (old.crush is new.crush) \
            or old.crush.encode() == new.crush.encode()
        if not crush_same or len(old.osd_up) != len(new.osd_up):
            # topology changed: ship the full map (the reference's
            # Incremental::fullmap escape hatch)
            inc.full_blob = new.encode()
            return inc
        for o in np.nonzero(old.osd_up != new.osd_up)[0]:
            (inc.new_up if new.osd_up[o] else inc.new_down).append(int(o))
        for o in np.nonzero(old.osd_weight != new.osd_weight)[0]:
            inc.new_weights[int(o)] = int(new.osd_weight[o])
        for o in np.nonzero(old.osd_up_thru != new.osd_up_thru)[0]:
            inc.new_up_thru[int(o)] = int(new.osd_up_thru[o])
        for pid, p in new.pools.items():
            if old.pools.get(pid) != p:
                inc.new_pools.append(p)
        inc.removed_pools = sorted(pid for pid in old.pools
                                   if pid not in new.pools)
        for attr, out, erase in (
                ("pg_temp", inc.new_pg_temp, []),
                ("primary_temp", inc.new_primary_temp, -1),
                ("pg_upmap_items", inc.new_pg_upmap_items, [])):
            od, nd = getattr(old, attr), getattr(new, attr)
            for k, v in nd.items():
                if od.get(k) != v:
                    out[k] = v
            for k in od:
                if k not in nd:
                    out[k] = erase
        for k, v in new.config_kv.items():
            if old.config_kv.get(k) != v:
                inc.new_config[k] = v
        inc.removed_config = sorted(k for k in old.config_kv
                                    if k not in new.config_kv)
        if old.mon_members != new.mon_members:
            inc.new_mon_members = list(new.mon_members)
        if old.osd_admin_out != new.osd_admin_out:
            inc.new_admin_out = sorted(new.osd_admin_out)
        if old.osd_full_state != new.osd_full_state:
            inc.new_full_state = dict(new.osd_full_state)
        if old.cluster_full != new.cluster_full:
            inc.new_cluster_full = new.cluster_full
        if old.full_pools != new.full_pools:
            inc.new_full_pools = sorted(new.full_pools)
        return inc

    # -- application ---------------------------------------------------------

    def apply(self, m: "OSDMap") -> "OSDMap":
        """Apply onto `m` (must sit at base_epoch) and return the
        post-change map. The delta path mutates `m` IN PLACE —
        callers wanting atomicity clone first (shallow_clone); the
        full-map fallback returns a fresh decode."""
        if m.epoch != self.base_epoch:
            raise ValueError(f"incremental base {self.base_epoch} "
                             f"!= map epoch {m.epoch}")
        if self.full_blob is not None:
            return OSDMap.decode(self.full_blob)
        for o in self.new_up:
            m.osd_up[o] = True
        for o in self.new_down:
            m.osd_up[o] = False
        for o, w in self.new_weights.items():
            m.osd_weight[o] = w
        for o, t in self.new_up_thru.items():
            m.osd_up_thru[o] = t
        for p in self.new_pools:
            m.pools[p.pool_id] = p
        for pid in self.removed_pools:
            m.pools.pop(pid, None)
        for pg, v in self.new_pg_temp.items():
            if v:
                m.pg_temp[pg] = list(v)
            else:
                m.pg_temp.pop(pg, None)
        for pg, o in self.new_primary_temp.items():
            if o >= 0:
                m.primary_temp[pg] = o
            else:
                m.primary_temp.pop(pg, None)
        for pg, items in self.new_pg_upmap_items.items():
            if items:
                m.pg_upmap_items[pg] = [(int(f), int(t))
                                        for f, t in items]
            else:
                m.pg_upmap_items.pop(pg, None)
        for k, v in self.new_config.items():
            m.config_kv[k] = v
        for k in self.removed_config:
            m.config_kv.pop(k, None)
        if self.new_mon_members is not None:
            m.mon_members = list(self.new_mon_members)
        if self.new_admin_out is not None:
            m.osd_admin_out = set(self.new_admin_out)
        if self.new_full_state is not None:
            m.osd_full_state = dict(self.new_full_state)
        if self.new_cluster_full is not None:
            m.cluster_full = self.new_cluster_full
        if self.new_full_pools is not None:
            m.full_pools = set(self.new_full_pools)
        m.epoch = self.epoch
        m.__dict__.pop("_placement_cache", None)
        return m

    # -- wire form -----------------------------------------------------------

    def encode(self) -> bytes:
        from ..utils.encoding import Encoder
        e = Encoder().start(2, 1)
        e.u32(self.epoch).u32(self.base_epoch)
        e.boolean(self.full_blob is not None)
        if self.full_blob is not None:
            e.blob(self.full_blob)
            return e.finish().bytes()
        def enc_pg(en, k):
            en.i32(k[0]).u32(k[1])
        e.list(self.new_up, lambda en, o: en.i32(o))
        e.list(self.new_down, lambda en, o: en.i32(o))
        e.mapping(self.new_weights, lambda en, k: en.i32(k),
                  lambda en, v: en.i32(v))
        e.mapping(self.new_up_thru, lambda en, k: en.i32(k),
                  lambda en, v: en.u64(v))
        e.list(self.new_pools, _encode_pool)
        e.list(self.removed_pools, lambda en, p: en.i32(p))
        e.mapping(self.new_pg_temp, enc_pg,
                  lambda en, v: en.list(v, lambda e2, o: e2.i32(o)))
        e.mapping(self.new_primary_temp, enc_pg,
                  lambda en, v: en.i32(v))
        e.mapping(self.new_pg_upmap_items, enc_pg,
                  lambda en, v: en.list(
                      v, lambda e2, ft: e2.i32(ft[0]).i32(ft[1])))
        e.mapping(self.new_config, lambda en, k: en.string(k),
                  lambda en, v: en.string(v))
        e.list(self.removed_config, lambda en, k: en.string(k))
        e.boolean(self.new_mon_members is not None)
        if self.new_mon_members is not None:
            e.list(self.new_mon_members, lambda en, r: en.i32(r))
        e.boolean(self.new_admin_out is not None)
        if self.new_admin_out is not None:
            e.list(self.new_admin_out, lambda en, o: en.i32(o))
        e.boolean(self.new_full_state is not None)
        if self.new_full_state is not None:
            e.mapping({int(o): int(s)
                       for o, s in sorted(self.new_full_state.items())},
                      lambda e2, o: e2.i32(o), lambda e2, s: e2.u32(s))
        e.boolean(self.new_cluster_full is not None)
        if self.new_cluster_full is not None:
            e.boolean(self.new_cluster_full)
        e.boolean(self.new_full_pools is not None)
        if self.new_full_pools is not None:
            e.list(self.new_full_pools, lambda e2, p: e2.i32(p))
        return e.finish().bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Incremental":
        from ..utils.encoding import Decoder
        d = Decoder(data)
        v = d.start(2)
        inc = cls(d.u32(), d.u32())
        if d.boolean():
            inc.full_blob = d.blob()
            d.finish()
            return inc
        def dec_pg(dd):
            return (dd.i32(), dd.u32())
        inc.new_up = d.list(lambda dd: dd.i32())
        inc.new_down = d.list(lambda dd: dd.i32())
        inc.new_weights = d.mapping(lambda dd: dd.i32(),
                                    lambda dd: dd.i32())
        inc.new_up_thru = d.mapping(lambda dd: dd.i32(),
                                    lambda dd: dd.u64())
        inc.new_pools = d.list(_decode_pool)
        inc.removed_pools = d.list(lambda dd: dd.i32())
        inc.new_pg_temp = d.mapping(
            dec_pg, lambda dd: dd.list(lambda e2: e2.i32()))
        inc.new_primary_temp = d.mapping(dec_pg, lambda dd: dd.i32())
        inc.new_pg_upmap_items = d.mapping(
            dec_pg,
            lambda dd: dd.list(lambda e2: (e2.i32(), e2.i32())))
        inc.new_config = d.mapping(lambda dd: dd.string(),
                                   lambda dd: dd.string())
        inc.removed_config = d.list(lambda dd: dd.string())
        if d.boolean():
            inc.new_mon_members = d.list(lambda dd: dd.i32())
        if d.boolean():
            inc.new_admin_out = d.list(lambda dd: dd.i32())
        if v >= 2:
            if d.boolean():
                inc.new_full_state = d.mapping(lambda dd: dd.i32(),
                                               lambda dd: dd.u32())
            if d.boolean():
                inc.new_cluster_full = d.boolean()
            if d.boolean():
                inc.new_full_pools = d.list(lambda dd: dd.i32())
        d.finish()
        return inc
