"""PGBackend — the replicated-vs-erasure backend abstraction.

Rebuild of the reference's per-PG backend split (ref: src/osd/PGBackend.h
— PGBackend with submit_transaction / objects_read_async /
recover_object / be_deep_scrub, subclassed by ReplicatedBackend
(src/osd/ReplicatedBackend.{h,cc}) and ECBackend (src/osd/ECBackend.cc)).

The shared machinery both backends need — per-slot store plumbing, the
PG mutation log with per-shard applied cursors (staleness gating), the
min-size write gate — lives here; ECBackend (osd/ecbackend.py) and
ReplicatedBackend (below) differ only in how bytes are laid out across
the acting set:

* ReplicatedBackend: slot i holds a FULL copy of every object; writes
  fan the same bytes out, reads come from any caught-up live replica,
  recovery is a verified copy (push) from a surviving replica.
* ECBackend: slot i holds shard i of the stripe; writes encode, reads/
  recovery decode.

TPU-first shaping: the replicated path has no GF math, but its integrity
surface is the same batched checksum workload — full-object crc32c
digests (the role of object_info_t's data_digest) computed in one
device launch per equal-length group, both on write and on deep scrub.

Both backends expose the same surface, so SimCluster (osd/cluster.py)
drives either pool type through one code path — exactly how the
reference's PrimaryLogPG calls through the PGBackend interface without
knowing which backend it has.
"""

from __future__ import annotations

import numpy as np

from .memstore import MemStore, Transaction
from .pglog import PGLog
from .stripe import HashInfo, as_flat_u8

HINFO_KEY = "hinfo_key"  # same xattr name role as the reference


def shard_cid(pg: str, shard: int) -> str:
    """Collection name of one PG shard (role of spg_t's shard id)."""
    return f"{pg}s{shard}"


class PGBackend:
    """Common base: store plumbing + PG-log bookkeeping (ref:
    src/osd/PGBackend.h contract; log semantics ref: src/osd/PGLog.h)."""

    #: live slots a write needs before it may proceed (the pool
    #: min_size gate); subclasses set it in __init__
    min_live: int = 1

    def _init_common(self, pg: str, acting: list[int], cluster,
                     ensure_collections: bool = True) -> None:
        self.pg = pg
        self.acting = list(acting)
        self.n = len(acting)
        self.cluster = cluster
        if ensure_collections:
            # ensure_collections=False builds a READ-ONLY view (the
            # degraded-read fast path): no store mutation, and no txn
            # to an acting member that may be dead-but-not-yet-marked
            # (the collections already exist on every real member)
            for shard, osd in enumerate(self.acting):
                t = Transaction().create_collection(shard_cid(pg, shard))
                self.cluster.osd(osd).queue_transaction(t)
        self.object_sizes: dict[str, int] = {}  # authoritative size info
        # mutation log + per-shard applied cursor (ref: PGLog /
        # peering's last_update per shard): a shard that missed writes
        # replays just the delta on rejoin
        self.pg_log = PGLog()
        self.shard_applied = [0] * self.n
        self.object_versions: dict[str, int] = {}  # name -> last version

    # -- shared helpers ------------------------------------------------------

    def _store(self, shard: int) -> MemStore:
        return self.cluster.osd(self.acting[shard])

    def _live_slots(self, dead_osds: set[int] | None) -> list[int]:
        dead = dead_osds or set()
        return [s for s in range(self.n) if self.acting[s] not in dead]

    def _log_write(self, name: str, live: list[int]) -> None:
        """Append to the PG log and advance the applied cursor of every
        shard that received this write (down shards stay behind and
        replay the delta on rejoin).

        The cursor only advances CONTIGUOUSLY: a live-but-behind shard
        (revived, replay still pending) receives the new bytes but
        keeps its old cursor, else its gap would silently close and
        reads could select it as fresh for objects it missed (the
        reference keeps last_update + an explicit missing set; our
        conservative cursor re-replays a little instead)."""
        v = self.pg_log.append(name)
        self.object_versions[name] = v
        for s in live:
            if self.shard_applied[s] == v - 1:
                self.shard_applied[s] = v

    def _fresh_for(self, names: list[str], shards: list[int]) -> list[int]:
        """Shards (from `shards`) whose applied cursor covers the last
        write of every object in `names` — a shard that was down across
        a write holds STALE bytes for it and must not serve reads or
        helper gathers until it replays (ref: peering's missing-set)."""
        need = max((self.object_versions.get(n, 0) for n in names),
                   default=0)
        return [s for s in shards if self.shard_applied[s] >= need]

    def _fanout_txns(self, items) -> None:
        """Apply [(shard, Transaction)] across the acting set,
        PIPELINED where the store supports it (RemoteStore at the wire
        tier): every txn is transmitted before any ack is awaited, so
        the fan-out costs one overlapped round trip instead of
        len(items) sequential ones (the reference dispatches its
        MOSDECSubOpWrite sub-ops in parallel too). Durability point
        unchanged — this returns only after EVERY shard acked, and a
        shard failure raises exactly like the sequential loop did.
        In-process stores (MemStore/TinStore) take the sync path."""
        waits: list = []
        first_err: BaseException | None = None
        for shard, t in items:
            st = self._store(shard)
            submit = getattr(st, "queue_transaction_async", None)
            try:
                if submit is not None:
                    waits.append(submit(t))
                else:
                    st.queue_transaction(t)
            except (ConnectionError, OSError) as e:
                first_err = first_err or e
        for h in waits:
            try:
                h.result()
            except (ConnectionError, OSError) as e:
                first_err = first_err or e
        if first_err is not None:
            raise first_err

    def _check_min_size(self, live: list[int]) -> None:
        """Writes need >= min_live receiving slots or the PG goes
        inactive and blocks I/O (the pool min_size gate). Counts
        DISTINCT OSDs, not slots: mid-backfill an OSD can temporarily
        hold two slots, and two copies on one disk are one failure
        domain, not two."""
        distinct = len({self.acting[s] for s in live})
        if distinct < self.min_live:
            raise ValueError(
                f"PG below min_size: {distinct} live shards < "
                f"min_size={self.min_live}; write refused (pg inactive)")

    @staticmethod
    def _batched_crcs(blocks: np.ndarray) -> np.ndarray:
        """One device launch for a (B, L) stack of byte rows -> (B,)
        uint32 CRCs (raw register, seed -1 — the HashInfo convention).
        The row count is bucketed to a power of two: per-PG batches
        vary freely and each distinct B would otherwise compile its
        own program."""
        from ..csum.kernels import crc32c_blocks
        from ..ops.rs_kernels import run_bucketed
        return np.asarray(run_bucketed(
            lambda b: crc32c_blocks(b, init=0xFFFFFFFF, xorout=0),
            np.asarray(blocks, dtype=np.uint8)))

    def _remove_strays(self, dead: set[int]) -> int:
        """Remove per-slot leftover objects the PG's metadata no
        longer knows: divergent dead-interval writes kept by a member
        that rejoined as a NON-primary (only the restoring primary
        runs the divergent-log rewind), or delete leftovers a trimmed
        log can never replay. Ref: PrimaryLogPG's stray/unexpected
        object handling on scrub repair."""
        removed = 0
        for s in range(self.n):
            if self.acting[s] in dead:
                continue
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            strays = [n for n in st.list_objects(cid)
                      if not n.startswith("__")
                      and n not in self.object_sizes]
            if not strays:
                continue
            t = Transaction()   # one combined txn (one wire frame)
            for name in strays:
                t.remove(cid, name)
            st.queue_transaction(t)
            removed += len(strays)
        return removed

    # -- contract (ref: PGBackend.h pure virtuals) ---------------------------

    def write_objects(self, objects, dead_osds=None) -> None:
        raise NotImplementedError

    def write_ranges(self, ops, dead_osds=None) -> None:
        raise NotImplementedError

    def write_at(self, name: str, offset: int, data,
                 dead_osds: set[int] | None = None) -> None:
        self.write_ranges([(name, offset, data)], dead_osds)

    def append_objects(self, appends, dead_osds=None) -> None:
        """Append streams: each name's bytes land at its current tail
        (creating absent objects at offset 0). On an EC pool a tail
        landing inside the padded stripe is the RMW append fast path:
        the pre-image is zeros by the layout rule, so no read phase
        and only the tail data shard + m parity shards move."""
        self.write_ranges(
            [(name, self.object_sizes.get(name, 0), data)
             for name, data in appends.items()], dead_osds)

    def read_objects(self, names, dead_osds=None) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def read_object(self, name: str,
                    dead_osds: set[int] | None = None) -> np.ndarray:
        return self.read_objects([name], dead_osds)[name]

    def remove_objects(self, names, dead_osds=None) -> None:
        """Delete objects from every live slot. A remove is a LOGGED
        mutation (ref: pg_log_entry_t DELETE): a shard that was down
        across it replays the delete on rejoin instead of resurrecting
        a stale copy."""
        live = self._live_slots(dead_osds)
        self._check_min_size(live)
        names = list(names)
        # validate the whole batch before mutating anything (the
        # recover_shards convention): a bad name mid-batch must not
        # leave a half-applied, half-logged delete
        for name in names:
            if name not in self.object_sizes:
                raise KeyError(f"no object {name!r}")
        # ONE combined txn per shard for the whole batch (the window's
        # store-apply unit — the per-name loop cost B*n transactions
        # and B*n wire frames where n now suffice; ROADMAP item 2b's
        # `store.apply` wall), fanned out pipelined
        seen: set[str] = set()
        doomed: list[str] = []
        for name in names:
            if name not in seen:
                seen.add(name)
                doomed.append(name)
        txns = []
        for s in live:
            t = Transaction()
            for name in doomed:
                t.remove(shard_cid(self.pg, s), name)
            txns.append((s, t))
        self._fanout_txns(txns)
        for name in doomed:
            del self.object_sizes[name]
            self._log_write(name, live)

    def stat_object(self, name: str) -> int:
        """Logical object size (the rados_stat role)."""
        return self.object_sizes[name]

    def list_pg_objects(self) -> list[str]:
        return sorted(self.object_sizes)

    def split_to(self, child: "PGBackend", names) -> int:
        """PG split, the data half (ref: src/osd/PG.cc split machinery;
        on-disk it is a LOCAL collection split — no bytes cross OSDs):
        move `names`' shards store-locally from this PG's collections
        into `child`'s, carrying the hinfo xattrs, and log the transfer
        on both sides (child: create entries; parent: delete entries)
        so later delta-rejoins replay exactly. The child must start on
        the parent's acting set — relocation to its own CRUSH targets
        is the cluster layer's pg_temp-protected backfill, afterwards.

        Caller contract: every shard caught up (a clean PG) — enforced
        here because a behind shard would silently split a stale copy.
        """
        if child.acting != self.acting:
            raise ValueError("split child must start on the parent's "
                             "acting set")
        for s in range(self.n):
            if self.shard_applied[s] < self.pg_log.head:
                raise ValueError(
                    f"shard {s} is behind (applied "
                    f"{self.shard_applied[s]} < head {self.pg_log.head}); "
                    f"split requires a clean PG")
        moved = [n for n in names if n in self.object_sizes]
        for s in range(self.n):
            st = self._store(s)
            src = shard_cid(self.pg, s)
            dst = shard_cid(child.pg, s)
            t = Transaction()
            for name in moved:
                if not st.exists(src, name):
                    # clean PG + absent store entry = the zero-length
                    # convention; mirror it on the child WITH an empty
                    # hinfo — deep scrub reads the xattr unguarded
                    t.touch(dst, name).truncate(dst, name, 0)
                    t.setattr(dst, name, HINFO_KEY,
                              HashInfo(1, 0, [0xFFFFFFFF]).to_bytes())
                    continue
                data = st.read(src, name)
                t.write(dst, name, 0, data).truncate(dst, name, len(data))
                try:
                    t.setattr(dst, name, HINFO_KEY,
                              st.getattr(src, name, HINFO_KEY))
                except KeyError:
                    pass    # zero-length objects may carry no hinfo
                t.remove(src, name)
            st.queue_transaction(t)
        live = list(range(self.n))
        for name in moved:
            child.object_sizes[name] = self.object_sizes.pop(name)
            child._log_write(name, live)
            self.object_versions.pop(name, None)
            self._log_write(name, live)   # the parent-side DELETE entry
        return len(moved)

    def _replay_deletes(self, lost: list[int], names) -> list[str]:
        """Split a recovery name list: apply deletes for names the PG
        no longer knows (their last log entry was a remove) to the
        recovering slots, and return the names still to rebuild.

        Batched per slot: ONE listing + ONE combined remove txn
        instead of a per-name exists+remove pair — at the wire tier
        the per-name form cost 2B round trips per recovering slot."""
        keep = [n for n in names if n in self.object_sizes]
        dels = [n for n in names if n not in self.object_sizes]
        if dels:
            for s in lost:
                cid = shard_cid(self.pg, s)
                present = set(self._store(s).list_objects(cid))
                doomed = [n for n in dels if n in present]
                if not doomed:
                    continue
                t = Transaction()
                for name in doomed:
                    t.remove(cid, name)
                self._store(s).queue_transaction(t)
        return keep

    def recover_shards(self, lost_shards, replacement_osds=None,
                       batch: int = 128, verify_hinfo: bool = True,
                       names=None, helper_exclude=None) -> dict:
        raise NotImplementedError

    def _mark_caught_up(self, lost: list[int], full_plan: bool,
                        provided: set) -> None:
        """Advance recovered slots' applied cursors to the log head —
        but only when the recovered names cover the slot's whole
        missing set. A narrower caller-supplied subset must not mark
        objects it never touched as fresh (that would defeat
        _fresh_for's staleness gate). Shared by both backends so the
        gate can't silently diverge."""
        for s in lost:
            missing = self.pg_log.missing_since(self.shard_applied[s])
            if missing is None:           # log trimmed: backfill must
                missing = self.object_sizes   # have covered everything
            if full_plan or set(missing) <= provided:
                self.shard_applied[s] = self.pg_log.head

    def deep_scrub(self) -> dict:
        raise NotImplementedError

    # -- shallow scrub (shared) ----------------------------------------------

    def _expected_shard_len(self, object_size: int) -> int:
        """Bytes slot s should hold for an object of `object_size`
        logical bytes (replicated: the full object; EC: the shard)."""
        raise NotImplementedError

    def shallow_scrub(self, skip_slots: set[int] | None = None) -> dict:
        """Metadata-only audit — no data reads (ref: the scrubber's
        shallow pass compares object set, sizes, and attrs across
        shards; src/osd/scrubber/pg_scrubber.cc). Checks every slot
        against the authoritative object map: presence, stored length,
        hinfo attr presence + its recorded length, and flags stray
        objects the PG doesn't know about."""
        skip = skip_slots or set()
        errors: list[tuple[str, int, str]] = []  # (name, slot, what)
        checked = 0
        for s in range(self.n):
            if s in skip:
                continue
            store = self._store(s)
            cid = shard_cid(self.pg, s)
            on_disk = set(store.list_objects(cid))
            for name, osize in self.object_sizes.items():
                checked += 1
                # a shard that missed this object's last write is
                # legitimately behind, not inconsistent
                if self.shard_applied[s] < self.object_versions.get(
                        name, 0):
                    continue
                if name not in on_disk:
                    errors.append((name, s, "missing"))
                    continue
                want = self._expected_shard_len(osize)
                have = store.stat(cid, name)
                if have != want:
                    errors.append((name, s, f"size {have} != {want}"))
                try:
                    hb = store.getattr(cid, name, HINFO_KEY)
                except KeyError:
                    errors.append((name, s, "no hinfo attr"))
                    continue
                hinfo = HashInfo.from_bytes(hb)
                if hinfo.total_chunk_size != want:
                    errors.append(
                        (name, s, f"hinfo len {hinfo.total_chunk_size} "
                                  f"!= {want}"))
            for stray in on_disk - set(self.object_sizes):
                # "__"-prefixed names are PG-internal bookkeeping
                # (stripe journal, standalone __pg_meta__): never
                # client data, never stray
                if stray.startswith("__"):
                    continue
                # a behind shard may hold an object whose delete it
                # hasn't replayed yet — lag, not corruption (same
                # excuse the missing/size checks apply above)
                if self.shard_applied[s] < self.object_versions.get(
                        stray, 0):
                    continue
                errors.append((stray, s, "stray object"))
        return {"checked": checked, "errors": errors}


class ReplicatedBackend(PGBackend):
    """Full-copy replication across the acting set (ref:
    src/osd/ReplicatedBackend.{h,cc} — submit_transaction fans the same
    transaction out to every replica; recovery pushes whole objects from
    a surviving replica; be_deep_scrub compares replica digests).

    Every slot stores the complete object plus a HashInfo xattr whose
    single CRC covers the full byte stream (the data_digest role). The
    xattr layout matches ECBackend's, so SimCluster's backfill copy loop
    works unchanged for either pool type.
    """

    def __init__(self, size: int, pg: str, acting: list[int],
                 cluster=None, min_size: int | None = None,
                 ensure_collections: bool = True):
        if len(acting) != size:
            raise ValueError(f"acting set size {len(acting)} != size={size}")
        from .ecbackend import ShardSet
        self.size = size
        # the reference default: size - size/2, i.e. ceil(size/2)
        # (osd_pool_default_min_size=0 behavior) — 2 for size 3 AND 4
        self.min_live = min_size if min_size is not None \
            else size - size // 2
        if not (1 <= self.min_live <= size):
            raise ValueError(f"min_size {self.min_live} not in [1, {size}]")
        self._init_common(pg, acting, cluster or ShardSet(),
                          ensure_collections=ensure_collections)
        self.eio_stats = {"read_eio": 0, "repaired": 0}

    def _expected_shard_len(self, object_size: int) -> int:
        return object_size  # every replica holds the whole object

    # -- write path ----------------------------------------------------------

    def _put_full(self, name: str, arr: np.ndarray, crc: int,
                  live: list[int]) -> None:
        self._put_group([(name, arr, crc)], live)

    def _put_group(self, items, live: list[int]) -> None:
        """Fan a group of (name, bytes, crc) puts out as ONE combined
        transaction per replica (the window's store-apply unit;
        ROADMAP item 2b — the per-object fan-out cost B*n store
        transactions and B*n `store.apply` passes where n suffice)."""
        txns = []
        for s in live:
            cid = shard_cid(self.pg, s)
            t = Transaction()
            for name, arr, crc in items:
                hinfo = HashInfo(1, len(arr), [crc])
                t.write(cid, name, 0, arr) \
                 .truncate(cid, name, len(arr)) \
                 .setattr(cid, name, HINFO_KEY, hinfo.to_bytes())
            txns.append((s, t))
        self._fanout_txns(txns)
        for name, arr, _crc in items:
            self.object_sizes[name] = len(arr)
            self._log_write(name, live)

    def write_objects(self, objects, dead_osds=None) -> None:
        """Full-object writes: digest every equal-length group in one
        batched CRC launch, then fan identical bytes to each live
        replica (the repop fan-out, minus the network) — one combined
        transaction per replica per group."""
        live = self._live_slots(dead_osds)
        self._check_min_size(live)
        by_len: dict[int, list[tuple[str, np.ndarray]]] = {}
        for name, data in objects.items():
            arr = as_flat_u8(data)
            by_len.setdefault(len(arr), []).append((name, arr))
        for olen, group in by_len.items():
            if olen == 0:
                self._put_group([(n, a, 0xFFFFFFFF) for n, a in group],
                                live)
                continue
            crcs = self._batched_crcs(np.stack([a for _, a in group]))
            self._put_group([(n, a, int(c))
                             for (n, a), c in zip(group, crcs)], live)

    def write_ranges(self, ops, dead_osds=None) -> None:
        """Arbitrary (offset, len) overwrites. Replication needs no RMW
        of other shards — but the full-object digest does need the
        pre-image, read from any caught-up live replica."""
        dead = dead_osds or set()
        live = self._live_slots(dead)
        self._check_min_size(live)
        per_obj: dict[str, list[tuple[int, np.ndarray]]] = {}
        for name, offset, data in ops:
            if offset < 0:
                raise ValueError(f"negative offset {offset}")
            per_obj.setdefault(name, []).append((int(offset),
                                                as_flat_u8(data)))
        staged: list[tuple[str, np.ndarray]] = []
        for name, writes in per_obj.items():
            old_size = self.object_sizes.get(name, 0)
            writes = [(off, a) for off, a in writes if len(a)]
            if not writes:
                if name not in self.object_sizes:
                    self._put_full(name, np.zeros(0, np.uint8),
                                   0xFFFFFFFF, live)
                continue
            new_size = max(old_size,
                           max(off + len(a) for off, a in writes))
            buf = np.zeros(new_size, dtype=np.uint8)
            if old_size:
                src = self._fresh_for([name], live)
                if not src:
                    raise ValueError(
                        f"no caught-up live replica holds {name!r}; "
                        f"write blocked until recovery")
                buf[:old_size] = self._store(src[0]).read(
                    shard_cid(self.pg, src[0]), name)
            for off, arr in writes:
                buf[off:off + len(arr)] = arr
            staged.append((name, buf))
        # batched digest per equal new-length group, then ONE combined
        # txn per replica per group (the grouped put fan-out)
        by_len: dict[int, list[tuple[str, np.ndarray]]] = {}
        for name, buf in staged:
            by_len.setdefault(len(buf), []).append((name, buf))
        for olen, group in by_len.items():
            crcs = (self._batched_crcs(np.stack([b for _, b in group]))
                    if olen else [0xFFFFFFFF] * len(group))
            self._put_group([(n, b, int(c))
                             for (n, b), c in zip(group, crcs)], live)

    # -- read path -----------------------------------------------------------

    def read_objects(self, names, dead_osds=None,
                     verify: bool = True,
                     repair: bool = True,
                     helper_costs=None) -> dict[str, np.ndarray]:
        """Serve each object from the first caught-up live replica
        (primary-first, the reference's default read path), with
        verify-on-read: a digest mismatch fails over to the next good
        replica and repairs the rotten copy in place (the read-error
        EIO path). repair=False fails over without the writeback — the
        read-only contract of a degraded-read view served by a
        non-primary (only an activated primary may mutate shards).
        `helper_costs` (slot -> cost) reorders the candidate replicas
        cheapest-first — the replicated twin of the EC planner's
        cost-ranked helper pick."""
        alive = self._live_slots(dead_osds)
        out: dict[str, np.ndarray] = {}
        srcs_of: dict[str, list[int]] = {}
        # happy path batched per (chosen replica, size): ONE CRC launch
        # per group, matching the file's batch-per-equal-length
        # convention everywhere else
        plan: dict[tuple[int, int], list[str]] = {}
        for name in names:
            if name not in self.object_sizes:
                raise KeyError(f"no object {name!r}")
            srcs = self._fresh_for([name], alive)
            if helper_costs:
                srcs.sort(key=lambda s: (int(helper_costs.get(s, 0)),
                                         s))
            if not srcs:
                raise ValueError(f"no caught-up live replica for {name!r}")
            if not verify:
                out[name] = self._store(srcs[0]).read(
                    shard_cid(self.pg, srcs[0]), name)
                continue
            srcs_of[name] = srcs
            plan.setdefault((srcs[0], self.object_sizes[name]),
                            []).append(name)
        suspects: list[str] = []
        for (s, size), group in plan.items():
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            datas = {n: st.read(cid, n) for n in group}
            ok_len = [n for n in group if len(datas[n]) == size]
            for n in group:  # length rot can't even be stacked
                if n not in ok_len:
                    self.eio_stats["read_eio"] += 1
                    suspects.append(n)
            if not ok_len:
                continue
            crcs = (self._batched_crcs(
                np.stack([datas[n] for n in ok_len]))
                if size else [0xFFFFFFFF] * len(ok_len))
            for n, crc in zip(ok_len, crcs):
                hinfo = HashInfo.from_bytes(
                    st.getattr(cid, n, HINFO_KEY))
                if int(crc) == hinfo.get_chunk_hash(0):
                    out[n] = datas[n]
                else:
                    self.eio_stats["read_eio"] += 1
                    suspects.append(n)
        for name in suspects:  # EIO path: failover + repair
            out[name] = self._read_failover(name, srcs_of[name],
                                            {srcs_of[name][0]},
                                            repair=repair)
        return out

    def _read_failover(self, name: str, srcs: list[int],
                       bad: set[int],
                       repair: bool = True) -> np.ndarray:
        """Try the remaining fresh replicas in order; the first
        digest-valid copy wins and repairs every rotten one met
        (unless repair=False — the read-only degraded view)."""
        good = None
        for s in srcs:
            if s in bad:
                continue
            st = self._store(s)
            cid = shard_cid(self.pg, s)
            data = st.read(cid, name)
            crc = (int(self._batched_crcs(data[None, :])[0])
                   if data.size else 0xFFFFFFFF)
            hinfo = HashInfo.from_bytes(st.getattr(cid, name,
                                                   HINFO_KEY))
            if crc == hinfo.get_chunk_hash(0) \
                    and len(data) == self.object_sizes[name]:
                good = data
                break
            self.eio_stats["read_eio"] += 1
            bad.add(s)
        if good is None:
            raise ValueError(
                f"every replica of {name!r} fails its digest")
        if repair:
            for s in bad:
                self._rewrite_replica(name, s, good)
        return good

    def _rewrite_replica(self, name: str, s: int,
                         good: np.ndarray) -> None:
        crc = (int(self._batched_crcs(good[None, :])[0])
               if good.size else 0xFFFFFFFF)
        hinfo = HashInfo(1, len(good), [crc])
        t = (Transaction()
             .write(shard_cid(self.pg, s), name, 0, good)
             .truncate(shard_cid(self.pg, s), name, len(good))
             .setattr(shard_cid(self.pg, s), name,
                      HINFO_KEY, hinfo.to_bytes()))
        self._store(s).queue_transaction(t)
        self.eio_stats["repaired"] += 1

    def repair_pg(self, dead_osds: set[int] | None = None) -> dict:
        """`ceph pg repair`: deep-scrub, rewrite every inconsistent
        replica the scrub flagged from a digest-valid copy (not just
        the ones a read would stumble over). Dead slots are recovery's
        job, not repair's; replicas the verified read already fixed in
        passing are not rewritten (or counted) twice."""
        dead = dead_osds or set()
        rep = self.deep_scrub(dead_osds=dead)
        alive_set = set(self._live_slots(dead))
        by_name: dict[str, list[int]] = {}
        skipped = 0
        for name, slot in rep["inconsistent"]:
            if slot not in alive_set or name not in self.object_sizes:
                skipped += 1
                continue
            by_name.setdefault(name, []).append(slot)
        repaired = 0
        for name, slots in sorted(by_name.items()):
            good = self.read_objects([name], dead_osds,
                                     verify=True)[name]
            want_crc = (int(self._batched_crcs(good[None, :])[0])
                        if good.size else 0xFFFFFFFF)
            for s in slots:
                st = self._store(s)
                cid = shard_cid(self.pg, s)
                cur = st.read(cid, name)
                cur_crc = (int(self._batched_crcs(cur[None, :])[0])
                           if cur.size else 0xFFFFFFFF)
                if cur_crc == want_crc:
                    continue  # the verified read repaired it already
                self._rewrite_replica(name, s, good)
                repaired += 1
        return {"checked": rep["checked"], "repaired": repaired,
                "objects": len(by_name), "skipped": skipped,
                "strays_removed": self._remove_strays(dead)}

    # -- recovery ------------------------------------------------------------

    def recover_shards(self, lost_shards, replacement_osds=None,
                       batch: int = 128, verify_hinfo: bool = True,
                       names=None, helper_exclude=None,
                       helper_costs=None) -> dict:
        """Rebuild lost replicas by pushing verified copies from a
        surviving replica (ref: ReplicatedBackend::recover_object /
        prep_push). Copies are batched per equal length so the source-
        verify CRC is one device launch per group. `helper_costs`
        orders the candidate push sources cheapest-first.

        Same signature/counters as ECBackend.recover_shards so
        SimCluster's repeer/backfill/catch-up paths drive either."""
        lost = sorted(set(lost_shards))
        excluded = helper_exclude or set()
        full_plan = names is None
        names = sorted(self.object_sizes) if names is None \
            else sorted(set(names))
        provided = set(names)
        # a deletes-only replay pushes nothing and needs no source
        rebuild = [n for n in names if n in self.object_sizes]
        survivors: list[int] = []
        if rebuild:
            survivors = self._fresh_for(
                rebuild, [s for s in range(self.n)
                          if s not in lost and s not in excluded])
            if helper_costs:
                survivors.sort(
                    key=lambda s: (int(helper_costs.get(s, 0)), s))
            if not survivors:
                raise ValueError(
                    "no caught-up surviving replica to push from")
        repl = replacement_osds or {}
        for s in lost:
            new_osd = repl.get(s, self.acting[s])
            self.acting[s] = new_osd
            t = Transaction().create_collection(shard_cid(self.pg, s))
            self.cluster.osd(new_osd).queue_transaction(t)
        counters = {"objects": 0, "bytes": 0, "hinfo_failures": 0}
        # names whose last log entry was a DELETE replay as removals
        names = self._replay_deletes(lost, names)

        by_len: dict[int, list[str]] = {}
        for name in names:
            by_len.setdefault(self.object_sizes[name], []).append(name)
        for olen, group in by_len.items():
            for i in range(0, len(group), batch):
                sub = group[i:i + batch]
                self._push_batch(sub, olen, lost, survivors,
                                 verify_hinfo, counters)
        self._mark_caught_up(lost, full_plan, provided)
        return counters

    def _push_batch(self, sub: list[str], olen: int, lost: list[int],
                    survivors: list[int], verify: bool,
                    counters: dict) -> None:
        src = survivors[0]
        cid_src = shard_cid(self.pg, src)
        st = self._store(src)
        data = [st.read(cid_src, n) for n in sub]
        crcs = [0xFFFFFFFF] * len(sub)
        if olen:
            crcs = [int(c) for c in
                    self._batched_crcs(np.stack(data))]
        for ni, name in enumerate(sub):
            want = HashInfo.from_bytes(
                st.getattr(cid_src, name, HINFO_KEY)).get_chunk_hash(0)
            if verify and olen and crcs[ni] != want:
                # source copy is corrupt: try the other survivors (the
                # read-error failover the reference does on pull)
                counters["hinfo_failures"] += 1
                for alt in survivors[1:]:
                    cid_a = shard_cid(self.pg, alt)
                    cand = self._store(alt).read(cid_a, name)
                    cc = int(self._batched_crcs(cand[None, :])[0])
                    aw = HashInfo.from_bytes(self._store(alt).getattr(
                        cid_a, name, HINFO_KEY)).get_chunk_hash(0)
                    if cc == aw:
                        data[ni], crcs[ni] = cand, cc
                        break
                else:
                    raise ValueError(
                        f"all surviving replicas of {name!r} fail digest")
        # ONE combined txn per recovering replica for the whole batch
        # (was one per (object, slot)), fanned out pipelined
        txns = []
        for s in lost:
            cid = shard_cid(self.pg, s)
            t = Transaction()
            for ni, name in enumerate(sub):
                hinfo = HashInfo(1, olen, [crcs[ni]])
                t.write(cid, name, 0, data[ni]) \
                 .truncate(cid, name, olen) \
                 .setattr(cid, name, HINFO_KEY, hinfo.to_bytes())
                counters["bytes"] += olen
            txns.append((s, t))
        self._fanout_txns(txns)
        counters["objects"] += len(sub)

    # -- scrub ---------------------------------------------------------------

    def deep_scrub(self, dead_osds: set[int] | None = None) -> dict:
        """Read every LIVE replica of every object, verify its stored
        digest (batched CRC per replica), and cross-check replicas
        agree (ref: be_deep_scrub + the scrubber's authoritative-copy
        compare). Dead slots are skipped — touching their stores would
        resurrect destroyed OSD ids."""
        dead = dead_osds or set()
        bad: list[tuple[str, int]] = []
        checked = 0
        digests: dict[str, set[int]] = {}
        for s in range(self.n):
            if self.acting[s] in dead:
                continue
            store = self._store(s)
            cid = shard_cid(self.pg, s)
            # a replica that missed an object's last write is behind
            # (pending replay), not corrupt — the scrubber's "missing"
            # bucket; filter BEFORE reading so stale rows cost nothing
            # strays (objects the PG metadata doesn't know — e.g. a
            # non-primary rejoiner's divergent leftovers) may lack
            # hinfo entirely: they are repair's to REMOVE, not the
            # digest audit's to crash on
            names = [n for n in store.list_objects(cid)
                     if n in self.object_sizes
                     and self.shard_applied[s]
                     >= self.object_versions.get(n, 0)]
            by_len: dict[int, list[str]] = {}
            for n in names:
                by_len.setdefault(store.stat(cid, n), []).append(n)
            for ln, group in by_len.items():
                if ln:
                    crcs = self._batched_crcs(
                        np.stack([store.read(cid, n) for n in group]))
                else:
                    crcs = [0xFFFFFFFF] * len(group)
                for n, c in zip(group, crcs):
                    hinfo = HashInfo.from_bytes(
                        store.getattr(cid, n, HINFO_KEY))
                    checked += 1
                    if hinfo.get_chunk_hash(0) != int(c):
                        bad.append((n, s))
                    digests.setdefault(n, set()).add(int(c))
        # replicas that all self-verify but disagree with each other
        # (e.g. a stale-but-internally-consistent copy)
        split = [n for n, ds in digests.items() if len(ds) > 1]
        return {"checked": checked, "inconsistent": bad,
                "digest_mismatch": sorted(split)}
