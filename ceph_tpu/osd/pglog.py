"""PGLog — per-PG mutation log enabling delta rejoin.

Rebuild of the reference's log-based catch-up (ref: src/osd/PGLog.{h,cc}
pg_log_t entries with eversion_t versions; PeeringState GetLog/
GetMissing computes a missing set from the authoritative log, and a
rejoining OSD either LOG-REPLAYS the delta or, when the log has been
trimmed past its last-applied version, falls back to BACKFILL).

Simplified to what the sim's write model needs: every object mutation
appends (version, name); a shard that was down across some window asks
`missing_since(last_applied)` and gets the deduplicated set of objects
it must re-apply — or None when the log no longer reaches back that far
(the backfill signal). Versions are a single monotone counter per PG
(the reference's eversion epoch component is carried by the OSDMap
epoch at the cluster layer)."""

from __future__ import annotations

from collections import deque


def share_history(local: "PGLog", auth: "PGLog") -> bool:
    """True when the two logs demonstrably belong to one history: some
    retained entry agrees, or local's retained window entirely
    predates auth's trimmed tail (unverifiable => assume shared). A
    local log with entries and NO agreement at all signals interval
    DISCONTINUITY (e.g. the PG restarted virgin on fresh OSDs after a
    full-acting-set outage) — a rewind there would delete the only
    surviving copies, not roll back an uncommitted tail."""
    if not len(local._entries):
        return True
    auth_at = dict(auth._entries)
    for v, name in local._entries:
        if v <= auth.tail or auth_at.get(v) == name:
            return True
    return False


def divergent_names(local: "PGLog", auth: "PGLog") -> list[str]:
    """Names whose entries in `local` the authoritative log does not
    contain (ref: PGLog::merge_log divergent-entry handling): an entry
    past auth.head, or one whose version names a DIFFERENT object in
    the authoritative history, records a write that never committed
    cluster-wide. The rejoining holder must roll those objects back to
    (or re-copy) the authoritative state — serving them would
    resurrect unacknowledged writes. Versions at or before auth.tail
    are unverifiable (trimmed) and assumed converged — the backfill
    path owns that window."""
    auth_at = dict(auth._entries)
    out: dict[str, None] = {}
    for v, name in local._entries:
        if v > auth.head or (v > auth.tail and auth_at.get(v) != name):
            out.setdefault(name)
    return list(out)


class PGLog:
    """Append-only bounded mutation log for one PG."""

    def __init__(self, max_entries: int = 10000):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        self.head = 0          # newest version (0 = empty history)
        self.tail = 0          # entries cover versions (tail, head]
        self._entries: deque[tuple[int, str]] = deque()

    def append(self, name: str) -> int:
        """Record a mutation of `name`; returns its version."""
        self.head += 1
        self._entries.append((self.head, name))
        while len(self._entries) > self.max_entries:
            v, _ = self._entries.popleft()
            self.tail = v
        return self.head

    def append_entry(self, version: int, name: str) -> None:
        """Replay a known (version, name) entry — the delta-meta
        restore path reapplying entries persisted after the last full
        snapshot. Versions must arrive strictly ascending past head."""
        if version <= self.head:
            raise ValueError(f"append_entry {version} <= head "
                             f"{self.head}")
        self.head = version
        self._entries.append((version, name))
        while len(self._entries) > self.max_entries:
            v, _ = self._entries.popleft()
            self.tail = v

    def missing_since(self, version: int) -> list[str] | None:
        """Objects mutated after `version` (dedup, oldest-first), or
        None when `version` predates the retained log — the caller must
        backfill (full copy) instead of replaying."""
        if version >= self.head:
            return []
        if version < self.tail:
            return None
        seen: dict[str, None] = {}
        for v, name in self._entries:
            if v > version:
                seen.setdefault(name)
        return list(seen)

    def __len__(self) -> int:
        return len(self._entries)

    # -- wire form (ref: pg_log_t encode/decode) ----------------------------

    def encode(self) -> bytes:
        from ..utils.encoding import Encoder
        e = Encoder().start(1, 1)
        e.u32(self.max_entries).u64(self.head).u64(self.tail)
        e.list(list(self._entries),
               lambda en, ent: en.u64(ent[0]).string(ent[1]))
        return e.finish().bytes()

    @classmethod
    def decode(cls, data: bytes) -> "PGLog":
        from ..utils.encoding import Decoder
        d = Decoder(data)
        d.start(1)
        log = cls(max_entries=d.u32())
        log.head = d.u64()
        log.tail = d.u64()
        for v, name in d.list(lambda dd: (dd.u64(), dd.string())):
            log._entries.append((v, name))
        d.finish()
        return log
