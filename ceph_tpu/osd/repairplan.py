"""Repair-locality planner — code-family-aware minimal-helper recovery.

The codecs have known HOW to repair cheaply for a while (LRC's local
groups, Clay's repair planes, SHEC's shingle windows), but the live
recovery and degraded-read paths asked only the generic availability
question ("which chunks decode this?") and then pulled FULL shards
from the answer. This module is the missing middle layer: per code
family it emits a `RepairPlan` naming the minimal helper set AND the
byte ranges each helper must ship, so the wire moves the bytes the
math actually needs — the repair-network-traffic problem of the
Facebook warehouse study (arxiv 1309.0186) and the regenerating-codes
bandwidth line (arxiv 1412.3022), where repair traffic, not decode
FLOPs, dominates rebuild cost at fleet scale.

Plan shapes per family (ref: the reference's per-plugin
minimum_to_decode overrides, src/erasure-code/*/ErasureCode*.cc):

* LRC   — single-shard loss repairs inside ONE local group
          (`_repair_plan`'s structural layer walk); a second loss in
          the same group breaks locality and the plan ladders to the
          wider/global layers automatically. Full rows, `row`
          integrity (the r10 whole-row hinfo fold).
* Clay  — single-shard loss reads only the `repair_plan_matrix`
          repair planes: beta = subchunks/q sub-chunks from each of d
          helpers (`range` integrity — see below). Multi-loss or
          degraded-below-d ladders to the coupled full decode.
* SHEC  — cost-ranked structural search over shingle windows
          (`minimum_to_decode_with_cost`); full rows.
* RS    — MDS default: k cheapest available chunks; full rows.

Integrity modes (the plan carries its own): `row` keeps the r10
whole-row CRC fold against stored hinfo. Sub-chunk reads break that
fold — the receiver never sees the whole helper row — so `range` mode
moves rot detection to the SOURCE (the helper checksums its full
shard against its stored hinfo before slicing) and ships range-level
crc32c over the planned bytes, which the receiver fold-verifies
exactly like r10 (CRC32C stays GF(2)-linear at any row length). The
rebuilt output is re-CRC'd and stamped into fresh hinfo either way.

Costs: `plan_repair`/`plan_read` accept a {chunk: cost} mapping (the
daemon feeds per-helper costs from its down/slow complaint memory and
peer-latency EWMAs) and route it into each family's
minimum_to_decode_with_cost, so helper selection prefers fast, trusted
sources instead of pretending reads are uniform.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

__all__ = ["RepairPlan", "plan_repair", "plan_read", "coalesce_ranges"]


def coalesce_ranges(ranges: Sequence[tuple[int, int]]
                    ) -> tuple[tuple[int, int], ...]:
    """Merge adjacent/overlapping (offset, length) pairs — fewer wire
    range entries for runs of contiguous repair planes."""
    out: list[list[int]] = []
    for off, ln in sorted((int(o), int(l)) for o, l in ranges):
        if out and off <= out[-1][0] + out[-1][1]:
            out[-1][1] = max(out[-1][1], off + ln - out[-1][0])
        else:
            out.append([off, ln])
    return tuple((o, l) for o, l in out)


@dataclass(frozen=True)
class RepairPlan:
    """One loss pattern's repair recipe: who ships what, verified how.

    helpers are chunk ids (= shard slots) in ascending order — the
    staging/stacking order every consumer (range decoder row layout,
    readv frames) relies on. `planes` names the sub-chunk indices each
    helper ships (identical across helpers for Clay, the only
    sub-chunk family); None means full rows."""

    family: str                      # "lrc_local" | "lrc_multi" |
    #                                  "clay_planes" | "clay_full" |
    #                                  "shec_cost" | "mds" | "direct"
    lost: tuple[int, ...]
    helpers: tuple[int, ...]
    planes: tuple[int, ...] | None   # sub-chunk ids per helper, or None
    sub_chunk_count: int             # q^t for clay; 1 otherwise
    integrity: str                   # "row" | "range"
    cost_ranked: bool = False        # helper pick consumed real costs

    @property
    def wire_fraction(self) -> float:
        """Fraction of each helper row that ships (beta/q^t for Clay,
        1.0 for full-row families) — the per-helper bandwidth saving."""
        if self.planes is None:
            return 1.0
        return len(self.planes) / self.sub_chunk_count

    def row_bytes(self, shard_len: int) -> int:
        """Bytes one helper ships for a shard of `shard_len` bytes."""
        if self.planes is None:
            return shard_len
        return len(self.planes) * (shard_len // self.sub_chunk_count)

    def ranges(self, shard_len: int) -> tuple[tuple[int, int], ...] | None:
        """The (offset, length) list each helper reads at this shard
        length (coalesced), or None for full-row plans."""
        if self.planes is None:
            return None
        P = self.sub_chunk_count
        if shard_len % P:
            raise ValueError(
                f"shard length {shard_len} not divisible into {P} "
                f"sub-chunks")
        s = shard_len // P
        return coalesce_ranges((z * s, s) for z in self.planes)

    def wire_bytes(self, shard_len: int, n_objects: int) -> int:
        """Total helper bytes on the wire for `n_objects` rebuilds."""
        return self.row_bytes(shard_len) * len(self.helpers) * n_objects


def _with_costs(coder, want, avail: set[int],
                costs: Mapping[int, int] | None) -> set[int]:
    """Route through minimum_to_decode_with_cost when costs are known
    (every family overrides it structurally where the MDS default's
    'k cheapest' could pick an undecodable set)."""
    if costs:
        table = {c: int(costs.get(c, 0)) for c in avail}
        return set(coder.minimum_to_decode_with_cost(sorted(want), table))
    return set(coder.minimum_to_decode(sorted(want), sorted(avail)))


def _plan_lrc(coder, lost: list[int], avail: set[int],
              costs: Mapping[int, int] | None) -> RepairPlan:
    """Structural layer walk (the codec's own `_repair_plan`): local
    when ONE small layer covers the loss, laddering to the wider
    layers when a second loss in the group breaks locality."""
    steps, reads, _ = coder._repair_plan(set(lost), avail, costs=costs)
    local = (len(steps) >= 1
             and all(layer.k < coder.k for layer, _missing in steps))
    return RepairPlan(
        family="lrc_local" if local else "lrc_multi",
        lost=tuple(lost), helpers=tuple(sorted(reads)),
        planes=None, sub_chunk_count=1, integrity="row",
        cost_ranked=bool(costs))


def _plan_clay(coder, lost: list[int], avail: set[int],
               costs: Mapping[int, int] | None) -> RepairPlan:
    """Single loss with >= d live helpers: the MSR repair planes —
    beta = q^(t-1) sub-chunks per helper. Anything else ladders to the
    coupled full decode over every survivor."""
    if len(lost) == 1 and len(avail) >= coder.d:
        helpers = coder._pick_helpers(lost[0], sorted(avail),
                                      costs=costs)
        return RepairPlan(
            family="clay_planes", lost=tuple(lost),
            helpers=tuple(sorted(helpers)),
            planes=tuple(coder._repair_planes(lost[0])),
            sub_chunk_count=coder.get_sub_chunk_count(),
            integrity="range", cost_ranked=bool(costs))
    need = _with_costs(coder, set(lost), avail, costs)
    return RepairPlan(
        family="clay_full", lost=tuple(lost),
        helpers=tuple(sorted(need - set(lost))),
        planes=None, sub_chunk_count=1, integrity="row",
        cost_ranked=bool(costs))


def plan_repair(coder, lost_chunks: Sequence[int],
                available: Sequence[int],
                costs: Mapping[int, int] | None = None) -> RepairPlan:
    """Plan the rebuild of `lost_chunks` from `available` survivors.

    Raises ValueError (before anyone moved a byte) when the survivors
    cannot reconstruct the loss — the same no-partial-state contract
    plan_recovery always had."""
    lost = sorted(int(c) for c in set(lost_chunks))
    avail = {int(c) for c in available} - set(lost)
    if not lost:
        return RepairPlan("direct", (), (), None, 1, "row")
    if hasattr(coder, "_repair_plan"):               # LRC layer stack
        return _plan_lrc(coder, lost, avail, costs)
    if hasattr(coder, "repair_plan_matrix"):         # Clay / MSR
        return _plan_clay(coder, lost, avail, costs)
    need = _with_costs(coder, set(lost), avail, costs)
    family = "shec_cost" if hasattr(coder, "windows") else "mds"
    return RepairPlan(
        family=family, lost=tuple(lost),
        helpers=tuple(sorted(need - set(lost))),
        planes=None, sub_chunk_count=1, integrity="row",
        cost_ranked=bool(costs))


def plan_read(coder, want: Sequence[int], available: Sequence[int],
              costs: Mapping[int, int] | None = None
              ) -> tuple[set[int], str]:
    """Read-path twin of plan_repair: the chunk set a (possibly
    degraded) read must gather to produce `want`, plus the family
    label for accounting. Chunks in `want` that are available read
    themselves; the missing ones are planned like a repair — so an LRC
    single-shard degraded read gathers its local group, not k shards."""
    want_s = {int(c) for c in want}
    avail = {int(c) for c in available}
    missing = want_s - avail
    if not missing:
        return set(want_s), "direct"
    rp = plan_repair(coder, sorted(missing), avail, costs=costs)
    return (want_s & avail) | set(rp.helpers), rp.family
