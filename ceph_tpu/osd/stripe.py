"""Stripe geometry + per-shard integrity bookkeeping.

Rebuild of the reference's EC stripe math (ref: src/osd/ECUtil.{h,cc} —
`stripe_info_t` with stripe_width = k * chunk_size, the logical<->chunk
offset maps used by ECBackend/ECTransaction to turn object byte ranges
into shard sub-ranges, and `HashInfo`, the per-shard cumulative crc32c
vector stored in the hinfo xattr and checked by deep scrub).

This file freezes the on-host byte format:

  * an object's logical bytes are laid out round-robin in stripe units:
    stripe s, chunk j holds logical bytes
    [s*stripe_width + j*chunk_size, s*stripe_width + (j+1)*chunk_size);
  * each shard's store file is the concatenation of its chunk of every
    stripe (so shard offset = logical_offset / k for aligned offsets);
  * objects are zero-padded up to the next stripe boundary (matching
    ErasureCode::encode's padding rule; trailing zeros are trimmed on
    read via the recorded object size).

Because GF encoding is positionwise, applying a coding matrix across
whole shard arrays encodes every stripe at once — the layout here is
exactly what the batched kernels consume: (batch, shard, shard_len).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..csum.kernels import crc32c_extend
from ..csum.reference import ceph_crc32c


def as_flat_u8(data) -> np.ndarray:
    """Coerce bytes/memoryview/array input to a flat uint8 array — the
    one shared byte-coercion rule for every write path."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        return np.frombuffer(bytes(data), dtype=np.uint8)
    return np.asarray(data, np.uint8).ravel()


@dataclass(frozen=True)
class StripeInfo:
    """Geometry of one EC pool's stripes (ref: ECUtil::stripe_info_t)."""

    k: int
    chunk_size: int  # bytes each shard contributes per stripe

    def __post_init__(self):
        if self.k < 1 or self.chunk_size < 1:
            raise ValueError(f"bad stripe geometry k={self.k} "
                             f"chunk_size={self.chunk_size}")

    @property
    def stripe_width(self) -> int:
        return self.k * self.chunk_size

    # -- offset maps (ref: stripe_info_t logical<->chunk methods) ---------

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - offset % self.stripe_width

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.stripe_width

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        """Shard-file offset of the stripe containing logical `offset`."""
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return -(-offset // self.stripe_width) * self.chunk_size

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        if offset % self.stripe_width:
            raise ValueError(f"offset {offset} not stripe-aligned "
                             f"(stripe_width={self.stripe_width})")
        return offset // self.k

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        if offset % self.chunk_size:
            raise ValueError(f"chunk offset {offset} not chunk-aligned "
                             f"(chunk_size={self.chunk_size})")
        return offset * self.k

    def offset_len_to_stripe_bounds(self, offset: int,
                                    length: int) -> tuple[int, int]:
        """Smallest stripe-aligned (offset, len) covering the range —
        what an RMW must read (ref: sinfo usage in ECCommon::RMWPipeline)."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    def offset_len_to_chunk_bounds(self, offset: int,
                                   length: int) -> tuple[int, int]:
        """Shard-file (offset, len) each shard must touch for the range."""
        start, width = self.offset_len_to_stripe_bounds(offset, length)
        return start // self.k, width // self.k

    def chunk_index_of(self, offset: int) -> int:
        """Which data shard holds logical byte `offset`."""
        return (offset % self.stripe_width) // self.chunk_size

    def object_size_to_shard_size(self, object_size: int) -> int:
        return self.logical_to_next_chunk_offset(object_size)

    # -- layout transforms -------------------------------------------------

    def object_to_shards(self, data) -> np.ndarray:
        """(B, object_bytes) or flat bytes -> (B, k, shard_len) uint8,
        zero-padded to the next stripe boundary."""
        arr = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else np.asarray(
                data, np.uint8)
        squeeze = arr.ndim == 1
        if squeeze:
            arr = arr[None, :]
        b, n = arr.shape
        padded_len = self.logical_to_next_stripe_offset(n)
        padded = np.zeros((b, padded_len), dtype=np.uint8)
        padded[:, :n] = arr
        n_stripes = padded_len // self.stripe_width
        shards = padded.reshape(b, n_stripes, self.k, self.chunk_size)
        shards = shards.transpose(0, 2, 1, 3).reshape(
            b, self.k, n_stripes * self.chunk_size)
        return shards[0] if squeeze else shards

    def shards_to_object(self, shards: np.ndarray,
                         object_size: int | None = None) -> np.ndarray:
        """Inverse of object_to_shards; trims padding if object_size given."""
        arr = np.asarray(shards, np.uint8)
        squeeze = arr.ndim == 2
        if squeeze:
            arr = arr[None]
        b, k, shard_len = arr.shape
        if k != self.k or shard_len % self.chunk_size:
            raise ValueError(f"shards shape {arr.shape[1:]} does not match "
                             f"k={self.k} chunk_size={self.chunk_size}")
        n_stripes = shard_len // self.chunk_size
        obj = arr.reshape(b, self.k, n_stripes, self.chunk_size)
        obj = obj.transpose(0, 2, 1, 3).reshape(b, n_stripes * self.stripe_width)
        if object_size is not None:
            obj = obj[:, :object_size]
        return obj[0] if squeeze else obj


_HINFO_SEED = 0xFFFFFFFF  # the reference seeds shard CRCs with -1


@dataclass
class HashInfo:
    """Cumulative per-shard crc32c (ref: ECUtil::HashInfo, stored in the
    hinfo_key xattr; appended on every shard write, compared by deep
    scrub). Register convention: ceph_crc32c chained from seed -1."""

    n_shards: int
    total_chunk_size: int = 0
    cumulative_shard_hashes: list[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.cumulative_shard_hashes:
            self.cumulative_shard_hashes = [_HINFO_SEED] * self.n_shards
        if len(self.cumulative_shard_hashes) != self.n_shards:
            raise ValueError("hash vector length != n_shards")

    def append(self, old_size: int, shard_chunks: np.ndarray) -> None:
        """Extend every shard's CRC with its new chunk bytes.

        shard_chunks: (n_shards, L) uint8 — the bytes appended to each
        shard at shard-offset old_size (must equal current total, the
        same append-only invariant the reference asserts).
        """
        chunks = np.asarray(shard_chunks, np.uint8)
        if chunks.ndim != 2 or chunks.shape[0] != self.n_shards:
            raise ValueError(f"shard_chunks must be ({self.n_shards}, L), "
                             f"got {chunks.shape}")
        if old_size != self.total_chunk_size:
            raise ValueError(f"append at shard offset {old_size} but "
                             f"current shard size is {self.total_chunk_size}")
        if chunks.shape[1] == 0:
            return
        regs = np.asarray(self.cumulative_shard_hashes, dtype=np.uint32)
        new = np.asarray(crc32c_extend(regs, chunks))
        self.cumulative_shard_hashes = [int(v) for v in new]
        self.total_chunk_size += chunks.shape[1]

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def verify_shard(self, shard: int, data: np.ndarray) -> bool:
        """Deep-scrub check: does this shard's full byte stream hash to
        the recorded cumulative CRC? (host path; batched scrub uses
        csum.kernels directly)."""
        arr = np.asarray(data, np.uint8).ravel()
        if arr.size != self.total_chunk_size:
            return False
        return ceph_crc32c(_HINFO_SEED, arr) == \
            self.cumulative_shard_hashes[shard]

    # -- serialization (the hinfo xattr byte format) -----------------------

    def to_bytes(self) -> bytes:
        import struct
        return struct.pack(
            f"<II{self.n_shards}I", self.n_shards,
            self.total_chunk_size, *self.cumulative_shard_hashes)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HashInfo":
        import struct
        n, total = struct.unpack_from("<II", raw)
        hashes = list(struct.unpack_from(f"<{n}I", raw, 8))
        return cls(n_shards=n, total_chunk_size=total,
                   cumulative_shard_hashes=hashes)
