"""SimCluster — hermetic multi-OSD cluster with failure detection.

Rebuild of the reference's elastic-recovery loop, in-process (refs:
heartbeats src/osd/OSD.cc handle_osd_ping/maybe_update_heartbeat_peers
with osd_heartbeat_grace; failure reports -> OSDMonitor::prepare_failure
marking down, mon_osd_down_out_interval auto-out (src/mon/OSDMonitor.cc);
map-change re-peering src/osd/PeeringState.cc choose_acting/activate;
the standalone many-daemons-one-host test pattern qa/standalone/
ceph-helpers.sh). The reference's teuthology Thrasher (qa/tasks/
ceph_manager.py) is mirrored by tests/test_cluster.py's
thrash-under-io property test.

Everything runs on a VIRTUAL clock — tick(dt) advances time, delivers
heartbeats, expires grace windows, applies down/out transitions, and
drives recovery — so failure/recovery scenarios are deterministic and
fast. Data lives in MemStores (one per OSD); each PG is a mini-
ECBackend whose acting set tracks the OSDMap.
"""

from __future__ import annotations

import numpy as np

from ..crush.map import (CRUSH_ITEM_NONE, Tunables, build_hierarchy, ec_rule,
                         replicated_rule)
from ..utils.log import g_log
from ..utils.perf_counters import PerfCountersBuilder
from .ecbackend import ECBackend, ShardSet
from .osdmap import OSDMap, PGPool
from .pgbackend import PGBackend, ReplicatedBackend


class StaleMap(Exception):
    """Op addressed to the wrong/unreachable primary — the OSD's
    'I have a newer map' reply (the client must refresh and resend)."""

    def __init__(self, epoch: int, why: str):
        super().__init__(f"stale map (cluster at epoch {epoch}): {why}")
        self.epoch = epoch


class SimCluster:
    """n_osds OSDs, one EC pool, pg_num PGs, virtual-time failure
    handling."""

    def __init__(self, n_osds: int = 12, profile: str | dict =
                 "plugin=tpu_rs k=4 m=2 impl=bitlinear",
                 pg_num: int = 8, osds_per_host: int = 1,
                 chunk_size: int = 256,
                 heartbeat_interval: float = 6.0,
                 heartbeat_grace: float = 20.0,
                 down_out_interval: float = 600.0,
                 min_down_reporters: int = 2,
                 n_mons: int = 3,
                 hosts_per_rack: int | None = None,
                 store: str = "mem",
                 store_dir: str | None = None,
                 store_compression: str | None = None):
        if hosts_per_rack is None:
            hosts_per_rack = max(4, n_osds)  # one big rack by default
        crush = build_hierarchy(n_osds, osds_per_host=osds_per_host,
                                hosts_per_rack=hosts_per_rack)
        # the reference default (51): plenty of retry headroom once
        # several OSDs are out; the vectorized mapper's while_loop
        # early-exits, so unused rounds cost nothing
        crush.tunables = Tunables(choose_total_tries=51)
        self.osdmap = OSDMap(crush)
        self.cluster = ShardSet()
        # store backend switch (the store_test.cc parameterization):
        # "mem" = RAM MemStore (process death keeps bytes by fiat);
        # "tin" = persistent TinStore (kill really drops RAM and revive
        # really recovers from WAL+checkpoint — measured, not assumed)
        if store not in ("mem", "tin"):
            raise ValueError(f"store={store!r} not in ('mem', 'tin')")
        if store_compression is not None:
            from .tinstore import TinStore
            if store != "tin":
                raise ValueError("store_compression requires "
                                 "store='tin' (MemStore never "
                                 "compresses — a silent no-op would "
                                 "fake a compressed-path test)")
            if store_compression not in TinStore.COMPRESSION_ALGS:
                raise ValueError(
                    f"unknown store_compression "
                    f"{store_compression!r}; use one of "
                    f"{TinStore.COMPRESSION_ALGS}")
        self.store_kind = store
        self.store_dir = store_dir
        if store == "tin":
            import os as _os
            import tempfile
            from .tinstore import TinStore
            if store_dir is None:
                self.store_dir = tempfile.mkdtemp(prefix="tinstore-")
            # verify_reads off INSIDE the cluster: shard integrity is
            # the backend's hinfo CRC layer (verify-on-read + EIO
            # reconstruct), which must see rotten bytes to repair them;
            # TinStore still verifies every object at mount/fsck
            # cache_bytes is deliberately TINY: sim datasets are small,
            # and a cache several times smaller than the working set
            # keeps the chaos/recovery suites exercising the eviction +
            # device-read path, not an accidental RAM mirror
            self.cluster.store_factory = lambda o: TinStore(
                _os.path.join(self.store_dir, f"osd.{o}"),
                verify_reads=False, cache_bytes=32 << 10,
                compression=store_compression,
                # sim-scale blobs are far below the production 4 KiB
                # floor; compress anything that plausibly shrinks
                compression_min_blob=64)
        self.profile = profile
        # pool type switch (ref: pg_pool_t TYPE_REPLICATED vs
        # TYPE_ERASURE; PrimaryLogPG drives either through PGBackend):
        # profile "replicated size=3 [min_size=2]" makes a replicated
        # pool; anything else is an EC profile string
        from ..ec.interface import profile_from_string
        if isinstance(profile, str):
            toks = profile.split()
            if toks and toks[0] == "replicated":  # "replicated size=3"
                prof = {"plugin": "replicated",
                        **profile_from_string(" ".join(toks[1:]))}
            else:
                prof = profile_from_string(profile)
        else:
            prof = dict(profile)
        self.is_erasure = prof.get("plugin", "") != "replicated"
        # the reference's pool creation consumes crush-failure-domain
        # from the EC profile (ref: OSDMonitor pool create ->
        # CrushWrapper rule from profile); honor the same key
        domains = {"osd": 0, "host": 1, "rack": 2}
        fd = prof.get("crush-failure-domain", "host")
        if fd not in domains:
            raise ValueError(f"crush-failure-domain {fd!r} not in "
                             f"{sorted(domains)}")
        choose_type = domains[fd]
        # the domain must actually exist in enough copies, or every PG
        # would come up short at creation with a confusing error
        n_hosts = -(-n_osds // osds_per_host)
        n_domains = {0: n_osds, 1: n_hosts,
                     2: -(-n_hosts // hosts_per_rack)}[choose_type]
        if self.is_erasure:
            from ..ec.registry import factory
            coder = factory(profile)
            self.pool_size = coder.get_chunk_count()
            self.m = coder.get_coding_chunk_count()
            min_size = self.pool_size - self.m
            ec_rule(crush, 1, choose_type=choose_type)
        else:
            self.pool_size = int(prof.get("size", 3))
            min_size = int(prof.get("min_size",
                                    self.pool_size - self.pool_size // 2))
            self.m = self.pool_size - min_size
            replicated_rule(crush, 1, choose_type=choose_type,
                            firstn=True)
        if n_domains < self.pool_size:
            raise ValueError(
                f"crush-failure-domain={fd}: only {n_domains} "
                f"domain(s) in the topology but the pool needs "
                f"{self.pool_size}; add osds/hosts/racks (e.g. "
                f"hosts_per_rack=) or pick a finer domain")
        self.pool_min_size = min_size
        self.osdmap.add_pool(PGPool(1, pg_num=pg_num, size=self.pool_size,
                                    min_size=min_size,
                                    crush_rule=1,
                                    is_erasure=self.is_erasure))
        self.pg_num = pg_num
        self.chunk_size = chunk_size
        # timing / failure model
        self.now = 0.0
        self.hb_interval = heartbeat_interval
        self.hb_grace = heartbeat_grace
        self.down_out_interval = down_out_interval
        self.min_down_reporters = min_down_reporters
        self.alive = np.ones(n_osds, dtype=bool)      # process up?
        self.destroyed: set[int] = set()              # disk gone for good
        # monitor quorum gates every map mutation (ref: OSDMonitor
        # commits through Paxos; no majority -> the map freezes and
        # failure handling stalls cluster-wide)
        from ..mon.monitor import MonitorCluster, NoQuorum
        self._NoQuorum = NoQuorum
        self.mons = MonitorCluster(n_mons)
        self.last_heard = np.zeros((n_osds, n_osds))  # peer hb stamps
        self.down_since: dict[int, float] = {}
        # async backfill state: ps -> {"moves": [(slot, old, new)],
        # "names": objects still to copy, "queued": names already
        # enqueued on the op scheduler}; while a PG backfills, pg_temp
        # keeps the OLD acting set serving I/O (ref: PeeringState
        # requests pg_temp until backfill completes)
        self.backfills: dict[int, dict] = {}
        # pool snapshots (ref: pg_pool_t snap_seq/snaps; PrimaryLogPG
        # make_writeable copy-on-write clones + SnapSet; snaptrim):
        # clones are REGULAR objects (placed/recovered/scrubbed like
        # any other; divergence from the reference disclosed: they
        # hash to their own PG rather than the head's), metadata here
        self.snap_seq = 0
        self.snaps: dict[int, float] = {}          # id -> ctime
        # self-managed snaps (ref: pg_pool_t FLAG_SELFMANAGED_SNAPS;
        # librados selfmanaged_snap_create + per-op SnapContext): ids
        # share the pool seq space, but COW is driven by the snapc the
        # CLIENT sends with each write, not the pool's own snap list —
        # how RBD gets per-image snapshots out of a shared pool. The
        # two modes are mutually exclusive per pool, as upstream.
        self.sm_snaps: set[int] = set()
        self.selfmanaged = False
        # head -> [(clone seq, birth era)]: a clone covers snaps s
        # with birth < s <= seq (the birth rides with the clone so an
        # object born BETWEEN snaps never phantom-exists at the older
        # one, even after the head is removed or recreated)
        self.snapsets: dict[str, list[tuple[int, int]]] = {}
        self.object_births: dict[str, int] = {}    # head -> seq at create
        # watch/notify registry (ref: PrimaryLogPG watch/notify;
        # Objecter::linger): cookie -> callback per object
        self.watches: dict[str, dict[int, object]] = {}
        self._next_cookie = 1
        # object-class KV plane (ref: cls_* methods' omap usage)
        self.obj_kv: dict[str, dict] = {}
        # mClock op scheduler paces background work (ref: src/osd/
        # scheduler/mClockScheduler.cc); backfill copies ride the
        # background_recovery class, whose limit is backfill_rate
        # objects/s in virtual time
        from .scheduler import MClockScheduler
        self.sched = MClockScheduler()
        self.backfill_rate = 32   # objects/s (sets the mclock limit)
        # scrub scheduling (ref: osd_scrub_min_interval /
        # osd_deep_scrub_interval; defaults scaled to virtual time)
        self.scrub_interval = 300.0
        self.deep_scrub_interval = 1800.0
        self.last_scrub: dict[int, float] = {}
        self.last_deep_scrub: dict[int, float] = {}
        self._scrub_queued: set[int] = set()
        self.scrub_reports: dict[int, dict] = {}
        # epoch at which each PG's serving set last changed; client ops
        # carrying an older epoch are rejected with the current map
        # (the reference OSD's require_same_or_newer_map behavior)
        self.pg_changed_epoch: dict[int, int] = {}
        # interval-freshness bookkeeping (the up_thru machinery, ref:
        # osd_info_t::up_thru + PeeringState WaitUpThru): ps -> epoch
        # at which its acting primary last changed (the interval's
        # start). A primary whose map-recorded up_thru lags its
        # interval start holds the PG in "peering" until the monitors
        # commit it (_record_up_thrus).
        self.interval_start: dict[int, int] = {}
        self._pg_primary: dict[int, int] = {}
        # per-op stage tracking on the client path (ref: OpTracker/
        # TrackedOp, dump_historic_ops on the admin socket)
        from ..utils.config import g_conf
        from ..utils.op_tracker import OpTracker
        # thresholds resolve through the process config, so
        # osd_op_complaint_time / osd_op_history_* apply to the sim
        # tier's tracker the same way they do per wire daemon
        self.op_tracker = OpTracker(config=g_conf)
        self.perf = (PerfCountersBuilder("cluster")
                     .add_u64_counter("recovered_objects")
                     .add_u64_counter("log_replayed_objects")
                     .add_u64_counter("backfilled_objects")
                     .add_u64_counter("backfills_completed")
                     .add_u64_counter("revive_full_rebuilds")
                     .add_u64_counter("deferred_replays")
                     .add_u64_counter("osd_marked_down")
                     .add_u64_counter("osd_marked_out")
                     .add_u64_counter("scrubs_shallow")
                     .add_u64_counter("scrubs_deep")
                     .add_u64_counter("scrub_errors")
                     .add_u64("degraded_pgs")
                     .create_perf_counters())
        # PG backends at their initial acting sets
        self.pgs: dict[int, PGBackend] = {}
        for ps in range(pg_num):
            acting = self._acting(ps)
            if any(a == CRUSH_ITEM_NONE for a in acting):
                raise ValueError(f"pg {ps} has unfilled slots at creation; "
                                 f"use more osds/hosts")
            self.pgs[ps] = self._make_backend(f"1.{ps}", acting)
        # the creation interval: every primary records its up_thru
        # through the (fully alive) monitor quorum before I/O starts
        self._refresh_intervals()
        self._record_up_thrus()

    def _make_backend(self, pg: str, acting: list[int]) -> PGBackend:
        if self.is_erasure:
            return ECBackend(self.profile, pg, acting, self.cluster,
                             chunk_size=self.chunk_size)
        return ReplicatedBackend(self.pool_size, pg, acting,
                                 self.cluster, min_size=self.pool_min_size)

    # -- QoS ----------------------------------------------------------------

    @property
    def backfill_rate(self) -> float:
        return self._backfill_rate

    @backfill_rate.setter
    def backfill_rate(self, objs_per_s: float) -> None:
        """Retune the background_recovery mClock limit (the
        osd_mclock config-change path)."""
        from .scheduler import ClientProfile
        self._backfill_rate = objs_per_s
        self.sched.set_profile(
            "background_recovery",
            ClientProfile(reservation=0.0, weight=5.0,
                          limit=float(objs_per_s)))

    # -- placement helpers --------------------------------------------------

    def _acting(self, ps: int) -> list[int]:
        up, _upp, acting, _actp = self.osdmap.pg_to_up_acting_osds(1, ps)
        return acting

    def _up(self, ps: int) -> list[int]:
        """The CRUSH-mapped target set, ignoring pg_temp overrides —
        what re-peering steers toward (acting may lag behind during
        backfill by design)."""
        return self.osdmap.pg_to_up_acting_osds(1, ps)[0]

    def locate(self, name: str) -> int:
        return self.osdmap.object_to_pg(1, name)[1]

    # -- interval freshness (up_thru) ----------------------------------------

    def _refresh_intervals(self) -> None:
        """Detect acting-primary changes — each one starts a NEW
        INTERVAL for that PG — and stamp the start epoch (the
        PastIntervals bookkeeping, collapsed to the piece up_thru
        needs: who led, since when)."""
        for ps in range(self.pg_num):
            p = self.osdmap.pg_to_up_acting_osds(1, ps)[3]
            if self._pg_primary.get(ps) != p:
                self._pg_primary[ps] = p
                self.interval_start[ps] = self.osdmap.epoch

    def _record_up_thrus(self) -> None:
        """Primaries of fresh intervals get their up_thru recorded
        through the monitor quorum (the MOSDAlive flow, ref:
        OSDMonitor::prepare_alive). No quorum -> nothing is recorded,
        the PG stays in WaitUpThru (client ops park), and the request
        retries on the next tick — monitor loss visibly gates
        activation of new intervals, exactly the reference behavior."""
        for ps in range(self.pg_num):
            p = self._pg_primary.get(ps, -1)
            start = self.interval_start.get(ps, 0)
            if not (0 <= p < len(self.alive)) or not self.alive[p] \
                    or not self.osdmap.osd_up[p] \
                    or self.osdmap.osd_up_thru[p] >= start:
                continue
            try:
                self.mons.record_up_thru(p, start)
            except self._NoQuorum:
                g_log.dout("mon", 0, f"no quorum; up_thru for osd.{p} "
                                     f"(pg 1.{ps}) deferred")
                continue
            self.osdmap.record_up_thru(p, start)
            g_log.dout("mon", 1, f"osd.{p} up_thru {start} recorded "
                                 f"(epoch {self.osdmap.epoch})")

    def _peer_classify(self, ps: int):
        """One classify-only peering pass with the up_thru consult
        (shared by the client-op gate and the health view)."""
        from .peering import peer
        p = self._pg_primary.get(ps, -1)
        up_thru = int(self.osdmap.osd_up_thru[p]) \
            if 0 <= p < len(self.alive) else None
        return peer(self.pgs[ps], self.alive,
                    backfilling=ps in self.backfills,
                    compute_missing=False,
                    interval_start=self.interval_start.get(ps, 0),
                    up_thru=up_thru)

    # -- client I/O ---------------------------------------------------------

    def _apply_write(self, ps: int, kind: str, payload,
                     dead: set[int], snapc: int = 0) -> None:
        """One PG write (full objects or ranges) with the invariants
        every write path must keep: dead OSDs receive nothing (PGLog
        records the gap), and objects written during a backfill are
        (re-)queued for copy — the bytes went to the OLD serving set."""
        be = self.pgs[ps]
        if kind == "write":
            names = set(payload.keys())
        elif kind == "remove":
            names = set(payload)
        else:  # write_ranges
            names = {n for n, _, _ in payload}
        # snapshot copy-on-write (PrimaryLogPG::make_writeable): any
        # mutation of a head whose newest clone predates the newest
        # snap first preserves the current state as a clone. Pool-snap
        # pools use the pool's own seq; selfmanaged pools use the seq
        # the client's SnapContext carries (a writer that knows no
        # snaps preserves nothing — librados semantics).
        if self.snaps:
            self._preserve_clones(names, self.snap_seq)
        elif snapc and self.sm_snaps:
            self._preserve_clones(names, min(snapc, self.snap_seq))
        if kind == "write":
            be.write_objects(payload, dead_osds=dead)
        elif kind == "remove":
            be.remove_objects(payload, dead_osds=dead)
            # per-object side state dies with the object (the
            # reference's omap and watches are object-lifetime): a
            # recreated name must not inherit a dead object's locks,
            # watchers, or birth era. SnapSets survive — clones
            # outlive the head by design.
            for name in names:
                self.obj_kv.pop(name, None)
                self.watches.pop(name, None)
                self.object_births.pop(name, None)
        else:
            be.write_ranges(payload, dead_osds=dead)
        job = self.backfills.get(ps)
        if job is not None:
            job["names"].update(names)

    def _dead_osds(self) -> set[int]:
        return {o for o in range(len(self.alive)) if not self.alive[o]}

    def write(self, objects: dict[str, bytes | np.ndarray],
              snapc: int = 0) -> None:
        # dead processes get no sub-writes; their shards fall behind in
        # the PG log and catch up on revive (ref: a down OSD misses
        # MOSDECSubOpWrite fan-out; PGLog records the gap). One dead-set
        # snapshot serves every PG group of this dispatch (the groups
        # all commit under the same failure view, matching the wire
        # tier's one-op-one-suspect-set semantics), and each group runs
        # the backend's fused encode+CRC launch.
        by_pg: dict[int, dict] = {}
        for name, data in objects.items():
            by_pg.setdefault(self.locate(name), {})[name] = data
        dead = self._dead_osds()
        for ps, group in by_pg.items():
            self._apply_write(ps, "write", group, dead, snapc=snapc)

    def read(self, name: str) -> np.ndarray:
        ps = self.locate(name)
        dead = self._dead_osds()
        return self.pgs[ps].read_object(name, dead_osds=dead)

    def repair_pg(self, ps: int) -> dict:
        """`ceph pg repair 1.<ps>`: scrub + rewrite inconsistent
        shards/replicas from the surviving good copies."""
        rep = self.pgs[ps].repair_pg(dead_osds=self._dead_osds())
        if rep["repaired"]:
            self.scrub_reports.pop(ps, None)  # rot is gone
            g_log.dout("scrub", 1, f"pg 1.{ps} repaired "
                                   f"{rep['repaired']} shard(s)")
        return rep

    # -- PG splitting (pg_num increase) --------------------------------------

    def split_pgs(self, new_pg_num: int) -> dict:
        """Execute a pg_num increase — the split machinery the
        autoscaler's recommendation needs (ref: src/osd/PG.cc split;
        src/mon/OSDMonitor.cc pg_num handling; ceph_stable_mod
        re-bucketing). Sequence:

        1. quorum-gated map mutation (pg_num is monitor state);
        2. children are created ON THEIR PARENT'S acting set and the
           re-bucketed objects move store-LOCALLY (collection split —
           no bytes cross OSDs, both PG logs record the transfer);
        3. _repeer_all() then steers each child toward its own CRUSH
           targets with the standard pg_temp-protected backfill, so
           reads keep working from the parent's OSDs mid-move.

        Requires a settled cluster (no live backfills, every parent
        clean) — the reference likewise splits healthy PGs; the
        autoscaler simply retries later otherwise."""
        old = self.pg_num
        if new_pg_num <= old:
            raise ValueError(f"pg_num {new_pg_num} <= current {old} "
                             f"(merges not supported)")
        if self.backfills:
            raise ValueError("backfills in flight; let the cluster "
                             "settle before splitting")
        dead = self._dead_osds()
        for ps in range(old):
            be = self.pgs[ps]
            if any(o in dead or o not in self.cluster.stores
                   for o in be.acting):
                raise ValueError(f"pg 1.{ps} degraded; heal before "
                                 f"splitting")
            # a live-but-behind shard (revive during quorum loss defers
            # its catch-up) must refuse HERE, while nothing has moved
            # and the map is untouched — split_to's own check would
            # otherwise abort mid-split with children half-created
            for s in range(be.n):
                if be.shard_applied[s] < be.pg_log.head:
                    raise ValueError(
                        f"pg 1.{ps} shard {s} not caught up; heal "
                        f"before splitting")
        if not self._mon_commit(f"pool 1 pg_num {old} -> {new_pg_num}"):
            raise ValueError("no monitor quorum; pg_num change refused")
        from .osdmap import (ceph_stable_mod, pg_num_mask,
                             str_hash_rjenkins)
        old_mask = pg_num_mask(old)
        new_mask = pg_num_mask(new_pg_num)
        children: dict[int, int] = {}
        moved = 0
        # one hash pass per parent buckets every re-homed object (the
        # child ids are deterministic: parent == stable_mod(child, old))
        kids_of: dict[int, list[int]] = {}
        for child_ps in range(old, new_pg_num):
            kids_of.setdefault(
                int(ceph_stable_mod(child_ps, old, old_mask)),
                []).append(child_ps)
        for parent_ps, kids in kids_of.items():
            parent = self.pgs[parent_ps]
            rehome: dict[int, list[str]] = {c: [] for c in kids}
            for n in parent.list_pg_objects():
                tgt = int(ceph_stable_mod(str_hash_rjenkins(n),
                                          new_pg_num, new_mask))
                if tgt != parent_ps:
                    rehome[tgt].append(n)
            for child_ps in kids:
                child = self._make_backend(f"1.{child_ps}",
                                           list(parent.acting))
                moved += parent.split_to(child, rehome[child_ps])
                self.pgs[child_ps] = child
                children[child_ps] = parent_ps
        # flip the map LAST: every re-homed byte is already in its
        # child's collections, so the instant locate() starts routing
        # to children their data is in place (no observable gap, and
        # no abort path can leave pg_num pointing at missing PGs)
        self.osdmap.set_pg_num(1, new_pg_num)
        self.pg_num = new_pg_num
        g_log.dout("osd", 1,
                   f"pool 1 split {old} -> {new_pg_num} pgs; "
                   f"{moved} objects re-homed into "
                   f"{len(children)} children (collection split)")
        # steer children from their parents' OSDs to their own CRUSH
        # targets; pg_temp keeps the parent set serving meanwhile
        self._repeer_all()
        return {"pg_num": new_pg_num, "children": children,
                "objects_moved": moved}

    def apply_autoscale(self, target_pg_per_osd: int = 100,
                        threshold: float = 3.0,
                        max_pg_num: int | None = None) -> dict | None:
        """Run the autoscaler and EXECUTE its recommendation (the
        reference's autoscale `on` mode, vs the advisory `warn` the
        mgr module defaults to; ref: src/pybind/mgr/pg_autoscaler).
        Returns split_pgs()' report, or None when no change is due.
        `max_pg_num` caps the jump (mon_max_pool_pg_num role)."""
        from ..mgr.pg_autoscaler import recommend_pg_num
        rec = recommend_pg_num(self.osdmap, 1, target_pg_per_osd,
                               threshold)
        target = rec["pg_num_recommended"]
        if max_pg_num is not None:
            target = min(target, max_pg_num)
        if not rec["would_adjust"] or target <= self.pg_num:
            return None
        return self.split_pgs(target)

    # -- pool snapshots (PrimaryLogPG snap machinery) ------------------------

    _SNAP_SEP = "@@snap."

    @classmethod
    def _clone_name(cls, name: str, seq: int) -> str:
        return f"{name}{cls._SNAP_SEP}{seq:08x}"

    def _preserve_clones(self, names, eff_seq: int) -> None:
        """COW step: for each head about to mutate, if its state hasn't
        been preserved since snap era `eff_seq` (the newest pool snap,
        or the newest snap the client's SnapContext names), write the
        current bytes as a clone object and record it in the SnapSet."""
        dead = self._dead_osds()
        for name in sorted(names):
            if self._SNAP_SEP in name:
                continue            # clones never re-clone
            ps = self.locate(name)
            be = self.pgs[ps]
            if name not in be.object_sizes:
                # creation: remember the snap era it was born in, so
                # reads at older snaps correctly say "didn't exist"
                self.object_births[name] = eff_seq
                continue
            if self.object_births.get(name, 0) >= eff_seq:
                # born AFTER the newest snap: no snap contains it, so
                # preserving a clone would make it phantom-exist there
                continue
            ss = self.snapsets.setdefault(name, [])
            if ss and ss[-1][0] >= eff_seq:
                continue            # newest snap already has its clone
            data = be.read_object(name, dead_osds=dead)
            clone = self._clone_name(name, eff_seq)
            cps = self.locate(clone)
            self._apply_write(cps, "write", {clone: data}, dead)
            ss.append((eff_seq,
                       self.object_births.get(name, 0)))

    def snap_create(self) -> int:
        """Take a pool snapshot (ref: OSDMonitor pool mksnap ->
        pg_pool_t::add_snap): monitor-quorum-gated seq bump; data is
        preserved lazily by the write-path COW."""
        if self.selfmanaged:
            raise ValueError("pool uses selfmanaged snaps; pool "
                             "snapshots refused (ref: pg_pool_t "
                             "FLAG_SELFMANAGED_SNAPS exclusivity)")
        if not self._mon_commit(f"pool 1 mksnap {self.snap_seq + 1}"):
            raise ValueError("no monitor quorum; snap refused")
        self.snap_seq += 1
        self.snaps[self.snap_seq] = self.now
        return self.snap_seq

    def selfmanaged_snap_create(self) -> int:
        """Allocate a self-managed snap id (ref: librados
        selfmanaged_snap_create -> OSDMonitor pool selfmanaged mksnap).
        No pool-wide COW follows from this alone: clones are made only
        for writes whose SnapContext names the id (`snapc=` on the
        write path) — per-image snapshots for RBD."""
        if self.snaps:
            raise ValueError("pool already has pool snapshots; "
                             "selfmanaged snaps refused")
        if not self._mon_commit(
                f"pool 1 selfmanaged mksnap {self.snap_seq + 1}"):
            raise ValueError("no monitor quorum; snap refused")
        self.selfmanaged = True
        self.snap_seq += 1
        self.sm_snaps.add(self.snap_seq)
        return self.snap_seq

    def selfmanaged_snap_remove(self, sid: int) -> int:
        """Delete a self-managed snap + snaptrim (ref: librados
        selfmanaged_snap_remove). Returns clones trimmed."""
        if sid not in self.sm_snaps:
            raise KeyError(f"no selfmanaged snap {sid}")
        if not self._mon_commit(f"pool 1 selfmanaged rmsnap {sid}"):
            raise ValueError("no monitor quorum; snap removal refused")
        self.sm_snaps.discard(sid)
        return self._snap_trim()

    def _live_snaps(self):
        """Snap ids any clone may still serve (pool + selfmanaged)."""
        return set(self.snaps) | self.sm_snaps

    def snap_read(self, name: str, sid: int) -> np.ndarray:
        """Read an object's state as of snap `sid`: the OLDEST clone
        with seq >= sid, else the unmodified head (ref: PrimaryLogPG
        find_object_context snap resolution via SnapSet.clones)."""
        if sid not in self.snaps and sid not in self.sm_snaps:
            raise KeyError(f"no snap {sid}")
        cands = [seq for seq, birth in self.snapsets.get(name, [])
                 if seq >= sid and birth < sid]   # alive AT the snap
        if cands:
            return self.read(self._clone_name(name, min(cands)))
        ps = self.locate(name)
        if name in self.pgs[ps].object_sizes \
                and self.object_births.get(name, 0) < sid:
            return self.read(name)   # unchanged since before the snap
        raise KeyError(f"{name!r} did not exist at snap {sid}")

    def snap_rollback(self, name: str, sid: int) -> None:
        """rados rollback: write the snap's state back onto the head
        (itself COW-protected, so the pre-rollback head is preserved
        if a newer snap needs it)."""
        self.write({name: self.snap_read(name, sid)})

    def snap_remove(self, sid: int) -> int:
        """Delete a snap + trim clones no live snap reads anymore (the
        snaptrim role; ref: PrimaryLogPG::trim_object). Returns the
        number of clone objects trimmed."""
        if sid not in self.snaps:
            raise KeyError(f"no snap {sid}")
        if not self._mon_commit(f"pool 1 rmsnap {sid}"):
            raise ValueError("no monitor quorum; snap removal refused")
        del self.snaps[sid]
        return self._snap_trim()

    def snap_changed(self, name: str, sid: int) -> bool:
        """Has `name`'s head diverged from its state at snap `sid`?
        Metadata-only (SnapSet + birth eras — the object-map/fast-diff
        role, ref: librbd fast-diff via cls_rbd object map; the slow
        path lists per-object snaps): no data is read or compared."""
        if sid not in self.snaps and sid not in self.sm_snaps:
            raise KeyError(f"no snap {sid}")
        exists_now = name in self.pgs[self.locate(name)].object_sizes
        covered = any(seq >= sid and birth < sid
                      for seq, birth in self.snapsets.get(name, []))
        if covered:
            return True      # a clone was preserved => head mutated
        if not exists_now:
            return False     # didn't exist then (no covering clone),
                             # doesn't exist now
        # head unchanged since before the snap iff it was born earlier
        return self.object_births.get(name, 0) >= sid

    def _snap_trim(self) -> int:
        """Drop clones no live snap reads anymore. Idempotent and
        failure-tolerant: a clone whose removal is refused mid-chaos
        (degraded PG) stays in the SnapSet and is retried on the next
        trim — the snap deletion itself never half-applies."""
        trimmed = 0
        live = self._live_snaps()
        for name, ss in list(self.snapsets.items()):
            keep: list[tuple[int, int]] = []
            prev = 0
            for c, birth in ss:      # ascending; clone c covers snaps
                # (prev_kept, c], minus snaps older than its birth era
                if any(prev < s <= c and s > birth
                       for s in live):
                    keep.append((c, birth))
                    prev = c
                    continue
                try:
                    self.remove(self._clone_name(name, c))
                    trimmed += 1
                except KeyError:
                    trimmed += 1     # already gone: count as trimmed
                except ValueError:
                    keep.append((c, birth))   # PG unwritable: keep the
                    prev = c                  # clone, retry later
            if keep:
                self.snapsets[name] = keep
            else:
                del self.snapsets[name]
        return trimmed

    # -- watch / notify ------------------------------------------------------

    def watch(self, name: str, callback) -> int:
        """Register interest in an object (ref: PrimaryLogPG watch;
        callback(notifier_name, payload) -> optional reply bytes)."""
        ps = self.locate(name)
        if name not in self.pgs[ps].object_sizes:
            raise KeyError(f"no object {name!r}")
        cookie = self._next_cookie
        self._next_cookie += 1
        self.watches.setdefault(name, {})[cookie] = callback
        return cookie

    def unwatch(self, name: str, cookie: int) -> None:
        self.watches.get(name, {}).pop(cookie, None)

    def notify(self, name: str, payload: bytes = b"") -> dict:
        """Invoke every watcher; returns {cookie: reply-or-None}. A
        watcher whose callback raises is reported as None (the
        timed-out-watcher slot in the reference's notify reply)."""
        acks: dict[int, bytes | None] = {}
        for cookie, cb in list(self.watches.get(name, {}).items()):
            try:
                acks[cookie] = cb(name, payload)
            except Exception:        # noqa: BLE001 — a broken watcher
                acks[cookie] = None  # must not kill the notify fan-out
        return acks

    # -- object classes ------------------------------------------------------

    def cls_exec(self, name: str, cls: str, method: str,
                 inp: bytes = b"") -> bytes:
        """Execute a registered object-class method against an object
        at its primary (ref: PrimaryLogPG::do_osd_ops OP_CALL ->
        ClassHandler). Writes made by the method ride the normal
        client path (COW, PG log, EC fan-out included)."""
        from .objclass import cls_call
        return cls_call(self, name, cls, method, inp)

    def remove(self, names: list[str] | str, snapc: int = 0) -> None:
        names = [names] if isinstance(names, str) else list(names)
        by_pg: dict[int, list[str]] = {}
        for name in names:
            by_pg.setdefault(self.locate(name), []).append(name)
        for ps, group in by_pg.items():
            self._apply_write(ps, "remove", group, self._dead_osds(),
                              snapc=snapc)

    # -- client RPC (the primary-OSD session an Objecter talks to) ----------

    def _note_pg_change(self, ps: int) -> None:
        self.pg_changed_epoch[ps] = self.osdmap.epoch

    def client_rpc(self, target_osd: int, epoch: int, kind: str, ps: int,
                   payload, snapc: int = 0):
        """One client op addressed to `target_osd` as pg `ps`'s
        primary, carrying the client's map `epoch`. Raises StaleMap
        when the op's epoch predates the PG's last serving-set change,
        when the target is not the current acting primary, or when its
        process is dead — the signals that make the Objecter refresh +
        retarget (ref: OSD require_same_or_newer_map + map sharing;
        lossy client connections)."""
        with self.op_tracker.create_op(
                f"client_rpc {kind} pg 1.{ps} -> osd.{target_osd}") as op:
            return self._client_rpc_tracked(op, target_osd, epoch, kind,
                                            ps, payload, snapc)

    def _client_rpc_tracked(self, op, target_osd: int, epoch: int,
                            kind: str, ps: int, payload,
                            snapc: int = 0):
        if epoch < self.pg_changed_epoch.get(ps, 0):
            raise StaleMap(self.osdmap.epoch,
                           f"pg 1.{ps} remapped at epoch "
                           f"{self.pg_changed_epoch[ps]}, op carries "
                           f"epoch {epoch}")
        primary = self.osdmap.pg_to_up_acting_osds(1, ps)[3]
        if target_osd < 0 or target_osd != primary:
            raise StaleMap(self.osdmap.epoch,
                           f"pg 1.{ps} primary is osd.{primary}, "
                           f"op sent to osd.{target_osd}")
        if not self.alive[target_osd]:
            raise StaleMap(self.osdmap.epoch,
                           f"osd.{target_osd} is not answering")
        # a PG that peered down/incomplete blocks I/O entirely, and so
        # does one still in WaitUpThru — serving a write before the
        # monitors recorded this interval's up_thru would create a
        # write nobody can later prove happened (the reference parks
        # ops on a waiting list; our client retries until the PG is
        # serviceable again)
        res = self._peer_classify(ps)
        if not res.serviceable:
            raise StaleMap(self.osdmap.epoch,
                           f"pg 1.{ps} is {res.state}; op parked")
        op.mark_event("reached_pg")  # map checks + peering gate passed
        dead = self._dead_osds()
        if kind == "append":
            # tail append (librados rados_append): the PRIMARY owns
            # the authoritative size, so the offset resolves here —
            # two appenders racing through the same primary serialize
            # instead of clobbering. Rides _apply_write as a range
            # write so COW + backfill requeue apply; on an EC pool a
            # tail inside stripe padding takes the r16 append fast
            # path (no pre-read) inside write_ranges.
            name, data = payload
            off = int(self.pgs[ps].object_sizes.get(name, 0))
            self._apply_write(ps, "write_ranges", [(name, off, data)],
                              dead, snapc=snapc)
            op.mark_event("commit_sent")
            return off
        if kind in ("write", "write_ranges", "remove"):
            self._apply_write(ps, kind, payload, dead, snapc=snapc)
            op.mark_event("commit_sent")
            return None
        if kind == "read":
            out = self.pgs[ps].read_objects(payload, dead_osds=dead)
            op.mark_event("reply_sent")
            return out
        raise ValueError(f"unknown client op kind {kind!r}")

    def degraded_read(self, ps: int, names):
        """Degraded-read fast path (the wire tier's `read_degraded`
        analog, ROADMAP item 3): serve a read from any k surviving
        shards RIGHT NOW, bypassing the primary-session and peering
        gates client_rpc enforces — a dead or still-peering primary
        must cost a decode, not a detection + activation wait (the
        degraded-read tail of the online-EC study, arxiv 1709.05365).
        Reads mutate nothing, so no EIO repair writeback either
        (repair=False keeps the re-decode)."""
        with self.op_tracker.create_op(
                f"degraded_read pg 1.{ps}") as op:
            dead = self._dead_osds()
            out = self.pgs[ps].read_objects(names, dead_osds=dead,
                                            repair=False)
            op.mark_event("reply_sent")
            return out

    # -- failure model ------------------------------------------------------

    def kill_osd(self, osd: int) -> None:
        """Process death: store bytes survive, peer stops answering.
        On a persistent store this is REAL SIGKILL semantics — the RAM
        mirror is dropped and only WAL+checkpoint bytes remain; any
        path that still reads the dead store raises instead of quietly
        seeing ghost state."""
        self.alive[osd] = False
        st = self.cluster.stores.get(osd)
        if st is not None:
            st.crash()
        g_log.dout("osd", 1, f"osd.{osd} killed at t={self.now}")

    def destroy_osd(self, osd: int) -> None:
        """Disk loss: kill + drop the store (and its on-disk files)."""
        self.kill_osd(osd)
        st = self.cluster.stores.pop(osd, None)
        if st is not None and st.path is not None:
            import shutil
            shutil.rmtree(st.path, ignore_errors=True)
        self.destroyed.add(osd)

    def revive_osd(self, osd: int) -> None:
        """Process restart with its store intact: the OSD rejoins and
        every PG catches its shard up via PG-log delta replay (ref:
        PeeringState GetLog/GetMissing -> log-based recovery), falling
        back to a full shard rebuild only when the log was trimmed past
        the shard's applied cursor (the backfill case). A destroyed
        store cannot rejoin — recovery re-places its data instead."""
        if osd in self.destroyed:
            raise ValueError(
                f"osd.{osd} was destroyed (disk lost); it cannot rejoin "
                f"with its old identity — let recovery re-place its data")
        st = self.cluster.stores.get(osd)
        if st is not None and st.is_down:
            # persistent store: recover state from WAL+checkpoint (the
            # OSD boot mount; what MemStore keeps by fiat, TinStore
            # must actually replay)
            st.remount()
        self.alive[osd] = True
        self.last_heard[:, osd] = self.now
        if not self.osdmap.osd_up[osd]:
            if not self._mon_commit(f"osd.{osd} up"):
                # the process is back but the map can't record it; the
                # next tick with quorum will (boot message retried)
                return
            self.osdmap.mark_up(osd)
        was_out = self.osdmap.osd_weight[osd] == 0
        self.down_since.pop(osd, None)
        g_log.dout("osd", 1, f"osd.{osd} revived at t={self.now}")
        # every shard left behind (this OSD's, and any whose earlier
        # replay was deferred for lack of live peers) tries to catch up
        # now; reads stay safe meanwhile because ECBackend never serves
        # an object from a shard whose cursor predates its last write
        self._catch_up_all()
        if was_out:
            # rejoin after auto-out: weight restored -> CRUSH moves
            # slots back from their interim holders; those are live
            # sources, so the moves run as pg_temp-protected backfills
            self.osdmap.mark_in(osd)
            g_log.dout("mon", 1, f"osd.{osd} marked in (epoch "
                                 f"{self.osdmap.epoch})")
            self._repeer_all()

    def _catch_up_all(self) -> None:
        """Re-peer every PG (GetInfo -> GetLog -> GetMissing via
        peering.peer) and execute the resulting per-shard missing plan:
        behind live shards replay the log delta, log-trimmed shards get
        a full rebuild. Shards whose PGs lack enough caught-up live
        peers stay deferred (the down/incomplete PG state) and retry on
        the next revive."""
        from .peering import BACKFILL, peer
        for ps in range(self.pg_num):
            be = self.pgs[ps]
            res = peer(be, self.alive, backfilling=ps in self.backfills)
            for slot, plan in sorted(res.missing.items()):
                o = be.acting[slot]
                backfill = plan == BACKFILL
                if backfill:
                    # full rebuild, PLUS purge of objects deleted while
                    # the shard was down (the trimmed log can't name
                    # them, but the shard's own store can)
                    from .ecbackend import shard_cid
                    cid = shard_cid(be.pg, slot)
                    strays = [n for n in
                              self.cluster.osd(o).list_objects(cid)
                              if n not in be.object_sizes]
                    missed = sorted(be.object_sizes) + strays
                else:
                    missed = plan
                if not missed:
                    be.shard_applied[slot] = be.pg_log.head
                    continue
                exclude = {i.slot for i in res.infos
                           if i.slot != slot and not i.alive}
                try:
                    counters = be.recover_shards(
                        [slot], replacement_osds={slot: o}, names=missed,
                        helper_exclude=exclude)
                except ValueError as e:
                    g_log.dout("recovery", 0,
                               f"pg 1.{ps}: osd.{o} catch-up deferred "
                               f"({e})")
                    self.perf.inc("deferred_replays")
                    continue
                if backfill:
                    self.perf.inc("revive_full_rebuilds")
                    self.perf.inc("backfilled_objects",
                                  counters["objects"])
                else:
                    self.perf.inc("log_replayed_objects",
                                  counters["objects"])
                g_log.dout("recovery", 1,
                           f"pg 1.{ps}: osd.{o} "
                           f"{'backfilled' if backfill else 'replayed'} "
                           f"{counters['objects']} objects")

    def tick(self, dt: float = 1.0) -> None:
        """Advance virtual time; deliver heartbeats; run the
        monitor's failure logic; trigger recovery on map changes."""
        steps = max(1, int(round(dt / self.hb_interval)))
        for _ in range(steps):
            self.now += dt / steps
            up = self.alive
            # alive peers hear each other every interval
            self.last_heard[np.ix_(up, up)] = self.now
            # grace expiry: alive i reports silent j
            silent = self.now - self.last_heard > self.hb_grace
            for j in range(len(up)):
                if not self.osdmap.osd_up[j]:
                    continue
                reporters = int(silent[up, j].sum())
                if reporters >= self.min_down_reporters:
                    self._mark_down(j)
            # boot retries FIRST: an OSD revived during monitor quorum
            # loss is alive but still map-down (down_since retained);
            # re-announcing before the down->out pass prevents a
            # spurious mark-out + double repeer of a live OSD the
            # instant quorum heals
            for o in np.nonzero(self.alive & ~self.osdmap.osd_up)[0]:
                if int(o) not in self.destroyed:
                    self.revive_osd(int(o))
            # down long enough -> out -> remap + recover
            for j, since in list(self.down_since.items()):
                if self.now - since >= self.down_out_interval:
                    self._mark_out(j)
            self._progress_backfills()
            self._schedule_scrubs()
            self._pump()
            # close any WaitUpThru window this step opened (mark_down
            # primary changes, backfill cutovers) or a previous quorum
            # loss left behind — the MOSDAlive retry
            self._refresh_intervals()
            self._record_up_thrus()

    # -- monitor plumbing ---------------------------------------------------

    def _mon_commit(self, what: str) -> bool:
        """Commit a map mutation through the monitor quorum; False
        (and no mutation) when the monitors lack a majority."""
        try:
            self.mons.propose("osdmap/last_change",
                              (self.osdmap.epoch + 1, what))
            return True
        except self._NoQuorum:
            g_log.dout("mon", 0, f"no quorum; {what} deferred")
            return False

    def kill_mon(self, rank: int) -> None:
        self.mons.kill(rank)
        g_log.dout("mon", 1, f"mon.{rank} killed")

    def revive_mon(self, rank: int) -> None:
        self.mons.revive(rank)
        g_log.dout("mon", 1, f"mon.{rank} revived")

    def config_set(self, name: str, value) -> None:
        """`ceph config set` analog: VALIDATE, commit through the
        monitor KV, then distribute into the runtime config (the
        ConfigMonitor -> md_config_t observer path). A value the
        schema rejects must never reach the replicated KV — a
        poisoned KV would re-distribute the bad value on every sync."""
        from ..utils.config import g_conf
        declared = name in g_conf.schema
        if declared:
            value = g_conf.schema[name].coerce(value)  # raises on junk
        self.mons.config_set(name, value)  # NoQuorum -> nothing applied
        if declared:
            g_conf.set(name, value, level="mon")
            g_log.dout("mon", 1, f"config set {name} = {value}")

    def _mark_down(self, osd: int) -> None:
        if not self.osdmap.osd_up[osd]:
            return
        if not self._mon_commit(f"osd.{osd} down"):
            return
        self.osdmap.mark_down(osd)
        self.down_since[osd] = self.now
        self.perf.inc("osd_marked_down")
        g_log.dout("mon", 1, f"osd.{osd} marked down (epoch "
                             f"{self.osdmap.epoch})")
        self._update_degraded()

    def _mark_out(self, osd: int) -> None:
        if osd not in self.down_since:
            return
        if not self._mon_commit(f"osd.{osd} out"):
            return
        self.osdmap.mark_out(osd)
        del self.down_since[osd]
        self.perf.inc("osd_marked_out")
        g_log.dout("mon", 1, f"osd.{osd} marked out (epoch "
                             f"{self.osdmap.epoch})")
        self._repeer_all()

    def _update_degraded(self) -> None:
        dead = self._dead_osds()
        degraded = sum(
            1 for ps in range(self.pg_num)
            if any(o in dead for o in self.pgs[ps].acting))
        self.perf.set("degraded_pgs", degraded)

    def _repeer_all(self) -> None:
        """Map changed: every PG re-derives its acting set; shards on
        replaced OSDs are recovered (dead source) or copied (backfill
        from live source)."""
        for ps in range(self.pg_num):
            be = self.pgs[ps]
            new_acting = self._up(ps)
            # reconcile in-flight backfills with the new map: a move
            # whose destination died or is no longer the CRUSH target
            # is cancelled (the old holder simply keeps serving)
            job = self.backfills.get(ps)
            if job is not None:
                kept = [(s, o, n) for (s, o, n) in job["moves"]
                        if self.alive[n] and new_acting[s] == n]
                if len(kept) != len(job["moves"]):
                    g_log.dout("osd", 1, f"pg 1.{ps}: cancelled "
                               f"{len(job['moves']) - len(kept)} stale "
                               f"backfill move(s) on map change")
                job["moves"] = kept
                if not kept:
                    self._drop_backfill_job(ps)
            if new_acting == be.acting:
                continue
            if any(a == CRUSH_ITEM_NONE for a in new_acting):
                g_log.dout("osd", 0, f"pg 1.{ps} undersized after remap")
                continue
            lost, moved = [], []
            for slot, (old, new) in enumerate(zip(be.acting, new_acting)):
                if old == new:
                    continue
                if not self.alive[new]:
                    # destination died but isn't marked down in the map
                    # yet (the kill->grace->report window): writing to
                    # its store would be lost bytes on MemStore and an
                    # outright error on a crashed TinStore. Defer — the
                    # mark-down bumps the map and re-plans this slot.
                    continue
                if self.alive[old] and old in self.cluster.stores:
                    moved.append((slot, old, new))
                else:
                    lost.append((slot, new))
            if lost:
                slots = [s for s, _ in lost]
                repl = {s: n for s, n in lost}
                # never read helper chunks from shards whose OSD is
                # still dead (their stores are stale or gone)
                exclude = {s for s, o in enumerate(be.acting)
                           if s not in slots and
                           (not self.alive[o] or
                            o not in self.cluster.stores)}
                counters = be.recover_shards(slots, replacement_osds=repl,
                                             helper_exclude=exclude)
                self.perf.inc("recovered_objects", counters["objects"])
                self._note_pg_change(ps)
                g_log.dout("recovery", 1,
                           f"pg 1.{ps}: rebuilt {counters['objects']} "
                           f"objects onto {repl}")
            if moved:
                # recovered slots are already flipped; moved slots keep
                # serving from the OLD osd via pg_temp until the copy
                # completes (ref: pg_temp during backfill)
                self._start_backfill(ps, moved)
        self._update_degraded()
        # map change may have started new intervals: their primaries
        # record up_thru NOW (quorum permitting) so a healthy cluster
        # activates synchronously; under quorum loss the PGs stay in
        # WaitUpThru and the tick loop retries
        self._refresh_intervals()
        self._record_up_thrus()

    # -- backfill (async, pg_temp-protected) --------------------------------

    def _start_backfill(self, ps: int, moves: list[tuple[int, int, int]]) \
            -> None:
        from .ecbackend import shard_cid
        from .memstore import Transaction
        be = self.pgs[ps]
        job = self.backfills.setdefault(ps, {"moves": [], "names": set()})
        fresh = False
        for slot, old, new in moves:
            if (slot, old, new) in job["moves"]:
                continue  # already in flight — keep its copy progress
            job["moves"] = [mv for mv in job["moves"] if mv[0] != slot]
            job["moves"].append((slot, old, new))
            fresh = True
            t = Transaction().create_collection(shard_cid(be.pg, slot))
            self.cluster.osd(new).queue_transaction(t)
        if fresh:
            # only a NEW destination needs the full object list; an
            # unchanged in-flight move keeps its remaining set
            job["names"].update(be.object_sizes)
        self.osdmap.set_pg_temp((1, ps), list(be.acting))
        self._note_pg_change(ps)
        g_log.dout("osd", 1, f"pg 1.{ps} backfilling {len(job['moves'])} "
                             f"slot(s); pg_temp keeps old acting serving")

    def _drop_backfill_job(self, ps: int) -> None:
        """Cancel a backfill: clear pg_temp AND purge its queued copy
        ops so cancelled work doesn't burn recovery limit budget."""
        self.osdmap.set_pg_temp((1, ps), [])
        self._note_pg_change(ps)
        del self.backfills[ps]
        self.sched.remove_if("background_recovery",
                             lambda op: op[0] == ps)

    def _progress_backfills(self) -> None:
        """Pump backfill copies through the mClock scheduler (class
        background_recovery, limit = backfill_rate objects/s in virtual
        time), then cut over: flip acting, clear pg_temp. A source that
        died mid-backfill converts that slot to recovery."""
        for ps, job in list(self.backfills.items()):
            be = self.pgs[ps]
            for slot, old, new in list(job["moves"]):
                # a dead destination cancels the move (the old holder
                # keeps serving; a later map change re-plans the slot)
                if not self.alive[new]:
                    job["moves"].remove((slot, old, new))
                    g_log.dout("osd", 1, f"pg 1.{ps}: backfill dest "
                                         f"osd.{new} died; move cancelled")
                    continue
                # sources must still be alive; otherwise recover
                if self.alive[old] and old in self.cluster.stores:
                    continue
                job["moves"].remove((slot, old, new))
                exclude = {s for s, o in enumerate(be.acting)
                           if s != slot and (not self.alive[o]
                                             or o not in self.cluster.stores)}
                try:
                    counters = be.recover_shards(
                        [slot], replacement_osds={slot: new},
                        helper_exclude=exclude)
                except ValueError as e:
                    # not enough live helpers right now: the slot stays
                    # with its (dead) holder, the PG degraded; a later
                    # revive or map change resolves it
                    g_log.dout("recovery", 0,
                               f"pg 1.{ps}: slot {slot} recovery "
                               f"deferred during backfill ({e})")
                    self.perf.inc("deferred_replays")
                    continue
                self.perf.inc("recovered_objects", counters["objects"])
                # acting changed (slot flipped to `new`): keep pg_temp
                # pointing at the real serving set, or clients would be
                # steered at the dead old holder
                self.osdmap.set_pg_temp((1, ps), list(be.acting))
                self._note_pg_change(ps)
            if not job["moves"]:
                # nothing left to copy toward: drop the job without
                # claiming a completed backfill
                self._drop_backfill_job(ps)
                continue
        # enqueue copy ops the scheduler hasn't seen yet
        for ps, job in self.backfills.items():
            queued = job.setdefault("queued", set())
            for name in sorted(set(job["names"]) - queued):
                self.sched.enqueue("background_recovery", (ps, name))
                queued.add(name)

    def _do_backfill_copy(self, ps: int, name: str) -> None:
        from .ecbackend import HINFO_KEY, shard_cid
        from .memstore import Transaction
        job = self.backfills.get(ps)
        if job is None:
            return  # op outlived its backfill (cancelled/done)
        job.setdefault("queued", set()).discard(name)
        if name not in job["names"]:
            return
        job["names"].discard(name)
        be = self.pgs[ps]
        for slot, old, new in job["moves"]:
            src = self.cluster.osd(old)
            dst = self.cluster.osd(new)
            cid = shard_cid(be.pg, slot)
            if not src.exists(cid, name):
                # removed (or never written): propagate the delete so a
                # previously-copied version doesn't survive at the dest
                if dst.exists(cid, name):
                    dst.queue_transaction(Transaction().remove(cid, name))
                continue
            data = src.read(cid, name)
            t = (Transaction()
                 .write(cid, name, 0, data)
                 .truncate(cid, name, len(data))
                 .setattr(cid, name, HINFO_KEY,
                          src.getattr(cid, name, HINFO_KEY)))
            dst.queue_transaction(t)
        self.perf.inc("backfilled_objects")

    def _complete_backfills(self) -> None:
        """Cut over: everything copied and nothing still queued."""
        for ps, job in list(self.backfills.items()):
            if job["names"] or job.get("queued"):
                continue
            be = self.pgs[ps]
            for slot, old, new in job["moves"]:
                be.acting[slot] = new
                be.shard_applied[slot] = be.pg_log.head
            self.osdmap.set_pg_temp((1, ps), [])
            self._note_pg_change(ps)
            del self.backfills[ps]
            self.perf.inc("backfills_completed")
            g_log.dout("osd", 1, f"pg 1.{ps} backfill complete; "
                                 f"pg_temp cleared")

    # -- scrub scheduling ---------------------------------------------------

    def _schedule_scrubs(self) -> None:
        """Enqueue due scrubs on the scrub QoS class (ref: the scrub
        scheduler in src/osd/scrubber/osd_scrub_sched.cc: periodic
        shallow every osd_scrub_min_interval, deep every
        osd_deep_scrub_interval). Degraded/backfilling PGs are skipped
        until healthy, like the reference's active+clean gate."""
        dead = self._dead_osds()
        for ps in range(self.pg_num):
            if ps in self.backfills or ps in self._scrub_queued:
                continue
            if any(o in dead for o in self.pgs[ps].acting):
                continue
            deep_due = (self.now - self.last_deep_scrub.get(ps, 0.0)
                        >= self.deep_scrub_interval)
            shallow_due = (self.now - self.last_scrub.get(ps, 0.0)
                           >= self.scrub_interval)
            if deep_due or shallow_due:
                self.sched.enqueue(
                    "scrub", (ps, "deep" if deep_due else "shallow"))
                self._scrub_queued.add(ps)

    def _do_scrub(self, ps: int, kind: str) -> None:
        self._scrub_queued.discard(ps)
        be = self.pgs[ps]
        dead = self._dead_osds()
        if ps in self.backfills or any(o in dead for o in be.acting):
            return  # went unhealthy while queued; rescheduled when due
        if kind == "deep":
            rep = be.deep_scrub()
            errs = len(rep["inconsistent"]) + len(
                rep.get("digest_mismatch", []))
            self.last_deep_scrub[ps] = self.now
            self.last_scrub[ps] = self.now  # deep subsumes shallow
            self.perf.inc("scrubs_deep")
        else:
            rep = be.shallow_scrub()
            errs = len(rep["errors"])
            self.last_scrub[ps] = self.now
            self.perf.inc("scrubs_shallow")
        if errs:
            self.perf.inc("scrub_errors", errs)
            self.scrub_reports[ps] = rep
            g_log.dout("scrub", 0,
                       f"pg 1.{ps} {kind} scrub: {errs} error(s)")
        else:
            # a clean scrub clears any stale error report — monitoring
            # must not show a repaired PG as inconsistent forever
            self.scrub_reports.pop(ps, None)

    # -- op pump ------------------------------------------------------------

    def _pump(self) -> None:
        """One scheduler drain per tick step: background work (backfill
        copies, scrubs) executes in mClock order until every class is
        limit-bound for this instant of virtual time."""
        for cls, op in self.sched.drain(self.now):
            if cls == "background_recovery":
                self._do_backfill_copy(*op)
            elif cls == "scrub":
                self._do_scrub(*op)
        self._complete_backfills()

    # -- health -------------------------------------------------------------

    def pg_state(self, ps: int) -> str:
        """Current pg_state string from a fresh peering pass (the
        `ceph pg stat` view), up_thru consult included."""
        return self._peer_classify(ps).state

    def health(self) -> dict:
        states = {ps: self.pg_state(ps) for ps in range(self.pg_num)}
        return {
            "epoch": self.osdmap.epoch,
            "mon_quorum": self.mons.quorum(),
            "mon_leader": self.mons.leader(),
            "osds_up": int(self.osdmap.osd_up.sum()),
            "osds_alive": int(self.alive.sum()),
            "pgs_active_clean": sum(
                1 for s in states.values() if s == "active+clean"),
            "pgs_degraded": sum(
                1 for s in states.values() if "degraded" in s),
            "pgs_undersized": sum(
                1 for s in states.values() if "undersized" in s),
            "pgs_backfilling": len(self.backfills),
            "pgs_peering": sum(
                1 for s in states.values() if s.startswith("peering")),
            "pgs_down": sum(
                1 for s in states.values()
                if s in ("down", "incomplete")),
            "pg_states": states,
        }

    def df(self) -> dict:
        """`ceph df` (ref: src/mon/PGMap.cc dump_cluster_stats +
        dump_pool_stats_full): logical bytes, raw bytes after EC/
        replication amplification, object + snapshot-clone counts."""
        objects = clones = 0
        logical = 0
        for ps in range(self.pg_num):
            be = self.pgs[ps]
            for name in be.list_pg_objects():
                sz = be.stat_object(name)
                if self._SNAP_SEP in name:
                    clones += 1
                else:
                    objects += 1
                logical += sz
        k = self.pool_size - self.m
        raw = logical * self.pool_size // max(1, k) if self.is_erasure \
            else logical * self.pool_size
        return {
            "pools": {"default": {
                "id": 1, "objects": objects, "snap_clones": clones,
                "bytes_used": logical, "bytes_raw": raw,
                "amplification": round(raw / logical, 2) if logical
                else (self.pool_size / k if self.is_erasure
                      else float(self.pool_size)),
            }},
            "cluster": {"osds": len(self.alive),
                        "osds_in": int((self.osdmap.osd_weight > 0)
                                       .sum()),
                        "bytes_used_raw": raw},
        }

    def verify_all(self, expected: dict[str, np.ndarray]) -> int:
        """Read every object back and byte-compare; returns count."""
        ok = 0
        for name, data in expected.items():
            got = self.read(name)
            if not np.array_equal(got, np.asarray(data, np.uint8)):
                raise AssertionError(f"data loss: {name}")
            ok += 1
        return ok
