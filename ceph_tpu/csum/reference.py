"""Checksum oracles and table/matrix construction (host-side numpy).

crc32c: Castagnoli polynomial 0x1EDC6F41, reflected form 0x82F63B78 —
the same CRC the reference computes in src/common/crc32c.cc
(`ceph_crc32c`, hardware-dispatched to crc32c_intel_fast / aarch64 CRC
extensions). Two conventions are exposed:

  crc32c(data)          — the standard CRC-32C (init ~0, final xor ~0);
                          matches the RFC 3720 iSCSI test vectors.
  ceph_crc32c(seed, d)  — the reference's raw-register convention: the
                          caller supplies the register seed and no final
                          inversion is applied (Ceph callers pass -1 and
                          chain block CRCs by feeding the result back in).

xxh32 / xxh64: XXHash as bundled by the reference (src/xxHash/), needed
for BlueStore csum_type=xxhash32/64 parity.

Everything that the device kernels close over (slicing tables, GF(2)
shift matrices for the log-depth CRC combine) is built here once.
"""

from __future__ import annotations

import functools

import numpy as np

CRC32C_POLY_REFLECTED = 0x82F63B78
_M32 = 0xFFFFFFFF
_M64 = 0xFFFFFFFFFFFFFFFF


# --------------------------------------------------------------- crc32c

@functools.cache
def crc32c_table() -> np.ndarray:
    """Byte-at-a-time table: T[v] = register after consuming byte v from 0."""
    v = np.arange(256, dtype=np.uint32)
    c = v.copy()
    for _ in range(8):
        c = (c >> 1) ^ np.where(c & 1, np.uint32(CRC32C_POLY_REFLECTED),
                                np.uint32(0))
    return c


@functools.cache
def crc32c_slice8_tables() -> np.ndarray:
    """Slicing-by-8 tables (8, 256) uint32.

    T[0] is the basic table; T[j+1][v] advances T[j][v] through one more
    zero byte. With a zero initial register, the CRC register after 8
    bytes b0..b7 is XOR_i T[7-i][b_i] — the byte-parallel form the device
    kernel uses (same math as the reference's sctp_crc32 slicing fallback
    and the PCLMUL folding constants, ref: src/common/crc32c_intel_fast_asm.s).
    """
    t0 = crc32c_table()
    out = np.zeros((8, 256), dtype=np.uint32)
    out[0] = t0
    for j in range(1, 8):
        out[j] = (out[j - 1] >> 8) ^ t0[out[j - 1] & 0xFF]
    return out


def _crc32c_update(reg: int, data: bytes | np.ndarray) -> int:
    """Advance the raw CRC register over data (no init/final inversion).
    Plain python ints over a list table — ~10x the numpy-scalar loop
    this replaced (numpy scalar ops pay per-op boxing; the reference
    oracle is still O(n) per byte — bulk paths use csum/kernels)."""
    t = _crc32c_pylist()
    buf = bytes(data) if not isinstance(data, np.ndarray) \
        else data.astype(np.uint8).ravel().tobytes()
    reg = int(reg) & 0xFFFFFFFF
    for b in buf:
        reg = (reg >> 8) ^ t[(reg ^ b) & 0xFF]
    return reg


_PYLIST_CACHE: list[int] | None = None


def _crc32c_pylist() -> list[int]:
    global _PYLIST_CACHE
    if _PYLIST_CACHE is None:
        _PYLIST_CACHE = [int(x) for x in crc32c_table()]
    return _PYLIST_CACHE


def crc32c(data: bytes | np.ndarray, init: int = 0xFFFFFFFF,
           xorout: int = 0xFFFFFFFF) -> int:
    """Standard CRC-32C. crc32c(b'123456789') == 0xE3069283."""
    return _crc32c_update(init, data) ^ xorout


def ceph_crc32c(seed: int, data: bytes | np.ndarray) -> int:
    """The reference's convention (ref: src/common/crc32c.h ceph_crc32c):
    raw register update from `seed`, no final inversion. Chainable:
    ceph_crc32c(ceph_crc32c(s, a), b) == ceph_crc32c(s, a+b)."""
    return _crc32c_update(seed & _M32, data)


def ceph_crc32c_iov(seed: int, parts, update=ceph_crc32c) -> int:
    """Running ceph_crc32c over an iovec (list of buffers): the
    seeded-continuation form the scatter-gather framing path uses —
    bit-identical to ceph_crc32c(seed, join(parts)) without ever
    joining. `update` may be any chainable ceph_crc32c implementation
    (e.g. the native codec's)."""
    reg = seed & _M32
    for p in parts:
        reg = update(reg, p)
    return reg & _M32


# ------------------------------------------------- GF(2) combine matrices

def _zero_byte_matrix() -> np.ndarray:
    """32x32 GF(2) matrix advancing the register through ONE zero byte.

    Column b = register result of (1<<b) after a zero byte. CRC register
    update is GF(2)-linear in the register when the data byte is zero.
    """
    t = crc32c_table()
    cols = np.zeros((32, 32), dtype=np.uint8)
    for b in range(32):
        reg = np.uint32(1 << b)
        reg = (reg >> np.uint32(8)) ^ t[reg & np.uint32(0xFF)]
        for r in range(32):
            cols[r, b] = (int(reg) >> r) & 1
    return cols


def _matmul_gf2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.int32) @ b.astype(np.int32)) % 2


@functools.cache
def shift_matrix(nbytes: int) -> np.ndarray:
    """32x32 GF(2) matrix advancing the CRC register through `nbytes`
    zero bytes (i.e. the linear 'shift by nbytes' operator), via square-
    and-multiply so 4 KiB shifts cost ~log2 steps."""
    if nbytes == 0:
        return np.eye(32, dtype=np.uint8)
    if nbytes == 1:
        return _zero_byte_matrix().astype(np.uint8)
    half = shift_matrix(nbytes // 2)
    sq = _matmul_gf2(half, half).astype(np.uint8)
    if nbytes % 2:
        sq = _matmul_gf2(shift_matrix(1), sq).astype(np.uint8)
    return sq


@functools.cache
def inv_shift_matrix(nbytes: int) -> np.ndarray:
    """Inverse of shift_matrix(nbytes): un-advances the register through
    `nbytes` zero bytes. The zero-byte operator is a bijection on the
    register space, so this always exists; built by GF(2) Gauss-Jordan
    on the single-byte matrix, then square-and-multiply."""
    if nbytes == 0:
        return np.eye(32, dtype=np.uint8)
    if nbytes == 1:
        a = _zero_byte_matrix().astype(np.uint8) % 2
        inv = np.eye(32, dtype=np.uint8)
        a = a.copy()
        for col in range(32):
            pivot = col
            while a[pivot, col] == 0:
                pivot += 1
            if pivot != col:
                a[[col, pivot]] = a[[pivot, col]]
                inv[[col, pivot]] = inv[[pivot, col]]
            for row in range(32):
                if row != col and a[row, col]:
                    a[row] ^= a[col]
                    inv[row] ^= inv[col]
        return inv
    half = inv_shift_matrix(nbytes // 2)
    sq = _matmul_gf2(half, half).astype(np.uint8)
    if nbytes % 2:
        sq = _matmul_gf2(inv_shift_matrix(1), sq).astype(np.uint8)
    return sq


def matrix_cols_u32(m: np.ndarray) -> np.ndarray:
    """Pack a 32x32 GF(2) matrix into 32 uint32 column constants so that
    apply(m, x) == XOR over set bits b of x of cols[b]."""
    bits = np.arange(32, dtype=np.uint32)
    return (m.astype(np.uint32) << bits[:, None]).sum(axis=0,
                                                      dtype=np.uint32)


def apply_shift(reg: int, nbytes: int) -> int:
    """Advance register `reg` through nbytes zero bytes (host scalar)."""
    cols = matrix_cols_u32(shift_matrix(nbytes))
    out = np.uint32(0)
    for b in range(32):
        if (reg >> b) & 1:
            out ^= cols[b]
    return int(out)


# --------------------------------------------------------------- xxhash

_P32 = (2654435761, 2246822519, 3266489917, 668265263, 374761393)
_P64 = (11400714785074694791, 14029467366897019727, 1609587929392839161,
        9650029242287828579, 2870177450012600261)


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh32(data: bytes | np.ndarray, seed: int = 0) -> int:
    """XXH32 oracle (ref: bundled src/xxHash XXH32). Byte-exact."""
    d = bytes(data) if not isinstance(data, np.ndarray) else data.astype(
        np.uint8).tobytes()
    n = len(d)
    p = 0
    if n >= 16:
        v1 = (seed + _P32[0] + _P32[1]) & _M32
        v2 = (seed + _P32[1]) & _M32
        v3 = seed & _M32
        v4 = (seed - _P32[0]) & _M32
        while p + 16 <= n:
            for i, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(d[p + 4 * i:p + 4 * i + 4], "little")
                v = (v + lane * _P32[1]) & _M32
                v = _rotl32(v, 13)
                v = (v * _P32[0]) & _M32
                if i == 0:
                    v1 = v
                elif i == 1:
                    v2 = v
                elif i == 2:
                    v3 = v
                else:
                    v4 = v
            p += 16
        h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12) +
             _rotl32(v4, 18)) & _M32
    else:
        h = (seed + _P32[4]) & _M32
    h = (h + n) & _M32
    while p + 4 <= n:
        lane = int.from_bytes(d[p:p + 4], "little")
        h = (h + lane * _P32[2]) & _M32
        h = (_rotl32(h, 17) * _P32[3]) & _M32
        p += 4
    while p < n:
        h = (h + d[p] * _P32[4]) & _M32
        h = (_rotl32(h, 11) * _P32[0]) & _M32
        p += 1
    h ^= h >> 15
    h = (h * _P32[1]) & _M32
    h ^= h >> 13
    h = (h * _P32[2]) & _M32
    h ^= h >> 16
    return h


def _xxh64_round(acc: int, lane: int) -> int:
    acc = (acc + lane * _P64[1]) & _M64
    acc = _rotl64(acc, 31)
    return (acc * _P64[0]) & _M64


def _xxh64_merge(h: int, v: int) -> int:
    h ^= _xxh64_round(0, v)
    return ((h * _P64[0]) + _P64[3]) & _M64


def xxh64(data: bytes | np.ndarray, seed: int = 0) -> int:
    """XXH64 oracle (ref: bundled src/xxHash XXH64). Byte-exact."""
    d = bytes(data) if not isinstance(data, np.ndarray) else data.astype(
        np.uint8).tobytes()
    n = len(d)
    p = 0
    if n >= 32:
        v1 = (seed + _P64[0] + _P64[1]) & _M64
        v2 = (seed + _P64[1]) & _M64
        v3 = seed & _M64
        v4 = (seed - _P64[0]) & _M64
        while p + 32 <= n:
            v1 = _xxh64_round(v1, int.from_bytes(d[p:p + 8], "little"))
            v2 = _xxh64_round(v2, int.from_bytes(d[p + 8:p + 16], "little"))
            v3 = _xxh64_round(v3, int.from_bytes(d[p + 16:p + 24], "little"))
            v4 = _xxh64_round(v4, int.from_bytes(d[p + 24:p + 32], "little"))
            p += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) +
             _rotl64(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = _xxh64_merge(h, v)
    else:
        h = (seed + _P64[4]) & _M64
    h = (h + n) & _M64
    while p + 8 <= n:
        h ^= _xxh64_round(0, int.from_bytes(d[p:p + 8], "little"))
        h = (_rotl64(h, 27) * _P64[0] + _P64[3]) & _M64
        p += 8
    if p + 4 <= n:
        h ^= (int.from_bytes(d[p:p + 4], "little") * _P64[0]) & _M64
        h = (_rotl64(h, 23) * _P64[1] + _P64[2]) & _M64
        p += 4
    while p < n:
        h ^= (d[p] * _P64[4]) & _M64
        h = (_rotl64(h, 11) * _P64[0]) & _M64
        p += 1
    h ^= h >> 33
    h = (h * _P64[1]) & _M64
    h ^= h >> 29
    h = (h * _P64[2]) & _M64
    h ^= h >> 32
    return h
