"""Batched checksum kernels for TPU.

The device-side replacement for the reference's CPU checksum hot loops
(ref: src/common/crc32c_intel_fast_asm.s PCLMUL folding,
src/common/crc32c_aarch64.c, bundled src/xxHash) — the bulk path behind
deep-scrub (ref: src/osd/scrubber + ECBackend::be_deep_scrub) and
BlueStore per-block verify (ref: src/os/bluestore/Checksummer.h).

Unit of work: (batch, block_len) uint8 — many equal-sized blocks checked
in one launch (exactly the Checksummer csum_block_size model).

crc32c lowering: CRC is GF(2)-linear in the message, so instead of the
CPU's serial byte loop we
  1. compute the 8-byte chunk CRCs of all chunks in parallel
     (slicing-by-8 tables as vectorized gathers),
  2. reduce across the chunk axis in log2(n) levels; the "advance
     register by S zero bytes" operator of each level is a constant
     32x32 GF(2) matrix applied as 32 masked-XOR ops on uint32 lanes,
  3. fold in the (static) init/xorout contribution as host constants.
No per-byte dependency chain remains — wall time scales with the VPU,
not the byte count.

xxhash is NOT linear (mod-2^32/64 mul/add/rot), so it keeps its stripe
recurrence: lax.fori_loop over 16/32-byte stripes, batch-parallel.
XXH64's 64-bit arithmetic is built from uint32 limb pairs so the kernel
never needs the global x64 flag.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reference import (apply_shift, crc32c_slice8_tables, crc32c_table,
                        inv_shift_matrix, matrix_cols_u32, shift_matrix)

Array = jax.Array

_SLICE8 = jnp.asarray(crc32c_slice8_tables())  # (8, 256) uint32
_T0 = jnp.asarray(crc32c_table())              # (256,) uint32


def _apply_bitmatrix32(cols: np.ndarray, x: Array) -> Array:
    """y = M @ x over GF(2), M given as 32 uint32 column constants."""
    acc = jnp.zeros_like(x)
    for b in range(32):
        c = int(cols[b])
        if c == 0:
            continue
        mask = jnp.uint32(0) - ((x >> np.uint32(b)) & np.uint32(1))
        acc = acc ^ (mask & np.uint32(c))
    return acc


def _crc32c_linear(blocks: Array) -> Array:
    """Zero-init CRC register over each row of (B, L) uint8, L % 8 == 0."""
    B, L = blocks.shape
    n = L // 8
    chunks = blocks.reshape(B, n, 8).astype(jnp.int32)
    # chunk CRC: XOR_i T[7-i][byte_i]  (slicing-by-8, zero-init)
    c = jnp.zeros((B, n), dtype=jnp.uint32)
    for i in range(8):
        c = c ^ jnp.take(_SLICE8[7 - i], chunks[:, :, i], axis=0)
    # log-depth combine; pad FRONT with zero chunks (zero-init register
    # stays 0 through a zero prefix, so the result is unchanged)
    span = 8
    while c.shape[1] > 1:
        m = c.shape[1]
        if m % 2:
            c = jnp.concatenate(
                [jnp.zeros((B, 1), dtype=jnp.uint32), c], axis=1)
            m += 1
        left, right = c[:, 0::2], c[:, 1::2]
        cols = matrix_cols_u32(shift_matrix(span))
        c = _apply_bitmatrix32(cols, left) ^ right
        span *= 2
    return c[:, 0]


def _crc32c_zero_seed(blocks: Array) -> Array:
    """Zero-seed CRC register over each row of (B, L) uint8, any L:
    parallel slicing + log-depth combine for the 8-aligned head, <=7
    unrolled byte steps for the tail."""
    block_len = blocks.shape[1]
    main = (block_len // 8) * 8
    if main:
        reg = _crc32c_linear(blocks[:, :main])
    else:
        reg = jnp.zeros((blocks.shape[0],), dtype=jnp.uint32)
    for t in range(main, block_len):
        byte = blocks[:, t].astype(jnp.uint32)
        reg = (reg >> np.uint32(8)) ^ jnp.take(
            _T0, ((reg ^ byte) & np.uint32(0xFF)).astype(jnp.int32))
    return reg


@functools.lru_cache(maxsize=64)
def _crc32c_jit(block_len: int, init: int, xorout: int):
    # init contribution: shift^{block_len}(init), a host constant
    const = apply_shift(init, block_len) ^ xorout if block_len else init ^ xorout

    def fn(blocks: Array) -> Array:
        if blocks.dtype != jnp.uint8 or blocks.ndim != 2:
            raise ValueError(f"blocks must be (B, {block_len}) uint8")
        return _crc32c_zero_seed(blocks) ^ np.uint32(const)

    return jax.jit(fn)


def crc32c_blocks(blocks, init: int = 0xFFFFFFFF,
                  xorout: int = 0xFFFFFFFF) -> Array:
    """CRC-32C of each row of (B, L) uint8. Defaults = standard CRC-32C;
    use init=seed, xorout=0 for the reference's raw ceph_crc32c(seed, ·)
    convention (what BlueStore/HashInfo store, seed -1)."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    return _crc32c_jit(int(blocks.shape[1]), init & 0xFFFFFFFF,
                       xorout & 0xFFFFFFFF)(blocks)


@functools.lru_cache(maxsize=64)
def _crc32c_extend_jit(block_len: int):
    shift_cols = matrix_cols_u32(shift_matrix(block_len))

    def fn(regs: Array, blocks: Array) -> Array:
        # register after block with runtime seed r: shift^{len}(r) ^ L(block)
        return _apply_bitmatrix32(shift_cols, regs) ^ _crc32c_zero_seed(blocks)

    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _inv_shift_cols(pad: int) -> np.ndarray:
    return matrix_cols_u32(inv_shift_matrix(pad))


def _unshift_host(regs: np.ndarray, pad: int) -> np.ndarray:
    """Un-advance registers through `pad` zero bytes — a 32-constant XOR
    on host uint32s, no device dispatch."""
    cols = _inv_shift_cols(pad)
    bits = (regs[:, None] >> np.arange(32, dtype=np.uint32)) & np.uint32(1)
    terms = np.where(bits.astype(bool), cols[None, :], np.uint32(0))
    return np.bitwise_xor.reduce(terms, axis=1)


def crc32c_extend(regs, blocks) -> Array:
    """Advance raw CRC registers through one block each: regs (B,) uint32
    current registers (the ceph_crc32c chaining state), blocks (B, L)
    uint8. Returns the new registers — the batched form of
    ceph_crc32c(reg, block), used by HashInfo appends across shards.

    The kernel specializes on block length; arbitrary lengths would
    compile (and cache-thrash) one program each, so blocks are zero-
    padded up to the next power of two and the padding's register shift
    is undone afterwards with the cached inverse GF(2) shift matrix —
    a 32-bit host fixup, not a data pass.
    """
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    regs = jnp.asarray(regs, dtype=jnp.uint32)
    L = int(blocks.shape[1])
    bucket = max(64, 1 << (L - 1).bit_length()) if L else 0
    pad = bucket - L
    if pad:
        blocks = jnp.pad(blocks, ((0, 0), (0, pad)))
    out = _crc32c_extend_jit(bucket)(regs, blocks)
    if pad:
        # out = shift^pad(true): undo the zero-padding's linear shift
        # (host fixup, then back to a device array so the return type is
        # a jax Array on every path)
        out = jnp.asarray(_unshift_host(np.asarray(out, np.uint32), pad))
    return out


# ----------------------------------------------------------------- xxh32

_P32 = tuple(np.uint32(p) for p in
             (2654435761, 2246822519, 3266489917, 668265263, 374761393))


def _rotl32(x: Array, r: int) -> Array:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _lanes_u32(blocks: Array) -> Array:
    """(B, L) uint8 -> (B, L//4) uint32 little-endian lanes."""
    B, L = blocks.shape
    b = blocks.reshape(B, L // 4, 4).astype(jnp.uint32)
    return (b[..., 0] | (b[..., 1] << np.uint32(8)) |
            (b[..., 2] << np.uint32(16)) | (b[..., 3] << np.uint32(24)))


@functools.lru_cache(maxsize=64)
def _xxh32_jit(block_len: int, seed: int):
    s = np.uint32(seed)
    n_stripes = block_len // 16
    after = n_stripes * 16

    def fn(blocks: Array) -> Array:
        B = blocks.shape[0]
        if n_stripes:
            lanes = _lanes_u32(blocks[:, :after]).reshape(B, n_stripes, 4)

            def body(i, vs):
                v1, v2, v3, v4 = vs
                ln = lanes[:, i, :]

                def rnd(v, lane):
                    return _rotl32(v + lane * _P32[1], 13) * _P32[0]
                return (rnd(v1, ln[:, 0]), rnd(v2, ln[:, 1]),
                        rnd(v3, ln[:, 2]), rnd(v4, ln[:, 3]))

            init = (jnp.full((B,), (seed + 2654435761 + 2246822519)
                            & 0xFFFFFFFF, jnp.uint32),
                    jnp.full((B,), (seed + 2246822519) & 0xFFFFFFFF,
                             jnp.uint32),
                    jnp.full((B,), s, jnp.uint32),
                    jnp.full((B,), (seed - 2654435761) & 0xFFFFFFFF,
                             jnp.uint32))
            v1, v2, v3, v4 = jax.lax.fori_loop(0, n_stripes, body, init)
            h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12) +
                 _rotl32(v4, 18))
        else:
            h = jnp.full((B,), s + _P32[4], jnp.uint32)
        h = h + np.uint32(block_len)
        p = after
        while p + 4 <= block_len:
            lane = _lanes_u32(blocks[:, p:p + 4])[:, 0]
            h = _rotl32(h + lane * _P32[2], 17) * _P32[3]
            p += 4
        while p < block_len:
            h = _rotl32(h + blocks[:, p].astype(jnp.uint32) * _P32[4],
                        11) * _P32[0]
            p += 1
        h = h ^ (h >> np.uint32(15))
        h = h * _P32[1]
        h = h ^ (h >> np.uint32(13))
        h = h * _P32[2]
        return h ^ (h >> np.uint32(16))

    return jax.jit(fn)


def xxh32_blocks(blocks, seed: int = 0) -> Array:
    """XXH32 of each row of (B, L) uint8."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    return _xxh32_jit(int(blocks.shape[1]), seed & 0xFFFFFFFF)(blocks)


# ----------------------------------------------------------------- xxh64
# uint64 as (hi, lo) uint32 limb pairs — no dependence on jax_enable_x64.

_P64 = (11400714785074694791, 14029467366897019727, 1609587929392839161,
        9650029242287828579, 2870177450012600261)


def _c64(v: int):
    v &= (1 << 64) - 1
    return (np.uint32(v >> 32), np.uint32(v & 0xFFFFFFFF))


def _add64(a, b):
    ah, al = a
    bh, bl = b
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return (ah + bh + carry, lo)


def _xor64(a, b):
    return (a[0] ^ b[0], a[1] ^ b[1])


def _mulhi32(a: Array, b: Array) -> Array:
    a0, a1 = a & np.uint32(0xFFFF), a >> np.uint32(16)
    b0, b1 = b & np.uint32(0xFFFF), b >> np.uint32(16)
    lo = a0 * b0
    m1 = a1 * b0
    m2 = a0 * b1
    t = (lo >> np.uint32(16)) + (m1 & np.uint32(0xFFFF)) + \
        (m2 & np.uint32(0xFFFF))
    return a1 * b1 + (m1 >> np.uint32(16)) + (m2 >> np.uint32(16)) + \
        (t >> np.uint32(16))


def _mul64(a, b):
    ah, al = a
    bh, bl = b
    lo = al * bl
    hi = _mulhi32(al, bl) + al * bh + ah * bl
    return (hi, lo)


def _rotl64(a, r: int):
    ah, al = a
    if r == 0:
        return a
    if r < 32:
        return ((ah << np.uint32(r)) | (al >> np.uint32(32 - r)),
                (al << np.uint32(r)) | (ah >> np.uint32(32 - r)))
    if r == 32:
        return (al, ah)
    r -= 32
    return ((al << np.uint32(r)) | (ah >> np.uint32(32 - r)),
            (ah << np.uint32(r)) | (al >> np.uint32(32 - r)))


def _shr64(a, s: int):
    ah, al = a
    if s == 0:
        return a
    if s < 32:
        return (ah >> np.uint32(s),
                (al >> np.uint32(s)) | (ah << np.uint32(32 - s)))
    if s == 32:
        return (jnp.zeros_like(ah), ah)
    return (jnp.zeros_like(ah), ah >> np.uint32(s - 32))


def _round64(acc, lane):
    acc = _add64(acc, _mul64(lane, _c64(_P64[1])))
    acc = _rotl64(acc, 31)
    return _mul64(acc, _c64(_P64[0]))


def _merge64(h, v):
    zero = (jnp.zeros_like(h[0]), jnp.zeros_like(h[1]))
    h = _xor64(h, _round64(zero, v))
    return _add64(_mul64(h, _c64(_P64[0])), _c64(_P64[3]))


def _broadcast_c64(v: int, B: int):
    hi, lo = _c64(v)
    return (jnp.full((B,), hi, jnp.uint32), jnp.full((B,), lo, jnp.uint32))


@functools.lru_cache(maxsize=64)
def _xxh64_jit(block_len: int, seed: int):
    n_stripes = block_len // 32
    after = n_stripes * 32

    def lane64(blocks, p):
        """8 bytes at static offset p -> (hi, lo) uint32 pair."""
        lanes = _lanes_u32(blocks[:, p:p + 8])
        return (lanes[:, 1], lanes[:, 0])

    def fn(blocks: Array):
        B = blocks.shape[0]
        if n_stripes:
            lanes = _lanes_u32(blocks[:, :after]).reshape(B, n_stripes, 8)

            def body(i, vs):
                out = []
                for j in range(4):
                    lane = (lanes[:, i, 2 * j + 1], lanes[:, i, 2 * j])
                    out.append(_round64(vs[j], lane))
                return tuple(out)

            init = (_broadcast_c64(seed + _P64[0] + _P64[1], B),
                    _broadcast_c64(seed + _P64[1], B),
                    _broadcast_c64(seed, B),
                    _broadcast_c64(seed - _P64[0], B))
            v1, v2, v3, v4 = jax.lax.fori_loop(0, n_stripes, body, init)
            h = _add64(_add64(_rotl64(v1, 1), _rotl64(v2, 7)),
                       _add64(_rotl64(v3, 12), _rotl64(v4, 18)))
            for v in (v1, v2, v3, v4):
                h = _merge64(h, v)
        else:
            h = _broadcast_c64(seed + _P64[4], B)
        h = _add64(h, _broadcast_c64(block_len, B))
        p = after
        while p + 8 <= block_len:
            zero = (jnp.zeros_like(h[0]), jnp.zeros_like(h[1]))
            h = _xor64(h, _round64(zero, lane64(blocks, p)))
            h = _add64(_mul64(_rotl64(h, 27), _c64(_P64[0])), _c64(_P64[3]))
            p += 8
        if p + 4 <= block_len:
            lane = (jnp.zeros((blocks.shape[0],), jnp.uint32),
                    _lanes_u32(blocks[:, p:p + 4])[:, 0])
            h = _xor64(h, _mul64(lane, _c64(_P64[0])))
            h = _add64(_mul64(_rotl64(h, 23), _c64(_P64[1])), _c64(_P64[2]))
            p += 4
        while p < block_len:
            lane = (jnp.zeros((blocks.shape[0],), jnp.uint32),
                    blocks[:, p].astype(jnp.uint32))
            h = _xor64(h, _mul64(lane, _c64(_P64[4])))
            h = _mul64(_rotl64(h, 11), _c64(_P64[0]))
            p += 1
        h = _xor64(h, _shr64(h, 33))
        h = _mul64(h, _c64(_P64[1]))
        h = _xor64(h, _shr64(h, 29))
        h = _mul64(h, _c64(_P64[2]))
        h = _xor64(h, _shr64(h, 32))
        return jnp.stack([h[0], h[1]], axis=-1)  # (B, 2): [hi, lo]

    return jax.jit(fn)


def xxh64_blocks(blocks, seed: int = 0) -> Array:
    """XXH64 of each row of (B, L) uint8; returns (B, 2) uint32 [hi, lo]
    pairs (combine as (hi << 32) | lo)."""
    blocks = jnp.asarray(blocks, dtype=jnp.uint8)
    return _xxh64_jit(int(blocks.shape[1]),
                      seed & ((1 << 64) - 1))(blocks)
