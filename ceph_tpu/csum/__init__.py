"""Checksum subsystem — crc32c / xxhash, batched for TPU.

Rebuild of the reference's block-checksum stack (ref: src/common/crc32c.cc
`ceph_crc32c` dispatch + src/common/crc32c_intel_fast.c PCLMUL path;
bundled src/xxHash/ XXH32/XXH64; consumed by BlueStore's per-blob
Checksummer — src/os/bluestore/Checksummer.h — and by EC HashInfo
bookkeeping in src/osd/ECUtil.{h,cc}).

Layout:
  reference.py   — pure numpy/python oracles + table/matrix construction
  kernels.py     — batched JAX device kernels (deep-scrub bulk path)
  checksummer.py — Checksummer-style per-block calculate/verify API
"""

from .checksummer import CSUM_ALGORITHMS, Checksummer  # noqa: F401
from .reference import ceph_crc32c, crc32c, xxh32, xxh64  # noqa: F401
