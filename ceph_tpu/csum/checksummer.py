"""Checksummer — per-block checksum calculate/verify.

Semantic rebuild of the reference's BlueStore block checksummer
(ref: src/os/bluestore/Checksummer.h — templates Checksummer::crc32c /
crc32c_16 / crc32c_8 / xxhash32 / xxhash64 with `calculate` filling a
csum vector per csum_block and `verify` returning the first bad offset;
ref: src/os/bluestore/BlueStore.cc `_verify_csum` caller), re-shaped for
batched device execution: `data` is all the blocks of a blob at once and
the per-block checksums come back as one array from one kernel launch.

The crc32c variants use the reference's convention: register seeded with
-1, no final inversion (what BlueStore stores on disk). The truncated
crc32c_16/_8 keep the low 16/8 bits, like the reference's templates.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import kernels, reference

CSUM_ALGORITHMS = ("crc32c", "crc32c_16", "crc32c_8", "xxhash32", "xxhash64")
_CRC_SEED = 0xFFFFFFFF  # BlueStore seeds the register with -1


def _as_blocks(data, block_size: int) -> np.ndarray:
    arr = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(data, np.uint8)
    if arr.ndim == 1:
        if arr.size % block_size:
            raise ValueError(
                f"data length {arr.size} not a multiple of csum block size "
                f"{block_size}")
        arr = arr.reshape(-1, block_size)
    elif arr.ndim != 2 or arr.shape[1] != block_size:
        raise ValueError(f"data must be flat bytes or (nblocks, "
                         f"{block_size}), got shape {arr.shape}")
    return arr


@dataclass(frozen=True)
class Checksummer:
    """One algorithm + block size, like a blob's csum settings."""

    algorithm: str = "crc32c"
    block_size: int = 4096  # bluestore csum_block_size default

    def __post_init__(self):
        if self.algorithm not in CSUM_ALGORITHMS:
            raise ValueError(f"unknown csum algorithm {self.algorithm!r}; "
                             f"one of {CSUM_ALGORITHMS}")
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")

    @property
    def csum_value_size(self) -> int:
        """Bytes per stored checksum (ref: Checksummer value_t sizes)."""
        return {"crc32c": 4, "crc32c_16": 2, "crc32c_8": 1,
                "xxhash32": 4, "xxhash64": 8}[self.algorithm]

    # -- device path -------------------------------------------------------

    def calculate(self, data, device: bool = True) -> np.ndarray:
        """Per-block checksums of `data` (flat bytes or (nblocks, bs)).

        Returns uint32 (or uint64 for xxhash64), one value per block.
        device=False forces the numpy/python oracle (used in tests and
        for host-side metadata paths where launch latency dominates).
        """
        blocks = _as_blocks(data, self.block_size)
        if not device:
            return self._calculate_host(blocks)
        a = self.algorithm
        if a in ("crc32c", "crc32c_16", "crc32c_8"):
            out = np.asarray(kernels.crc32c_blocks(
                blocks, init=_CRC_SEED, xorout=0))
            if a == "crc32c_16":
                out = out & np.uint32(0xFFFF)
            elif a == "crc32c_8":
                out = out & np.uint32(0xFF)
            return out
        if a == "xxhash32":
            return np.asarray(kernels.xxh32_blocks(blocks, seed=0))
        pairs = np.asarray(kernels.xxh64_blocks(blocks, seed=0))
        return (pairs[:, 0].astype(np.uint64) << np.uint64(32)) | \
            pairs[:, 1].astype(np.uint64)

    def _calculate_host(self, blocks: np.ndarray) -> np.ndarray:
        a = self.algorithm
        if a in ("crc32c", "crc32c_16", "crc32c_8"):
            vals = [reference.ceph_crc32c(_CRC_SEED, row) for row in blocks]
            mask = {"crc32c": 0xFFFFFFFF, "crc32c_16": 0xFFFF,
                    "crc32c_8": 0xFF}[a]
            return np.array([v & mask for v in vals], dtype=np.uint32)
        if a == "xxhash32":
            return np.array([reference.xxh32(row) for row in blocks],
                            dtype=np.uint32)
        return np.array([reference.xxh64(row) for row in blocks],
                        dtype=np.uint64)

    def verify(self, data, expected, device: bool = True) -> int:
        """Return -1 if every block's checksum matches `expected`, else
        the BYTE offset of the first bad block (mirrors the reference's
        `verify` returning the bad_csum offset for _verify_csum's EIO)."""
        got = self.calculate(data, device=device)
        expected = np.asarray(expected)
        if expected.shape != got.shape:
            raise ValueError(f"expected {got.shape[0]} checksums, "
                             f"got {expected.shape}")
        bad = np.nonzero(got != expected.astype(got.dtype))[0]
        if bad.size == 0:
            return -1
        return int(bad[0]) * self.block_size
