"""GF(2^8) table construction.

TPU-native rebuild of the gf-complete w=8 arithmetic layer
(ref: src/erasure-code/jerasure/gf-complete/src/gf_w8.c — SPLIT 4,8
table multiplication; primitive polynomial 0x11D, the gf-complete /
ISA-L default for w=8).

Everything here is built once with numpy at import time; the resulting
tables are the constants that JAX/Pallas kernels close over.

Conventions:
  - Field: GF(2^8) = GF(2)[x] / (x^8 + x^4 + x^3 + x^2 + 1)  (0x11D).
  - Generator: alpha = x = 0x02 (primitive for 0x11D).
  - Bit order: bit b of a byte is the coefficient of x^b (LSB-first).
"""

from __future__ import annotations

import functools

import numpy as np

PRIM_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1 (gf-complete w=8 default)
GF_SIZE = 256


def _build_exp_log() -> tuple[np.ndarray, np.ndarray]:
    """exp/log tables for generator 0x02 under PRIM_POLY.

    exp has 512 entries so exp[log a + log b] needs no modular reduction.
    log[0] is set to 0 but must never be consumed (guarded by callers).
    """
    exp = np.zeros(512, dtype=np.uint16)
    log = np.zeros(256, dtype=np.uint16)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= PRIM_POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp.astype(np.uint8), log

GF_EXP, GF_LOG = _build_exp_log()


def gf_mul_scalar(a: int, b: int) -> int:
    """Single GF(2^8) multiply (python ints). Reference implementation."""
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + int(GF_LOG[b])])


def gf_inv_scalar(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - int(GF_LOG[a])])


def gf_div_scalar(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) divide by 0")
    if a == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + 255 - int(GF_LOG[b])])


def gf_pow_scalar(a: int, n: int) -> int:
    """a**n in GF(2^8), with the jerasure convention 0**0 == 1."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


@functools.cache
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table, MUL[a, b] = a*b. 64 KiB."""
    a = np.arange(256, dtype=np.int32)
    la = GF_LOG[a].astype(np.int32)
    s = la[:, None] + la[None, :]
    prod = GF_EXP[s]
    prod = prod.copy()
    prod[0, :] = 0
    prod[:, 0] = 0
    return prod.astype(np.uint8)


@functools.cache
def inv_table() -> np.ndarray:
    """INV[a] = a^-1; INV[0] = 0 (never valid to use)."""
    inv = np.zeros(256, dtype=np.uint8)
    inv[1:] = GF_EXP[255 - GF_LOG[np.arange(1, 256)].astype(np.int32)]
    return inv


@functools.cache
def nibble_tables() -> tuple[np.ndarray, np.ndarray]:
    """SPLIT 4,8-style tables (ref: gf_w8_split_4_8 in gf_w8.c).

    Returns (LO, HI), each (256, 16) uint8:
      LO[c, n] = c * n          (low-nibble products)
      HI[c, n] = c * (n << 4)   (high-nibble products)
    so  c * x == LO[c, x & 0xF] ^ HI[c, x >> 4].
    """
    mt = mul_table()
    lo = mt[:, :16].copy()
    hi = mt[:, [n << 4 for n in range(16)]].copy()
    return lo, hi


@functools.cache
def bit_powers() -> np.ndarray:
    """P[c, b] = c * (1 << b): products of every constant with each bit.

    Because GF(2^8) multiplication is GF(2)-linear in each operand,
      c * x == XOR_{b: bit b of x set} P[c, b].
    This is the basis of the gather-free "bit-linear" device kernels.
    Shape (256, 8) uint8.
    """
    mt = mul_table()
    return mt[:, [1 << b for b in range(8)]].copy()


def gf_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix M of multiply-by-c: bits(c*x) = M @ bits(x) mod 2.

    Column b of M holds the bits of c * 2^b (LSB-first rows). This is the
    same companion-matrix expansion jerasure's *_to_bitmatrix performs for
    its Cauchy/"schedule" codecs (ref: jerasure.c jerasure_matrix_to_bitmatrix),
    transposed to column-acts-on-input convention.
    """
    m = np.zeros((8, 8), dtype=np.uint8)
    for b in range(8):
        p = gf_mul_scalar(c, 1 << b)
        for r in range(8):
            m[r, b] = (p >> r) & 1
    return m


def matrix_to_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Expand an (r, c) GF(2^8) matrix to an (r*8, c*8) GF(2) bit matrix.

    Encoding over the bit matrix (XOR-accumulated AND products on the
    bit-planes of the data) is bit-exact with GF encoding over `mat`.
    """
    r, c = mat.shape
    out = np.zeros((r * 8, c * 8), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            out[i * 8:(i + 1) * 8, j * 8:(j + 1) * 8] = gf_bitmatrix(int(mat[i, j]))
    return out
