"""Pure-numpy GF(2^8) linear algebra — the host-side oracle.

Plays two roles:
  1. Test oracle for the JAX/Pallas device kernels (slow but obviously
     correct, mirrors jerasure's galois_* / jerasure_matrix_* semantics;
     ref: src/erasure-code/jerasure/jerasure/src/jerasure.c).
  2. Host-side construction of tiny decode matrices (invert a k x k
     surviving submatrix — microseconds on host, not worth a device trip;
     jerasure does the same on CPU in jerasure_matrix_decode).
"""

from __future__ import annotations

import numpy as np

from .tables import GF_EXP, GF_LOG, inv_table, mul_table


def gf_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise GF(2^8) product of uint8 arrays (broadcasting ok)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    la = GF_LOG[a].astype(np.int32)
    lb = GF_LOG[b].astype(np.int32)
    out = GF_EXP[la + lb]
    return np.where((a == 0) | (b == 0), np.uint8(0), out)


def gf_matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product: XOR-accumulated gf_mul. A:(r,k) B:(k,c)."""
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    assert A.shape[1] == B.shape[0], (A.shape, B.shape)
    prod = gf_mul(A[:, :, None], B[None, :, :])  # (r, k, c)
    return np.bitwise_xor.reduce(prod, axis=1)


def gf_matvec(A: np.ndarray, x: np.ndarray) -> np.ndarray:
    return gf_matmul(A, np.asarray(x, dtype=np.uint8).reshape(-1, 1)).reshape(-1)


def gf_inv_matrix(A: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination.

    Semantics of jerasure_invert_matrix (jerasure.c): row swaps for zero
    pivots, scale pivot row by pivot^-1, eliminate all other rows.
    Raises ValueError on singular input.
    """
    A = np.array(A, dtype=np.uint8, copy=True)
    n = A.shape[0]
    assert A.shape == (n, n), A.shape
    inv = np.eye(n, dtype=np.uint8)
    invt = inv_table()
    mt = mul_table()
    for col in range(n):
        pivot = col
        while pivot < n and A[pivot, col] == 0:
            pivot += 1
        if pivot == n:
            raise ValueError("singular GF(2^8) matrix")
        if pivot != col:
            A[[col, pivot]] = A[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        p = A[col, col]
        if p != 1:
            pinv = invt[p]
            A[col] = mt[pinv, A[col]]
            inv[col] = mt[pinv, inv[col]]
        for row in range(n):
            if row != col and A[row, col] != 0:
                f = A[row, col]
                A[row] ^= mt[f, A[col]]
                inv[row] ^= mt[f, inv[col]]
    return inv


def encode_ref(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Reference systematic encode: parity = matrix @ data.

    matrix: (m, k) uint8 coding matrix.
    data:   (..., k, L) uint8 chunk bytes (leading batch dims allowed).
    returns (..., m, L) parity chunks.

    Mirrors jerasure_matrix_encode (jerasure.c): each coding chunk is the
    XOR over data chunks of the GF product with its matrix coefficient.
    """
    matrix = np.asarray(matrix, dtype=np.uint8)
    data = np.asarray(data, dtype=np.uint8)
    m, k = matrix.shape
    assert data.shape[-2] == k, (matrix.shape, data.shape)
    mt = mul_table()
    out = np.zeros(data.shape[:-2] + (m, data.shape[-1]), dtype=np.uint8)
    for i in range(m):
        acc = np.zeros(data.shape[:-2] + (data.shape[-1],), dtype=np.uint8)
        for j in range(k):
            c = matrix[i, j]
            if c == 0:
                continue
            acc ^= mt[c, data[..., j, :]]
        out[..., i, :] = acc
    return out


def decode_matrix(matrix: np.ndarray, erasures: list[int], k: int,
                  survivors: list[int] | None = None) -> np.ndarray:
    """Build the decode matrix for recovering erased chunks.

    matrix: (m, k) coding matrix of the systematic code [I; matrix].
    erasures: chunk ids that were lost (data ids < k, parity ids >= k).
    survivors: the k chunk ids actually used as decode input, in the
        order they will be stacked; defaults to the first k non-erased
        ids. Returns (len(erasures), k) matrix D with lost = D @ survivors.

    Same construction as jerasure_matrix_decode (jerasure.c): take the
    rows of [I; matrix] for the k chosen survivors, invert, then for each
    erased data chunk use the corresponding row of the inverse; for each
    erased parity chunk re-encode from the recovered data row combination.
    """
    matrix = np.asarray(matrix, dtype=np.uint8)
    m, _ = matrix.shape
    n = k + m
    erased = set(erasures)
    if any(not 0 <= e < n for e in erased):
        raise ValueError(f"erasure ids must be in [0, {n}), got {sorted(erased)}")
    if len(erased) > m:
        raise ValueError(f"cannot decode {len(erased)} erasures with m={m}")
    if survivors is None:
        survivors = [i for i in range(n) if i not in erased][:k]
    if (len(survivors) != k or erased & set(survivors)
            or any(not 0 <= s < n for s in survivors)):
        raise ValueError("need exactly k surviving chunk ids disjoint from erasures")
    full = np.vstack([np.eye(k, dtype=np.uint8), matrix])  # (n, k)
    sub = full[survivors]  # (k, k)
    inv = gf_inv_matrix(sub)
    rows = []
    for e in erasures:
        if e < k:
            rows.append(inv[e])
        else:
            # parity chunk: its row in [I;C] applied to recovered data
            rows.append(gf_matmul(matrix[e - k].reshape(1, -1), inv).reshape(-1))
    return np.asarray(rows, dtype=np.uint8)


def decode_ref(matrix: np.ndarray, chunks: dict[int, np.ndarray], erasures: list[int],
               k: int) -> dict[int, np.ndarray]:
    """Reference decode: reconstruct `erasures` from surviving `chunks`.

    chunks: {chunk_id: (..., L) uint8}; must contain >= k survivors.
    Returns {erased_id: recovered bytes}.
    """
    erased = set(erasures)
    survivors = sorted(i for i in chunks if i not in erased)[:k]
    D = decode_matrix(matrix, list(erasures), k, survivors)
    stack = np.stack([chunks[s] for s in survivors], axis=-2)  # (..., k, L)
    rec = encode_ref(D, stack)  # (..., E, L)
    return {e: rec[..., idx, :] for idx, e in enumerate(erasures)}
