"""S3 request authentication for RGW-lite.

Rebuild of the reference's S3 auth engine (ref: src/rgw/rgw_auth_s3.cc
— AWSv4 canonical request assembly, the HMAC key-derivation chain in
get_v4_signing_key, clock-skew enforcement in RGW_AUTH_GRACE;
src/rgw/rgw_rest_s3.cc dispatches verified requests to the ops). Shape
kept, trimmed to this framework's surface:

* CANONICAL REQUEST. Every call signs (op, bucket, key, client nonce,
  sorted-params JSON, SHA-256 of the payload). The server recomputes
  the canonical string from the parameters it will actually execute —
  tampering with ANY of them (op swap, key swap, payload swap, range
  change) breaks the signature.
* KEY DERIVATION (SigV4's chain, re-labeled): the signing key is
  HMAC-chained from the user's secret through date / region / service
  / terminator, so a leaked per-request signing key expires with its
  date and never reveals the long-term secret.
* CLOCK SKEW. Requests carry an amz-date; outside the +/-900 s window
  the server refuses (RequestTimeTooSkewed) BEFORE any signature
  math — same order as the reference.
* REPLAY. The reference leans on TLS + the skew window; this wire has
  sessions of its own (msgr secure mode), but the gateway ALSO keeps
  a seen-signature cache for the skew window so a captured request
  cannot be re-executed inside it (the client nonce makes legitimate
  identical calls sign differently).

Credentials are (access_key, secret_key) pairs from UserStore — the
RGWUserCtl role, kept in-memory because user metadata storage is a
context-tier concern (SURVEY L8)."""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import time

from .gateway import Gateway, GatewayError, NoSuchBucket

ALGO = "CEPH-TPU-HMAC-SHA256"
REGION = "tpu"
SERVICE = "s3"
TERM = "ceph4_request"
SKEW_MAX = 900.0            # seconds, the reference's auth grace


class AuthError(GatewayError):
    pass


class AccessDenied(AuthError):
    pass


class SignatureDoesNotMatch(AuthError):
    pass


class RequestTimeTooSkewed(AuthError):
    pass


def _hex_sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def canonical_request(op: str, bucket: str, key: str, nonce: str,
                      params: dict, payload: bytes) -> str:
    """Everything the server will act on, in one deterministic
    string (the AWSv4 canonical request role). Fields are LENGTH-
    PREFIXED, not merely joined: client-controlled fields containing
    the join character must not let two different (bucket, key,
    nonce) bindings collapse to one canonical string (SigV4 gets the
    same property from URI-encoding)."""
    fields = [op, bucket, key, nonce,
              json.dumps(params, sort_keys=True),
              _hex_sha256(payload)]
    return "".join(f"{len(f)}:{f}\n" for f in fields)


def signing_key(secret_key: str, date: str) -> bytes:
    """SigV4's derivation chain: secret -> date -> region -> service
    -> terminator (ref: rgw_auth_s3.cc get_v4_signing_key)."""
    k = _hmac(("CEPH4" + secret_key).encode(), date)
    k = _hmac(k, REGION)
    k = _hmac(k, SERVICE)
    return _hmac(k, TERM)


def sign(secret_key: str, amz_date: str, op: str, bucket: str,
         key: str, nonce: str, params: dict, payload: bytes) -> str:
    scope = f"{amz_date[:8]}/{REGION}/{SERVICE}/{TERM}"
    string_to_sign = "\n".join([
        ALGO, amz_date, scope,
        _hex_sha256(canonical_request(op, bucket, key, nonce, params,
                                      payload).encode()),
    ])
    return hmac.new(signing_key(secret_key, amz_date[:8]),
                    string_to_sign.encode(), hashlib.sha256).hexdigest()


def amz_date(t: float) -> str:
    return time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(t))


def _parse_amz_date(s: str) -> float:
    import calendar
    try:
        return calendar.timegm(time.strptime(s, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        raise AccessDenied(f"malformed amz-date {s!r}") from None


class UserStore:
    """access_key -> (uid, secret_key) — the RGWUserCtl role."""

    def __init__(self):
        self._by_access: dict[str, tuple[str, str]] = {}

    def create_user(self, uid: str) -> tuple[str, str]:
        access = "AK" + os.urandom(8).hex().upper()
        secret = os.urandom(20).hex()
        self._by_access[access] = (uid, secret)
        return access, secret

    def lookup(self, access_key: str) -> tuple[str, str]:
        """(uid, secret_key) — uid drives authorization, secret the
        signature check."""
        ent = self._by_access.get(access_key)
        if ent is None:
            raise AccessDenied(f"InvalidAccessKeyId: {access_key}")
        return ent


class AuthedGateway:
    """Signature-checking front of a Gateway: verify, then dispatch.
    The op table is the REST dispatch role (rgw_rest_s3.cc) without
    the HTTP parsing."""

    _OPS = ("create_bucket", "delete_bucket", "list_buckets",
            "put_object", "get_object", "head_object", "delete_object",
            "list_objects", "initiate_multipart", "upload_part",
            "complete_multipart", "abort_multipart",
            "put_bucket_versioning", "get_bucket_versioning",
            "list_object_versions", "copy_object")

    def __init__(self, gateway: Gateway, users: UserStore,
                 clock=time.time):
        import threading
        self._gw = gateway
        self._users = users
        self._clock = clock
        self._seen: dict[str, float] = {}    # signature -> expiry
        self._seen_lock = threading.Lock()
        self._last_prune = 0.0
        # bucket -> owning uid, for buckets created THROUGH this
        # authed front (the rgw_bucket owner field's role). A bucket
        # owned by another uid — or with NO recorded owner (created
        # on the raw Gateway, outside this auth layer) — is denied
        # outright: unknown ownership must not read as world-access.
        self._owner: dict[str, str] = {}

    def adopt_bucket(self, bucket: str, uid: str) -> None:
        """Admin-plane ownership link for a bucket created outside
        this auth layer (the radosgw-admin `bucket link` role) —
        without it, unknown-owner buckets are denied to everyone."""
        if bucket not in self._gw.list_buckets():
            raise NoSuchBucket(bucket)
        self._owner[bucket] = uid

    def call(self, access_key: str, date: str, signature: str,
             op: str, bucket: str = "", key: str = "",
             nonce: str = "", payload: bytes = b"",
             **params):
        now = self._clock()
        # 1. clock skew gate BEFORE any signature math (ref order)
        if abs(now - _parse_amz_date(date)) > SKEW_MAX:
            raise RequestTimeTooSkewed(
                f"request time {date} outside +/-{SKEW_MAX:.0f}s")
        # 2. signature over exactly what will execute
        uid, secret = self._users.lookup(access_key)
        want = sign(secret, date, op, bucket, key, nonce, params,
                    bytes(payload))
        if not hmac.compare_digest(want, signature):
            raise SignatureDoesNotMatch(op)
        # 3. replay rejection inside the skew window — check+insert
        # atomically (per-connection reader threads submit in
        # parallel; a race here would execute a replay twice)
        with self._seen_lock:
            if len(self._seen) > 4096 \
                    and now - self._last_prune > 60.0:
                self._seen = {s: t for s, t in self._seen.items()
                              if t > now}
                self._last_prune = now
            if signature in self._seen:
                raise AccessDenied("replayed request")
            self._seen[signature] = now + 2 * SKEW_MAX
        # 4. authorization: bucket ownership (authN without authZ
        # would let any valid user delete any other user's data)
        if op not in self._OPS:
            raise AccessDenied(f"unknown op {op!r}")
        if op not in ("list_buckets", "create_bucket"):
            owner = self._owner.get(bucket)
            if owner != uid:
                raise AccessDenied(
                    f"bucket {bucket!r} is owned by another user"
                    if owner is not None else
                    f"bucket {bucket!r} has no recorded owner")
        # 5. dispatch (explicit binding per op: the signed bucket/key
        # must never re-bind to a different parameter slot)
        gw = self._gw
        if op == "list_buckets":
            # strict owner match: orphan buckets (no recorded owner)
            # must not appear in anyone's listing either
            return [b for b in gw.list_buckets()
                    if self._owner.get(b) == uid]
        if op == "create_bucket":
            out = gw.create_bucket(bucket)
            self._owner[bucket] = uid
            return out
        if op == "delete_bucket":
            out = gw.delete_bucket(bucket)
            self._owner.pop(bucket, None)
            return out
        if op == "list_objects":
            return gw.list_objects(bucket, **params)
        if op == "put_bucket_versioning":
            return gw.set_bucket_versioning(bucket, params["enabled"])
        if op == "get_bucket_versioning":
            return gw.get_bucket_versioning(bucket)
        if op == "list_object_versions":
            return gw.list_object_versions(bucket, **params)
        if op == "put_object":
            return gw.put_object(bucket, key, payload)
        if op == "copy_object":
            # the signed (bucket, key) is the DESTINATION; the source
            # bucket needs its own ownership check — authenticated
            # users must not read each other's buckets via copy
            # unknown-owner sources (buckets made on the raw Gateway,
            # outside this auth layer) are DENIED, not world-readable
            src_owner = self._owner.get(params["src_bucket"])
            if src_owner != uid:
                raise AccessDenied(
                    f"source bucket {params['src_bucket']!r} is "
                    "owned by another user" if src_owner is not None
                    else f"source bucket {params['src_bucket']!r} "
                    "has no recorded owner")
            return gw.copy_object(
                params["src_bucket"], params["src_key"], bucket, key,
                src_version_id=params.get("src_version_id"))
        if op == "upload_part":
            return gw.upload_part(bucket, key, params["upload_id"],
                                  params["part_number"], payload)
        if op in ("complete_multipart", "abort_multipart"):
            return getattr(gw, op)(bucket, key, params["upload_id"])
        # get_object / head_object / delete_object / initiate_multipart
        return getattr(gw, op)(bucket, key, **params)


class S3Client:
    """Client-side signer (the SDK role): stamps date + nonce, signs
    the canonical request, ships the call."""

    def __init__(self, authed: AuthedGateway, access_key: str,
                 secret_key: str, clock=time.time):
        self._a = authed
        self._access = access_key
        self._secret = secret_key
        self._clock = clock

    def _call(self, op: str, bucket: str = "", key: str = "",
              payload: bytes = b"", **params):
        date = amz_date(self._clock())
        nonce = os.urandom(8).hex()
        sig = sign(self._secret, date, op, bucket, key, nonce, params,
                   bytes(payload))
        return self._a.call(self._access, date, sig, op, bucket=bucket,
                            key=key, nonce=nonce, payload=payload,
                            **params)

    # -- the S3 surface, signed ----------------------------------------------

    def create_bucket(self, bucket):
        return self._call("create_bucket", bucket)

    def delete_bucket(self, bucket):
        return self._call("delete_bucket", bucket)

    def list_buckets(self):
        return self._call("list_buckets")

    def put_object(self, bucket, key, data: bytes):
        return self._call("put_object", bucket, key, payload=data)

    def get_object(self, bucket, key, offset: int = 0,
                   length: int | None = None,
                   version_id: str | None = None):
        return self._call("get_object", bucket, key, offset=offset,
                          length=length, version_id=version_id)

    def copy_object(self, src_bucket, src_key, dst_bucket, dst_key,
                    src_version_id: str | None = None):
        return self._call("copy_object", dst_bucket, dst_key,
                          src_bucket=src_bucket, src_key=src_key,
                          src_version_id=src_version_id)

    def head_object(self, bucket, key, version_id: str | None = None):
        return self._call("head_object", bucket, key,
                          version_id=version_id)

    def delete_object(self, bucket, key,
                      version_id: str | None = None):
        return self._call("delete_object", bucket, key,
                          version_id=version_id)

    def put_bucket_versioning(self, bucket, enabled: bool):
        return self._call("put_bucket_versioning", bucket,
                          enabled=enabled)

    def get_bucket_versioning(self, bucket):
        return self._call("get_bucket_versioning", bucket)

    def list_object_versions(self, bucket, prefix: str = ""):
        return self._call("list_object_versions", bucket,
                          prefix=prefix)

    def list_objects(self, bucket, prefix: str = "", marker: str = "",
                     limit: int = 1000, delimiter: str = ""):
        return self._call("list_objects", bucket, prefix=prefix,
                          marker=marker, limit=limit,
                          delimiter=delimiter)

    def initiate_multipart(self, bucket, key):
        return self._call("initiate_multipart", bucket, key)

    def upload_part(self, bucket, key, upload_id, part_number,
                    data: bytes):
        return self._call("upload_part", bucket, key, payload=data,
                          upload_id=upload_id, part_number=part_number)

    def complete_multipart(self, bucket, key, upload_id):
        return self._call("complete_multipart", bucket, key,
                          upload_id=upload_id)

    def abort_multipart(self, bucket, key, upload_id):
        return self._call("abort_multipart", bucket, key,
                          upload_id=upload_id)
