from .gateway import Gateway, GatewayError, NoSuchBucket, NoSuchKey

__all__ = ["Gateway", "GatewayError", "NoSuchBucket", "NoSuchKey"]
