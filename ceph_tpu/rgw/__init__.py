from .auth import (AccessDenied, AuthedGateway, RequestTimeTooSkewed,
                   S3Client, SignatureDoesNotMatch, UserStore)
from .gateway import Gateway, GatewayError, NoSuchBucket, NoSuchKey

__all__ = ["Gateway", "GatewayError", "NoSuchBucket", "NoSuchKey",
           "AuthedGateway", "S3Client", "UserStore", "AccessDenied",
           "SignatureDoesNotMatch", "RequestTimeTooSkewed"]
