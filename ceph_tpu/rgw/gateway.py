"""RGW-lite — the S3-shaped object gateway over rados.

Rebuild of the reference's radosgw data path (ref: src/rgw/ —
rgw_op.cc RGWPutObj/RGWGetObj/RGWDeleteObj/RGWListBucket,
rgw_rados.cc head+tail object layout, cls/rgw/cls_rgw.cc bucket-index
omap ops, multipart assembly in rgw_multi.cc). What's kept and how it
maps onto this framework:

* BUCKETS + INDEX. Each bucket has an index object whose entries are
  maintained by a server-side object class (`rgw_index` below) — the
  exact role cls_rgw plays for the reference: the index mutates
  atomically AT the object, not read-modify-write from the client.
  Listing supports prefix + marker pagination like ListObjectsV2.
* OBJECT LAYOUT. Small objects land in one rados object; everything
  is written through the RadosStriper, so big S3 objects stripe
  across rados objects exactly as RGW's head+tails do. ETag =
  hex(crc32c) of the payload (the reference uses MD5; the framework's
  native checksum keeps the property that matters — content-derived,
  verified end to end).
* MULTIPART. initiate/upload_part/complete/abort: parts are striped
  objects of their own; complete writes a MANIFEST the GET path
  follows (RGW's multipart manifest), so completion is O(parts), not
  a data rewrite.
* S3-AUTH lives in auth.py (SigV4-shaped canonical requests, HMAC
  key-derivation chain, skew window, replay cache) as a verifying
  front over this gateway.
* VERSIONING (ref: rgw_bucket_dir_entry instance entries +
  RGWRados olh/instance objects; S3 bucket versioning semantics).
  Bucket state Off -> Enabled <-> Suspended via the index cls; a
  versioned PUT appends an instance entry whose payload lives at its
  own soid (.v.{vid}); unversioned DELETE writes a delete marker;
  DELETE with versionId permanently removes that instance + payload;
  Suspended writes/overwrites the "null" version; GET/HEAD accept
  version_id; ListObjectVersions reports history newest-first with
  is_latest and markers. Objects predating versioning materialize as
  the null version on first versioned write (payload stays at the
  legacy soid).

Everything routes through librados/striper, so EC encode fan-out,
snapshots' COW, scrub, recovery, and PG splits all apply to gateway
data with no special cases."""

from __future__ import annotations

import json
import time

from ..client.rados import IoCtx, RadosStriper
from ..osd.objclass import ClsError, ClsHandle, register_cls

_BUCKETS_ROOT = ".rgw.root"          # object listing all buckets


class GatewayError(Exception):
    pass


class NoSuchBucket(GatewayError, KeyError):
    pass


class NoSuchKey(GatewayError, KeyError):
    pass


# -- bucket index object class (the cls_rgw role) ----------------------------

@register_cls("rgw_index", "add")
def _idx_add(h: ClsHandle, inp: bytes) -> bytes:
    ent = json.loads(inp)
    idx = h.kv.setdefault("entries", {})
    idx[ent["key"]] = {"size": ent["size"], "etag": ent["etag"],
                       "mtime": ent["mtime"]}
    return b"{}"


@register_cls("rgw_index", "rm")
def _idx_rm(h: ClsHandle, inp: bytes) -> bytes:
    key = json.loads(inp)["key"]
    idx = h.kv.setdefault("entries", {})
    if key not in idx:
        raise ClsError(f"ENOENT: {key}")
    del idx[key]
    return b"{}"


@register_cls("rgw_index", "list")
def _idx_list(h: ClsHandle, inp: bytes) -> bytes:
    """ListObjectsV2 shape incl. `delimiter` rollup: keys sharing
    prefix..delimiter collapse into common_prefixes (the S3 "folder"
    view; ref: cls_rgw bucket listing + RGWListBucket::execute)."""
    req = json.loads(inp or b"{}")
    prefix = req.get("prefix", "")
    marker = req.get("marker", "")
    delim = req.get("delimiter", "")
    limit = int(req.get("limit", 1000))
    idx = h.kv.get("entries", {})
    if not delim:
        keys = sorted(k for k in idx
                      if k.startswith(prefix) and k > marker)
        page = keys[:limit]
        return json.dumps({
            "entries": [{"key": k, **idx[k]} for k in page],
            "truncated": len(keys) > limit,
            "next_marker": page[-1] if page and len(keys) > limit
            else "",
        }).encode()
    # S3 marker semantics: keys strictly after the marker, THEN the
    # rollup — except that a marker which IS a rolled-up prefix (our
    # next_marker after a delimiter page) skips everything under it,
    # or pagination would re-emit the prefix forever. A plain-key
    # marker inside a prefix still surfaces that prefix for the
    # remaining keys, as S3 does.
    entries, prefixes, taken = [], [], 0
    last = ""
    more = False
    # a rolled-up-prefix marker is always STRICTLY longer than the
    # listing prefix (rollup appends at least one char + delim), so
    # marker == prefix can only be a real zero-byte "folder marker"
    # object ('a/' listed as an ENTRY under prefix='a/') — treating
    # it as a rollup would silently skip the whole subtree
    marker_is_prefix = bool(marker) and marker.endswith(delim) \
        and marker != prefix
    for k in sorted(k for k in idx if k.startswith(prefix)):
        if k <= marker:
            continue
        if marker_is_prefix and k.startswith(marker):
            continue         # under an already-listed rollup page
        rest = k[len(prefix):]
        cut = rest.find(delim)
        rolled = prefix + rest[:cut + len(delim)] if cut >= 0 else k
        if cut >= 0 and prefixes and prefixes[-1] == rolled:
            last = rolled        # absorbed into the current rollup
            continue
        if taken >= limit:
            more = True
            break
        if cut >= 0:
            prefixes.append(rolled)
        else:
            entries.append({"key": k, **idx[k]})
        taken += 1
        last = rolled
    return json.dumps({
        "entries": entries, "common_prefixes": prefixes,
        "truncated": more,
        "next_marker": last if more else "",
    }).encode()


@register_cls("rgw_index", "set_manifest")
def _idx_set_manifest(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    ent = h.kv.get("entries", {}).get(req["key"])
    if ent is None:
        raise ClsError(f"ENOENT: {req['key']}")
    ent["manifest"] = req["manifest"]
    ent["part_sizes"] = req["part_sizes"]
    return b"{}"


@register_cls("rgw_index", "stat")
def _idx_stat(h: ClsHandle, inp: bytes) -> bytes:
    key = json.loads(inp)["key"]
    ent = h.kv.get("entries", {}).get(key)
    if ent is None:
        raise ClsError(f"ENOENT: {key}")
    return json.dumps(ent).encode()


# -- lifecycle configuration (cls-held, ref: RGWLC + cls_rgw lc ops) --

@register_cls("rgw_index", "set_lc")
def _idx_set_lc(h: ClsHandle, inp: bytes) -> bytes:
    h.kv["lifecycle"] = json.loads(inp)
    return b"{}"


@register_cls("rgw_index", "get_lc")
def _idx_get_lc(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps(h.kv.get("lifecycle", [])).encode()


@register_cls("rgw_index", "del_lc")
def _idx_del_lc(h: ClsHandle, inp: bytes) -> bytes:
    h.kv.pop("lifecycle", None)
    return b"{}"


# -- versioning (cls_rgw bucket-index instance entries, ref:
#    rgw_bucket_dir_entry instances + RGWRados::Bucket::UpdateIndex;
#    S3 semantics: PUT appends a version, unversioned DELETE writes a
#    delete marker, Suspended writes/overwrites the "null" version) --

def _idx_current_view(ent: dict) -> dict:
    """The entries{} (latest-view) projection of a version entry."""
    view = {"size": ent["size"], "etag": ent["etag"],
            "mtime": ent["mtime"], "vid": ent["vid"]}
    for f in ("soid", "manifest", "part_sizes"):
        if f in ent:
            view[f] = ent[f]
    return view


@register_cls("rgw_index", "set_versioning")
def _idx_set_versioning(h: ClsHandle, inp: bytes) -> bytes:
    status = json.loads(inp)["status"]
    if status not in ("Enabled", "Suspended"):
        raise ClsError(f"bad versioning status {status!r}")
    h.kv["versioning"] = status
    return b"{}"


@register_cls("rgw_index", "get_versioning")
def _idx_get_versioning(h: ClsHandle, inp: bytes) -> bytes:
    # "Off" = never enabled (S3: unversioned bucket); once enabled a
    # bucket can only flip Enabled <-> Suspended
    return json.dumps({"status": h.kv.get("versioning", "Off")}).encode()


@register_cls("rgw_index", "alloc_vid")
def _idx_alloc_vid(h: ClsHandle, inp: bytes) -> bytes:
    n = h.kv.get("next_vid", 1)
    h.kv["next_vid"] = n + 1
    return json.dumps({"vid": f"v{n:08d}"}).encode()


@register_cls("rgw_index", "put_version")
def _idx_put_version(h: ClsHandle, inp: bytes) -> bytes:
    """Append a version entry (newest LAST) and refresh the latest
    view. A 'null' vid replaces any existing null entry (Suspended
    semantics); the replaced entry is returned so the caller can wipe
    its payload. If the key predates versioning, its legacy entry is
    first materialized as the null version (payload at legacy_soid)."""
    req = json.loads(inp)
    key, ent = req["key"], req["ent"]
    versions = h.kv.setdefault("versions", {})
    entries = h.kv.setdefault("entries", {})
    lst = versions.setdefault(key, [])
    if not lst and key in entries and "vid" not in entries[key]:
        legacy = dict(entries[key])
        legacy.update(vid="null", delete_marker=False,
                      soid=req["legacy_soid"])
        lst.append(legacy)
    replaced = None
    if ent["vid"] == "null":
        for i, v in enumerate(lst):
            if v["vid"] == "null":
                replaced = lst.pop(i)
                break
    lst.append(ent)
    if ent.get("delete_marker"):
        entries.pop(key, None)
    else:
        entries[key] = _idx_current_view(ent)
    return json.dumps({"replaced": replaced}).encode()


@register_cls("rgw_index", "rm_version")
def _idx_rm_version(h: ClsHandle, inp: bytes) -> bytes:
    """Remove ONE version (S3 DELETE with versionId) and recompute
    the latest view from what remains. Returns the removed entry so
    the caller wipes its payload."""
    req = json.loads(inp)
    key, vid = req["key"], req["vid"]
    versions = h.kv.get("versions", {})
    lst = versions.get(key, [])
    removed = None
    for i, v in enumerate(lst):
        if v["vid"] == vid:
            removed = lst.pop(i)
            break
    if removed is None:
        raise ClsError(f"NoSuchVersion: {key}@{vid}")
    entries = h.kv.setdefault("entries", {})
    if not lst:
        versions.pop(key, None)
        entries.pop(key, None)
    elif lst[-1].get("delete_marker"):
        entries.pop(key, None)
    else:
        entries[key] = _idx_current_view(lst[-1])
    return json.dumps(removed).encode()


@register_cls("rgw_index", "has_versions")
def _idx_has_versions(h: ClsHandle, inp: bytes) -> bytes:
    """O(1) membership probe: key given -> that key has history;
    no key -> ANY key does (the delete_bucket emptiness check)."""
    key = json.loads(inp or b"{}").get("key")
    versions = h.kv.get("versions", {})
    if key is None:
        any_v = any(bool(v) for v in versions.values())
    else:
        any_v = bool(versions.get(key))
    return json.dumps({"any": any_v}).encode()


@register_cls("rgw_index", "stat_version")
def _idx_stat_version(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    for v in h.kv.get("versions", {}).get(req["key"], []):
        if v["vid"] == req["vid"]:
            return json.dumps(v).encode()
    raise ClsError(f"NoSuchVersion: {req['key']}@{req['vid']}")


@register_cls("rgw_index", "list_versions")
def _idx_list_versions(h: ClsHandle, inp: bytes) -> bytes:
    """ListObjectVersions shape: per key newest-first, is_latest on
    the newest, delete markers included."""
    req = json.loads(inp or b"{}")
    prefix = req.get("prefix", "")
    versions = h.kv.get("versions", {})
    out = []
    for key in sorted(k for k in versions if k.startswith(prefix)):
        for i, v in enumerate(reversed(versions[key])):
            out.append({"key": key, "vid": v["vid"],
                        "is_latest": i == 0,
                        "delete_marker": bool(v.get("delete_marker")),
                        "size": v["size"], "etag": v["etag"],
                        "mtime": v["mtime"]})
    return json.dumps({"versions": out}).encode()


class Gateway:
    """One S3-facing endpoint over an IoCtx (the radosgw process)."""

    #: striping geometry for object payloads (RGW head+tail analog)
    STRIPE_UNIT = 1 << 16
    STRIPE_COUNT = 4
    OBJECT_SIZE = 1 << 20

    def __init__(self, ioctx: IoCtx):
        self.io = ioctx
        self._striper = RadosStriper(
            ioctx, stripe_unit=self.STRIPE_UNIT,
            stripe_count=self.STRIPE_COUNT,
            object_size=self.OBJECT_SIZE)

    # -- naming --------------------------------------------------------------

    @staticmethod
    def _index_obj(bucket: str) -> str:
        return f".bucket.index.{bucket}"

    @staticmethod
    def _data_obj(bucket: str, key: str) -> str:
        return f".bucket.data.{bucket}/{key}"

    @staticmethod
    def _upload_obj(bucket: str, key: str, upload_id: str,
                    part: int | None = None) -> str:
        base = f".bucket.multipart.{bucket}/{key}/{upload_id}"
        return base if part is None else f"{base}/part.{part:05d}"

    def _clock(self) -> float:
        from ..client.rados import sim_clock
        return sim_clock(self.io)

    def _etag(self, data: bytes) -> str:
        from ..osd.tinstore import _crc32c
        return f"{_crc32c(data):08x}"

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, bucket: str) -> None:
        if not bucket or "/" in bucket:
            raise GatewayError(f"bad bucket name {bucket!r}")
        roots = self._root_read()
        if bucket in roots:
            raise GatewayError(f"BucketAlreadyExists: {bucket}")
        self.io.write_full(self._index_obj(bucket), b"index")
        roots.append(bucket)
        self._root_write(roots)

    def delete_bucket(self, bucket: str) -> None:
        self._check_bucket(bucket)
        listing = self.list_objects(bucket, limit=1)
        if listing["entries"]:
            raise GatewayError(f"BucketNotEmpty: {bucket}")
        out = json.loads(self.io.execute(
            self._index_obj(bucket), "rgw_index", "has_versions"))
        if out["any"]:
            # S3: noncurrent versions and delete markers also block
            # bucket deletion — their payloads would orphan
            raise GatewayError(f"BucketNotEmpty: {bucket} "
                               f"(noncurrent versions remain)")
        self.io.remove(self._index_obj(bucket))
        roots = self._root_read()
        roots.remove(bucket)
        self._root_write(roots)

    def list_buckets(self) -> list[str]:
        return sorted(self._root_read())

    def _root_read(self) -> list[str]:
        try:
            return json.loads(self.io.read(_BUCKETS_ROOT))
        except KeyError:
            return []

    def _root_write(self, roots: list[str]) -> None:
        self.io.write_full(_BUCKETS_ROOT, json.dumps(sorted(roots)).encode())

    def _check_bucket(self, bucket: str) -> None:
        try:
            self.io.stat(self._index_obj(bucket))
        except KeyError:
            raise NoSuchBucket(bucket) from None

    # -- versioning ----------------------------------------------------------

    @staticmethod
    def _vdata_obj(bucket: str, key: str, vid: str) -> str:
        # A namespace of its own, collision-free by construction:
        # '.bucket.vdata.' is disjoint from _data_obj/_upload_obj
        # prefixes; '/' joins bucket to key exactly like _data_obj
        # ('.'-joining would let ('b.k','x') and ('b','k.x') share a
        # soid — bucket names may contain '.'); and within the
        # namespace (key, vid) -> f"{key}.v.{vid}" is injective
        # because vids match ^(null|v\d{8})$ — suffixes of equal vids
        # force equal keys, and 'null' vs 'v\d{8}' differ in both
        # length-tail and final character, so no key can absorb the
        # difference.
        return f".bucket.vdata.{bucket}/{key}.v.{vid}"

    def set_bucket_versioning(self, bucket: str, enabled: bool) -> None:
        """PutBucketVersioning: Enabled / Suspended (a bucket that was
        ever versioned cannot return to Off — S3 semantics)."""
        self._check_bucket(bucket)
        self.io.execute(self._index_obj(bucket), "rgw_index",
                        "set_versioning", json.dumps(
                            {"status": "Enabled" if enabled
                             else "Suspended"}).encode())

    def get_bucket_versioning(self, bucket: str) -> str:
        self._check_bucket(bucket)
        return self._versioning(bucket)

    def _versioning(self, bucket: str) -> str:
        out = self.io.execute(self._index_obj(bucket), "rgw_index",
                              "get_versioning")
        return json.loads(out)["status"]

    def _alloc_vid(self, bucket: str) -> str:
        out = self.io.execute(self._index_obj(bucket), "rgw_index",
                              "alloc_vid")
        return json.loads(out)["vid"]

    def _put_version(self, bucket: str, key: str, ent: dict) -> None:
        """Record a version entry; wipe whatever payload a replaced
        null version owned (Suspended-overwrite semantics)."""
        out = self.io.execute(
            self._index_obj(bucket), "rgw_index", "put_version",
            json.dumps({"key": key, "ent": ent,
                        "legacy_soid": self._data_obj(bucket, key)}
                       ).encode())
        replaced = json.loads(out)["replaced"]
        if replaced is not None:
            self._wipe_version_payload(replaced, keep=ent.get("soid"))

    def _next_vid(self, bucket: str, status: str) -> str:
        """Fresh vid under Enabled; the null slot under Suspended."""
        return self._alloc_vid(bucket) if status == "Enabled" else "null"

    def _record_version(self, bucket: str, key: str, vid: str,
                        **fields) -> str:
        """Shared versioned-write tail: record the entry (mtime
        stamped, live unless delete_marker overridden), return the
        vid. `fields` supplies size/etag/soid/manifest/..."""
        ent = {"vid": vid, "mtime": self._clock(),
               "delete_marker": False, **fields}
        self._put_version(bucket, key, ent)
        return vid

    def _wipe_version_payload(self, ent: dict,
                              keep: str | None = None) -> None:
        if "manifest" in ent:
            for part_soid in ent["manifest"]:
                self._wipe_striped(part_soid)
        elif ent.get("soid") and ent["soid"] != keep:
            self._wipe_striped(ent["soid"])

    def list_object_versions(self, bucket: str,
                             prefix: str = "") -> dict:
        """ListObjectVersions: every version + delete marker, per key
        newest-first with is_latest on the newest."""
        self._check_bucket(bucket)
        out = self.io.execute(self._index_obj(bucket), "rgw_index",
                              "list_versions",
                              json.dumps({"prefix": prefix}).encode())
        return json.loads(out)

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, data: bytes) -> str:
        """PUT: payload through the striper, then the index entry via
        the cls (atomic at the index object). Returns the ETag.
        Versioned buckets append a new version (Enabled) or replace
        the null version (Suspended) instead of overwriting."""
        self._check_bucket(bucket)
        if not key:
            raise GatewayError("empty key")
        data = bytes(data)
        etag = self._etag(data)
        status = self._versioning(bucket)
        if status != "Off":
            vid = self._next_vid(bucket, status)
            soid = self._vdata_obj(bucket, key, vid)
            self._wipe_striped(soid)     # null overwrite-in-place
            self._striper.write(soid, data)
            self._record_version(bucket, key, vid, soid=soid,
                                 size=len(data), etag=etag)
            return etag
        soid = self._data_obj(bucket, key)
        self._wipe_replaced(bucket, key)
        self._wipe_striped(soid)
        self._striper.write(soid, data)
        self.io.execute(self._index_obj(bucket), "rgw_index", "add",
                        json.dumps({"key": key, "size": len(data),
                                    "etag": etag,
                                    "mtime": self._clock()}).encode())
        return etag

    def _stat_version(self, bucket: str, key: str, vid: str) -> dict:
        try:
            return json.loads(self.io.execute(
                self._index_obj(bucket), "rgw_index", "stat_version",
                json.dumps({"key": key, "vid": vid}).encode()))
        except ClsError:
            raise NoSuchKey(f"{bucket}/{key}@{vid}") from None

    def get_object(self, bucket: str, key: str,
                   offset: int = 0, length: int | None = None,
                   version_id: str | None = None) -> bytes:
        self._check_bucket(bucket)
        if version_id is not None:
            ent = self._stat_version(bucket, key, version_id)
            if ent.get("delete_marker"):
                raise NoSuchKey(f"{bucket}/{key}@{version_id} "
                                f"is a delete marker")
        else:
            ent = self._stat_entry(bucket, key)
        if "manifest" in ent:
            return self._read_manifest(bucket, key, ent, offset, length)
        soid = ent.get("soid") or self._data_obj(bucket, key)
        try:
            if length is None:
                length = max(0, ent["size"] - offset)
            return self._striper.read(soid, length=length, offset=offset)
        except KeyError:
            raise NoSuchKey(f"{bucket}/{key}") from None

    def head_object(self, bucket: str, key: str,
                    version_id: str | None = None) -> dict:
        self._check_bucket(bucket)
        if version_id is not None:
            ent = self._stat_version(bucket, key, version_id)
            if ent.get("delete_marker"):
                # S3 fails HEAD on a marker too (405 +
                # x-amz-delete-marker); succeeding here while GET
                # refuses would split the surface
                raise NoSuchKey(f"{bucket}/{key}@{version_id} "
                                f"is a delete marker")
            return ent
        return self._stat_entry(bucket, key)

    def delete_object(self, bucket: str, key: str,
                      version_id: str | None = None) -> dict:
        """DELETE. Unversioned bucket: remove key + payload. Versioned,
        no version_id: write a delete marker (payloads stay). With
        version_id: permanently remove THAT version and its payload.
        Returns {'delete_marker': bool, 'version_id': str|None}."""
        self._check_bucket(bucket)
        status = self._versioning(bucket)
        if version_id is not None:
            if status == "Off":
                raise NoSuchKey(f"{bucket}/{key}@{version_id}")
            try:
                removed = json.loads(self.io.execute(
                    self._index_obj(bucket), "rgw_index", "rm_version",
                    json.dumps({"key": key,
                                "vid": version_id}).encode()))
            except ClsError:
                raise NoSuchKey(f"{bucket}/{key}@{version_id}") \
                    from None
            self._wipe_version_payload(removed)
            return {"delete_marker": bool(removed.get("delete_marker")),
                    "version_id": version_id}
        if status != "Off":
            # a marker needs SOMETHING to mark: a current entry or
            # existing version history (S3 would even mark a
            # never-seen key; refusing those keeps delete-of-nothing
            # an error, consistent with the unversioned path)
            try:
                self._stat_entry(bucket, key)
            except NoSuchKey:
                out = json.loads(self.io.execute(
                    self._index_obj(bucket), "rgw_index",
                    "has_versions", json.dumps({"key": key}).encode()))
                if not out["any"]:
                    raise
            vid = self._record_version(
                bucket, key, self._next_vid(bucket, status),
                size=0, etag="", delete_marker=True)
            return {"delete_marker": True, "version_id": vid}
        ent = self._stat_entry(bucket, key)
        if "manifest" in ent:
            for part_soid in ent["manifest"]:
                self._wipe_striped(part_soid)
        else:
            self._wipe_striped(self._data_obj(bucket, key))
        self.io.execute(self._index_obj(bucket), "rgw_index", "rm",
                        json.dumps({"key": key}).encode())
        return {"delete_marker": False, "version_id": None}

    def copy_object(self, src_bucket: str, src_key: str,
                    dst_bucket: str, dst_key: str,
                    src_version_id: str | None = None) -> str:
        """CopyObject (ref: rgw_op.cc RGWCopyObj; S3
        x-amz-copy-source): server-side copy — the client never
        carries the bytes. The destination is a normal PUT (fresh
        payload objects, fresh mtime, versioning semantics of the
        DESTINATION bucket apply); the source may be a specific
        version. Returns the new ETag."""
        self._check_bucket(src_bucket)
        self._check_bucket(dst_bucket)
        if src_bucket == dst_bucket and src_key == dst_key \
                and src_version_id is None:
            # S3 rejects an in-place copy with no changes
            raise GatewayError(
                "InvalidRequest: copy onto itself without a source "
                "version changes nothing")
        data = self.get_object(src_bucket, src_key,
                               version_id=src_version_id)
        return self.put_object(dst_bucket, dst_key, data)

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", limit: int = 1000,
                     delimiter: str = "") -> dict:
        """ListObjectsV2 shape: {entries, truncated, next_marker} plus
        common_prefixes when a delimiter rolls up "folders"."""
        self._check_bucket(bucket)
        out = self.io.execute(
            self._index_obj(bucket), "rgw_index", "list",
            json.dumps({"prefix": prefix, "marker": marker,
                        "limit": limit,
                        "delimiter": delimiter}).encode())
        return json.loads(out)

    def _stat_entry(self, bucket: str, key: str) -> dict:
        try:
            return json.loads(self.io.execute(
                self._index_obj(bucket), "rgw_index", "stat",
                json.dumps({"key": key}).encode()))
        except ClsError:
            raise NoSuchKey(f"{bucket}/{key}") from None

    def _wipe_striped(self, soid: str) -> None:
        try:
            self._striper.remove(soid)
        except KeyError:
            pass

    def _wipe_replaced(self, bucket: str, key: str) -> None:
        """Overwrite cleanup shared by every writer that replaces an
        index entry (put_object AND complete_multipart): the index
        'add' drops any existing manifest wholesale, so a replaced
        multipart object's part payloads must be wiped NOW or they
        orphan forever; a replaced plain object's data object is wiped
        by the writer that owns its soid."""
        try:
            old = self._stat_entry(bucket, key)
        except NoSuchKey:
            return
        if "manifest" in old:
            for part_soid in old["manifest"]:
                self._wipe_striped(part_soid)

    # -- multipart -----------------------------------------------------------

    def initiate_multipart(self, bucket: str, key: str) -> str:
        self._check_bucket(bucket)
        # random, not clock-derived: two initiates within one virtual
        # clock tick must not collide (upstream upload ids are opaque
        # unique strings too)
        import os as _os
        upload_id = f"u{_os.urandom(8).hex()}"
        self.io.write_full(self._upload_obj(bucket, key, upload_id),
                           json.dumps({"parts": {}}).encode())
        return upload_id

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        if part_number < 1:
            raise GatewayError("part numbers start at 1")
        meta_obj = self._upload_obj(bucket, key, upload_id)
        try:
            meta = json.loads(self.io.read(meta_obj))
        except KeyError:
            raise GatewayError(f"NoSuchUpload: {upload_id}") from None
        soid = self._upload_obj(bucket, key, upload_id, part_number)
        self._wipe_striped(soid)
        self._striper.write(soid, bytes(data))
        etag = self._etag(bytes(data))
        meta["parts"][str(part_number)] = {"size": len(data),
                                           "etag": etag}
        self.io.write_full(meta_obj, json.dumps(meta).encode())
        return etag

    def complete_multipart(self, bucket: str, key: str,
                           upload_id: str) -> str:
        """Assemble by MANIFEST (no data rewrite): the index entry
        records the part objects; GET stitches them on read."""
        meta_obj = self._upload_obj(bucket, key, upload_id)
        try:
            meta = json.loads(self.io.read(meta_obj))
        except KeyError:
            raise GatewayError(f"NoSuchUpload: {upload_id}") from None
        parts = sorted(((int(n), p) for n, p in meta["parts"].items()))
        if not parts:
            raise GatewayError("no parts uploaded")
        manifest = [self._upload_obj(bucket, key, upload_id, n)
                    for n, _ in parts]
        sizes = [p["size"] for _, p in parts]
        etag = self._etag("".join(p["etag"] for _, p in parts).encode()) \
            + f"-{len(parts)}"
        status = self._versioning(bucket)
        if status != "Off":
            # versioned completion: the manifest IS the version's
            # payload (part objects are unique per upload_id, so
            # history never collides); nothing existing is wiped
            # except a replaced null version under Suspended
            self._record_version(
                bucket, key, self._next_vid(bucket, status),
                size=sum(sizes), etag=etag, manifest=manifest,
                part_sizes=sizes)
            self.io.remove(meta_obj)
            return etag
        # replacing an existing entry: wipe a previous upload's
        # manifest parts AND a previous plain object's data (the new
        # entry is manifest-backed, so the plain soid would orphan)
        self._wipe_replaced(bucket, key)
        self._wipe_striped(self._data_obj(bucket, key))
        self.io.execute(self._index_obj(bucket), "rgw_index", "add",
                        json.dumps({"key": key, "size": sum(sizes),
                                    "etag": etag,
                                    "mtime": self._clock()}).encode())
        self.io.execute(self._index_obj(bucket), "rgw_index",
                        "set_manifest",
                        json.dumps({"key": key, "manifest": manifest,
                                    "part_sizes": sizes}).encode())
        self.io.remove(meta_obj)
        return etag

    def abort_multipart(self, bucket: str, key: str,
                        upload_id: str) -> None:
        meta_obj = self._upload_obj(bucket, key, upload_id)
        try:
            meta = json.loads(self.io.read(meta_obj))
        except KeyError:
            raise GatewayError(f"NoSuchUpload: {upload_id}") from None
        for n in meta["parts"]:
            self._wipe_striped(
                self._upload_obj(bucket, key, upload_id, int(n)))
        self.io.remove(meta_obj)

    def _read_manifest(self, bucket: str, key: str, ent: dict,
                       offset: int, length: int | None) -> bytes:
        total = ent["size"]
        if length is None:
            length = max(0, total - offset)
        end = min(offset + length, total)
        out = bytearray()
        pos = 0
        for soid, size in zip(ent["manifest"], ent["part_sizes"]):
            pstart, pend = pos, pos + size
            lo, hi = max(offset, pstart), min(end, pend)
            if lo < hi:
                out += self._striper.read(soid, length=hi - lo,
                                          offset=lo - pstart)
            pos = pend
            if pos >= end:
                break
        return bytes(out)

    # -- lifecycle (ref: src/rgw/rgw_lc.cc RGWLC::process; S3
    #    Put/Get/DeleteBucketLifecycleConfiguration) -----------------------

    _LC_DAY = 86400.0

    def put_bucket_lifecycle(self, bucket: str,
                             rules: list[dict]) -> None:
        """Install lifecycle rules. Each rule: {id, prefix?, status
        Enabled|Disabled, expiration_days? and/or noncurrent_days?}
        — the S3 Expiration / NoncurrentVersionExpiration actions."""
        self._check_bucket(bucket)
        if not rules:
            raise GatewayError("MalformedXML: empty rule list")
        seen = set()
        for r in rules:
            rid = r.get("id")
            if not rid or rid in seen:
                raise GatewayError(
                    f"InvalidArgument: missing/duplicate rule id {rid!r}")
            seen.add(rid)
            if r.get("status", "Enabled") not in ("Enabled", "Disabled"):
                raise GatewayError(
                    f"MalformedXML: bad status in rule {rid!r}")
            days = r.get("expiration_days")
            ncdays = r.get("noncurrent_days")
            if days is None and ncdays is None:
                raise GatewayError(
                    f"InvalidRequest: rule {rid!r} has no action")
            for v in (days, ncdays):
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool) or v < 1):
                    raise GatewayError(
                        f"InvalidArgument: days must be a positive "
                        f"int in rule {rid!r}")
        self.io.execute(self._index_obj(bucket), "rgw_index",
                        "set_lc", json.dumps(rules).encode())

    def get_bucket_lifecycle(self, bucket: str) -> list[dict]:
        self._check_bucket(bucket)
        return json.loads(self.io.execute(
            self._index_obj(bucket), "rgw_index", "get_lc"))

    def delete_bucket_lifecycle(self, bucket: str) -> None:
        self._check_bucket(bucket)
        self.io.execute(self._index_obj(bucket), "rgw_index", "del_lc")

    def _list_all_entries(self, bucket: str, prefix: str) -> list[dict]:
        out, marker = [], ""
        while True:
            page = self.list_objects(bucket, prefix=prefix,
                                     marker=marker, limit=1000)
            out.extend(page["entries"])
            if not page.get("truncated"):
                return out
            marker = page["next_marker"]

    def lc_process(self, bucket: str | None = None) -> dict:
        """One lifecycle worker pass (upstream's RGWLC runs this on a
        schedule; here the driver/test calls it — same model as scrub).
        Applies Enabled rules against the gateway clock and returns
        {bucket: {expired: [keys], noncurrent_expired: [(key, vid)],
        markers_cleaned: [keys]}}."""
        buckets = [bucket] if bucket is not None else self.list_buckets()
        now = self._clock()
        report: dict = {}
        for b in buckets:
            rules = [r for r in self.get_bucket_lifecycle(b)
                     if r.get("status", "Enabled") == "Enabled"]
            if not rules:
                continue
            rep = {"expired": [], "noncurrent_expired": [],
                   "markers_cleaned": []}
            versioned = self._versioning(b) != "Off"
            for r in rules:
                prefix = r.get("prefix", "")
                days = r.get("expiration_days")
                if days is not None:
                    for ent in self._list_all_entries(b, prefix):
                        if now - ent["mtime"] >= days * self._LC_DAY:
                            # versioned: becomes a delete marker;
                            # unversioned: gone for real (S3 semantics)
                            self.delete_object(b, ent["key"])
                            rep["expired"].append(ent["key"])
                ncdays = r.get("noncurrent_days")
                if ncdays is not None and versioned:
                    vs = self.list_object_versions(b, prefix=prefix)
                    # versions arrive per key newest-first: a version
                    # became NONCURRENT when its successor was written,
                    # so its retention clock starts at the PREVIOUS
                    # (newer) entry's mtime — S3 guarantees
                    # NoncurrentDays of retention from succession, not
                    # from the version's own creation (ref: rgw_lc.cc
                    # effective_mtime of the next entry)
                    prev_by_key: dict[str, float] = {}
                    for v in vs["versions"]:
                        since = prev_by_key.get(v["key"])
                        prev_by_key[v["key"]] = v["mtime"]
                        if v.get("is_latest") or since is None:
                            continue
                        if now - since >= ncdays * self._LC_DAY:
                            self.delete_object(b, v["key"],
                                               version_id=v["vid"])
                            rep["noncurrent_expired"].append(
                                (v["key"], v["vid"]))
                if days is not None and versioned:
                    # expired-object-delete-marker cleanup, scoped to
                    # THIS rule's prefix (the cleanup is part of the
                    # Expiration action, not bucket-wide — ref: S3
                    # ExpiredObjectDeleteMarker): a key whose only
                    # remaining version is its latest delete marker
                    # serves nothing
                    by_key: dict[str, list] = {}
                    for v in self.list_object_versions(
                            b, prefix=prefix)["versions"]:
                        by_key.setdefault(v["key"], []).append(v)
                    for key, kvs in by_key.items():
                        if len(kvs) == 1 \
                                and kvs[0].get("delete_marker") \
                                and kvs[0].get("is_latest"):
                            self.delete_object(b, key,
                                               version_id=kvs[0]["vid"])
                            rep["markers_cleaned"].append(key)
            if any(rep.values()):
                report[b] = rep
        return report
