"""AEAD primitive with native and stdlib fallbacks.

AES-256-GCM via the `cryptography` wheel when importable; else the
native codec's AES-256-GCM (AES-NI + PCLMUL, ~1.1 GB/s — the same
NIST cipher, so the two interoperate on the wire; NIST-vector-pinned
in tests/test_native.py); else an encrypt-then-MAC construction from
the stdlib (SHAKE-256 XOF keystream XOR — one C-speed sponge squeeze
for the whole message, the Keccak-stream-cipher construction — and an
HMAC-SHA256 tag over nonce+aad+ciphertext). The surface matches what
cephx tickets and msgr secure mode need: (key, nonce, aad) sealing
with a 16-byte tag, tamper -> InvalidTag.

Every endpoint of the sim lives in one process, so both sides always
resolve to the SAME implementation — there is no cross-implementation
wire case. The fallback keeps the auth/secure planes runnable on
images without the wheel; it is a legitimate AEAD composition but not
a constant-time production cipher (this codebase is a simulation).
"""

from __future__ import annotations

import hmac
from hashlib import sha256, shake_256

TAG_LEN = 16


class InvalidTag(Exception):
    """Decrypt failed authentication (tampered or wrong key)."""


def _xor(key: bytes, nonce: bytes, data: bytes) -> bytes:
    n = len(data)
    ks = shake_256(len(key).to_bytes(4, "little") + key
                   + b"ks" + nonce).digest(n)
    if n >= 1024:
        # bulk path: elementwise XOR via numpy (zero-copy views in,
        # one output buffer out) — the bignum int round-trip this
        # replaces cost ~40% of a 64 KiB seal. Bytes are identical.
        import numpy as np
        return (np.frombuffer(data, np.uint8)
                ^ np.frombuffer(ks, np.uint8)).tobytes()
    x = int.from_bytes(data, "little") ^ int.from_bytes(ks, "little")
    return x.to_bytes(n, "little")


def _tag(key: bytes, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
    h = hmac.new(key, b"tag", sha256)
    for p in (nonce, aad, ct):
        h.update(len(p).to_bytes(4, "little"))
        h.update(p)
    return h.digest()[:TAG_LEN]


def _native_gcm():
    """The native codec's AES-256-GCM (AES-NI + PCLMUL) when the .so
    is already built and the CPU supports it — bit-identical output to
    cryptography's AESGCM, at ~0.8 GB/s vs the SHAKE fallback's ~0.3.
    Never triggers a compile (ready() gate)."""
    try:
        from .. import native
        if native.aes256gcm_supported():
            return native
    except Exception:          # noqa: BLE001 — optional native lib
        pass
    return None


class AEAD:
    """AESGCM-shaped: encrypt/decrypt(nonce, data, aad).

    Implementation selection (consistent within one process, which is
    the deployment unit of every cluster here): the `cryptography`
    wheel's AESGCM, else the native codec's AES-256-GCM (the same NIST
    cipher — the two interoperate on the wire), else the stdlib
    SHAKE-256 + HMAC construction."""

    def __init__(self, key: bytes):
        self._native = None
        try:
            from cryptography.hazmat.primitives.ciphers.aead import \
                AESGCM
            self._gcm = AESGCM(key)
            self._key = None
        except ImportError:
            self._gcm = None
            self._key = bytes(key)
            if len(self._key) == 32:   # native path is AES-256 only
                self._native = _native_gcm()

    def encrypt(self, nonce: bytes, plain, aad: bytes) -> bytes:
        """`plain` is one buffer or a list of segments; segments are
        staged into ONE contiguous buffer here (the only copy the
        secure framing path makes) before the cipher runs."""
        if isinstance(plain, (list, tuple)):
            plain = b"".join(plain)
        if self._gcm is not None:
            return self._gcm.encrypt(nonce, bytes(plain), aad)
        if self._native is not None:
            return self._native.aes256gcm_seal(self._key, nonce,
                                               bytes(plain), aad)
        ct = _xor(self._key, nonce, bytes(plain))
        return ct + _tag(self._key, nonce, aad, ct)

    def decrypt(self, nonce: bytes, blob: bytes, aad: bytes) -> bytes:
        if self._gcm is not None:
            from cryptography.exceptions import InvalidTag as _IT
            try:
                return self._gcm.decrypt(nonce, blob, aad)
            except _IT:
                raise InvalidTag from None
        if self._native is not None:
            try:
                return self._native.aes256gcm_open(self._key, nonce,
                                                   bytes(blob), aad)
            except ValueError:
                raise InvalidTag from None
        if len(blob) < TAG_LEN:
            raise InvalidTag
        ct, tag = blob[:-TAG_LEN], blob[-TAG_LEN:]
        if not hmac.compare_digest(_tag(self._key, nonce, aad, ct),
                                   tag):
            raise InvalidTag
        return _xor(self._key, nonce, ct)


def hkdf_sha256(secret: bytes, salt: bytes, info: bytes) -> bytes:
    """RFC 5869 HKDF-SHA256, L=32 (single expand block) — identical
    output to cryptography's HKDF, so either path derives the same
    session key."""
    prk = hmac.new(salt, secret, sha256).digest()
    return hmac.new(prk, info + b"\x01", sha256).digest()
