"""AEAD primitive with a stdlib fallback.

AES-256-GCM via the `cryptography` wheel when importable; otherwise an
encrypt-then-MAC construction from the stdlib (SHAKE-256 XOF keystream
XOR — one C-speed sponge squeeze for the whole message, the
Keccak-stream-cipher construction — and an HMAC-SHA256 tag over
nonce+aad+ciphertext). The surface matches what cephx tickets and msgr
secure mode need: (key, nonce, aad) sealing with a 16-byte tag,
tamper -> InvalidTag.

Every endpoint of the sim lives in one process, so both sides always
resolve to the SAME implementation — there is no cross-implementation
wire case. The fallback keeps the auth/secure planes runnable on
images without the wheel; it is a legitimate AEAD composition but not
a constant-time production cipher (this codebase is a simulation).
"""

from __future__ import annotations

import hmac
from hashlib import sha256, shake_256

TAG_LEN = 16


class InvalidTag(Exception):
    """Decrypt failed authentication (tampered or wrong key)."""


def _xor(key: bytes, nonce: bytes, data: bytes) -> bytes:
    n = len(data)
    ks = shake_256(len(key).to_bytes(4, "little") + key
                   + b"ks" + nonce).digest(n)
    x = int.from_bytes(data, "little") ^ int.from_bytes(ks, "little")
    return x.to_bytes(n, "little")


def _tag(key: bytes, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
    h = hmac.new(key, b"tag", sha256)
    for p in (nonce, aad, ct):
        h.update(len(p).to_bytes(4, "little"))
        h.update(p)
    return h.digest()[:TAG_LEN]


class AEAD:
    """AESGCM-shaped: encrypt/decrypt(nonce, data, aad)."""

    def __init__(self, key: bytes):
        try:
            from cryptography.hazmat.primitives.ciphers.aead import \
                AESGCM
            self._gcm = AESGCM(key)
            self._key = None
        except ImportError:
            self._gcm = None
            self._key = bytes(key)

    def encrypt(self, nonce: bytes, plain: bytes, aad: bytes) -> bytes:
        if self._gcm is not None:
            return self._gcm.encrypt(nonce, plain, aad)
        ct = _xor(self._key, nonce, plain)
        return ct + _tag(self._key, nonce, aad, ct)

    def decrypt(self, nonce: bytes, blob: bytes, aad: bytes) -> bytes:
        if self._gcm is not None:
            from cryptography.exceptions import InvalidTag as _IT
            try:
                return self._gcm.decrypt(nonce, blob, aad)
            except _IT:
                raise InvalidTag from None
        if len(blob) < TAG_LEN:
            raise InvalidTag
        ct, tag = blob[:-TAG_LEN], blob[-TAG_LEN:]
        if not hmac.compare_digest(_tag(self._key, nonce, aad, ct),
                                   tag):
            raise InvalidTag
        return _xor(self._key, nonce, ct)


def hkdf_sha256(secret: bytes, salt: bytes, info: bytes) -> bytes:
    """RFC 5869 HKDF-SHA256, L=32 (single expand block) — identical
    output to cryptography's HKDF, so either path derives the same
    session key."""
    prk = hmac.new(salt, secret, sha256).digest()
    return hmac.new(prk, info + b"\x01", sha256).digest()
