"""cephx-shaped ticket authentication.

Rebuild of the reference's auth subsystem behavior (ref: src/auth/
cephx — CephxKeyServer rotating service secrets, CephxServiceHandler
challenge/response, CephxClientHandler, CephxAuthorizeHandler;
mon side: src/mon/AuthMonitor.cc; caps grammar: src/mon/MonCap.cc,
src/osd/OSDCap.cc). The protocol SHAPE is kept — Kerberos-style
tickets so OSDs never hold client secrets and the monitor is not on
the data path — while the primitives are this framework's existing
ones (HMAC-SHA256 proofs, AES-256-GCM sealed ticket blobs, the same
AEAD the ProtocolV2 secure mode uses), not a transliteration of
cephx's AES-CBC constructions.

Flow (mirrors CEPHX_GET_AUTH_SESSION_KEY / CEPHX_GET_PRINCIPAL_SESSION_KEY):

1. client -> mon   : hello(entity, client_challenge)
2. mon    -> client: server_challenge
3. client -> mon   : proof = HMAC(entity_secret, sc || cc)
4. mon    -> client: auth ticket = {enc(entity_secret, session_key),
                     blob sealed under the AUTH service secret}
   — possession of the entity secret is needed to read session_key;
   the blob is opaque to the client.
5. client -> mon   : authorizer(session_key) + wanted services
   mon    -> client: per-service tickets {enc(session_key,
                     svc_session_key), blob under that service's
                     ROTATING secret}
6. client -> osd   : authorizer = (blob, nonce, HMAC(svc_session_key,
                     nonce)); the OSD unseals the blob with its
                     distributed rotating secret, checks expiry+MAC,
                     learns (entity, caps, svc_session_key) and
                     replies HMAC(svc_session_key, nonce || "server")
                     — mutual auth (the CephxAuthorizeHandler
                     challenge round).

Rotating secrets: per-service list of (secret_id, key, expiry); the
newest seals new tickets, the previous two still open blobs (ref:
KeyServerData::rotating_secrets keeps current/prev/next), so daemons
that refresh on a timer never race a rotation.
"""

from __future__ import annotations

import hmac
import json
import os
import struct
import time as _time
from hashlib import sha256


class AuthError(Exception):
    pass


class NeedChallenge(AuthError):
    """The daemon demands a fresh server challenge be bound into the
    authorizer MAC before it will accept it (ref: the cephx server
    challenge added for CVE-2018-1128 — without it a captured
    authorizer replays)."""

    def __init__(self, challenge_hex: str):
        super().__init__("server challenge required")
        self.challenge = challenge_hex


def _hmac(key: bytes, *parts: bytes) -> bytes:
    h = hmac.new(key, digestmod=sha256)
    for p in parts:
        h.update(struct.pack("<I", len(p)))
        h.update(p)
    return h.digest()


def _seal(key: bytes, payload: dict) -> bytes:
    from .aead import AEAD
    nonce = os.urandom(12)
    plain = json.dumps(payload, sort_keys=True).encode()
    return nonce + AEAD(key).encrypt(nonce, plain, b"cephx-tkt")


def _unseal(key: bytes, blob: bytes) -> dict:
    from .aead import AEAD, InvalidTag
    if len(blob) < 12 + 16:
        raise AuthError("ticket blob truncated")
    try:
        plain = AEAD(key).decrypt(blob[:12], blob[12:], b"cephx-tkt")
    except InvalidTag:
        raise AuthError("ticket blob failed authentication (tampered "
                        "or wrong secret)")
    return json.loads(plain.decode())


def _b(x: bytes) -> str:
    return x.hex()


def _ub(s: str) -> bytes:
    return bytes.fromhex(s)


# -- capabilities ------------------------------------------------------------

class Caps:
    """Simplified MonCap/OSDCap grammar: comma-separated grants of
    `allow <perms>[ pool=<name>]`, perms in {r, w, x} combos or `*`.
    A grant with pool= applies only to that pool; without, to all."""

    def __init__(self, spec: str):
        self.grants: list[tuple[set, str | None]] = []
        spec = spec.strip()
        if not spec:
            return
        for part in spec.split(","):
            toks = part.split()
            if not toks or toks[0] != "allow":
                raise AuthError(f"bad cap grant {part!r}")
            perms: set[str] = set()
            pool = None
            for t in toks[1:]:
                if t.startswith("pool="):
                    pool = t[5:]
                elif t == "*":
                    perms |= {"r", "w", "x"}
                elif set(t) <= {"r", "w", "x"}:
                    perms |= set(t)
                else:
                    raise AuthError(f"bad cap token {t!r} in {part!r}")
            if not perms:
                raise AuthError(f"empty perms in cap grant {part!r}")
            self.grants.append((perms, pool))

    def allows(self, op: str, pool: str | None = None) -> bool:
        for perms, gpool in self.grants:
            if op in perms and (gpool is None or gpool == pool):
                return True
        return False


# -- key server (monitor-resident) -------------------------------------------

ROTATING_KEEP = 3          # current + two predecessors stay valid
DEFAULT_TTL = 3600.0       # ticket / rotating-secret lifetime


class KeyServer:
    """Entity secrets + per-service rotating secrets (ref:
    src/auth/cephx/CephxKeyServer.cc KeyServerData)."""

    def __init__(self, ttl: float = DEFAULT_TTL, now_fn=_time.time):
        self.ttl = ttl
        self.now = now_fn
        self.entities: dict[str, dict] = {}
        # service -> newest-first [(secret_id, key, expires)]
        self.rotating: dict[str, list[tuple[int, bytes, float]]] = {}
        self._next_id = 1

    def create_entity(self, name: str,
                      caps: dict[str, str] | None = None) -> bytes:
        secret = os.urandom(32)
        self.entities[name] = {"secret": secret, "caps": caps or {}}
        return secret

    def entity_secret(self, name: str) -> bytes:
        try:
            return self.entities[name]["secret"]
        except KeyError:
            raise AuthError(f"unknown entity {name!r}")

    def rotate(self, service: str) -> int:
        """Mint a new rotating secret for `service`; the previous
        ROTATING_KEEP-1 stay openable."""
        sid = self._next_id
        self._next_id += 1
        lst = self.rotating.setdefault(service, [])
        lst.insert(0, (sid, os.urandom(32),
                       self.now() + self.ttl * ROTATING_KEEP))
        del lst[ROTATING_KEEP:]
        return sid

    def current_secret(self, service: str) -> tuple[int, bytes]:
        lst = self.rotating.get(service)
        if not lst:
            self.rotate(service)
            lst = self.rotating[service]
        sid, key, exp = lst[0]
        # auto-rotate once the newest secret has served a full ttl
        # (ref: the monitor's rotating-secret timer): without this a
        # long-lived realm seals new tickets under an aging secret
        # until EVERYTHING expires at once and auth bricks
        minted = exp - self.ttl * ROTATING_KEEP
        if self.now() >= minted + self.ttl:
            self.rotate(service)
            sid, key, exp = self.rotating[service][0]
        return sid, key

    def secret_by_id(self, service: str, sid: int) -> bytes:
        for s, key, exp in self.rotating.get(service, []):
            if s == sid:
                if self.now() > exp:
                    raise AuthError(f"{service} secret {sid} expired")
                return key
        raise AuthError(f"{service} secret {sid} rotated out")

    def export_rotating(self, service: str) -> list[tuple[int, str, float]]:
        """What the monitor pushes to daemons of `service` (ref:
        MAuth rotating_secrets distribution)."""
        self.current_secret(service)   # ensure one exists
        return [(sid, _b(key), exp)
                for sid, key, exp in self.rotating[service]]


class AuthService:
    """Monitor-side handler (ref: CephxServiceHandler +
    AuthMonitor)."""

    MAX_PENDING = 256
    MAX_PENDING_PER_ENTITY = 8
    PENDING_TTL = 60.0

    def __init__(self, ks: KeyServer):
        self.ks = ks
        # (entity, client_challenge) -> (server challenge, issued-at):
        # keyed by the PAIR so concurrent logins of one entity (two
        # clients sharing client.admin) can't clobber each other's
        # outstanding challenge. Eviction is per-entity + by age — a
        # spammer repeating hello() for one known entity name only
        # evicts its OWN challenges, never another entity's in-flight
        # login (the r4 advisor's bounded-DoS finding)
        self._pending: dict[tuple[str, str], tuple[bytes, float]] = {}

    def _expire_pending(self, now: float) -> None:
        dead = [k for k, (_, ts) in self._pending.items()
                if now - ts > self.PENDING_TTL]
        for k in dead:
            del self._pending[k]

    # step 2
    def hello(self, entity: str, client_challenge: bytes) -> bytes:
        self.ks.entity_secret(entity)          # unknown entity -> err
        now = self.ks.now()
        self._expire_pending(now)
        mine = [k for k in self._pending if k[0] == entity]
        while len(mine) >= self.MAX_PENDING_PER_ENTITY:
            self._pending.pop(mine.pop(0), None)
        if len(self._pending) >= self.MAX_PENDING:
            # global pressure: evict the oldest challenge of the
            # entity holding the MOST pending entries (under attack
            # that is an attacker name at its per-entity cap; a
            # legitimate login holds 1). Hard-rejecting here would
            # itself be a login DoS for uninvolved entities.
            by_entity: dict[str, list] = {}
            for k in self._pending:
                by_entity.setdefault(k[0], []).append(k)
            heaviest = max(by_entity.values(), key=len)
            self._pending.pop(heaviest[0], None)
        sc = os.urandom(16)
        self._pending[(entity, client_challenge.hex())] = (sc, now)
        return sc

    # steps 3-4
    def authenticate(self, entity: str, client_challenge: bytes,
                     proof: bytes) -> dict:
        secret = self.ks.entity_secret(entity)
        entry = self._pending.pop(
            (entity, client_challenge.hex()), None)  # single-use
        if entry is None:
            raise AuthError("no outstanding challenge (replay?)")
        sc, issued = entry
        if self.ks.now() - issued > self.PENDING_TTL:
            raise AuthError("challenge expired")
        want = _hmac(secret, sc, client_challenge)
        if not hmac.compare_digest(want, proof):
            raise AuthError(f"bad proof for {entity!r}")
        session_key = os.urandom(32)
        expires = self.ks.now() + self.ks.ttl
        sid, auth_secret = self.ks.current_secret("auth")
        blob = _seal(auth_secret, {
            "entity": entity, "session_key": _b(session_key),
            "expires": expires,
            "caps": self.ks.entities[entity]["caps"]})
        return {
            # only the entity-secret holder can read the session key
            "enc_session_key": _b(_seal(secret, {
                "session_key": _b(session_key), "expires": expires})),
            "ticket": {"secret_id": sid, "blob": _b(blob)},
        }

    # step 5
    def get_service_tickets(self, ticket: dict, nonce: bytes,
                            mac: bytes, services: list[str]) -> dict:
        auth_secret = self.ks.secret_by_id("auth", ticket["secret_id"])
        t = _unseal(auth_secret, _ub(ticket["blob"]))
        if self.ks.now() > t["expires"]:
            raise AuthError("auth ticket expired")
        session_key = _ub(t["session_key"])
        if not hmac.compare_digest(_hmac(session_key, nonce), mac):
            raise AuthError("bad authorizer on ticket request")
        out = {}
        for svc in services:
            svc_key = os.urandom(32)
            expires = self.ks.now() + self.ks.ttl
            sid, rot = self.ks.current_secret(svc)
            blob = _seal(rot, {
                "entity": t["entity"], "session_key": _b(svc_key),
                "expires": expires,
                "caps": t["caps"]})
            out[svc] = {
                "enc_session_key": _b(_seal(session_key, {
                    "session_key": _b(svc_key), "expires": expires})),
                "ticket": {"secret_id": sid, "blob": _b(blob)},
            }
        return out


class ClientAuth:
    """Client-side driver (ref: CephxClientHandler). `auth` is the
    AuthService (or any transport proxying to one)."""

    def __init__(self, auth: AuthService, entity: str, secret: bytes,
                 now_fn=_time.time):
        import threading
        self.auth = auth
        self.entity = entity
        self.secret = secret
        self.now = now_fn
        self.session_key: bytes | None = None
        self._auth_ticket: dict | None = None
        self._svc: dict[str, dict] = {}   # service -> {key, expires, ticket}
        # one ClientAuth is shared by a daemon's dispatch threads AND
        # its background ticket prewarm. Two locks, two jobs:
        # _lock guards STATE only (never held across network I/O), so
        # authorizer_with_key's cached fast path can't stall behind a
        # monitor hunt; _io_lock serializes the refresh I/O itself
        # (login + ticket fetch) so concurrent refreshers don't
        # stampede the monitors. Ordering: _io_lock may take _lock,
        # never the reverse.
        self._lock = threading.RLock()
        self._io_lock = threading.Lock()
        # declared ticket-lifecycle counters ("cephx" logger): a daemon
        # nests them in its perf dump; single-flight-wait accounting
        # (refreshes deferred because one was already running) lives
        # at the daemon, which owns that gate
        from ..utils.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("cephx")
                     .add_u64_counter("logins",
                                      "hello/authenticate rounds run")
                     .add_u64_counter("ticket_fetches",
                                      "service-ticket fetch rounds")
                     .add_u64_counter("ticket_relogins",
                                      "fetch rounds that re-logged in "
                                      "(auth ticket aged/rotated out)")
                     .add_time_avg("fetch_time",
                                   "fetch_tickets wall time incl. "
                                   "monitor hunt")
                     .create_perf_counters())

    def login(self) -> None:
        with self._io_lock:
            self._login_io()

    def _login_io(self) -> None:
        """Caller holds _io_lock. Network rounds WITHOUT _lock; the
        session state installs atomically at the end."""
        # one retry when the challenge went missing between hello and
        # authenticate (the answering monitor died in between, or an
        # overloaded auth service evicted it) — a fresh hello gets a
        # fresh challenge; a WRONG-SECRET failure stays terminal
        for attempt in range(2):
            cc = os.urandom(16)
            sc = self.auth.hello(self.entity, cc)
            proof = _hmac(self.secret, sc, cc)
            try:
                got = self.auth.authenticate(self.entity, cc, proof)
            except AuthError as e:
                if "challenge" in str(e) and attempt == 0:
                    continue
                raise
            break
        sk = _unseal(self.secret, _ub(got["enc_session_key"]))
        self.perf.inc("logins")
        with self._lock:
            self.session_key = _ub(sk["session_key"])
            self._auth_ticket = got["ticket"]

    def fetch_tickets(self, services: list[str]) -> None:
        t0 = _time.perf_counter()
        with self._io_lock:
            self.perf.inc("ticket_fetches")
            with self._lock:
                need_login = self.session_key is None
            if need_login:
                self._login_io()
            for attempt in range(2):
                with self._lock:
                    ticket = self._auth_ticket
                    skey = self.session_key
                nonce = os.urandom(16)
                try:
                    got = self.auth.get_service_tickets(
                        ticket, nonce, _hmac(skey, nonce), services)
                    break
                except AuthError as e:
                    # the AUTH ticket itself aged out (expired, or its
                    # sealing secret rotated out): re-login under the
                    # entity secret and retry — the long-lived-client
                    # path; a genuine refusal stays terminal
                    if attempt == 0 and ("expired" in str(e)
                                         or "rotated out" in str(e)):
                        self.perf.inc("ticket_relogins")
                        self._login_io()
                        continue
                    raise
            # unseal with the session key that REQUESTED the tickets
            fresh = {}
            for svc, entry in got.items():
                sk = _unseal(skey, _ub(entry["enc_session_key"]))
                fresh[svc] = {"key": _ub(sk["session_key"]),
                              "expires": sk["expires"],
                              "ticket": entry["ticket"]}
            with self._lock:
                self._svc.update(fresh)
        self.perf.tinc("fetch_time", _time.perf_counter() - t0)

    def has_ticket(self, service: str) -> bool:
        """Is a cached, unexpired `service` ticket present? Zero I/O:
        lets dispatch-path callers FAIL FAST on a cold cache instead
        of hunting monitors while holding their daemon lock — the
        monitor's reply can be head-of-line-blocked behind undelivered
        frames on the very connection whose reader waits for that
        lock (the boot map-storm deadlock)."""
        with self._lock:
            ent = self._svc.get(service)
            return ent is not None and self.now() <= ent["expires"] - 1.0

    def authorizer_for(self, service: str,
                       server_challenge: str | None = None) -> dict:
        return self.authorizer_with_key(service, server_challenge)[0]

    def authorizer_with_key(self, service: str,
                            server_challenge: str | None = None
                            ) -> tuple[dict, bytes]:
        """((ticket, nonce, mac), session_key) to present to a daemon;
        refreshes the service ticket when missing or expired. The key
        is returned ALONGSIDE so the caller can verify the daemon's
        mutual-auth reply against the key that built this authorizer
        even if a concurrent refresh swaps the cached ticket. A server
        challenge (NeedChallenge) is bound into the MAC — the
        anti-replay round."""
        for _ in range(2):
            with self._lock:
                ent = self._svc.get(service)
                if ent is not None \
                        and self.now() <= ent["expires"] - 1.0:
                    # fast path: cached valid ticket, zero I/O under
                    # the lock — concurrent callers for other
                    # services never wait behind a monitor hunt
                    nonce = os.urandom(16)
                    az = {"ticket": ent["ticket"], "nonce": _b(nonce),
                          "mac": _b(_hmac(ent["key"], nonce,
                                          _ub(server_challenge or "")))}
                    if server_challenge is not None:
                        az["server_challenge"] = server_challenge
                    return az, ent["key"]
            # slow path OUTSIDE the fast-path lock window: the fetch
            # takes the lock itself around state updates; two racing
            # refreshes are idempotent
            self.fetch_tickets([service])
        raise AuthError(f"could not obtain a {service!r} ticket")

    def verify_reply(self, service: str, authorizer: dict,
                     reply_mac: bytes,
                     key: bytes | None = None) -> bool:
        """Mutual auth: did the daemon prove it unsealed our ticket
        (i.e. holds the rotating secret)? Pass the key returned by
        authorizer_with_key when other threads may refresh tickets
        concurrently."""
        if key is None:
            with self._lock:
                key = self._svc[service]["key"]
        want = _hmac(key, _ub(authorizer["nonce"]), b"server")
        return hmac.compare_digest(want, reply_mac)


class ServiceVerifier:
    """Daemon-side authorizer check (ref: CephxAuthorizeHandler +
    the rotating secrets a daemon refreshes from the monitor).

    Replay defense: the first authorize from a peer is answered with
    NeedChallenge carrying a single-use server challenge; only an
    authorizer whose MAC binds that challenge is accepted (producing
    it requires the sealed session key, which a frame-capturing
    attacker never has). Peer identity here is the transport's —
    binding challenges to the right connection is the messenger's
    secure mode's job, as upstream."""

    MAX_CHALLENGES = 1024

    def __init__(self, service: str,
                 rotating: list[tuple[int, str, float]],
                 now_fn=_time.time):
        self.service = service
        self.now = now_fn
        self._secrets = {sid: (_ub(key), exp)
                         for sid, key, exp in rotating}
        self._challenges: dict[str, str] = {}   # peer -> hex

    def refresh(self, rotating: list[tuple[int, str, float]]) -> None:
        self._secrets = {sid: (_ub(key), exp)
                         for sid, key, exp in rotating}

    def verify(self, authorizer: dict, peer: str = "") -> dict:
        """Returns {entity, caps, session_key, reply_mac}, raises
        NeedChallenge for the anti-replay round, or AuthError.
        reply_mac completes mutual auth."""
        tk = authorizer["ticket"]
        ent = self._secrets.get(tk["secret_id"])
        if ent is None:
            raise AuthError(
                f"{self.service} secret {tk['secret_id']} unknown "
                "(rotated out; client must refresh tickets)")
        rot, exp = ent
        if self.now() > exp:
            raise AuthError(f"{self.service} secret expired "
                            "(rotated out of this daemon's window)")
        t = _unseal(rot, _ub(tk["blob"]))
        if self.now() > t["expires"]:
            raise AuthError("service ticket expired")
        chal = authorizer.get("server_challenge")
        outstanding = self._challenges.get(peer)
        if chal is None or outstanding is None or chal != outstanding:
            while len(self._challenges) >= self.MAX_CHALLENGES:
                self._challenges.pop(next(iter(self._challenges)))
            fresh = os.urandom(16).hex()
            self._challenges[peer] = fresh
            raise NeedChallenge(fresh)
        key = _ub(t["session_key"])
        nonce = _ub(authorizer["nonce"])
        if not hmac.compare_digest(_hmac(key, nonce, _ub(chal)),
                                   _ub(authorizer["mac"])):
            raise AuthError("bad authorizer MAC")
        self._challenges.pop(peer, None)    # single use
        return {"entity": t["entity"],
                "caps": {s: Caps(c) for s, c in t["caps"].items()},
                "session_key": key,
                "reply_mac": _hmac(key, nonce, b"server")}


def local_authorize(cauth: "ClientAuth", verifier: ServiceVerifier,
                    service: str, peer: str = "local") -> dict:
    """In-process client<->daemon authorize handshake including the
    challenge round — what the wire tier does over MAuthOp frames."""
    az = cauth.authorizer_for(service)
    try:
        return verifier.verify(az, peer)
    except NeedChallenge as nc:
        az = cauth.authorizer_for(service, server_challenge=nc.challenge)
        return verifier.verify(az, peer)
