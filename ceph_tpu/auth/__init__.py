from .cephx import (AuthError, AuthService, Caps, ClientAuth, KeyServer,
                    NeedChallenge, ServiceVerifier, local_authorize)

__all__ = ["AuthError", "AuthService", "Caps", "ClientAuth",
           "KeyServer", "NeedChallenge", "ServiceVerifier",
           "local_authorize"]
