from .cephx import (AuthError, AuthService, Caps, ClientAuth, KeyServer,
                    ServiceVerifier)

__all__ = ["AuthError", "AuthService", "Caps", "ClientAuth",
           "KeyServer", "ServiceVerifier"]
