"""LRC — layered locally-repairable erasure code.

Rebuild of the reference's lrc plugin (ref: src/erasure-code/lrc/
ErasureCodeLrc.{h,cc} + ErasureCodePluginLrc.cc): a stack of sub-codes
("layers") over one set of global chunk positions, so a single lost
chunk is repaired from its small local group instead of k chunks — the
repair-I/O-proportional-to-l property that is the whole point of LRC.

Profile forms (both reference-compatible):

  * low-level:  mapping="__DD__DD"
                layers='[[ "_cDD_cDD", "" ], [ "cDDD____", "" ],
                         [ "____cDDD", "" ]]'
    Each position is one chunk. In `mapping`, 'D' marks the k data
    positions. Each layer is an MDS sub-code over a subset of positions:
    'D' = input to that layer, 'c' = parity written by that layer,
    '_' = not in the layer. Layers encode in order, so a later layer can
    consume an earlier layer's parity as input (the doc example's local
    groups cover the global parities).

  * k/m/l:      k=4 m=2 l=3
    Expanded to mapping/layers exactly like the reference's parse_kml:
    (k+m) must divide by l; chunks sit in (k+m)/l groups of l+1 positions
    (1 local parity + l data/global chunks); the m global parities are
    distributed round-robin across groups, earliest slots first — this
    reproduces the documented expansion of k=4 m=2 l=3.

Chunk ids are mapping POSITIONS (the reference's convention), so data
lives at the 'D' positions, not at ids 0..k-1.

Layer coders default to the RS plugin (plugin=tpu_rs), i.e. the same
batched GF kernels; any registered plugin works via the layer's profile
string, mirroring the reference wrapping jerasure per layer.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from .interface import ErasureCode, profile_from_string
from .registry import register


@dataclass
class _Layer:
    d_pos: tuple[int, ...]   # global positions of the layer's data, in order
    c_pos: tuple[int, ...]   # global positions of the layer's parity
    coder: ErasureCode

    @property
    def positions(self) -> frozenset[int]:
        return frozenset(self.d_pos) | frozenset(self.c_pos)

    @property
    def k(self) -> int:
        return len(self.d_pos)

    def local_id(self, pos: int) -> int:
        """Map a global position to this layer's coder chunk id."""
        if pos in self.d_pos:
            return self.d_pos.index(pos)
        return self.k + self.c_pos.index(pos)


def _expand_kml(k: int, m: int, l: int) -> tuple[str, list[list[str]]]:
    """k/m/l -> (mapping, layers), the reference parse_kml expansion."""
    if l < 2:
        raise ValueError(f"lrc l={l}: local groups need at least 2 chunks")
    if (k + m) % l:
        raise ValueError(f"lrc k+m={k + m} must be a multiple of l={l}")
    groups = (k + m) // l
    n = k + m + groups
    # slot layout: each group is [local parity, l data/global slots]
    kind = ["D"] * n  # overwritten below for parity slots
    for g in range(groups):
        kind[g * (l + 1)] = "local"
    free = [i for i in range(n) if kind[i] == "D"]
    # distribute the m global parities round-robin across groups,
    # earliest free slot of each group first
    by_group: list[list[int]] = [[] for _ in range(groups)]
    for pos in free:
        by_group[pos // (l + 1)].append(pos)
    taken: list[int] = []
    for i in range(m):
        g = i % groups
        taken.append(by_group[g].pop(0))
    for pos in taken:
        kind[pos] = "global"
    mapping = "".join("D" if c == "D" else "_" for c in kind)
    global_layer = "".join(
        {"D": "D", "global": "c", "local": "_"}[c] for c in kind)
    layers = [[global_layer, ""]]
    for g in range(groups):
        lo, hi = g * (l + 1), (g + 1) * (l + 1)
        chars = []
        for i in range(n):
            if not lo <= i < hi:
                chars.append("_")
            elif kind[i] == "local":
                chars.append("c")
            else:
                chars.append("D")
        layers.append(["".join(chars), ""])
    return mapping, layers


@register("lrc")
@register("tpu_lrc")
class Lrc(ErasureCode):
    """Layered code; chunk ids are mapping positions."""

    def init(self, profile: Mapping[str, str]) -> None:
        from .registry import factory
        if "mapping" in profile:
            mapping = profile["mapping"]
            raw_layers = profile.get("layers", "[]")
            layer_specs = (json.loads(raw_layers)
                           if isinstance(raw_layers, str) else raw_layers)
            if not layer_specs:
                raise ValueError("lrc: mapping given but no layers")
        else:
            k = int(profile.get("k", 4))
            m = int(profile.get("m", 2))
            l = int(profile.get("l", 3))
            mapping, layer_specs = _expand_kml(k, m, l)
        self.mapping = mapping
        n = len(mapping)
        self.k = mapping.count("D")
        self.m = n - self.k
        if self.k == 0:
            raise ValueError("lrc mapping has no data positions")
        self.data_positions = tuple(i for i, c in enumerate(mapping)
                                    if c == "D")
        self.layers: list[_Layer] = []
        covered: set[int] = set(self.data_positions)
        written: set[int] = set(self.data_positions)
        for spec in layer_specs:
            if len(spec) != 2:
                raise ValueError(f"lrc layer spec must be "
                                 f"[mapping, profile], got {spec!r}")
            lmap, lprof_s = spec
            if len(lmap) != n:
                raise ValueError(f"lrc layer mapping {lmap!r} length "
                                 f"{len(lmap)} != {n}")
            d_pos = tuple(i for i, c in enumerate(lmap) if c == "D")
            c_pos = tuple(i for i, c in enumerate(lmap) if c == "c")
            bad = [c for c in lmap if c not in "Dc_"]
            if bad:
                raise ValueError(f"lrc layer mapping char {bad[0]!r} "
                                 f"not in 'Dc_'")
            if not d_pos or not c_pos:
                raise ValueError(f"lrc layer {lmap!r} needs >=1 'D' and 'c'")
            unwritten = [p for p in d_pos if p not in written]
            if unwritten:
                # a layer may only consume data positions or parities an
                # EARLIER layer wrote; otherwise it encodes over
                # still-zero buffers and decode silently diverges
                raise ValueError(
                    f"lrc layer {lmap!r} reads positions {unwritten} that "
                    f"no earlier layer writes (layer order matters)")
            lprof = profile_from_string(lprof_s) if isinstance(
                lprof_s, str) and lprof_s else dict(lprof_s or {})
            lprof.setdefault("plugin", "tpu_rs")
            if "impl" in profile:
                # the top-level impl choice reaches the layer coders
                # (k/m/l expansions carry empty layer profiles, which
                # otherwise pinned every layer to the plugin default)
                lprof.setdefault("impl", profile["impl"])
            lprof["k"] = str(len(d_pos))
            lprof["m"] = str(len(c_pos))
            self.layers.append(_Layer(d_pos, c_pos, factory(lprof)))
            covered |= set(c_pos)
            written |= set(c_pos)
        if covered != set(range(n)):
            raise ValueError(
                f"lrc: positions {sorted(set(range(n)) - covered)} are "
                f"neither data nor written by any layer")

    # -- geometry overrides (chunk ids are positions) ----------------------

    def get_chunk_count(self) -> int:
        return len(self.mapping)

    def get_chunk_mapping(self) -> list[int]:
        return list(self.data_positions) + [
            i for i, c in enumerate(self.mapping) if c != "D"]

    # -- encode ------------------------------------------------------------

    def encode(self, want_to_encode: Sequence[int],
               data: bytes | np.ndarray) -> dict[int, np.ndarray]:
        # base-class pad/split/encode_chunks flow, then relabel chunk ids
        # from the dense (0..k-1 data, k.. coding) order to positions
        n = self.get_chunk_count()
        bad = [i for i in want_to_encode if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), "
                             f"got {sorted(bad)}")
        dense = super().encode(range(self.get_chunk_count()), data)
        coding_positions = [i for i in range(self.get_chunk_count())
                            if i not in set(self.data_positions)]
        by_pos = {p: dense[i] for i, p in enumerate(self.data_positions)}
        by_pos.update({p: dense[self.k + j]
                       for j, p in enumerate(coding_positions)})
        return {i: by_pos[i] for i in want_to_encode}

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """(B, k, L) data -> (B, m, L) parity, parity ordered by ascending
        position (the non-D positions)."""
        b, k, cs = data.shape
        n = self.get_chunk_count()
        full = np.zeros((b, n, cs), dtype=np.uint8)
        full[:, list(self.data_positions), :] = data
        for layer in self.layers:
            parity = np.asarray(layer.coder.encode_chunks(
                full[:, list(layer.d_pos), :]))
            full[:, list(layer.c_pos), :] = parity
        coding_positions = [i for i in range(n) if i not in
                            set(self.data_positions)]
        return full[:, coding_positions, :]

    # -- repair planning ---------------------------------------------------

    def _repair_plan(self, want: set[int], avail: set[int],
                     costs: Mapping[int, int] | None = None):
        """Sequence of (layer, missing_positions) repairs, preferring
        small (local) layers so repair reads stay proportional to l.
        `costs` biases which k chunks each repair reads (ref:
        minimum_to_decode_with_cost). Returns (plan, reads, known) or
        raises if unreconstructible."""
        known = set(avail)
        plan: list[tuple[_Layer, list[int]]] = []
        reads: set[int] = set()
        cost = (lambda p: costs.get(p, 0)) if costs else (lambda p: 0)
        order = sorted(self.layers, key=lambda la: la.k)
        while want - known:
            progressed = False
            for layer in order:
                missing = [p for p in layer.positions if p not in known]
                if not missing:
                    continue
                have = [p for p in layer.positions if p in known]
                if len(have) < layer.k:
                    continue
                plan.append((layer, missing))
                # the layer reads k of its known chunks; prefer ones some
                # earlier repair already reads, then cheapest, then lowest
                use = sorted(have, key=lambda p: (p not in reads,
                                                  cost(p), p))[:layer.k]
                reads |= {p for p in use if p in avail}
                known |= set(missing)
                progressed = True
                break
            if not progressed:
                raise ValueError(
                    f"lrc: cannot reconstruct {sorted(want - known)} "
                    f"from {sorted(avail)}")
        return plan, reads, known

    def minimum_to_decode(self, want_to_read: Sequence[int],
                          available: Sequence[int]) -> set[int]:
        n = self.get_chunk_count()
        want = set(want_to_read)
        avail = set(available)
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), "
                             f"got {sorted(bad)}")
        direct = want & avail
        if want <= avail:
            return direct
        _, reads, _ = self._repair_plan(want - avail, avail)
        return direct | reads

    def minimum_to_decode_with_cost(self, want_to_read: Sequence[int],
                                    available: Mapping[int, int]) -> set[int]:
        """Layer-aware: the MDS default's 'k cheapest chunks' can be an
        undecodable set for a layered code, so plan repairs structurally
        and use cost only to break ties among a layer's inputs."""
        n = self.get_chunk_count()
        want = set(want_to_read)
        avail = set(available)
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), "
                             f"got {sorted(bad)}")
        direct = want & avail
        if want <= avail:
            return direct
        _, reads, _ = self._repair_plan(want - avail, avail, costs=available)
        return direct | reads

    # -- device fast path ---------------------------------------------------

    @property
    def impl(self) -> str:
        """Device lowering for the base class's derived batch_decoder
        (the layered plan collapses to ONE static GF matrix via
        ec/linearize — positionwise-linear, so the multi-stage local/
        global walk composes into a single device launch; ref:
        ErasureCodeLrc::minimum_to_decode layer walk)."""
        return getattr(self.layers[0].coder, "impl", "mxu") \
            if self.layers else "mxu"

    # -- decode ------------------------------------------------------------

    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        want = set(want_to_read)
        known: dict[int, np.ndarray] = {p: np.asarray(v, np.uint8)
                                        for p, v in chunks.items()}
        plan, _, _ = self._repair_plan(want - set(known), set(known))
        for layer, missing in plan:
            local_have = {layer.local_id(p): known[p]
                          for p in layer.positions if p in known}
            rec = layer.coder.decode(
                [layer.local_id(p) for p in missing], local_have)
            for p in missing:
                known[p] = rec[layer.local_id(p)]
        return {p: known[p] for p in want}

    def decode_concat(self, chunks: Mapping[int, np.ndarray],
                      object_size: int | None = None) -> np.ndarray:
        rec = self.decode(list(self.data_positions), chunks)
        out = np.concatenate([rec[p] for p in self.data_positions], axis=-1)
        if object_size is not None:
            out = out[..., :object_size]
        return out
