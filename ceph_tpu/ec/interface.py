"""The erasure-code contract, batched-array edition.

Semantic mirror of the reference's plugin contract
(ref: src/erasure-code/ErasureCodeInterface.h — init, chunk geometry,
minimum_to_decode, encode/decode over shard-keyed buffers; and
src/erasure-code/ErasureCode.{h,cc} for the default padding/split/concat
behaviors), re-shaped for a TPU framework: the unit of work is a BATCH of
objects, chunks are uint8 arrays of shape (batch, L), and the hot paths
lower to the static-matrix kernels in ceph_tpu.ops.rs_kernels.

A profile is a {str: str} dict exactly like ErasureCodeProfile, so
reference profile strings (k=8 m=3 plugin=tpu technique=reed_sol_van)
round-trip unchanged.
"""

from __future__ import annotations

import abc
from typing import Mapping, Sequence

import numpy as np

# TPU lane width; also satisfies every CPU SIMD alignment the reference
# cares about (jerasure wants chunks aligned to w*packetsize; BlueStore
# to csum blocks). All chunk sizes are multiples of this.
CHUNK_ALIGNMENT = 128

ErasureCodeProfile = dict  # {str: str}


class ErasureCode(abc.ABC):
    """Base class: geometry + padding/split/concat defaults.

    Subclasses set self.k, self.m after init() and implement the chunk
    codecs. All byte-level layout rules (padding to stripe width, chunk
    order) live here so every codec shares one bit-exact object<->chunk
    mapping (ref: ErasureCode::encode prep + ECUtil stripe math).
    """

    k: int
    m: int

    # True when encode/decode act independently on every byte position
    # of a chunk (all matrix codes). Vector codes that couple bytes
    # across a chunk's sub-chunk axis (clay) set this False; callers
    # like the RMW write path then fall back to whole-object windows.
    positionwise: bool = True

    def __init__(self, profile: Mapping[str, str] | None = None):
        self.profile: ErasureCodeProfile = dict(profile or {})
        if profile is not None:
            self.init(self.profile)

    # -- lifecycle ---------------------------------------------------------

    @abc.abstractmethod
    def init(self, profile: Mapping[str, str]) -> None:
        """Parse/validate the profile; set k, m; build matrices."""

    # -- geometry ----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_chunk_mapping(self) -> list[int]:
        """Shard-id permutation; identity unless a subclass remaps."""
        return list(range(self.get_chunk_count()))

    def get_chunk_size(self, stripe_width: int) -> int:
        """Bytes per chunk for an object of `stripe_width` logical bytes,
        padded so chunk_size is CHUNK_ALIGNMENT-aligned."""
        align = self.k * CHUNK_ALIGNMENT
        padded = -(-stripe_width // align) * align
        return padded // self.k

    # -- device fast path --------------------------------------------------

    def batch_decoder(self, erasures: Sequence[int],
                      survivors: Sequence[int]):
        """Optional device fast path: a jitted fn mapping a survivor
        stack (B, H, L) uint8 (rows in `survivors` order, H =
        len(survivors)) to the rebuilt chunks (B, len(erasures), L) in
        `erasures` order, suitable for fusing into larger jitted
        pipelines (recovery CRC+decode+CRC in one launch). How many
        rows are consumed is codec-specific (RS: the first k; LRC: all
        — the local plan may need fewer than k rows total; Clay: all d
        helpers, repair planes selected on device). Returns None when
        the codec has no static-matrix form for this pattern; callers
        must then use decode_chunks."""
        if not getattr(self, "positionwise", True):
            return None          # byte positions couple (clay
        #                          overrides with its sub-chunk plan)
        impl = getattr(self, "impl", None) or "mxu"
        if impl == "ref":
            return None          # numpy oracle: no device path
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)
        cache = self.__dict__.setdefault("_bd_cache", {})
        fn = cache.get((erasures, survivors))
        if fn is None:
            from ..ops.rs_kernels import make_encoder
            from .linearize import derive_repair_matrix
            R = None
            for seed in range(3):  # a random probe matrix is singular
                try:               # ~0.4% of the time even when the
                    R = derive_repair_matrix(   # helpers suffice
                        self, erasures, survivors, seed=seed)
                    break
                except ValueError:
                    continue
            fn = make_encoder(R, impl) if R is not None else False
            cache[(erasures, survivors)] = fn
            if R is not None:
                self.__dict__.setdefault("_bd_keys", {})[
                    (erasures, survivors)] = (
                        "lin", R.tobytes(), R.shape, impl)
        return fn or None

    # -- parity-delta fast path (partial-stripe RMW) -----------------------

    def delta_matrix(self, touched: Sequence[int]):
        """(m, len(touched)) GF matrix D with parity_delta =
        D (GF@) data_delta byte-wise, or None when the codec has no
        static scalar form (vector codes, bitmatrix techniques) —
        callers then use parity_delta's generic XOR-linear path.
        `touched` names DENSE data rows (encode_chunks order).
        Cached per instance; derivation is probe-verified."""
        if not getattr(self, "positionwise", True):
            return None
        touched = tuple(int(t) for t in touched)
        cache = self.__dict__.setdefault("_dm_cache", {})
        if touched not in cache:
            from .linearize import derive_delta_matrix
            try:
                cache[touched] = derive_delta_matrix(self, touched)
            except ValueError:
                cache[touched] = None
        return cache[touched]

    def delta_program_key(self, touched: Sequence[int]):
        """Hashable identity of the fused delta-encode program, EQUAL
        across coder instances with the same geometry — the
        process-wide RMW program cache key (same sharing contract as
        decode_program_key: identical HLO compiles ONCE per process,
        not once per PG per daemon). None when there is no static
        form (callers cache the generic path per coder instance)."""
        touched = tuple(int(t) for t in touched)
        D = self.delta_matrix(touched)
        if D is None:
            return None
        impl = getattr(self, "impl", None) or "mxu"
        return ("delta", D.tobytes(), D.shape, impl)

    def parity_delta(self, touched: Sequence[int],
                     deltas: np.ndarray) -> np.ndarray:
        """(B, len(touched), L) data-shard deltas (new ^ old, DENSE
        row order per `touched`) -> (B, m, L) parity deltas: XOR each
        into its parity shard and the stripe re-encodes to the new
        bytes. Correct for EVERY additive (XOR-linear) code — all GF
        codes here, Clay included (whose sub-chunk coupling only
        requires L to be the FULL chunk length; positionwise callers
        may pass any sub-window). Uses the static delta matrix when
        one exists, else encodes the zero-padded delta through
        encode_chunks (linearity: encode(new^old) = parity(new) ^
        parity(old))."""
        deltas = np.asarray(deltas, np.uint8)
        touched = tuple(int(t) for t in touched)
        if deltas.ndim != 3 or deltas.shape[1] != len(touched):
            raise ValueError(
                f"deltas must be (B, {len(touched)}, L), "
                f"got {deltas.shape}")
        D = self.delta_matrix(touched)
        if D is not None:
            from ..gf.numpy_ref import gf_matmul
            B, t, L = deltas.shape
            out = np.empty((B, self.m, L), np.uint8)
            for bi in range(B):
                out[bi] = gf_matmul(D, deltas[bi])
            return out
        B, t, L = deltas.shape
        full = np.zeros((B, self.k, L), np.uint8)
        for ti, tr in enumerate(touched):
            full[:, tr, :] = deltas[:, ti, :]
        return np.asarray(self.encode_chunks(full))

    def range_batch_decoder(self, erasures: Sequence[int],
                            survivors: Sequence[int]):
        """Optional sub-chunk fast path: a jitted fn mapping the
        helpers' PLANNED BYTE RANGES — stacked (B, H, rl) uint8 where
        rl = row_bytes(shard_len) of the repair plan — to the rebuilt
        full chunks (B, len(erasures), shard_len). Only codecs whose
        repair touches a strict sub-range of each helper (Clay/MSR)
        provide one; None means the planner ships full rows and
        batch_decoder applies."""
        return None

    def range_decode_program_key(self, erasures: Sequence[int],
                                 survivors: Sequence[int]):
        """Process-wide program identity for range_batch_decoder
        (same sharing contract as decode_program_key)."""
        return None

    def decode_program_key(self, erasures: Sequence[int],
                           survivors: Sequence[int]):
        """Hashable identity of batch_decoder's compiled program, EQUAL
        across coder instances with the same geometry — the process-wide
        recovery program cache key (a per-backend cache recompiles the
        identical HLO once per PG per daemon; the write path learned
        this in round 8). None when there is no static form (callers
        fall back to caching per coder instance)."""
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)
        if self.batch_decoder(erasures, survivors) is None:
            return None
        return self.__dict__.get("_bd_keys", {}).get(
            (erasures, survivors))

    # -- availability ------------------------------------------------------

    def minimum_to_decode(self, want_to_read: Sequence[int],
                          available: Sequence[int]) -> set[int]:
        """Smallest chunk set from `available` able to produce `want_to_read`.

        MDS default: any k available chunks (prefer wanted ones, then data
        chunks — they're free to 'decode'). Locally-repairable codecs
        override (LRC: the local group; Clay: sub-chunk ranges).
        """
        avail = set(available)
        want = set(want_to_read)
        n = self.get_chunk_count()
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), got {sorted(bad)}")
        if want - avail:
            need = want & avail
            rest = sorted(avail - want)
            need.update(rest[:max(0, self.k - len(need))])
            if len(need) < self.k:
                raise ValueError(
                    f"cannot decode {sorted(want)} from {sorted(avail)}: "
                    f"only {len(avail)} chunks available, need {self.k}")
            return need
        return want

    def minimum_to_decode_with_cost(self, want_to_read: Sequence[int],
                                    available: Mapping[int, int]) -> set[int]:
        """Like minimum_to_decode but with per-chunk read costs; default
        picks the k cheapest (ref: ErasureCodeInterface minimum_to_decode_with_cost)."""
        want = set(want_to_read)
        avail = set(available)
        n = self.get_chunk_count()
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), got {sorted(bad)}")
        if want - avail:
            ordered = sorted(avail, key=lambda c: (available[c], c))
            need = set(ordered[:self.k])
            if len(need) < self.k:
                raise ValueError("not enough chunks")
            return need
        return want

    # -- byte-level encode/decode -----------------------------------------

    def encode(self, want_to_encode: Sequence[int],
               data: bytes | np.ndarray) -> dict[int, np.ndarray]:
        """Full-object encode: pad to stripe width, split into k data
        chunks, compute parity, return the requested chunk ids.

        data: bytes or (object_bytes,) uint8, or (batch, object_bytes).
        Returns {chunk_id: (batch, chunk_size) uint8} (batch dim kept).
        """
        n_chunks = self.get_chunk_count()
        bad = [i for i in want_to_encode if not 0 <= i < n_chunks]
        if bad:
            raise ValueError(
                f"chunk ids must be in [0, {n_chunks}), got {sorted(bad)}")
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else np.asarray(data, np.uint8)
        squeeze = arr.ndim == 1
        if squeeze:
            arr = arr[None, :]
        b, n = arr.shape
        cs = self.get_chunk_size(n)
        padded = np.zeros((b, self.k * cs), dtype=np.uint8)
        padded[:, :n] = arr
        chunks = padded.reshape(b, self.k, cs)
        coded = self.encode_chunks(chunks)  # (b, m, cs)
        full = {i: chunks[:, i, :] for i in range(self.k)}
        full.update({self.k + i: np.asarray(coded)[:, i, :] for i in range(self.m)})
        out = {i: full[i] for i in want_to_encode}
        if squeeze:
            out = {i: v[0] for i, v in out.items()}
        return out

    @abc.abstractmethod
    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        """(batch, k, L) data chunks -> (batch, m, L) coding chunks."""

    def decode(self, want_to_read: Sequence[int],
               chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Reconstruct `want_to_read` chunk ids from available `chunks`.

        Systematic default (ref: ErasureCode::_decode): wanted chunks that
        are already available pass through; the rest go to decode_chunks.
        """
        out: dict[int, np.ndarray] = {}
        missing = []
        for i in want_to_read:
            if i in chunks:
                out[i] = np.asarray(chunks[i])
            else:
                missing.append(i)
        if missing:
            out.update(self.decode_chunks(missing, chunks))
        return out

    @abc.abstractmethod
    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Reconstruct the (erased) `want_to_read` ids from `chunks`."""

    def decode_concat(self, chunks: Mapping[int, np.ndarray],
                      object_size: int | None = None) -> np.ndarray:
        """Recover and concatenate the data chunks (ref:
        ErasureCodeInterface::decode_concat), trimming padding if
        object_size is given."""
        rec = self.decode(list(range(self.k)), chunks)
        parts = [rec[i] for i in range(self.k)]
        out = np.concatenate(parts, axis=-1)
        if object_size is not None:
            out = out[..., :object_size]
        return out


def profile_from_string(s: str) -> ErasureCodeProfile:
    """Parse 'k=8 m=3 plugin=tpu technique=reed_sol_van' profile strings."""
    out: ErasureCodeProfile = {}
    for tok in s.split():
        if "=" not in tok:
            raise ValueError(f"bad profile token {tok!r}")
        key, val = tok.split("=", 1)
        out[key] = val
    return out
