"""SHEC — shingled erasure code with local parity groups.

Rebuild of the reference's shec plugin (ref: src/erasure-code/shec/
ErasureCodeShec.{h,cc} — ErasureCodeShecReedSolomonVandermonde with its
own decode-matrix search, plus ErasureCodeShecTableCache): a non-MDS
code trading storage efficiency for recovery I/O. Each of the m parity
chunks covers only a short "shingle" window of l = ceil(k*c/m)
consecutive data chunks (wrapping mod k, windows overlapping like roof
shingles), so a single lost chunk is rebuilt from ~l reads instead of k,
while any c concurrent failures stay recoverable.

Profile: k, m, c (durability estimator; c <= m). The coding matrix is a
reed_sol_van matrix masked to the shingle windows; init() verifies the
all-<=c-erasures guarantee exhaustively (budgeted) rather than trusting
the masked construction blindly.

Decode is a rowspace solve: with generator G = [I_k ; M], a chunk o is
recoverable from survivors S iff G[o] lies in the rowspace of G[S]; the
expressing combination IS the decode matrix, cached per erasure pattern
and applied as a batched GF(2^8) kernel. minimum_to_decode searches
parity subsets in increasing read-cost order — the reference's
"decode-matrix search", reshaped: cost ranking first, rank check via the
same rowspace solve.
"""

from __future__ import annotations

from itertools import combinations
from math import comb
from typing import Mapping, Sequence

import numpy as np

from ..gf.tables import inv_table, mul_table
from .interface import ErasureCode
from .matrices import reed_sol_van_matrix
from .registry import register


def gf_express(A: np.ndarray, B: np.ndarray) -> np.ndarray | None:
    """Find X with X @ A = B over GF(2^8), or None if some row of B is
    outside A's rowspace. A: (s, k), B: (r, k) -> X: (r, s)."""
    A = np.asarray(A, np.uint8)
    B = np.asarray(B, np.uint8)
    s, k = A.shape
    mt = mul_table()
    invt = inv_table()
    R = A.copy()
    T = np.eye(s, dtype=np.uint8)  # R = T @ A invariant
    pivots: list[tuple[int, int]] = []
    row = 0
    for col in range(k):
        p = row
        while p < s and R[p, col] == 0:
            p += 1
        if p == s:
            continue
        if p != row:
            R[[row, p]] = R[[p, row]]
            T[[row, p]] = T[[p, row]]
        pv = R[row, col]
        if pv != 1:
            pinv = invt[pv]
            R[row] = mt[pinv, R[row]]
            T[row] = mt[pinv, T[row]]
        f = R[:, col].copy()
        f[row] = 0
        nz = f.nonzero()[0]
        if nz.size:
            R[nz] ^= mt[f[nz, None], R[row][None, :]]
            T[nz] ^= mt[f[nz, None], T[row][None, :]]
        pivots.append((col, row))
        row += 1
        if row == s:
            break
    X = np.zeros((B.shape[0], s), np.uint8)
    for i in range(B.shape[0]):
        r = B[i].copy()
        for col, prow in pivots:
            f = r[col]
            if f:
                r ^= mt[f, R[prow]]
                X[i] ^= mt[f, T[prow]]
        if r.any():
            return None
    return X


@register("shec")
class Shec(ErasureCode):
    """Shingled EC: m local parities over overlapping windows of l data
    chunks; guaranteed recovery of any <= c erasures."""

    # exhaustive durability verification budget (subsets tested at init)
    _VERIFY_BUDGET = 100_000

    def init(self, profile: Mapping[str, str]) -> None:
        self.k = int(profile.get("k", 4))
        self.m = int(profile.get("m", 3))
        self.c = int(profile.get("c", 2))
        if not 1 <= self.c <= self.m:
            raise ValueError(f"shec c={self.c}: need 1 <= c <= m={self.m}")
        if self.m > self.k:
            raise ValueError(f"shec m={self.m} > k={self.k} unsupported")
        if self.k + self.m > 256:
            raise ValueError(f"bad geometry k={self.k} m={self.m} (w=8)")
        self.l = -(-self.k * self.c // self.m)  # ceil(k*c/m) window width
        self.impl = profile.get("impl", "bitlinear")
        base = reed_sol_van_matrix(self.k, self.m)
        M = np.zeros_like(base)
        self.windows: list[tuple[int, ...]] = []
        for i in range(self.m):
            start = i * self.k // self.m
            win = tuple(sorted((start + j) % self.k for j in range(self.l)))
            self.windows.append(win)
            for j in win:
                M[i, j] = base[i, j]
        self.matrix = M
        self.G = np.vstack([np.eye(self.k, dtype=np.uint8), M])
        self._decode_cache: dict[tuple, tuple] = {}
        self._mtd_cache: dict[tuple, set[int]] = {}
        self._fn_cache: dict[int, object] = {}
        self._verify_durability()
        if self.impl == "ref":
            from functools import partial

            from ..gf.numpy_ref import encode_ref
            self._encode_fn = partial(encode_ref, self.matrix)
        else:
            from ..ops.rs_kernels import make_encoder
            self._encode_fn = make_encoder(self.matrix, self.impl)

    def _verify_durability(self) -> None:
        n = self.k + self.m
        if comb(n, self.c) > self._VERIFY_BUDGET:
            return  # too big to verify exhaustively; constructions this
            # large should be validated offline (mirrors the isa MDS gate)
        for erased in combinations(range(n), self.c):
            surv = [i for i in range(n) if i not in erased]
            if gf_express(self.G[surv], self.G[list(erased)]) is None:
                raise ValueError(
                    f"shec k={self.k} m={self.m} c={self.c}: erasure "
                    f"{erased} unrecoverable — masked matrix degenerate "
                    f"for this geometry")

    # -- recovery planning --------------------------------------------------

    def _plan(self, unknown_data: frozenset[int], want: frozenset[int],
              avail: frozenset[int],
              costs: Mapping[int, int] | None = None
              ) -> tuple[set[int], tuple[int, ...]]:
        """Choose the cheapest survivor set able to produce `want`.

        Search: parity subsets of the available parities in increasing
        total-read order; a subset works if every wanted chunk's G row
        lies in the rowspace of [available window data rows + parity
        rows]. Returns (chunks to read, survivor order for decode).
        With `costs`, fewest reads still wins first (the shingle
        locality is the point of SHEC) and per-chunk costs break ties
        among equal-sized candidate sets.
        """
        avail_par = sorted(p for p in avail if p >= self.k)
        avail_data = frozenset(j for j in avail if j < self.k)
        want_rows = self.G[sorted(want)]
        best: tuple[tuple, set[int], tuple[int, ...]] | None = None
        # re-encoding a wanted (lost) parity consumes its own window data
        want_par_data: set[int] = set()
        for w in want:
            if w >= self.k:
                want_par_data.update(self.windows[w - self.k])
        for r in range(0, len(avail_par) + 1):
            for P in combinations(avail_par, r):
                need_data = set(want_par_data)
                for p in P:
                    need_data.update(self.windows[p - self.k])
                need_data -= unknown_data
                if not need_data <= avail_data:
                    continue
                surv = tuple(sorted(need_data) + list(P))
                # wanted data already available reads itself directly
                direct = {w for w in want if w in avail}
                surv_all = tuple(sorted(set(surv) | direct))
                if not surv_all:
                    continue
                if gf_express(self.G[list(surv_all)], want_rows) is None:
                    continue
                cost = (len(surv_all),
                        sum(int(costs.get(c, 0)) for c in surv_all)
                        if costs else 0)
                if best is None or cost < best[0]:
                    best = (cost, set(surv_all), surv_all)
            if best is not None:
                break  # smaller parity subsets tried first; cost ~ reads
        if best is None:
            raise ValueError(
                f"shec cannot produce {sorted(want)} from {sorted(avail)}")
        return best[1], best[2]

    def minimum_to_decode(self, want_to_read: Sequence[int],
                          available: Sequence[int]) -> set[int]:
        want = frozenset(want_to_read)
        avail = frozenset(available)
        n = self.get_chunk_count()
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), got {sorted(bad)}")
        if want <= avail:
            return set(want)
        key = (want, avail)
        hit = self._mtd_cache.get(key)
        if hit is None:
            unknown = frozenset(j for j in range(self.k) if j not in avail)
            hit = self._plan(unknown, want, avail)[0]
            self._mtd_cache[key] = hit
        return set(hit)

    def minimum_to_decode_with_cost(self, want_to_read: Sequence[int],
                                    available: Mapping[int, int]) -> set[int]:
        """Structural like minimum_to_decode — the MDS default's 'k
        cheapest' can be an undecodable set for a shingled matrix —
        with per-chunk costs breaking ties among the smallest
        workable survivor sets."""
        want = frozenset(want_to_read)
        avail = frozenset(available)
        n = self.get_chunk_count()
        bad = [i for i in want | avail if not 0 <= i < n]
        if bad:
            raise ValueError(f"chunk ids must be in [0, {n}), got {sorted(bad)}")
        if want <= avail:
            return set(want)
        unknown = frozenset(j for j in range(self.k) if j not in avail)
        return set(self._plan(unknown, want, avail,
                              costs=available)[0])

    # -- codec --------------------------------------------------------------

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode_fn(np.asarray(data, np.uint8)))

    def _decoder_for(self, want: tuple[int, ...], surv: tuple[int, ...]):
        key = (want, surv)
        hit = self._decode_cache.get(key)
        if hit is None:
            X = gf_express(self.G[list(surv)], self.G[list(want)])
            if X is None:
                raise ValueError(
                    f"shec cannot decode {list(want)} from {list(surv)}")
            if self.impl == "ref":
                from ..gf.numpy_ref import encode_ref
                from functools import partial
                fn = partial(encode_ref, X)
            else:
                from ..ops.rs_kernels import make_encoder
                fn = make_encoder(X, self.impl)
            hit = (fn, surv)
            self._decode_cache[key] = hit
        return hit

    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        want = tuple(sorted(set(want_to_read)))
        surv = tuple(sorted(chunks))
        fn, order = self._decoder_for(want, surv)
        arrs = [np.asarray(chunks[s], np.uint8) for s in order]
        squeeze = arrs[0].ndim == 1
        if squeeze:
            arrs = [a[None] for a in arrs]
        stack = np.stack(arrs, axis=-2)
        rec = np.asarray(fn(stack))
        if squeeze:
            rec = rec[0]
        return {w: rec[..., i, :] for i, w in enumerate(want)}

    # -- introspection ------------------------------------------------------

    def recovery_read_count(self, failed: int) -> int:
        """Chunks read to rebuild one lost chunk — the SHEC selling point
        (~l for a data chunk vs k for RS)."""
        avail = [i for i in range(self.get_chunk_count()) if i != failed]
        return len(self.minimum_to_decode([failed], avail))
