"""Derive static repair matrices from any positionwise codec.

Every positionwise-linear codec (all matrix codes: RS, LRC layers,
bitmatrix techniques viewed per byte position) satisfies
  lost_chunk = XOR_h C[h] * helper_chunk        (GF(2^8), byte-wise)
for SOME coefficient row C once the helper set can repair the loss.
This module recovers C empirically — probe the codec with random
objects, read one byte column per sample, solve the GF linear system,
verify on held-out samples and full chunks — so callers get a static
matrix usable in fused/sharded device pipelines even when the codec
(e.g. LRC's layered planner, ref: src/erasure-code/lrc/
ErasureCodeLrc.cc minimum_to_decode layer walk) only exposes a
procedural decode.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..gf.numpy_ref import gf_inv_matrix, gf_matmul
from .interface import CHUNK_ALIGNMENT, ErasureCode


def derive_delta_matrix(coder: ErasureCode,
                        touched: Sequence[int]) -> np.ndarray:
    """(m, len(touched)) GF matrix D with
    parity_delta = D (GF@) data_delta, byte-wise — the parity-update
    rule of a partial-stripe overwrite (delta_j = G[j,i] (x) (new_i ^
    old_i), ref: the RMW parity math in ECCommon; arxiv 1709.05365's
    online-EC overwrite cost model). `touched` names DENSE data rows
    (encode_chunks order).

    Probed, not assumed: unit vectors recover the candidate columns,
    then a random held-out delta must reproduce encode_chunks exactly
    — codecs whose per-byte map is not a GF(2^8) scalar (bitmatrix
    techniques) fail the verify and callers fall back to the generic
    XOR-linear path (encode_chunks of the zero-padded delta), which
    is always correct for additive codes.

    Raises ValueError when the codec is not positionwise or the probe
    verify fails."""
    if not getattr(coder, "positionwise", True):
        raise ValueError("codec couples byte positions (not positionwise); "
                         "no per-byte delta matrix exists")
    touched = [int(t) for t in touched]
    k = coder.get_data_chunk_count()
    m = coder.get_coding_chunk_count()
    bad = [t for t in touched if not 0 <= t < k]
    if bad:
        raise ValueError(f"touched rows must be data rows in [0, {k}), "
                         f"got {sorted(bad)}")
    L = 128     # any length works for a positionwise code
    D = np.zeros((m, len(touched)), np.uint8)
    probe = np.zeros((len(touched), k, L), np.uint8)
    for ti, t in enumerate(touched):
        probe[ti, t, :] = 1     # GF multiplicative identity
    parity = np.asarray(coder.encode_chunks(probe))     # (t, m, L)
    for ti in range(len(touched)):
        col = parity[ti, :, 0]
        if not np.array_equal(parity[ti],
                              np.repeat(col[:, None], L, axis=1)):
            raise ValueError("per-byte parity map is not constant "
                             "across positions; no scalar delta matrix")
        D[:, ti] = col
    # verify: a random delta through D must equal encode_chunks
    rng = np.random.default_rng(1)
    delta = rng.integers(0, 256, (len(touched), L), np.uint8)
    full = np.zeros((1, k, L), np.uint8)
    for ti, t in enumerate(touched):
        full[0, t] = delta[ti]
    want = np.asarray(coder.encode_chunks(full))[0]     # (m, L)
    if not np.array_equal(gf_matmul(D, delta), want):
        raise ValueError("delta matrix failed the held-out verify; "
                         "codec's per-byte map is not a GF(2^8) scalar")
    return D


def derive_repair_matrix(coder: ErasureCode, lost: Sequence[int],
                         helpers: Sequence[int],
                         seed: int = 0) -> np.ndarray:
    """(len(lost), len(helpers)) GF matrix R with
    lost_chunks = R (GF@) helper_chunks, byte-wise.

    Raises ValueError when the codec is not positionwise or the probe
    system is singular (helpers insufficient)."""
    if not getattr(coder, "positionwise", True):
        raise ValueError("codec couples byte positions (not positionwise); "
                         "no per-byte repair matrix exists")
    lost = [int(s) for s in lost]
    helpers = [int(s) for s in helpers]
    n = coder.get_chunk_count()
    k = coder.get_data_chunk_count()
    H = len(helpers)
    cs = coder.get_chunk_size(k * CHUNK_ALIGNMENT)
    rng = np.random.default_rng(seed)
    S = H + 4
    A = np.zeros((S, H), np.uint8)     # helper byte columns
    Y = np.zeros((S, len(lost)), np.uint8)
    full = []
    for s in range(S):
        obj = rng.integers(0, 256, k * cs, np.uint8)
        enc = coder.encode(range(n), obj)
        full.append(enc)
        A[s] = [enc[h][0] for h in helpers]
        Y[s] = [enc[t][0] for t in lost]
    sq = A[:H]
    try:
        inv = gf_inv_matrix(sq)
    except (ValueError, np.linalg.LinAlgError):
        raise ValueError("probe system singular; try different helpers "
                         "or another seed") from None
    R = gf_matmul(inv, Y[:H]).T        # (len(lost), H)
    # verify: held-out byte columns AND every byte of one full sample
    if not np.array_equal(gf_matmul(A[H:], R.T), Y[H:]):
        raise ValueError("repair relation failed held-out samples; "
                         "helpers cannot linearly produce the lost chunks")
    enc = full[0]
    hstack = np.stack([np.asarray(enc[h]) for h in helpers])  # (H, cs)
    want = np.stack([np.asarray(enc[t]) for t in lost])
    if not np.array_equal(gf_matmul(R, hstack), want):
        raise ValueError("repair matrix valid at byte 0 only — codec is "
                         "not positionwise after all")
    return R
