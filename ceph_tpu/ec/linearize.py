"""Derive static repair matrices from any positionwise codec.

Every positionwise-linear codec (all matrix codes: RS, LRC layers,
bitmatrix techniques viewed per byte position) satisfies
  lost_chunk = XOR_h C[h] * helper_chunk        (GF(2^8), byte-wise)
for SOME coefficient row C once the helper set can repair the loss.
This module recovers C empirically — probe the codec with random
objects, read one byte column per sample, solve the GF linear system,
verify on held-out samples and full chunks — so callers get a static
matrix usable in fused/sharded device pipelines even when the codec
(e.g. LRC's layered planner, ref: src/erasure-code/lrc/
ErasureCodeLrc.cc minimum_to_decode layer walk) only exposes a
procedural decode.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..gf.numpy_ref import gf_inv_matrix, gf_matmul
from .interface import CHUNK_ALIGNMENT, ErasureCode


def derive_repair_matrix(coder: ErasureCode, lost: Sequence[int],
                         helpers: Sequence[int],
                         seed: int = 0) -> np.ndarray:
    """(len(lost), len(helpers)) GF matrix R with
    lost_chunks = R (GF@) helper_chunks, byte-wise.

    Raises ValueError when the codec is not positionwise or the probe
    system is singular (helpers insufficient)."""
    if not getattr(coder, "positionwise", True):
        raise ValueError("codec couples byte positions (not positionwise); "
                         "no per-byte repair matrix exists")
    lost = [int(s) for s in lost]
    helpers = [int(s) for s in helpers]
    n = coder.get_chunk_count()
    k = coder.get_data_chunk_count()
    H = len(helpers)
    cs = coder.get_chunk_size(k * CHUNK_ALIGNMENT)
    rng = np.random.default_rng(seed)
    S = H + 4
    A = np.zeros((S, H), np.uint8)     # helper byte columns
    Y = np.zeros((S, len(lost)), np.uint8)
    full = []
    for s in range(S):
        obj = rng.integers(0, 256, k * cs, np.uint8)
        enc = coder.encode(range(n), obj)
        full.append(enc)
        A[s] = [enc[h][0] for h in helpers]
        Y[s] = [enc[t][0] for t in lost]
    sq = A[:H]
    try:
        inv = gf_inv_matrix(sq)
    except (ValueError, np.linalg.LinAlgError):
        raise ValueError("probe system singular; try different helpers "
                         "or another seed") from None
    R = gf_matmul(inv, Y[:H]).T        # (len(lost), H)
    # verify: held-out byte columns AND every byte of one full sample
    if not np.array_equal(gf_matmul(A[H:], R.T), Y[H:]):
        raise ValueError("repair relation failed held-out samples; "
                         "helpers cannot linearly produce the lost chunks")
    enc = full[0]
    hstack = np.stack([np.asarray(enc[h]) for h in helpers])  # (H, cs)
    want = np.stack([np.asarray(enc[t]) for t in lost])
    if not np.array_equal(gf_matmul(R, hstack), want):
        raise ValueError("repair matrix valid at byte 0 only — codec is "
                         "not positionwise after all")
    return R
