"""Coding-matrix construction, jerasure-compatible.

Re-derives the matrix algorithms of the reference's jerasure plugin
(ref: src/erasure-code/jerasure/ErasureCodeJerasure.cc techniques
`reed_sol_van`, `cauchy_orig`, `cauchy_good`; C library
src/erasure-code/jerasure/jerasure/src/reed_sol.c, cauchy.c).

NOTE on bit-exactness: the reference mount was empty at survey time
(SURVEY.md citation notice), so these are from-first-principles
implementations of the published algorithms (Plank's 1997 RS tutorial +
2005 correction; Blomer et al. Cauchy codes), with the gf-complete w=8
primitive polynomial 0x11D. Pinned non-regression corpora in
tests/corpus/ freeze OUR byte output so it can never drift; if the
reference tree materializes, parity vs jerasure is then a matrix-level
comparison (m x k coefficients), cheap to re-verify.
"""

from __future__ import annotations

import numpy as np

from ..gf.numpy_ref import gf_inv_matrix, gf_matmul
from ..gf.tables import (gf_div_scalar, gf_inv_scalar, gf_mul_scalar,
                         gf_pow_scalar, mul_table)


def vandermonde_raw(rows: int, cols: int) -> np.ndarray:
    """V[i, j] = i**j in GF(2^8) with 0**0 == 1 (Plank's construction)."""
    v = np.zeros((rows, cols), dtype=np.uint8)
    for i in range(rows):
        for j in range(cols):
            v[i, j] = gf_pow_scalar(i, j)
    return v


def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """The `reed_sol_van` coding matrix: (m, k) uint8.

    Algorithm (reed_sol.c reed_sol_big_vandermonde_distribution_matrix):
    build the (k+m) x k Vandermonde matrix V[i,j] = i^j, then apply
    elementary COLUMN operations (which preserve the any-k-rows-invertible
    property) to turn the top k x k block into the identity. The bottom m
    rows are the systematic coding matrix. Column ops, in order, per
    diagonal position i: swap in a nonzero pivot from the right, scale the
    pivot column to make V[i,i] == 1, then cancel every other nonzero
    entry of row i by subtracting a multiple of column i.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    v = vandermonde_raw(k + m, k)
    mt = mul_table()
    for i in range(k):
        if v[i, i] == 0:
            for j in range(i + 1, k):
                if v[i, j] != 0:
                    v[:, [i, j]] = v[:, [j, i]]
                    break
            else:
                raise AssertionError("vandermonde: no pivot")
        if v[i, i] != 1:
            inv = gf_inv_scalar(int(v[i, i]))
            v[:, i] = mt[inv, v[:, i]]
        for j in range(k):
            if j != i and v[i, j] != 0:
                v[:, j] ^= mt[int(v[i, j]), v[:, i]]
    assert (v[:k] == np.eye(k, dtype=np.uint8)).all()
    return v[k:].copy()


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """The `cauchy_orig` coding matrix (cauchy.c cauchy_original_coding_matrix):
    element (i, j) = 1 / (i XOR (m + j)) with X_i = i (i < m) and
    Y_j = m + j (j < k); X and Y disjoint so no division by zero."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf_div_scalar(1, i ^ (m + j))
    return mat


def _bitmatrix_ones(c: int) -> int:
    """Number of ones in the 8x8 bit-expansion of multiply-by-c."""
    from ..gf.tables import gf_bitmatrix
    return int(gf_bitmatrix(c).sum())


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """The `cauchy_good` matrix (cauchy.c cauchy_improve_coding_matrix).

    Starts from cauchy_orig and reduces total bitmatrix weight:
      1. divide each column j by its row-0 element (row 0 becomes all 1s);
      2. for every other row, try dividing the whole row by each of its
         elements and keep the division that minimizes the row's total
         bit-expansion weight (ones in the 8x8 bitmatrices).
    Division by an element keeps the code MDS (elementary row/col scaling).
    """
    mat = cauchy_orig_matrix(k, m)
    # step 1: normalize row 0 to all ones by scaling columns
    for j in range(k):
        d = int(mat[0, j])
        if d != 1:
            for i in range(m):
                mat[i, j] = gf_div_scalar(int(mat[i, j]), d)
    # step 2: per-row best divisor
    for i in range(1, m):
        best_w = sum(_bitmatrix_ones(int(c)) for c in mat[i])
        best_row = mat[i].copy()
        for div in mat[i].tolist():
            if div in (0, 1):
                continue
            cand = np.array([gf_div_scalar(int(c), int(div)) for c in mat[i]],
                            dtype=np.uint8)
            w = sum(_bitmatrix_ones(int(c)) for c in cand)
            if w < best_w:
                best_w = w
                best_row = cand
        mat[i] = best_row
    return mat


def liberation_like_xor_first_row(mat: np.ndarray) -> bool:
    """True if the first parity row is pure XOR (all-ones) — a documented
    property of reed_sol_van and cauchy_good first rows."""
    return bool((mat[0] == 1).all())


def isa_rs_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L-style RS matrix (semantic mirror of isa-l ec_base.c
    gf_gen_rs_matrix, used by the reference's isa plugin — ref:
    src/erasure-code/isa/ErasureCodeIsa.cc): coding row r has entries
    (2^r)^j — row 0 all ones, row 1 powers of 2, row 2 powers of 4, ...
    NOT guaranteed MDS for every geometry (a known ISA-L caveat); callers
    must check is_mds() or catch singular decode matrices.
    """
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    mat = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            mat[r, j] = p
            p = gf_mul_scalar(p, gen)
        gen = gf_mul_scalar(gen, 2)
    return mat


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L-style Cauchy matrix (semantic mirror of isa-l ec_base.c
    gf_gen_cauchy1, the reference isa plugin's technique=cauchy — ref:
    src/erasure-code/isa/ErasureCodeIsa.cc): coding element (i, j) =
    1 / ((k + i) XOR j). X = {k..k+m-1} and Y = {0..k-1} are disjoint, so
    this is a true Cauchy matrix — MDS for every geometry. Distinct from
    jerasure's cauchy_orig (1 / (i XOR (m + j))), so the two plugins'
    parity bytes differ, as they do in the reference."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf_inv_scalar((k + i) ^ j)
    return mat


def reed_sol_r6_matrix(k: int, m: int) -> np.ndarray:
    """The RAID-6 matrix (reed_sol.c reed_sol_r6_coding_matrix): P row is
    plain XOR, Q row is powers of the generator: Q[j] = 2**j. m must be 2."""
    if m != 2:
        raise ValueError(f"reed_sol_r6_op requires m=2, got m={m}")
    mat = np.ones((2, k), dtype=np.uint8)
    for j in range(k):
        mat[1, j] = gf_pow_scalar(2, j)
    return mat


TECHNIQUES = {
    "reed_sol_van": reed_sol_van_matrix,
    "reed_sol_r6_op": reed_sol_r6_matrix,
    "cauchy_orig": cauchy_orig_matrix,
    "cauchy_good": cauchy_good_matrix,
    "isa_reed_sol_van": isa_rs_matrix,
    "isa_cauchy": isa_cauchy_matrix,
}


def coding_matrix(technique: str, k: int, m: int) -> np.ndarray:
    try:
        fn = TECHNIQUES[technique]
    except KeyError:
        raise ValueError(f"unknown technique {technique!r}; "
                         f"available: {sorted(TECHNIQUES)}") from None
    return fn(k, m)


def is_mds(matrix: np.ndarray, k: int) -> bool:
    """Exhaustively check the MDS property for small k+m: every k x k
    submatrix of [I; C] must be invertible (i.e. any k chunks decode)."""
    from itertools import combinations
    m = matrix.shape[0]
    full = np.vstack([np.eye(k, dtype=np.uint8), matrix])
    for rows in combinations(range(k + m), k):
        try:
            gf_inv_matrix(full[list(rows)])
        except ValueError:
            return False
    return True
