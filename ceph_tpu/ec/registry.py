"""Plugin registry — the Python face of ErasureCodePluginRegistry.

The reference resolves plugins by dlopen("libec_<name>.so") and an
__erasure_code_init entry point (ref: src/erasure-code/ErasureCodePlugin.cc
ErasureCodePluginRegistry::{instance,load,factory,preload}). Here plugins
are Python factories registered by name; the C++ shim in native/ gives
out-of-process callers the same dlopen contract and forwards to this
registry. Profiles stay string-maps so reference profiles work verbatim.
"""

from __future__ import annotations

from typing import Callable, Mapping

from .interface import ErasureCode, ErasureCodeProfile, profile_from_string

_REGISTRY: dict[str, Callable[[Mapping[str, str]], ErasureCode]] = {}


def register(name: str):
    """Decorator: register an ErasureCode subclass (or factory) as a plugin."""
    def deco(factory):
        _REGISTRY[name] = factory
        return factory
    return deco


def plugins() -> list[str]:
    _ensure_loaded()
    return sorted(_REGISTRY)


def _ensure_loaded() -> None:
    # "preload": import the bundled plugin modules so they self-register,
    # mirroring ErasureCodePluginRegistry::preload's eager dlopen list.
    from . import rs as _rs  # noqa: F401
    for mod in ("lrc", "clay", "shec"):
        name = f"{__package__}.{mod}"
        try:
            __import__(name)
        except ModuleNotFoundError as e:
            if e.name != name:  # plugin exists but is broken — surface it
                raise


def get_factory(name: str):
    """Public lookup of a registered plugin factory by name (the
    ErasureCodePluginRegistry::load analog without instantiation).
    Raises ValueError for unknown plugins."""
    _ensure_loaded()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown EC plugin {name!r}; known: {sorted(_REGISTRY)}") from None


def factory(profile: Mapping[str, str] | str) -> ErasureCode:
    """Instantiate a coder from a profile (dict or profile string).

    The plugin name comes from profile['plugin'] (default 'tpu_rs', our
    jerasure-equivalent RS coder).
    """
    if isinstance(profile, str):
        profile = profile_from_string(profile)
    prof: ErasureCodeProfile = dict(profile)
    name = prof.get("plugin", "tpu_rs")
    _ensure_loaded()
    try:
        fac = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown EC plugin {name!r}; known: {sorted(_REGISTRY)}") from None
    return fac(prof)
