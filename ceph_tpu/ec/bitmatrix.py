"""Bitmatrix RAID-6 techniques: liberation, blaum_roth, liber8tion.

Rebuild of the reference's bitmatrix/schedule jerasure techniques (ref:
src/erasure-code/jerasure/ErasureCodeJerasure.cc classes
ErasureCodeJerasureLiberation / …BlaumRoth / …Liber8tion; C kernels
jerasure.c jerasure_bitmatrix_encode/decode, liberation.c).

A bitmatrix code treats each chunk as w PACKETS (equal byte regions).
The coding bitmatrix BM is (m*w, k*w) over GF(2); coding packet row r is
the XOR of the data packet rows c with BM[r, c] == 1. Encode/decode are
therefore pure XOR schedules over byte regions — no GF(2^8) multiplies
at all, which is the TPU-friendliest codec shape there is (elementwise
u8 XOR, batched).

Matrix constructions (from the published algorithms; the reference
mount is empty — see SURVEY.md citation notice — so these are
from-first-principles implementations pinned by our own corpus):

* liberation (Plank, "The RAID-6 Liberation Codes", FAST'08): w prime,
  k <= w, m == 2. P-blocks are identities; Q-block j is the cyclic
  rotation R^j plus, for j > 0, one extra bit at row y = j*(w-1)/2 mod w,
  column (y + j - 1) mod w — the minimal-density MDS family.
* blaum_roth (Blaum & Roth codes): w+1 prime, k <= w, m == 2. Q-block j
  is multiplication by x^j in the polynomial ring
  GF(2)[x] / M_p(x), M_p(x) = 1 + x + ... + x^w (p = w+1), using the
  reduction x^w = 1 + x + ... + x^(w-1).
* liber8tion (Plank, "Uber-CSHR and Liber8tion" family): w == 8 (not
  prime, so liberation's construction is unavailable), k <= 8, m == 2.
  The published matrices were found by search; the exact tables cannot
  be verified against the empty reference mount, so this module derives
  the family with a DETERMINISTIC backtracking search under the same
  structural constraints (X_0 = I, X_j = R^j plus minimal extra bits,
  every X_j and every X_i ^ X_j invertible — the exact MDS conditions
  for an m=2 block code). Output is deterministic and pinned in
  tests/corpus; byte-compatibility with jerasure's liber8tion table is
  explicitly NOT claimed.

Every construction is MDS-verified at init (X_j and pairwise X_i ^ X_j
invertibility), so a buggy matrix can never silently write stripes.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from .interface import CHUNK_ALIGNMENT, ErasureCode


# ---------------------------------------------------------------- GF(2)

def gf2_inv(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2) matrix (Gauss-Jordan); raises ValueError
    if singular."""
    n = mat.shape[0]
    a = (np.asarray(mat, dtype=np.uint8) & 1).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = None
        for r in range(col, n):
            if a[r, col]:
                piv = r
                break
        if piv is None:
            raise ValueError("singular GF(2) matrix")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


def gf2_is_invertible(mat: np.ndarray) -> bool:
    try:
        gf2_inv(mat)
        return True
    except ValueError:
        return False


def _rotation(w: int, j: int) -> np.ndarray:
    """R^j: ones at (i, (i + j) % w)."""
    m = np.zeros((w, w), dtype=np.uint8)
    for i in range(w):
        m[i, (i + j) % w] = 1
    return m


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    return all(n % d for d in range(2, int(n ** 0.5) + 1))


def _assemble(k: int, w: int, xblocks: list[np.ndarray]) -> np.ndarray:
    """[identity row | X row] -> (2w, k*w) coding bitmatrix."""
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        bm[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        bm[w:, j * w:(j + 1) * w] = xblocks[j]
    return bm


def _verify_mds_raid6(xblocks: list[np.ndarray], label: str) -> None:
    """m=2 block-code MDS conditions: every X_j invertible (data+P loss)
    and every X_i ^ X_j invertible (double data loss)."""
    k = len(xblocks)
    for j, x in enumerate(xblocks):
        if not gf2_is_invertible(x):
            raise ValueError(f"{label}: X_{j} singular — not MDS")
    for i in range(k):
        for j in range(i + 1, k):
            if not gf2_is_invertible(xblocks[i] ^ xblocks[j]):
                raise ValueError(f"{label}: X_{i}^X_{j} singular — not MDS")


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    if not _is_prime(w):
        raise ValueError(f"liberation requires prime w, got w={w}")
    if not 2 <= k <= w:
        raise ValueError(f"liberation requires 2 <= k <= w={w}, got k={k}")
    xb = []
    for j in range(k):
        x = _rotation(w, j)
        if j > 0:
            y = (j * ((w - 1) // 2)) % w
            x[y, (y + j - 1) % w] ^= 1
        xb.append(x)
    _verify_mds_raid6(xb, f"liberation k={k} w={w}")
    return _assemble(k, w, xb)


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    if not _is_prime(w + 1):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if not 2 <= k <= w:
        raise ValueError(f"blaum_roth requires 2 <= k <= w={w}, got k={k}")
    # multiplication by x in GF(2)[x] / (1 + x + ... + x^w):
    # shift up; x^w reduces to 1 + x + ... + x^(w-1)
    mulx = np.zeros((w, w), dtype=np.uint8)
    for b in range(w - 1):
        mulx[b + 1, b] = 1
    mulx[:, w - 1] = 1
    xb = []
    x = np.eye(w, dtype=np.uint8)
    for j in range(k):
        xb.append(x.copy())
        x = (mulx @ x) & 1
    _verify_mds_raid6(xb, f"blaum_roth k={k} w={w}")
    return _assemble(k, w, xb)


def liber8tion_bitmatrix(k: int, w: int = 8) -> np.ndarray:
    """w=8 m=2 RAID-6 bitmatrix (the liber8tion slot).

    Rotation-plus-extra-bit blocks (liberation's family) provably cannot
    cover w=8: rank(I ^ R^d) = 8 - gcd(8, d), so pairs with even shift
    difference are >= 2 ranks short of invertible — which is why the
    published liber8tion matrices came from Plank's Uber-CSHR search.
    Those tables cannot be verified against the empty reference mount,
    so this builds the X-blocks as companion-matrix powers instead:
    X_j = bitmatrix(2^j) over GF(2^8)/0x11D. MDS is automatic —
    X_i ^ X_j = bitmatrix(2^i ^ 2^j) with a nonzero constant, hence
    invertible — and the code is mathematically the generator-2 RAID-6
    (reed_sol_r6_op) evaluated over bit-sliced symbols: bit-lane t of
    the packet columns forms a GF(2^8) symbol, and parity lane t is
    P/Q of those symbols (a cross-implementation equivalence the tests
    pin). Same contract and packet layout as liber8tion; matrix family
    differs from the published search results."""
    if w != 8:
        raise ValueError(f"liber8tion requires w=8, got w={w}")
    if not 2 <= k <= 8:
        raise ValueError(f"liber8tion requires 2 <= k <= 8, got k={k}")
    from ..gf.tables import gf_bitmatrix, gf_pow_scalar
    xb = [gf_bitmatrix(gf_pow_scalar(2, j)) for j in range(k)]
    _verify_mds_raid6(xb, f"liber8tion k={k}")
    return _assemble(k, 8, xb)


BITMATRIX_TECHNIQUES = {
    "liberation": (liberation_bitmatrix, 7),   # default w
    "blaum_roth": (blaum_roth_bitmatrix, 6),   # w+1 = 7 prime
    "liber8tion": (liber8tion_bitmatrix, 8),
}


# ----------------------------------------------------- decode bitmatrix

def bitmatrix_decode_matrix(bm: np.ndarray, k: int, w: int,
                            erasures: Sequence[int],
                            survivors: Sequence[int]) -> np.ndarray:
    """Decode bitmatrix D: erased chunks' packet rows = D @ survivor
    packet rows (the role of jerasure_matrix_decode's inverted
    submatrix, in the GF(2) domain)."""
    n = (bm.shape[0] // w) + k
    full = np.zeros((n * w, k * w), dtype=np.uint8)
    full[:k * w] = np.kron(np.eye(k, dtype=np.uint8),
                           np.eye(w, dtype=np.uint8))
    full[k * w:] = bm
    surv = list(survivors)[:k]
    rows_s = np.concatenate([np.arange(s * w, (s + 1) * w) for s in surv])
    inv = gf2_inv(full[rows_s])          # (kw, kw): data = inv @ survivors
    rows_e = np.concatenate(
        [np.arange(e * w, (e + 1) * w) for e in erasures])
    return (full[rows_e] @ inv) & 1      # (|E|*w, kw)


# ---------------------------------------------------------- the plugin

class JerasureBitmatrix(ErasureCode):
    """liberation / blaum_roth / liber8tion coder: XOR schedules over
    chunk packets, batched on device."""

    # a coding BYTE is the XOR of input bytes from OTHER packet rows
    # (different intra-chunk offsets), so no per-byte-position GF(256)
    # repair matrix exists: the derived batch_decoder must refuse
    # immediately instead of paying 3 failing probe rounds per loss
    # pattern, and the RMW window path must use whole-object decode
    positionwise = False

    def init(self, profile: Mapping[str, str]) -> None:
        self.k = int(profile.get("k", 4))
        self.m = int(profile.get("m", 2))
        technique = profile.get("technique", "liberation")
        if technique not in BITMATRIX_TECHNIQUES:
            raise ValueError(f"not a bitmatrix technique: {technique!r}")
        if self.m != 2:
            raise ValueError(f"{technique} requires m=2, got m={self.m}")
        build, default_w = BITMATRIX_TECHNIQUES[technique]
        self.w = int(profile.get("w", default_w))
        self.technique = technique
        self.bitmatrix = build(self.k, self.w)  # (2w, kw)
        from ..ops.xor_kernels import make_xor_encoder
        self._make = make_xor_encoder
        self._encode_fn = make_xor_encoder(self.bitmatrix, self.w)
        self._decode_cache: dict[tuple, tuple] = {}

    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunks split into w equal packets, each CHUNK_ALIGNMENT-
        aligned (role of jerasure's w * packetsize alignment)."""
        align = self.k * self.w * CHUNK_ALIGNMENT
        padded = -(-stripe_width // align) * align if stripe_width else align
        return padded // self.k

    def _check_chunk(self, L: int) -> None:
        if L % self.w:
            raise ValueError(
                f"chunk length {L} not divisible into w={self.w} packets "
                f"(use get_chunk_size for aligned geometry)")

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, np.uint8)
        self._check_chunk(data.shape[-1])
        return np.asarray(self._encode_fn(data))

    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        erasures = tuple(sorted(want_to_read))
        survivors = tuple(sorted(
            i for i in chunks if i not in set(erasures))[:self.k])
        if len(survivors) < self.k:
            raise ValueError(
                f"need {self.k} chunks to decode, have {len(survivors)}")
        key = (erasures, survivors)
        hit = self._decode_cache.get(key)
        if hit is None:
            D = bitmatrix_decode_matrix(self.bitmatrix, self.k, self.w,
                                        erasures, survivors)
            hit = (self._make(D, self.w), survivors)
            self._decode_cache[key] = hit
        fn, surv = hit
        stack = np.stack([np.asarray(chunks[s], np.uint8) for s in surv],
                         axis=-2)
        self._check_chunk(stack.shape[-1])
        squeeze = stack.ndim == 2
        if squeeze:
            stack = stack[None]
        rec = np.asarray(fn(stack))  # (B, |E|, L)
        if squeeze:
            rec = rec[0]
        return {e: rec[..., i, :] for i, e in enumerate(erasures)}
