"""Clay — coupled-layer MSR regenerating code.

Rebuild of the reference's clay plugin (ref: src/erasure-code/clay/
ErasureCodeClay.{h,cc} + ErasureCodePluginClay.cc): an MDS code with
repair-bandwidth-optimal single-node recovery. Each of the k+m chunks is
split into q^t sub-chunks (q = d-k+1, t = ceil((k+m)/q)); nodes sit on a
q x t grid and sub-chunks are pairwise *coupled* across grid columns, so
repairing one chunk needs only beta = q^(t-1) = subchunks/q sub-chunks
from each of d helpers — total repair I/O d/(d-k+1) chunk-equivalents
instead of k full chunks.

Construction (FAST'18 Clay paper; same math the reference implements):

  * Grid: node i -> (x, y) = (i % q, i // q). Chunk ids map to nodes as
    [data 0..k-1, virtual k..k+nu-1, parity]: nu = q*t - (k+m) virtual
    nodes are all-zero chunks (code shortening), so chunk id k+j is node
    k+nu+j.
  * Planes: sub-chunk index z in [0, q^t) with base-q digits z_y.
  * Pairing: in plane z, node (x, y) with z_y != x pairs its sub-chunk
    with node (z_y, y)'s sub-chunk in plane z' = z with digit y set to x.
    Coupled C and uncoupled U values relate by the symmetric transform
        C1 = U1 + g*U2,   C2 = g*U1 + U2     (g = gamma, g^2 != 1)
    and unpaired sub-chunks (z_y == x) have C = U.
  * Per plane, the uncoupled symbols form a codeword of an (q*t, q*t - m)
    systematic MDS base code (jerasure reed_sol_van by default).

TPU-first design decision: instead of the reference's sequential
plane-by-plane "intersection score" schedule (ErasureCodeClay::
decode_layered), the whole decode/repair is LINEAR over GF(2^8), so we
symbolically solve the coupled system ONCE per erasure pattern and cache
a single (outputs x inputs) GF matrix. Applying it is then one batched
GF matmul on the MXU (ops.rs_kernels impl="mxu") — no data-dependent
control flow, perfectly XLA-shaped. Encode is "decode the parities".
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Mapping, Sequence

import numpy as np

from ..gf.numpy_ref import encode_ref, gf_mul
from ..gf.tables import inv_table, mul_table
from .interface import CHUNK_ALIGNMENT, ErasureCode
from .matrices import coding_matrix
from .registry import register


def _solve_affine(M: np.ndarray, K: np.ndarray,
                  A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Reduce outputs A @ v + B @ c to pure-input form D @ c, given the
    (consistent) constraint system M @ v = K @ c over GF(2^8).

    The system may be rank-deficient in v (e.g. Clay repair where a
    non-helper shares the failed node's grid column): free variables are
    fine as long as every output's dependence on them cancels — the MSR
    theory guarantees it for valid helper sets; we verify and raise if a
    free variable survives into an output.
    """
    M = np.array(M, dtype=np.uint8, copy=True)
    K = np.array(K, dtype=np.uint8, copy=True)
    neq, nv = M.shape
    mt = mul_table()
    invt = inv_table()
    row = 0
    pivots: list[tuple[int, int]] = []  # (col, row)
    for col in range(nv):
        pivot = row
        while pivot < neq and M[pivot, col] == 0:
            pivot += 1
        if pivot == neq:
            continue  # free variable
        if pivot != row:
            M[[row, pivot]] = M[[pivot, row]]
            K[[row, pivot]] = K[[pivot, row]]
        p = M[row, col]
        if p != 1:
            pinv = invt[p]
            M[row] = mt[pinv, M[row]]
            K[row] = mt[pinv, K[row]]
        f = M[:, col].copy()
        f[row] = 0
        nz = f.nonzero()[0]
        if nz.size:
            M[nz] ^= mt[f[nz, None], M[row][None, :]]
            K[nz] ^= mt[f[nz, None], K[row][None, :]]
        pivots.append((col, row))
        row += 1
        if row == neq:
            break
    # substitute pivot vars into the outputs:
    #   v_col = K[row] @ c  ^  (free-col part of M[row]) @ v_free
    A = np.array(A, dtype=np.uint8, copy=True)
    D = np.array(B, dtype=np.uint8, copy=True)
    for col, prow in pivots:
        f = A[:, col].copy()
        nz = f.nonzero()[0]
        if nz.size:
            A[nz] ^= mt[f[nz, None], M[prow][None, :]]
            D[nz] ^= mt[f[nz, None], K[prow][None, :]]
    if A.any():
        raise ValueError(
            "clay system underdetermined: outputs depend on unread data "
            "(invalid helper set, gamma, or base code)")
    return D


@register("clay")
class Clay(ErasureCode):
    """Coupled-layer MSR code: MDS with optimal single-failure repair."""

    DEFAULT_GAMMA = 2
    # bytes are coupled across the sub-chunk axis of each chunk, so a
    # sub-window of a chunk is not independently en/decodable
    positionwise = False

    def init(self, profile: Mapping[str, str]) -> None:
        self.k = int(profile.get("k", 4))
        self.m = int(profile.get("m", 2))
        self.d = int(profile.get("d", self.k + self.m - 1))
        if self.m < 2:
            raise ValueError(f"clay m={self.m}: need m >= 2")
        if not self.k + 1 <= self.d <= self.k + self.m - 1:
            raise ValueError(
                f"clay d={self.d} must be in [k+1={self.k + 1}, "
                f"k+m-1={self.k + self.m - 1}]")
        self.q = self.d - self.k + 1
        self.t = -(-(self.k + self.m) // self.q)
        self.nu = self.q * self.t - (self.k + self.m)
        self.sub_chunk_count = self.q ** self.t
        if self.sub_chunk_count > 1024:
            raise ValueError(
                f"clay k={self.k} m={self.m} d={self.d}: q^t = "
                f"{self.sub_chunk_count} sub-chunks exceeds the supported "
                f"1024 (matrix-cache construction cost)")
        self.gamma = int(profile.get("gamma", self.DEFAULT_GAMMA))
        if self.gamma in (0, 1) or gf_mul(self.gamma, self.gamma) == 1:
            raise ValueError(f"clay gamma={self.gamma}: need gamma^2 != 1")
        # base MDS code over the q*t grid symbols: k+nu data + m parity
        # (ref: ErasureCodeClay uses a jerasure/isa MDS coder the same way)
        technique = profile.get("technique", "reed_sol_van")
        self.base_matrix = coding_matrix(technique, self.k + self.nu, self.m)
        self.technique = technique
        self.impl = profile.get("impl", "mxu")
        nn = self.q * self.t
        # parity-check H = [C | I_m] over node order [data, virtual, parity]
        self.H = np.concatenate(
            [self.base_matrix, np.eye(self.m, dtype=np.uint8)], axis=1)
        assert self.H.shape == (self.m, nn)
        self._affine_cache: dict[tuple, tuple] = {}
        self._fn_cache: dict[int, object] = {}

    # -- grid / plane coordinate helpers ----------------------------------

    def _node_of_chunk(self, c: int) -> int:
        return c if c < self.k else c + self.nu

    def _chunk_of_node(self, n: int) -> int | None:
        """Inverse of _node_of_chunk; None for virtual nodes."""
        if n < self.k:
            return n
        if n < self.k + self.nu:
            return None
        return n - self.nu

    def _xy(self, n: int) -> tuple[int, int]:
        return n % self.q, n // self.q

    def _digit(self, z: int, y: int) -> int:
        return (z // self.q ** y) % self.q

    def _set_digit(self, z: int, y: int, v: int) -> int:
        return z + (v - self._digit(z, y)) * self.q ** y

    # -- geometry ----------------------------------------------------------

    def get_chunk_size(self, stripe_width: int) -> int:
        # chunk splits into q^t sub-chunks, each a full TPU lane wide
        sub_align = CHUNK_ALIGNMENT * self.sub_chunk_count
        align = self.k * sub_align
        padded = -(-stripe_width // align) * align
        return padded // self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_count

    # -- symbolic affine construction --------------------------------------
    #
    # Expressions are (var_vec, const_vec) uint8 rows: a GF(2^8) linear
    # combination of the unknown U values (vars) and the known coupled
    # sub-chunks read as input (consts). Everything below manipulates
    # those rows; the data never appears until apply time.

    def _u_expr(self, n: int, z: int, var_idx, const_idx, nv: int, nc: int,
                is_var) -> tuple[np.ndarray, np.ndarray]:
        """Uncoupled symbol U(n, z) of a KNOWN node as an affine row."""
        g = self.gamma
        invdet = int(inv_table()[1 ^ gf_mul(g, g)])  # 1/(1+g^2)
        V = np.zeros(nv, np.uint8)
        C = np.zeros(nc, np.uint8)
        x, y = self._xy(n)
        zy = self._digit(z, y)

        def cconst(node, plane, coef):
            ci = const_idx.get((node, plane))
            if ci is not None:  # virtual/zero chunks simply drop out
                C[ci] ^= np.uint8(coef)

        if zy == x:  # unpaired: C == U
            cconst(n, z, 1)
            return V, C
        p = y * self.q + zy
        zp = self._set_digit(z, y, x)
        if is_var(p):
            # partner U is unknown: U_self = C_self + g * U_partner
            cconst(n, z, 1)
            V[var_idx[(p, zp)]] ^= np.uint8(g)
        else:
            # both coupled values known: U_self = (C_self + g*C_partner)/(1+g^2)
            cconst(n, z, invdet)
            cconst(p, zp, gf_mul(g, invdet))
        return V, C

    def _affine_decode(self, erased_chunks: tuple[int, ...],
                       avail_chunks: tuple[int, ...]) -> tuple[np.ndarray, list]:
        """Full-decode matrix: erased chunks' coupled bytes from survivors.

        Returns (D, inputs) with D: (|E|*planes, len(inputs)*planes) and
        inputs the chunk ids consumed, so that
        stacked_erased_subchunks = D @ stacked_input_subchunks.
        Also used for encode (erased = the m parity chunks).
        """
        key = ("dec", erased_chunks, avail_chunks)
        hit = self._affine_cache.get(key)
        if hit is not None:
            return hit
        nn, P = self.q * self.t, self.sub_chunk_count
        E = [self._node_of_chunk(c) for c in erased_chunks]
        eset = set(E)
        inputs = list(avail_chunks)
        in_nodes = [self._node_of_chunk(c) for c in inputs]
        var_idx = {(n, z): i * P + z for i, n in enumerate(E) for z in range(P)}
        const_idx = {(n, z): i * P + z
                     for i, n in enumerate(in_nodes) for z in range(P)}
        nv, nc = len(E) * P, len(inputs) * P
        is_var = eset.__contains__
        known = [n for n in range(nn) if n not in eset]
        # cache U rows for known nodes per (node, plane)
        u_rows = {}
        for n in known:
            for z in range(P):
                u_rows[(n, z)] = self._u_expr(n, z, var_idx, const_idx,
                                              nv, nc, is_var)
        M = np.zeros((self.m * P, nv), np.uint8)
        K = np.zeros((self.m * P, nc), np.uint8)
        mt = mul_table()
        for z in range(P):
            for r in range(self.m):
                eq = z * self.m + r
                for n in range(nn):
                    h = int(self.H[r, n])
                    if h == 0:
                        continue
                    if n in eset:
                        M[eq, var_idx[(n, z)]] ^= np.uint8(h)
                    else:
                        V, C = u_rows[(n, z)]
                        M[eq] ^= mt[h, V]
                        K[eq] ^= mt[h, C]
        # coupled output expressions over (vars, consts), then eliminate
        g = self.gamma
        one_g2 = 1 ^ gf_mul(g, g)
        A = np.zeros((len(E) * P, nv), np.uint8)
        B = np.zeros((len(E) * P, nc), np.uint8)
        for i, n in enumerate(E):
            x, y = self._xy(n)
            for z in range(P):
                out = i * P + z
                zy = self._digit(z, y)
                if zy == x:
                    A[out, var_idx[(n, z)]] = 1
                    continue
                p = y * self.q + zy
                zp = self._set_digit(z, y, x)
                if p in eset:
                    # C = U + g * U_partner (both unknowns)
                    A[out, var_idx[(n, z)]] ^= np.uint8(1)
                    A[out, var_idx[(p, zp)]] ^= np.uint8(g)
                else:
                    # C = (1+g^2) U + g * C_partner
                    A[out, var_idx[(n, z)]] = one_g2
                    ci = const_idx.get((p, zp))
                    if ci is not None:
                        B[out, ci] ^= np.uint8(g)
        D = _solve_affine(M, K, A, B)
        result = (D, inputs)
        self._affine_cache[key] = result
        return result

    def _repair_planes(self, failed_chunk: int) -> list[int]:
        """Planes each helper must send for a single-chunk repair."""
        x0, y0 = self._xy(self._node_of_chunk(failed_chunk))
        return [z for z in range(self.sub_chunk_count)
                if self._digit(z, y0) == x0]

    def _affine_repair(self, failed_chunk: int,
                       helper_chunks: tuple[int, ...]) -> tuple[np.ndarray, list]:
        """Repair matrix: failed chunk's full sub-chunks from the d
        helpers' repair-plane sub-chunks only (the MSR bandwidth win)."""
        key = ("rep", failed_chunk, helper_chunks)
        hit = self._affine_cache.get(key)
        if hit is not None:
            return hit
        nn, P, q = self.q * self.t, self.sub_chunk_count, self.q
        nstar = self._node_of_chunk(failed_chunk)
        x0, y0 = self._xy(nstar)
        helpers = [self._node_of_chunk(c) for c in helper_chunks]
        hset = set(helpers)
        rplanes = self._repair_planes(failed_chunk)
        rpos = {z: i for i, z in enumerate(rplanes)}
        nrp = len(rplanes)  # q^(t-1)
        virt = set(range(self.k, self.k + self.nu))
        nonhelp = [n for n in range(nn)
                   if n != nstar and n not in hset and n not in virt]
        # vars: U(failed, every plane) + U(non-helper, repair planes)
        var_idx: dict[tuple[int, int], int] = {}
        for z in range(P):
            var_idx[(nstar, z)] = z
        base = P
        for j, n in enumerate(nonhelp):
            for z in rplanes:
                var_idx[(n, z)] = base + j * nrp + rpos[z]
        nv = P + len(nonhelp) * nrp
        const_idx = {(n, z): i * nrp + rpos[z]
                     for i, n in enumerate(helpers) for z in rplanes}
        nc = len(helpers) * nrp
        unknown = {nstar, *nonhelp}
        is_var = unknown.__contains__
        mt = mul_table()
        M = np.zeros((self.m * nrp, nv), np.uint8)
        K = np.zeros((self.m * nrp, nc), np.uint8)
        u_rows = {(n, z): self._u_expr(n, z, var_idx, const_idx, nv, nc, is_var)
                  for n in range(nn) if n not in unknown for z in rplanes}
        for zi, z in enumerate(rplanes):
            for r in range(self.m):
                eq = zi * self.m + r
                for n in range(nn):
                    h = int(self.H[r, n])
                    if h == 0:
                        continue
                    if n in unknown:
                        M[eq, var_idx[(n, z)]] ^= np.uint8(h)
                    else:
                        V, C = u_rows[(n, z)]
                        M[eq] ^= mt[h, V]
                        K[eq] ^= mt[h, C]
        g = self.gamma
        one_g2 = 1 ^ gf_mul(g, g)
        A = np.zeros((P, nv), np.uint8)
        B = np.zeros((P, nc), np.uint8)
        for z in range(P):
            zy = self._digit(z, y0)
            if zy == x0:  # repair plane: failed node is unpaired there
                A[z, var_idx[(nstar, z)]] = 1
                continue
            p = y0 * q + zy
            zp = self._set_digit(z, y0, x0)  # a repair plane
            if p in virt:
                A[z, var_idx[(nstar, z)]] = one_g2
            elif p in hset:
                A[z, var_idx[(nstar, z)]] = one_g2
                B[z, const_idx[(p, zp)]] ^= np.uint8(g)
            else:  # partner is a non-helper: its repair-plane U is a var
                A[z, var_idx[(nstar, z)]] ^= np.uint8(1)
                A[z, var_idx[(p, zp)]] ^= np.uint8(g)
        D = _solve_affine(M, K, A, B)
        result = (D, list(helper_chunks))
        self._affine_cache[key] = result
        return result

    def repair_plan_matrix(self, failed_chunk: int,
                           helper_chunks: Sequence[int]
                           ) -> tuple[np.ndarray, list[int]]:
        """Public face of the cached affine repair solve: returns
        (D, repair_planes) such that stacking the helpers' repair-plane
        sub-chunks as (B, d*len(planes), s) and applying the static GF
        matrix D yields the failed chunk's full (B, q^t, s) sub-chunks.
        Lets callers (the sharded mesh path) run the bandwidth-optimal
        MSR repair as one device matrix-apply."""
        D, _ = self._affine_repair(int(failed_chunk), tuple(helper_chunks))
        return D, self._repair_planes(int(failed_chunk))

    # -- device fast path ---------------------------------------------------

    def batch_decoder(self, erasures: Sequence[int],
                      survivors: Sequence[int]):
        """Fused single-chunk MSR repair: one jittable fn mapping the
        full helper stack (B, d, sl) to the rebuilt chunk (B, 1, sl).
        The repair-plane selection (each helper contributes only
        beta = sl/(d-k+1) bytes of GF math) happens ON DEVICE, so the
        whole repair is one launch — the bandwidth-optimal plan from
        repair_plan_matrix without the host-side sub-chunk staging.
        Multi-loss falls back (returns None → decode_chunks). Ref:
        ErasureCodeClay::repair / minimum_to_decode sub-chunk ranges."""
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)
        if len(erasures) != 1 or len(survivors) != self.d \
                or self.impl == "ref":   # ref = numpy oracle, no
            return None                  # device path to fuse into
        key = ("bd", erasures, survivors)
        fn = self._affine_cache.get(key)
        if fn is None:
            from ..ops.rs_kernels import make_encoder
            lost = erasures[0]
            D, planes = self.repair_plan_matrix(lost, survivors)
            mfn = make_encoder(D, self.impl)
            P = self.sub_chunk_count
            beta = len(planes)
            planes_idx = np.asarray(planes)

            def fn(stack):                      # (B, H, sl) u8
                B, H_, sl = stack.shape
                if sl % P:
                    raise ValueError(
                        f"shard length {sl} not divisible into "
                        f"{P} sub-chunks")
                s = sl // P
                sub = stack.reshape(B, H_, P, s)[:, :, planes_idx, :]
                out = mfn(sub.reshape(B, H_ * beta, s))  # (B, P, s)
                return out.reshape(B, 1, sl)
            self._affine_cache[key] = fn
        return fn

    def range_batch_decoder(self, erasures: Sequence[int],
                            survivors: Sequence[int]):
        """Sub-chunk-granular MSR repair for the range-read wire path:
        one jittable fn mapping the helpers' SHIPPED repair planes
        (B, d, rl) — rl = beta * sub_size, each row the concatenation
        of that helper's repair planes in ascending plane order — to
        the rebuilt chunk (B, 1, q^t * sub_size). Unlike batch_decoder
        the plane selection already happened at the SOURCE (the readv
        range list), so the wire moved only beta/q^t of each helper
        row; the device just applies the cached repair matrix."""
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)
        if len(erasures) != 1 or len(survivors) != self.d \
                or self.impl == "ref":
            return None
        key = ("bdr", erasures, survivors)
        fn = self._affine_cache.get(key)
        if fn is None:
            from ..ops.rs_kernels import make_encoder
            D, planes = self.repair_plan_matrix(erasures[0], survivors)
            mfn = make_encoder(D, self.impl)
            beta = len(planes)
            P = self.sub_chunk_count

            def fn(stack):                  # (B, H, rl) u8
                B, H_, rl = stack.shape
                if rl % beta:
                    raise ValueError(
                        f"range row length {rl} not divisible into "
                        f"{beta} repair planes")
                s = rl // beta
                # helper-major, plane-minor — the repair matrix's
                # input order (const_idx in _affine_repair)
                out = mfn(stack.reshape(B, H_ * beta, s))  # (B, P, s)
                return out.reshape(B, 1, P * s)
            self._affine_cache[key] = fn
        return fn

    def range_decode_program_key(self, erasures: Sequence[int],
                                 survivors: Sequence[int]):
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)
        if self.range_batch_decoder(erasures, survivors) is None:
            return None
        D, planes = self.repair_plan_matrix(erasures[0], survivors)
        return ("clayrng", D.tobytes(), D.shape, tuple(planes),
                self.impl)

    # -- data paths ---------------------------------------------------------

    def _apply(self, D: np.ndarray, stacked: np.ndarray) -> np.ndarray:
        """(B, nin, sub) -> (B, nout, sub) via the cached GF matrix."""
        if self.impl == "ref":
            return encode_ref(D, stacked)
        from ..ops.rs_kernels import make_encoder
        fid = id(D)
        fn = self._fn_cache.get(fid)
        if fn is None:
            fn = make_encoder(D, self.impl)
            self._fn_cache[fid] = fn
        return np.asarray(fn(stacked))

    def _split(self, chunk: np.ndarray) -> np.ndarray:
        """(..., L) chunk -> (..., q^t, sub) sub-chunks."""
        L = chunk.shape[-1]
        P = self.sub_chunk_count
        if L % P:
            raise ValueError(f"chunk size {L} not divisible into {P} sub-chunks")
        return chunk.reshape(chunk.shape[:-1] + (P, L // P))

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, np.uint8)
        B, k, L = data.shape
        assert k == self.k
        parity_ids = tuple(range(self.k, self.k + self.m))
        D, inputs = self._affine_decode(parity_ids, tuple(range(self.k)))
        sub = self._split(data)  # (B, k, P, s)
        stacked = sub.reshape(B, self.k * self.sub_chunk_count, -1)
        out = self._apply(D, stacked)  # (B, m*P, s)
        return out.reshape(B, self.m, L)

    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        want = tuple(sorted(set(want_to_read)))
        passthrough = {c: np.asarray(chunks[c], np.uint8)
                       for c in want if c in chunks}
        missing = tuple(c for c in want if c not in chunks)
        if not missing:
            return passthrough
        have = tuple(sorted(chunks))
        n = self.get_chunk_count()
        # the coupled system ties every chunk's sub-chunks together, so a
        # chunk neither wanted nor provided must be treated as ERASED too —
        # silently assuming it zero would corrupt the solve. Single-failure
        # reads that provide only the d chosen helpers (the
        # minimum_to_decode contract) go through the repair path instead.
        erased = tuple(sorted(set(range(n)) - set(have)))
        if len(erased) > self.m:
            if len(missing) == 1 and len(have) >= self.d:
                rebuilt = self.repair_from_chunks(missing[0], dict(chunks))
                return {**passthrough, missing[0]: rebuilt}
            raise ValueError(
                f"cannot decode {sorted(want)}: {len(erased)} chunks "
                f"unavailable (m={self.m}); provide more survivors")
        D, inputs = self._affine_decode(erased, have)
        arrs = [np.asarray(chunks[c], np.uint8) for c in inputs]
        squeeze = arrs[0].ndim == 1
        if squeeze:
            arrs = [a[None] for a in arrs]
        B, L = arrs[0].shape
        sub = np.stack([self._split(a) for a in arrs], axis=1)
        stacked = sub.reshape(B, len(inputs) * self.sub_chunk_count, -1)
        out = self._apply(D, stacked).reshape(B, len(erased), L)
        if squeeze:
            out = out[0]
        wanted = set(missing)
        solved = {e: out[..., i, :] for i, e in enumerate(erased) if e in wanted}
        return {**passthrough, **solved}

    # -- repair (the point of Clay) ----------------------------------------

    def minimum_to_decode(self, want_to_read: Sequence[int],
                          available: Sequence[int]) -> set[int]:
        """Single erasure: d helpers (sub-chunk ranges via
        minimum_to_decode_subchunks). Multi erasure: all survivors
        (the coupled decode consumes every available chunk)."""
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        if not missing:
            return want
        if len(missing) == 1:
            helpers = sorted(avail - want)
            if len(helpers) < self.d:
                # degraded below d: fall back to full decode if possible
                if len(avail) >= self.get_chunk_count() - self.m:
                    return set(avail)
                raise ValueError(
                    f"clay repair needs {self.d} helpers, have {len(helpers)}")
            failed = next(iter(missing))
            return set(self._pick_helpers(failed, helpers)) | (want & avail)
        survivors = avail - want
        if len(survivors) < self.get_chunk_count() - self.m:
            raise ValueError(
                f"cannot decode {sorted(missing)} from {sorted(avail)}")
        return set(avail)

    def _pick_helpers(self, failed_chunk: int,
                      candidates: Sequence[int],
                      costs: Mapping[int, int] | None = None) -> list[int]:
        """Choose d helpers for a single-chunk repair.

        The failed node's non-repair-plane sub-chunks are coupled only
        with its grid-COLUMN mates, so every surviving same-column chunk
        must be a helper or the repair system is underdetermined; the
        remaining slots are filled with the cheapest surviving ids
        (lowest id when no costs are given).
        """
        _, y0 = self._xy(self._node_of_chunk(failed_chunk))
        cand = sorted(set(candidates) - {failed_chunk})
        mates = [c for c in cand
                 if self._xy(self._node_of_chunk(c))[1] == y0]
        rest = [c for c in cand if c not in set(mates)]
        if costs:
            rest.sort(key=lambda c: (int(costs.get(c, 0)), c))
        # at most q-1 = d-k column mates survive, so mates never fill d
        helpers = sorted(mates + rest[:self.d - len(mates)])
        if len(helpers) < self.d:
            raise ValueError(f"need {self.d} helpers, have {len(helpers)}")
        return helpers

    def minimum_to_decode_with_cost(self, want_to_read: Sequence[int],
                                    available: Mapping[int, int]) -> set[int]:
        """Cost-aware override: the MDS default's 'k cheapest' is wrong
        for a coupled code (single-loss repair needs d helpers
        INCLUDING every surviving grid-column mate; multi-loss consumes
        every survivor), so pick structurally and spend the costs only
        on the free helper slots."""
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        if not missing:
            return want
        if len(missing) == 1:
            helpers = sorted(avail - want)
            if len(helpers) >= self.d:
                failed = next(iter(missing))
                return set(self._pick_helpers(failed, helpers,
                                              costs=available)) \
                    | (want & avail)
        return self.minimum_to_decode(sorted(want), sorted(avail))

    def minimum_to_decode_subchunks(
            self, failed_chunk: int,
            available: Sequence[int]) -> dict[int, list[int]]:
        """{helper chunk id: sub-chunk (plane) indices to read} for one
        failed chunk — beta = q^(t-1) planes per helper (ref:
        ErasureCodeClay::minimum_to_decode returning sub-chunk ranges)."""
        helpers = self._pick_helpers(failed_chunk, available)
        planes = self._repair_planes(failed_chunk)
        return {h: list(planes) for h in helpers}

    def repair_chunk(self, failed_chunk: int,
                     subchunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Rebuild one chunk from helpers' repair-plane sub-chunks.

        subchunks: {helper chunk id: (..., beta, sub_size) uint8} holding
        ONLY the repair planes (order = minimum_to_decode_subchunks).
        Returns the full (..., chunk_size) failed chunk.
        """
        helpers = tuple(sorted(subchunks))
        if len(helpers) != self.d:
            raise ValueError(f"need exactly d={self.d} helpers, got {len(helpers)}")
        D, order = self._affine_repair(failed_chunk, helpers)
        arrs = [np.asarray(subchunks[h], np.uint8) for h in order]
        squeeze = arrs[0].ndim == 2
        if squeeze:
            arrs = [a[None] for a in arrs]
        B, beta, s = arrs[0].shape
        stacked = np.stack(arrs, axis=1).reshape(B, len(order) * beta, s)
        out = self._apply(D, stacked)  # (B, P, s)
        out = out.reshape(B, self.sub_chunk_count * s)
        if squeeze:
            out = out[0]
        return out

    def repair_from_chunks(self, failed_chunk: int,
                           chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Convenience: slice repair planes out of full helper chunks and
        repair — still touching only beta/q^t of each helper's bytes."""
        need = self.minimum_to_decode_subchunks(failed_chunk, list(chunks))
        picked = {}
        for h, planes in need.items():
            sub = self._split(np.asarray(chunks[h], np.uint8))
            picked[h] = sub[..., planes, :]
        return self.repair_chunk(failed_chunk, picked)
