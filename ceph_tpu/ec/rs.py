"""Reed-Solomon coder — the jerasure/isa plugin equivalent.

Covers the reference's `jerasure` plugin techniques reed_sol_van /
cauchy_orig / cauchy_good (ref: src/erasure-code/jerasure/
ErasureCodeJerasure.cc) and, by the same contract, the `isa` plugin
(ref: src/erasure-code/isa/ErasureCodeIsa.cc — same math, different CPU
backend; here there is only one backend: the TPU kernels).

Encode: parity = C (GF@) data on device with a static matrix.
Decode: invert the surviving k x k submatrix on host (tiny, like
jerasure_matrix_decode does) and run the same static-matrix device kernel
with the decode matrix; decode matrices are cached per erasure pattern.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

from ..gf.numpy_ref import decode_matrix
from ..ops.rs_kernels import DEFAULT_IMPL, make_encoder
from .interface import ErasureCode
from .matrices import coding_matrix
from .registry import register


class ReedSolomon(ErasureCode):
    """MDS Reed-Solomon over GF(2^8), batched on TPU."""

    def init(self, profile: Mapping[str, str]) -> None:
        self.k = int(profile.get("k", 7))
        self.m = int(profile.get("m", 3))
        technique = profile.get("technique", "reed_sol_van")
        self.technique = technique
        self.impl = profile.get("impl", DEFAULT_IMPL)
        from ..ops.rs_kernels import _IMPLS
        if self.impl not in _IMPLS:
            raise ValueError(f"unknown impl {self.impl!r}; "
                             f"available: {sorted(_IMPLS)}")
        if self.k < 1 or self.m < 1 or self.k + self.m > 256:
            raise ValueError(f"bad geometry k={self.k} m={self.m} (w=8)")
        self.matrix = coding_matrix(technique, self.k, self.m)
        self._encode_fn = make_encoder(self.matrix, self.impl)
        self._decode_cache: dict[tuple[tuple[int, ...], tuple[int, ...]], tuple] = {}

    def encode_chunks(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode_fn(np.asarray(data, np.uint8)))

    def delta_matrix(self, touched):
        # exact: the parity-delta matrix IS the coding matrix's
        # touched columns (no probe needed; bit-parity with the probe
        # path is pinned by tests/test_rmw_delta.py)
        touched = tuple(int(t) for t in touched)
        if any(not 0 <= t < self.k for t in touched):
            raise ValueError(f"touched rows must be in [0, {self.k})")
        return np.ascontiguousarray(self.matrix[:, list(touched)])

    def _decoder_for(self, erasures: tuple[int, ...], survivors: tuple[int, ...]):
        key = (erasures, survivors)
        hit = self._decode_cache.get(key)
        if hit is None:
            D = decode_matrix(self.matrix, list(erasures), self.k, list(survivors))
            hit = (make_encoder(D, self.impl), survivors)
            self._decode_cache[key] = hit
        return hit

    def batch_decoder(self, erasures: Sequence[int],
                      survivors: Sequence[int]):
        # orders are honored as given (the interface contract: stack
        # rows arrive in `survivors` order, outputs in `erasures`
        # order); only the first k survivors are consumed
        erasures = tuple(erasures)
        survivors = tuple(survivors)[:self.k]
        if len(survivors) < self.k:
            return None
        fn, _ = self._decoder_for(erasures, survivors)
        return fn

    def decode_program_key(self, erasures: Sequence[int],
                           survivors: Sequence[int]):
        # the compiled program is a pure function of (coding matrix,
        # erasure/survivor pattern, impl) — every PG backend with the
        # same profile shares one program per pattern
        erasures = tuple(int(e) for e in erasures)
        survivors = tuple(int(s) for s in survivors)[:self.k]
        if len(survivors) < self.k:
            return None
        return ("rs", self.matrix.tobytes(), self.impl, erasures,
                survivors)

    def decode_chunks(self, want_to_read: Sequence[int],
                      chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        erasures = tuple(sorted(want_to_read))
        survivors = tuple(sorted(i for i in chunks if i not in set(erasures))[:self.k])
        if len(survivors) < self.k:
            raise ValueError(
                f"need {self.k} chunks to decode, have {len(survivors)}")
        fn, surv = self._decoder_for(erasures, survivors)
        stack = np.stack([np.asarray(chunks[s], np.uint8) for s in surv], axis=-2)
        squeeze = stack.ndim == 2
        if squeeze:
            stack = stack[None]
        rec = np.asarray(fn(stack))  # (B, E, L)
        if squeeze:
            rec = rec[0]
        return {e: rec[..., i, :] for i, e in enumerate(erasures)}


@register("tpu_rs")
@register("jerasure")  # accept reference profile strings unchanged
def _jerasure_factory(profile: Mapping[str, str]) -> ErasureCode:
    """The jerasure plugin face: matrix techniques go to ReedSolomon,
    bitmatrix/schedule techniques (liberation, blaum_roth, liber8tion)
    to the XOR-schedule coder (ref: ErasureCodeJerasure.cc technique
    dispatch in ErasureCodePluginJerasure::factory)."""
    from .bitmatrix import BITMATRIX_TECHNIQUES, JerasureBitmatrix
    technique = dict(profile).get("technique", "reed_sol_van")
    if technique in BITMATRIX_TECHNIQUES:
        return JerasureBitmatrix(profile)
    return ReedSolomon(profile)


@register("isa")
class IsaReedSolomon(ReedSolomon):
    """The isa plugin's coder (ref: src/erasure-code/isa/ErasureCodeIsa.cc
    ErasureCodeIsaDefault, techniques reed_sol_van / cauchy).

    Distinct from the jerasure plugin: ISA-L's reed_sol_van builds its
    matrix as gf_gen_rs_matrix does (row r = powers of 2^r), which is a
    DIFFERENT byte format from jerasure's column-reduced Vandermonde.
    That construction is not MDS for every geometry, so init() verifies
    decodability for small codes and rejects known-degenerate setups.
    """

    # exhaustive MDS verification is C(k+m, m) tiny matrix inversions;
    # above this budget reed_sol_van is refused rather than trusted.
    _MDS_CHECK_BUDGET = 200_000

    def init(self, profile: Mapping[str, str]) -> None:
        prof = dict(profile)
        technique = prof.get("technique", "reed_sol_van")
        if technique == "reed_sol_van":
            prof["technique"] = "isa_reed_sol_van"
        elif technique == "cauchy":
            prof["technique"] = "isa_cauchy"
        else:
            raise ValueError(f"isa plugin technique must be reed_sol_van or "
                             f"cauchy, got {technique!r}")
        super().init(prof)
        self.technique = technique
        if technique == "reed_sol_van":
            # ISA-L's gf_gen_rs_matrix construction is NOT MDS for every
            # geometry; accepting one would advertise fault tolerance that
            # fails at decode time. Verify exhaustively, or refuse when
            # the pattern space is too large to verify.
            from math import comb

            from .matrices import is_mds
            if comb(self.k + self.m, self.m) > self._MDS_CHECK_BUDGET:
                raise ValueError(
                    f"isa reed_sol_van k={self.k} m={self.m}: MDS property "
                    f"cannot be verified exhaustively at this size and the "
                    f"construction is not guaranteed MDS; use "
                    f"technique=cauchy (always MDS)")
            if not is_mds(self.matrix, self.k):
                raise ValueError(
                    f"isa reed_sol_van matrix is not MDS for k={self.k} "
                    f"m={self.m}; use technique=cauchy")
