"""ceph_tpu — a TPU-native erasure-coding / placement / integrity framework.

From-scratch rebuild of the capabilities of the reference's storage hot
paths (sashakot/ceph — see SURVEY.md): GF(2^8) Reed-Solomon / LRC / Clay
erasure codes as batched XLA/Pallas kernels, vectorized CRUSH placement,
crc32c/xxhash checksumming, and an ECBackend-style device-side recovery
pipeline — designed TPU-first (jax/pjit/shard_map), not ported.
"""

__version__ = "0.1.0"
