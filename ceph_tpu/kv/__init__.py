"""kv — the ordered-KV metadata plane (the RocksDB/BlueFS role).

`KeyValueDB` is the interface surface (ref: src/kv/KeyValueDB.h — the
abstraction BlueStore programs RocksDB through: prefixed key spaces,
atomic transaction batches, ordered prefix-bounded iterators,
snapshots). `TinDB` is the bundled LSM-lite implementation: in-memory
memtable over a crc32c-sealed WAL, sorted immutable segments with
index blocks, leveled compaction, and SIGKILL-real remount replay.
"""

from .interface import KeyValueDB, KVTransaction, combine_key, split_key  # noqa: F401
from .tindb import TinDB, TinDBCorruption, host_crc32c  # noqa: F401
