"""TinDB — LSM-lite ordered KV store (the RocksDB-over-BlueFS role).

The load-bearing slice of the reference's metadata engine (ref:
src/kv/RocksDBStore.cc behaviorally; durability contract ref:
BlueStore::_kv_sync_thread — a metadata mutation is committed when its
WAL record is on disk, everything else is rebuildable):

* MEMTABLE. Mutations land in a plain dict (None value = tombstone);
  ordered reads sort the memtable keys on demand. The memtable is
  BOUNDED (`memtable_max_bytes`), so that sort is O(bounded), never
  O(database) — the property the listing benchmark measures.
* WAL. Every submit_transaction appends ONE length-prefixed,
  crc32c-sealed record (same `<magic, seq, len> body crc` framing as
  the r5 TinStore WAL, crc via ceph_tpu/csum's raw-register crc32c)
  and flushes before the memtable mutates. A batch is wholly in the
  WAL or absent; a torn tail append is truncated at mount; a bad crc
  FOLLOWED by more records is real corruption and fails the mount.
* SEGMENTS. When the memtable exceeds its budget (or on flush()),
  its sorted contents — tombstones included, they must mask older
  segments — are written to an immutable `seg-*.tdb` file: sorted
  entries, a sparse index block (every Nth key → file offset) for
  point/seek reads, and a whole-file crc32c seal. Then the MANIFEST
  is atomically replaced (covered-seq advances) and the WAL resets.
* LEVELS + COMPACTION. The MANIFEST holds a list of levels; level 0
  collects flush segments (newest last, overlapping allowed), deeper
  levels hold one merged run each. When a level reaches `fanout`
  segments, the whole level is k-way merged with the level below it
  into one new run (newer source wins per key); tombstones are
  dropped only when the output lands on the deepest level (nothing
  older left to mask). Readers never block: segments are immutable,
  and replaced segments keep serving open snapshots through their
  still-open fds after the files are unlinked.
* RECOVERY. mount() = read MANIFEST (crc-sealed, atomically renamed)
  → open+verify its segments → delete orphan segment files (a crash
  between segment write and manifest swap leaves those) → replay WAL
  records with seq > covered_seq into the memtable. Crash anywhere
  = exact state at the last committed batch.
* SNAPSHOTS. snapshot() freezes (memtable copy, segment list) —
  point-in-time get/iterate that later writes/compactions can't
  disturb (the rocksdb GetSnapshot role).
* FSCK. TinDB.fsck(path) audits offline: manifest seal, every
  segment's seal + strict key ordering + index-block consistency,
  WAL chain, and reports orphan segment files — mutating nothing.

Crash-injection for the chaos tests: `db._fault = fn` gets called
with a named point (e.g. "compact.segments-written") and may raise —
the TinStore/TinDB chaos cases use it to SIGKILL mid-compaction and
prove remount+fsck come back clean on either side of the swap.
"""

from __future__ import annotations

import heapq
import os
import struct
import threading

from .interface import (KeyValueDB, KVTransaction, combine_key,
                        prefix_range)

_REC_MAGIC = 0x544E4952            # "RINT" — same framing as the r5
_REC_HDR = struct.Struct("<IQI")   # TinStore WAL (magic, seq, body_len)
_SEG_MAGIC = 0x47455354            # "TSEG"
_SEG_HDR = struct.Struct("<II")    # magic, version
_SEG_ENTRY = struct.Struct("<IBI")  # klen, flags, vlen
_SEG_FOOTER = struct.Struct("<QQI")  # index_off, n_entries, seal crc
_SEG_VERSION = 1
_INDEX_EVERY = 64
_TOMBSTONE = 1


class TinDBCorruption(IOError):
    """Checksum/structure mismatch in the KV plane (-EIO analog)."""


_crc_impl = None


def host_crc32c(data, seed: int = 0xFFFFFFFF) -> int:
    """Raw-register crc32c (seed 0xFFFFFFFF, no final inversion) —
    native C fast path, ceph_tpu.csum pure-python fallback. Chainable
    through `seed` for incremental seals."""
    global _crc_impl
    if _crc_impl is None:
        try:
            from ..native import lib
            L = lib()

            def _crc_impl(b, s, _L=L):
                return int(_L.ec_crc32c(s, b, len(b)))
        except Exception:          # no toolchain: correctness over speed
            from ..csum.reference import ceph_crc32c

            def _crc_impl(b, s):
                return int(ceph_crc32c(s, b))
    return _crc_impl(bytes(data), seed)


# -- WAL record framing (shared scan used by TinDB and legacy replay) ---------

def append_wal_record(f, seq: int, body: bytes, o_dsync: bool) -> None:
    rec = _REC_HDR.pack(_REC_MAGIC, seq, len(body)) + body
    rec += struct.pack("<I", host_crc32c(rec))
    f.write(rec)
    f.flush()                      # survives process kill
    if o_dsync:
        os.fsync(f.fileno())       # survives machine crash


def _valid_record_after(raw: bytes, start: int) -> bool:
    """Is there any crc-valid record at/after `start`? Resyncs on the
    magic. This is what tells a corrupt TAIL (recoverable — the torn-
    append class: truncate to the last sealed record) from MID-LOG
    corruption (fatal — later sealed records would be silently
    dropped by a truncation)."""
    magic = struct.pack("<I", _REC_MAGIC)
    n = len(raw)
    pos = raw.find(magic, start)
    while pos != -1:
        if pos + _REC_HDR.size + 4 <= n:
            _m, _seq, blen = _REC_HDR.unpack_from(raw, pos)
            end = pos + _REC_HDR.size + blen + 4
            if end <= n:
                (crc,) = struct.unpack_from("<I", raw, end - 4)
                if host_crc32c(raw[pos:end - 4]) == crc:
                    return True
        pos = raw.find(magic, pos + 1)
    return False


def scan_wal(path: str):
    """Yield (seq, body) for every valid record; StopIteration.value
    is the (good_bytes, torn_tail, error) triple. A record that fails
    its seal (bad magic, bad crc, short) is a TORN TAIL when no valid
    record follows it — a torn or partially-persisted last append,
    recovered by truncating to the last sealed record — and mid-log
    CORRUPTION (error, nothing truncated) when sealed records follow:
    truncating there would silently drop committed data."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return 0, False, None
    off = 0
    n = len(raw)
    while off < n:
        if off + _REC_HDR.size + 4 > n:
            return off, True, None           # torn header
        magic, seq, blen = _REC_HDR.unpack_from(raw, off)
        if magic != _REC_MAGIC:
            if not _valid_record_after(raw, off + 1):
                return off, True, None       # corrupt last record
            return off, False, f"bad magic at {off}"
        end = off + _REC_HDR.size + blen + 4
        if end > n:
            return off, True, None           # torn body
        (crc,) = struct.unpack_from("<I", raw, end - 4)
        if host_crc32c(raw[off:end - 4]) != crc:
            if end >= n or not _valid_record_after(raw, off + 1):
                return off, True, None       # corrupt last record
            return off, False, f"crc mismatch at {off}"
        yield seq, raw[off + _REC_HDR.size:end - 4]
        off = end
    return off, False, None


def _encode_batch(ops: list[tuple]) -> bytes:
    """WAL body for one txn: expanded point ops only (range deletes
    are expanded against live state at submit so replay is blind)."""
    out = bytearray()
    out += struct.pack("<I", len(ops))
    for op in ops:
        if op[0] == "set":
            out += struct.pack("<BI", 1, len(op[1])) + op[1]
            out += struct.pack("<I", len(op[2])) + op[2]
        else:                                  # ("rm", key)
            out += struct.pack("<BI", 2, len(op[1])) + op[1]
    return bytes(out)


def _decode_batch(body: bytes) -> list[tuple]:
    ops: list[tuple] = []
    try:
        (n,) = struct.unpack_from("<I", body, 0)
        off = 4
        for _ in range(n):
            kind, klen = struct.unpack_from("<BI", body, off)
            off += 5
            key = body[off:off + klen]
            if len(key) != klen:
                raise ValueError("short key")
            off += klen
            if kind == 1:
                (vlen,) = struct.unpack_from("<I", body, off)
                off += 4
                val = body[off:off + vlen]
                if len(val) != vlen:
                    raise ValueError("short value")
                off += vlen
                ops.append(("set", key, val))
            elif kind == 2:
                ops.append(("rm", key))
            else:
                raise ValueError(f"unknown batch op {kind}")
        if off != len(body):
            raise ValueError("trailing bytes in batch")
    except (struct.error, ValueError) as e:
        raise TinDBCorruption(f"bad WAL batch: {e}") from None
    return ops


# -- sorted immutable segment -------------------------------------------------

class Segment:
    """One immutable sorted run on disk. Readers go through a sparse
    in-RAM index (every Nth key → offset) + pread, so a point lookup
    or bounded scan touches O(index + window) bytes, not the file."""

    def __init__(self, path: str, verify: bool = True):
        self.path = path
        self.fd = os.open(path, os.O_RDONLY)
        try:
            self._load_footer(verify)
        except Exception:
            os.close(self.fd)
            self.fd = -1
            raise

    def _load_footer(self, verify: bool) -> None:
        size = os.fstat(self.fd).st_size
        if size < _SEG_HDR.size + _SEG_FOOTER.size:
            raise TinDBCorruption(f"{self.path}: truncated segment")
        magic, ver = _SEG_HDR.unpack(os.pread(self.fd, _SEG_HDR.size, 0))
        if magic != _SEG_MAGIC:
            raise TinDBCorruption(f"{self.path}: bad segment magic")
        if ver > _SEG_VERSION:
            raise TinDBCorruption(f"{self.path}: segment v{ver} from "
                                  f"a newer writer")
        foot = os.pread(self.fd, _SEG_FOOTER.size,
                        size - _SEG_FOOTER.size)
        self.index_off, self.n_entries, seal = _SEG_FOOTER.unpack(foot)
        if verify:
            body = os.pread(self.fd, size - 4, 0)
            if host_crc32c(body) != seal:
                raise TinDBCorruption(f"{self.path}: segment seal "
                                      f"crc mismatch")
        if not (_SEG_HDR.size <= self.index_off
                <= size - _SEG_FOOTER.size):
            raise TinDBCorruption(f"{self.path}: index offset "
                                  f"out of bounds")
        raw = os.pread(self.fd, size - _SEG_FOOTER.size - self.index_off,
                       self.index_off)
        self.index_keys: list[bytes] = []
        self.index_offs: list[int] = []
        try:
            (cnt,) = struct.unpack_from("<I", raw, 0)
            off = 4
            for _ in range(cnt):
                (klen,) = struct.unpack_from("<I", raw, off)
                off += 4
                self.index_keys.append(bytes(raw[off:off + klen]))
                off += klen
                (eoff,) = struct.unpack_from("<Q", raw, off)
                self.index_offs.append(eoff)
                off += 8
        except struct.error:
            raise TinDBCorruption(f"{self.path}: bad index block") \
                from None

    def close(self) -> None:
        if self.fd >= 0:
            try:
                os.close(self.fd)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
            self.fd = -1

    def __del__(self):  # snapshots may outlive the manifest reference
        self.close()

    def _read_entry(self, off: int):
        """(key, value|None, next_off) at file offset `off`, or None
        at the index block boundary."""
        if off >= self.index_off:
            return None
        hdr = os.pread(self.fd, _SEG_ENTRY.size, off)
        if len(hdr) < _SEG_ENTRY.size:
            raise TinDBCorruption(f"{self.path}: torn entry at {off}")
        klen, flags, vlen = _SEG_ENTRY.unpack(hdr)
        off += _SEG_ENTRY.size
        key = os.pread(self.fd, klen, off)
        off += klen
        if flags & _TOMBSTONE:
            return key, None, off
        val = os.pread(self.fd, vlen, off)
        if len(key) != klen or len(val) != vlen:
            raise TinDBCorruption(f"{self.path}: torn entry payload")
        return key, val, off + vlen

    def _seek_off(self, key: bytes) -> int:
        """File offset of the first entry with entry.key >= key."""
        import bisect
        i = bisect.bisect_right(self.index_keys, key) - 1
        off = self.index_offs[i] if i >= 0 else _SEG_HDR.size
        while True:
            ent = self._read_entry(off)
            if ent is None or ent[0] >= key:
                return off
            off = ent[2]

    def get(self, key: bytes):
        """(found, value|None-for-tombstone)."""
        if not self.index_keys and self.n_entries == 0:
            return False, None
        ent = self._read_entry(self._seek_off(key))
        if ent is not None and ent[0] == key:
            return True, ent[1]
        return False, None

    def iterate(self, start: bytes | None = None,
                end: bytes | None = None):
        """Yield (key, value|None) ascending in [start, end).
        Tombstones are yielded — merging layers need them."""
        off = _SEG_HDR.size if start is None else self._seek_off(start)
        while True:
            ent = self._read_entry(off)
            if ent is None:
                return
            key, val, off = ent
            if end is not None and key >= end:
                return
            yield key, val


def write_segment(path: str, items) -> int:
    """Write sorted (key, value|None) pairs as a sealed segment;
    returns the entry count. fsyncs before returning — the MANIFEST
    that references this file lands only after the bytes are real."""
    crc = 0xFFFFFFFF
    n = 0
    index = bytearray()
    with open(path, "wb") as f:
        def emit(b: bytes):
            nonlocal crc
            f.write(b)
            crc = host_crc32c(b, crc)

        emit(_SEG_HDR.pack(_SEG_MAGIC, _SEG_VERSION))
        off = _SEG_HDR.size
        n_index = 0
        for key, val in items:
            if n % _INDEX_EVERY == 0:
                index += struct.pack("<I", len(key)) + key
                index += struct.pack("<Q", off)
                n_index += 1
            flags = _TOMBSTONE if val is None else 0
            vlen = 0 if val is None else len(val)
            ent = _SEG_ENTRY.pack(len(key), flags, vlen) + key
            if val is not None:
                ent += val
            emit(ent)
            off += len(ent)
            n += 1
        index_off = off
        emit(struct.pack("<I", n_index) + bytes(index))
        emit(struct.pack("<QQ", index_off, n))
        f.write(struct.pack("<I", crc))
        f.flush()
        os.fsync(f.fileno())
    return n


# -- merge machinery ----------------------------------------------------------

def _merge_layers(layers, keep_tombstones=True):
    """K-way merge of (key, value|None) iterators, layers[0] newest;
    for equal keys the NEWEST layer wins. Yields ascending."""
    heap = []
    iters = []
    for rank, it in enumerate(layers):
        iters.append(it)
        try:
            k, v = next(it)
            heap.append((k, rank, v))
        except StopIteration:
            pass
    heapq.heapify(heap)
    last_key = None
    while heap:
        k, rank, v = heapq.heappop(heap)
        try:
            nk, nv = next(iters[rank])
            heapq.heappush(heap, (nk, rank, nv))
        except StopIteration:
            pass
        if k == last_key:
            continue                          # an older layer's value
        last_key = k
        if v is None and not keep_tombstones:
            continue
        yield k, v


def _mem_iter(mem: dict, start=None, end=None):
    keys = sorted(k for k in mem
                  if (start is None or k >= start)
                  and (end is None or k < end))
    for k in keys:
        yield k, mem[k]


# -- snapshot -----------------------------------------------------------------

class TinDBSnapshot:
    """Frozen read view: memtable copy + pinned segment objects.
    Segments are immutable and keep their fds open, so a compaction
    unlinking the files underneath cannot disturb this view."""

    def __init__(self, mem: dict, segments: list[Segment]):
        self._mem = mem                       # already a copy
        self._segments = segments             # newest first

    def get(self, prefix: str, key: bytes) -> bytes | None:
        full = combine_key(prefix, key)
        if full in self._mem:
            return self._mem[full]
        for seg in self._segments:
            found, val = seg.get(full)
            if found:
                return val
        return None

    def iterate(self, prefix: str, start: bytes | None = None,
                end: bytes | None = None):
        lo, hi = prefix_range(prefix)
        if start is not None:
            lo = combine_key(prefix, start)
        if end is not None:
            hi = combine_key(prefix, end)
        hi = hi or None                       # b"" successor = +inf
        plen = len(prefix.encode()) + 1
        layers = [_mem_iter(self._mem, lo, hi)]
        layers += [seg.iterate(lo, hi) for seg in self._segments]
        for k, v in _merge_layers(layers, keep_tombstones=False):
            yield k[plen:], v


# -- the store ----------------------------------------------------------------

class TinDB(KeyValueDB):
    """LSM-lite KeyValueDB over one directory (WAL + MANIFEST +
    seg-*.tdb). Thread-safe behind one RLock (the rocksdb write-mutex
    role at this scale)."""

    MANIFEST_VERSION = 1

    def __init__(self, path: str, o_dsync: bool = False,
                 memtable_max_bytes: int = 4 << 20,
                 fanout: int = 4,
                 wal_name: str = "wal.log",
                 mount: bool = True):
        self.path = path
        self.o_dsync = o_dsync
        self.memtable_max_bytes = memtable_max_bytes
        self.fanout = max(2, int(fanout))
        self.wal_name = wal_name
        self._lock = threading.RLock()
        self._mem: dict[bytes, bytes | None] | None = None
        self._mem_bytes = 0
        self._levels: list[list[Segment]] = []
        self._seq = 0                  # last written WAL seq
        self._covered_seq = 0          # WAL seqs <= this live in segments
        self._next_seg = 1
        self._wal_f = None
        self._fault = None             # crash-injection hook (tests)
        self.stats = {"gets": 0, "iterators": 0, "flushes": 0,
                      "compactions": 0, "submitted": 0,
                      "wal_replayed": 0}
        # declared counter mirror of `stats` plus byte/time detail —
        # what a daemon nests under "tindb" in its perf dump and what
        # MgrReports aggregate (the RocksDB statistics -> perf
        # counters bridge the reference's BlueStore maintains)
        from ..utils.perf_counters import PerfCountersBuilder
        self.perf = (PerfCountersBuilder("tindb")
                     .add_u64_counter("wal_records",
                                      "transaction batches appended")
                     .add_u64_counter("wal_bytes",
                                      "bytes appended to the WAL")
                     .add_u64_counter("wal_replayed",
                                      "records replayed at mount")
                     .add_u64_counter("flushes", "memtable flushes")
                     .add_u64_counter("compactions", "level merges")
                     .add_u64_counter("gets", "point lookups")
                     .add_u64_counter("iterators", "range scans opened")
                     .add_time_avg("submit_time",
                                   "submit_transaction wall time")
                     .add_time_avg("compact_time",
                                   "per-merge compaction wall time")
                     .create_perf_counters())
        os.makedirs(path, exist_ok=True)
        if mount:
            self.mount()

    # -- paths ---------------------------------------------------------------

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.path, self.wal_name)

    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.path, "MANIFEST")

    def _seg_path(self, seg_id: int) -> str:
        return os.path.join(self.path, f"seg-{seg_id:08d}.tdb")

    # -- manifest ------------------------------------------------------------

    def _write_manifest(self) -> None:
        from ..utils.encoding import Encoder
        e = Encoder()
        e.start(self.MANIFEST_VERSION, self.MANIFEST_VERSION)
        e.u64(self._covered_seq)
        e.u64(self._next_seg)
        e.u32(len(self._levels))
        for level in self._levels:
            e.list([os.path.basename(s.path) for s in level],
                   Encoder.string)
        e.finish()
        body = e.bytes()
        body += struct.pack("<I", host_crc32c(body))
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)

    @classmethod
    def _read_manifest(cls, path: str):
        """(covered_seq, next_seg, levels-as-filenames) or None when
        absent. Raises TinDBCorruption on a bad seal."""
        from ..utils.encoding import Decoder, EncodingError
        try:
            with open(os.path.join(path, "MANIFEST"), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        if len(raw) < 4:
            raise TinDBCorruption(f"{path}/MANIFEST: truncated")
        (crc,) = struct.unpack_from("<I", raw, len(raw) - 4)
        if host_crc32c(raw[:-4]) != crc:
            raise TinDBCorruption(f"{path}/MANIFEST: seal crc mismatch")
        d = Decoder(raw[:-4])
        try:
            d.start(cls.MANIFEST_VERSION)
            covered = d.u64()
            next_seg = d.u64()
            levels = [d.list(Decoder.string) for _ in range(d.u32())]
            d.finish()
        except EncodingError as e:
            raise TinDBCorruption(f"{path}/MANIFEST: {e}") from None
        return covered, next_seg, levels

    # -- lifecycle -----------------------------------------------------------

    def mount(self) -> None:
        with self._lock:
            self._mem = {}
            self._mem_bytes = 0
            self._levels = []
            man = self._read_manifest(self.path)
            if man is None:
                self._covered_seq = 0
                self._next_seg = 1
                self._write_manifest()       # claims the directory
            else:
                self._covered_seq, self._next_seg, names = man
                for level_names in names:
                    self._levels.append(
                        [Segment(os.path.join(self.path, n))
                         for n in level_names])
            live = {os.path.basename(s.path)
                    for lvl in self._levels for s in lvl}
            for fn in os.listdir(self.path):
                # crash between segment write and manifest swap
                # leaves an orphan run; reclaim it
                if fn.startswith("seg-") and fn.endswith(".tdb") \
                        and fn not in live:
                    try:
                        os.unlink(os.path.join(self.path, fn))
                    except OSError:
                        pass
            self._seq = self._covered_seq
            self._replay_wal()
            self._wal_f = open(self._wal_path, "ab")

    def _replay_wal(self) -> None:
        gen = scan_wal(self._wal_path)
        while True:
            try:
                seq, body = next(gen)
            except StopIteration as stop:
                good_bytes, torn, err = stop.value
                if err:
                    raise TinDBCorruption(
                        f"{self._wal_path}: {err} (mid-log corruption; "
                        f"run fsck)")
                if torn:
                    with open(self._wal_path, "ab") as f:
                        f.truncate(good_bytes)
                return
            if seq <= self._covered_seq:
                continue                     # segments cover it
            if seq != self._seq + 1:
                raise TinDBCorruption(
                    f"{self._wal_path}: seq jump {self._seq} -> {seq}")
            for op in _decode_batch(body):
                self._mem_apply(op)
            self.stats["wal_replayed"] += 1
            self.perf.inc("wal_replayed")
            self._seq = seq

    def crash(self) -> None:
        """SIGKILL semantics: drop RAM and handles, flush nothing."""
        with self._lock:
            if self._wal_f is not None:
                try:
                    self._wal_f.close()
                except OSError:
                    pass
                self._wal_f = None
            for lvl in self._levels:
                for seg in lvl:
                    seg.close()
            self._levels = []
            self._mem = None
            self._mem_bytes = 0

    def umount(self) -> None:
        """Clean shutdown: flush the memtable, release handles."""
        with self._lock:
            self.flush()
            self.crash()

    @property
    def is_down(self) -> bool:
        return self._mem is None

    def _alive(self) -> dict:
        if self._mem is None:
            raise RuntimeError(f"TinDB {self.path} is down "
                               f"(crashed/umounted; mount() first)")
        return self._mem

    def _hook(self, point: str) -> None:
        if self._fault is not None:
            self._fault(point)

    # -- writes --------------------------------------------------------------

    def _mem_apply(self, op: tuple) -> None:
        key = op[1]
        old = self._mem.get(key)
        if old is not None:
            self._mem_bytes -= len(key) + len(old)
        elif key in self._mem:
            self._mem_bytes -= len(key)
        if op[0] == "set":
            self._mem[key] = op[2]
            self._mem_bytes += len(key) + len(op[2])
        else:
            self._mem[key] = None            # tombstone masks segments
            self._mem_bytes += len(key)

    def _expand(self, txn: KVTransaction) -> list[tuple]:
        """Resolve range deletes into point tombstones against the
        state visible at their position in the batch (rocksdb
        DeleteRange is an optimization of exactly this semantics)."""
        out: list[tuple] = []
        overlay: dict[bytes, bytes | None] = {}
        for op in txn.ops:
            if op[0] in ("set", "rm"):
                out.append(op)
                overlay[op[1]] = op[2] if op[0] == "set" else None
                continue
            _, lo, hi = op
            hi_b = hi or None                # b"" successor = +inf
            doomed = set()
            for k in self._scan_full(lo, hi_b):
                if overlay.get(k, k) is not None:   # not deleted earlier
                    doomed.add(k)
            for k, v in overlay.items():
                if v is not None and k >= lo \
                        and (hi_b is None or k < hi_b):
                    doomed.add(k)
            for k in sorted(doomed):
                out.append(("rm", k))
                overlay[k] = None
        return out

    def _scan_full(self, lo: bytes, hi: bytes | None):
        """Live full keys in [lo, hi) (tombstones resolved)."""
        layers = [_mem_iter(self._mem, lo, hi)]
        for lvl in self._levels:
            layers += [seg.iterate(lo, hi) for seg in reversed(lvl)]
        for k, v in _merge_layers(layers, keep_tombstones=False):
            yield k

    def submit_transaction(self, txn: KVTransaction) -> None:
        import time as _time
        t0 = _time.perf_counter()
        with self._lock:
            self._alive()
            ops = self._expand(txn)
            body = _encode_batch(ops)
            self._hook("wal.append")
            # the append must be ATOMIC against ENOSPC (r21): seq only
            # advances once the record is durably on disk, and a
            # partial append (f.write stops mid-record when the device
            # fills) is truncated back to the sealed prefix —
            # shrinking a file needs no space. Without the rollback a
            # failed append left _seq advanced past the last durable
            # record (fatal seq-jump on replay) and without the
            # truncate a LATER successful append would bury garbage
            # mid-log (fatal "bad magic", not the recoverable torn
            # tail).
            start = self._wal_f.tell()
            try:
                append_wal_record(self._wal_f, self._seq + 1, body,
                                  self.o_dsync)
            except OSError:
                try:
                    self._wal_f.truncate(start)
                    self._wal_f.seek(start)
                except OSError:
                    pass    # crash-before-truncate = torn tail, which
                    #         scan_wal already recovers
                raise
            self._seq += 1
            for op in ops:
                self._mem_apply(op)
            self.stats["submitted"] += 1
            self.perf.inc_many(
                (("wal_records", 1),
                 ("wal_bytes", _REC_HDR.size + len(body) + 4)))
            if self._mem_bytes >= self.memtable_max_bytes:
                try:
                    self.flush()
                except OSError:
                    # ENOSPC flushing a full memtable: the txn above
                    # already committed to the WAL — swallow, keep
                    # accepting (bounded by the WAL) and retry the
                    # flush on a later submit
                    pass
        self.perf.tinc("submit_time", _time.perf_counter() - t0)

    # -- flush + compaction --------------------------------------------------

    def _all_segments(self) -> list[Segment]:
        """Newest-first flat view (L0 newest-last, deeper = older)."""
        out: list[Segment] = []
        if self._levels:
            out.extend(reversed(self._levels[0]))
            for lvl in self._levels[1:]:
                out.extend(reversed(lvl))
        return out

    def flush(self) -> None:
        """Memtable -> new L0 segment, MANIFEST swap, WAL reset.
        Crash windows: before the swap -> old manifest + full WAL
        (orphan segment reclaimed at mount); after the swap, before
        the reset -> covered_seq makes replay skip the stale records.
        Either way state is exact."""
        with self._lock:
            self._alive()
            if self._mem:
                seg_id = self._next_seg
                self._next_seg += 1
                path = self._seg_path(seg_id)
                try:
                    write_segment(path, ((k, self._mem[k])
                                         for k in sorted(self._mem)))
                    self._hook("flush.segment-written")
                except OSError:
                    # ENOSPC mid-segment (r21): unlink the partial
                    # run and abort — memtable, WAL and manifest are
                    # untouched, so the flush simply retries later
                    # (the seg-id gap is harmless; mount reclaims any
                    # leftover as an orphan)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    raise
                if not self._levels:
                    self._levels.append([])
                self._levels[0].append(Segment(path))
                self.stats["flushes"] += 1
                self.perf.inc("flushes")
            # covered_seq must equal the last written seq whenever the
            # WAL is truncated — even for an empty memtable (a no-op
            # batch still consumed a seq; replay after the reset must
            # not see a seq jump)
            if self._covered_seq != self._seq or self._mem:
                self._covered_seq = self._seq
                self._write_manifest()
                self._hook("flush.manifest-swapped")
            self._mem = {}
            self._mem_bytes = 0
            if self._wal_f is not None:
                self._wal_f.close()
            self._wal_f = open(self._wal_path, "wb")
            self.maybe_compact()

    def maybe_compact(self) -> None:
        with self._lock:
            while any(len(lvl) >= self.fanout for lvl in self._levels):
                for i, lvl in enumerate(self._levels):
                    if len(lvl) >= self.fanout:
                        try:
                            self.compact_level(i)
                        except OSError:
                            # ENOSPC: compaction is advisory — the
                            # flush that triggered us already
                            # committed; retry on a later flush
                            return
                        break

    def compact_level(self, i: int) -> None:
        """Merge level i and level i+1 into ONE run on level i+1
        (newer wins per key; tombstones dropped iff the output is the
        deepest level). Readers are never blocked: old segments stay
        readable through open fds until their objects die."""
        import time as _time
        t0 = _time.perf_counter()
        with self._lock:
            self._alive()
            if i >= len(self._levels) or not self._levels[i]:
                return
            below = self._levels[i + 1] if i + 1 < len(self._levels) \
                else []
            victims = list(self._levels[i]) + list(below)
            deepest = all(not lvl for lvl in self._levels[i + 2:])
            layers = [seg.iterate() for seg in reversed(self._levels[i])]
            layers += [seg.iterate() for seg in reversed(below)]
            seg_id = self._next_seg
            self._next_seg += 1
            path = self._seg_path(seg_id)
            try:
                write_segment(path, _merge_layers(
                    layers, keep_tombstones=not deepest))
                self._hook("compact.segments-written")
            except OSError:
                # ENOSPC mid-merge (r21): unlink the partial output
                # and abort — levels and manifest untouched, every
                # victim still live; the merge retries later
                try:
                    os.unlink(path)
                except OSError:
                    pass
                raise
            merged = Segment(path)
            if i + 1 >= len(self._levels):
                self._levels.append([])
            self._levels[i] = []
            self._levels[i + 1] = [merged]
            self._write_manifest()
            self._hook("compact.manifest-swapped")
            for seg in victims:
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
            self.stats["compactions"] += 1
            self.perf.inc("compactions")
        self.perf.tinc("compact_time", _time.perf_counter() - t0)

    def compact(self) -> None:
        """Full compaction (the `ceph-kvstore-tool compact` role):
        flush, then merge everything down to one run."""
        with self._lock:
            self.flush()
            while sum(1 for lvl in self._levels if lvl) > 1 \
                    or (self._levels and len(self._levels[0]) > 1):
                lo = next(j for j, lvl in enumerate(self._levels)
                          if lvl)
                self.compact_level(lo)

    # -- reads ---------------------------------------------------------------

    def get(self, prefix: str, key: bytes) -> bytes | None:
        with self._lock:
            self._alive()
            self.stats["gets"] += 1
            self.perf.inc("gets")
            full = combine_key(prefix, key)
            if full in self._mem:
                return self._mem[full]
            for seg in self._all_segments():
                found, val = seg.get(full)
                if found:
                    return val
            return None

    def iterate(self, prefix: str, start: bytes | None = None,
                end: bytes | None = None):
        """Ordered, prefix-bounded scan. Iterates over a SNAPSHOT
        taken at call time (memtable copy + pinned segments), so
        concurrent writes/flushes/compactions can't corrupt the walk."""
        with self._lock:
            self._alive()
            self.stats["iterators"] += 1
            self.perf.inc("iterators")
            snap = self.snapshot()
        return snap.iterate(prefix, start, end)

    def snapshot(self) -> TinDBSnapshot:
        with self._lock:
            self._alive()
            return TinDBSnapshot(dict(self._mem), self._all_segments())

    def wal_size(self) -> int:
        with self._lock:
            self._alive()
            return self._wal_f.tell()

    @classmethod
    def open_readonly(cls, path: str,
                      wal_name: str = "wal.log") -> TinDBSnapshot:
        """Offline point-in-time view for fsck/inspection tools:
        manifest + segments + in-memory WAL replay, with NO mutation
        (no manifest claim, no torn-tail truncation, no orphan
        cleanup). Raises TinDBCorruption on structural damage."""
        man = cls._read_manifest(path)
        if man is None:
            raise TinDBCorruption(f"{path}/MANIFEST: missing")
        covered, _next_seg, levels = man
        seg_levels = [[Segment(os.path.join(path, n)) for n in lvl]
                      for lvl in levels]
        mem: dict[bytes, bytes | None] = {}
        seq = covered
        gen = scan_wal(os.path.join(path, wal_name))
        while True:
            try:
                rseq, body = next(gen)
            except StopIteration as stop:
                _, _torn, err = stop.value
                if err:
                    raise TinDBCorruption(
                        f"{path}/{wal_name}: {err}")
                break
            if rseq <= covered:
                continue
            if rseq != seq + 1:
                raise TinDBCorruption(
                    f"{path}/{wal_name}: seq jump {seq} -> {rseq}")
            for op in _decode_batch(body):
                mem[op[1]] = op[2] if op[0] == "set" else None
            seq = rseq
        flat: list[Segment] = []
        if seg_levels:
            flat.extend(reversed(seg_levels[0]))
            for lvl in seg_levels[1:]:
                flat.extend(reversed(lvl))
        return TinDBSnapshot(mem, flat)

    def segment_stats(self) -> dict:
        with self._lock:
            return {
                "levels": [[os.path.basename(s.path) for s in lvl]
                           for lvl in self._levels],
                "segments": sum(len(lvl) for lvl in self._levels),
                "entries": sum(s.n_entries for lvl in self._levels
                               for s in lvl),
                "memtable_keys": len(self._mem or ()),
                "memtable_bytes": self._mem_bytes,
                "wal_seq": self._seq,
                "covered_seq": self._covered_seq,
            }

    # -- fsck ----------------------------------------------------------------

    @staticmethod
    def fsck(path: str, wal_name: str = "wal.log") -> dict:
        """Offline audit: manifest seal, segment seals + strict key
        order + index consistency, WAL chain, orphan files. Mutates
        nothing."""
        report = {"segments": 0, "entries": 0, "wal_records": 0,
                  "torn_tail": False, "errors": [], "orphans": []}
        try:
            man = TinDB._read_manifest(path)
        except TinDBCorruption as e:
            report["errors"].append(str(e))
            return report
        if man is None:
            report["errors"].append(f"{path}/MANIFEST: missing")
            return report
        covered, _next_seg, levels = man
        live = {n for lvl in levels for n in lvl}
        for fn in sorted(os.listdir(path)):
            if fn.startswith("seg-") and fn.endswith(".tdb") \
                    and fn not in live:
                report["orphans"].append(fn)
        for lvl in levels:
            for name in lvl:
                report["segments"] += 1
                try:
                    seg = Segment(os.path.join(path, name))
                except (TinDBCorruption, OSError) as e:
                    report["errors"].append(str(e))
                    continue
                prev = None
                n = 0
                try:
                    for k, _v in seg.iterate():
                        if prev is not None and k <= prev:
                            report["errors"].append(
                                f"{name}: keys out of order")
                            break
                        prev = k
                        n += 1
                except TinDBCorruption as e:
                    report["errors"].append(str(e))
                else:
                    if n != seg.n_entries:
                        report["errors"].append(
                            f"{name}: footer says {seg.n_entries} "
                            f"entries, scanned {n}")
                    report["entries"] += n
                seg.close()
        gen = scan_wal(os.path.join(path, wal_name))
        seq = covered
        while True:
            try:
                rseq, body = next(gen)
            except StopIteration as stop:
                _, torn, err = stop.value
                report["torn_tail"] = torn
                if err:
                    report["errors"].append(err)
                break
            if rseq <= covered:
                continue
            if rseq != seq + 1:
                report["errors"].append(f"wal seq jump {seq} -> {rseq}")
                break
            try:
                _decode_batch(body)
            except TinDBCorruption as e:
                report["errors"].append(f"wal record {rseq}: {e}")
                break
            seq = rseq
            report["wal_records"] += 1
        return report
