"""KeyValueDB — the ordered-KV interface (ref: src/kv/KeyValueDB.h).

The reference mediates every BlueStore metadata access through this
surface so the backing engine (RocksDB over BlueFS) is swappable; the
same shape is kept here so TinStore programs TinDB through an
interface, not an implementation:

* PREFIXED KEY SPACES. Every key lives under a short string prefix
  (the rocksdb column-family-by-convention trick: the stored key is
  `prefix + NUL + key`). Prefixes must not contain NUL; keys are raw
  bytes and may.  Because NUL sorts before every other byte, all keys
  of one prefix are contiguous in the total order.
* TRANSACTION BATCHES. Mutations accumulate in a `KVTransaction` and
  apply atomically at `submit_transaction` — wholly applied or wholly
  absent after a crash, exactly the WriteBatch contract BlueStore's
  _kv_sync_thread relies on.
* ORDERED ITERATORS. `iterate(prefix, start, end)` yields (key,
  value) in ascending key order, bounded to the prefix (and
  optionally to [start, end) inside it) — the get_iterator/
  lower_bound/upper_bound machinery collapsed into one generator
  shape, which is what every listing/omap scan in this codebase
  actually does with it.
* SNAPSHOTS. `snapshot()` returns a frozen point-in-time read view
  (get + iterate) that later writes and compactions cannot disturb.
"""

from __future__ import annotations

from collections.abc import Iterator


def combine_key(prefix: str, key: bytes) -> bytes:
    """`prefix + NUL + key` (the KeyValueDB combine convention)."""
    p = prefix.encode("utf-8")
    if b"\x00" in p:
        raise ValueError(f"prefix {prefix!r} contains NUL")
    return p + b"\x00" + bytes(key)


def split_key(full: bytes) -> tuple[str, bytes]:
    """Inverse of combine_key (split at the FIRST NUL)."""
    p, _, k = full.partition(b"\x00")
    return p.decode("utf-8"), k


def _successor(b: bytes) -> bytes:
    """Smallest byte string greater than every string prefixed by `b`
    (strip trailing 0xff, bump the last byte — the standard exclusive
    upper bound for a prefix scan). All-0xff has no successor; that
    degenerate bound is represented as b"" and treated as +inf by
    callers (no real prefix here is all-0xff)."""
    b = b.rstrip(b"\xff")
    if not b:
        return b""
    return b[:-1] + bytes([b[-1] + 1])


def prefix_range(prefix: str, key_prefix: bytes = b"") -> tuple[bytes, bytes]:
    """[lo, hi) full-key bounds covering every key of `prefix` that
    starts with `key_prefix`."""
    lo = combine_key(prefix, key_prefix)
    return lo, _successor(lo)


class KVTransaction:
    """Ordered mutation batch (the KeyValueDB::Transaction role).
    Ops apply in insertion order at submit; range deletes cover the
    state visible at their position in the batch."""

    __slots__ = ("ops",)

    def __init__(self):
        self.ops: list[tuple] = []

    def set(self, prefix: str, key: bytes, value: bytes) -> "KVTransaction":
        self.ops.append(("set", combine_key(prefix, key), bytes(value)))
        return self

    def rmkey(self, prefix: str, key: bytes) -> "KVTransaction":
        self.ops.append(("rm", combine_key(prefix, key)))
        return self

    def rm_range_keys(self, prefix: str, start: bytes,
                      end: bytes) -> "KVTransaction":
        """Delete every key of `prefix` in [start, end) (ref:
        KeyValueDB::Transaction::rm_range_keys)."""
        self.ops.append(("rm_range", combine_key(prefix, start),
                         combine_key(prefix, end)))
        return self

    def rmkeys_by_prefix(self, prefix: str,
                         key_prefix: bytes = b"") -> "KVTransaction":
        """Delete every key of `prefix` starting with `key_prefix`
        (ref: KeyValueDB::Transaction::rmkeys_by_prefix)."""
        lo, hi = prefix_range(prefix, key_prefix)
        self.ops.append(("rm_range", lo, hi))
        return self

    def __len__(self) -> int:
        return len(self.ops)


class KeyValueDB:
    """Interface contract; TinDB is the bundled implementation."""

    def get(self, prefix: str, key: bytes) -> bytes | None:
        raise NotImplementedError

    def transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, txn: KVTransaction) -> None:
        raise NotImplementedError

    def iterate(self, prefix: str, start: bytes | None = None,
                end: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Yield (key, value) ascending, bounded to `prefix` and to
        [start, end) within it (None = unbounded on that side)."""
        raise NotImplementedError

    def snapshot(self):
        raise NotImplementedError
