from .client import FsClient, FsError, IsADir, NotADir, NotEmpty

__all__ = ["FsClient", "FsError", "IsADir", "NotADir", "NotEmpty"]
