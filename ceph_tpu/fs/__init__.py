from .client import (FsBusy, FsClient, FsError, FsFile, IsADir, NotADir,
                     NotEmpty)

__all__ = ["FsBusy", "FsClient", "FsError", "FsFile", "IsADir", "NotADir",
           "NotEmpty"]
