"""CephFS-lite — the POSIX-shaped file layer over rados.

Rebuild of the reference's filesystem data/metadata split (ref:
src/mds/ — CInode/CDentry/MDCache; dirfrag omap objects holding
dentries with EMBEDDED inodes, src/mds/CDir.cc; file DATA addressed
by inode number through the file layout into plain rados objects,
src/osd + libcephfs read/write path; client ops shape ref:
src/client/Client.cc mkdir/create/unlink/rename/readdir).

Mapping onto this framework:

* DIRECTORIES are objects (`.fs.dir.{ino}`) whose dentries live in
  the object-class KV plane and mutate atomically AT the object via
  the `fs_dir` class below — exactly the dirfrag-omap role. Each
  dentry embeds its inode (type, size, mtime, ino), the reference's
  primary-dentry embedding.
* FILE DATA is striped at `.fs.data.{ino}` through the RadosStriper —
  the file-layout striping of {ino}.{index} objects, client-side.
* INODE NUMBERS come from an allocator object (`.fs.meta`) bumped via
  cls (the InoTable role).
* The MDS ITSELF — a metadata-caching server process — collapses to
  these object-class methods: metadata mutations are already atomic
  at the dirfrag object, so the sim needs no extra daemon between
  client and OSD.
* FILE CAPABILITIES (ref: src/mds/Locker.cc issue/revoke; client
  caps Fr/Fw in src/client/Client.cc) map onto the cls `lock` class
  on a per-inode caps anchor (`.fs.caps.{ino}`): `open(path, "r")`
  acquires a SHARED lock (the Fr cap), `open(path, "w"/"rw")` an
  EXCLUSIVE one (Fw); conflicting opens fail with FsBusy instead of
  the reference's asynchronous revoke (fail-fast-lite), bare
  write/truncate/unlink refuse while another client holds caps, and
  `break_caps` is the operator eviction path for a dead holder
  (`ceph tell mds.N client evict` role). Multiple-active-MDS stays
  out of scope.

Everything rides librados/striper: EC fan-out, snapshots' COW,
recovery, scrub, and PG splits apply to file data and dirfrags with
no special cases."""

from __future__ import annotations

import json
import posixpath

from ..client.rados import IoCtx, RadosStriper
from ..osd.objclass import ClsError, ClsHandle, register_cls

ROOT_INO = 1
_META_OBJ = ".fs.meta"


class FsError(Exception):
    pass


class NotADir(FsError, NotADirectoryError):
    pass


class IsADir(FsError, IsADirectoryError):
    pass


class NotEmpty(FsError, OSError):
    pass


class FsBusy(FsError, OSError):
    """A conflicting capability is held by another client."""


# -- dirfrag object class (CDir dentry ops) ----------------------------------

@register_cls("fs_dir", "link")
def _dir_link(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    dents = h.kv.setdefault("dentries", {})
    if req["name"] in dents and not req.get("replace", False):
        raise ClsError(f"EEXIST: {req['name']}")
    dents[req["name"]] = req["ent"]
    return b"{}"


@register_cls("fs_dir", "unlink")
def _dir_unlink(h: ClsHandle, inp: bytes) -> bytes:
    name = json.loads(inp)["name"]
    dents = h.kv.setdefault("dentries", {})
    if name not in dents:
        raise ClsError(f"ENOENT: {name}")
    return json.dumps(dents.pop(name)).encode()


@register_cls("fs_dir", "lookup")
def _dir_lookup(h: ClsHandle, inp: bytes) -> bytes:
    name = json.loads(inp)["name"]
    ent = h.kv.get("dentries", {}).get(name)
    if ent is None:
        raise ClsError(f"ENOENT: {name}")
    return json.dumps(ent).encode()


@register_cls("fs_dir", "list")
def _dir_list(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps(h.kv.get("dentries", {})).encode()


@register_cls("fs_dir", "update")
def _dir_update(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    ent = h.kv.get("dentries", {}).get(req["name"])
    if ent is None:
        raise ClsError(f"ENOENT: {req['name']}")
    ent.update(req["fields"])
    return json.dumps(ent).encode()


@register_cls("fs_meta", "alloc_ino")
def _meta_alloc(h: ClsHandle, inp: bytes) -> bytes:
    nxt = h.kv.get("next_ino", ROOT_INO + 1)
    h.kv["next_ino"] = nxt + 1
    return json.dumps({"ino": nxt}).encode()


class FsClient:
    """A mounted filesystem handle (the libcephfs Client role).

    `name` identifies this mount as a capability owner (the client
    session id the MDS would track); two FsClients with different
    names contend for caps. Each open handle is its own locker
    ('{name}#{seq}'), so shared handles of one mount coexist and
    close independently; exclusive conflicts — including same-mount
    upgrades — fail fast with FsBusy."""

    STRIPE_UNIT = 1 << 16
    STRIPE_COUNT = 4
    OBJECT_SIZE = 1 << 20

    def __init__(self, ioctx: IoCtx, name: str = "fsclient"):
        self.io = ioctx
        self.name = name
        self._striper = RadosStriper(
            ioctx, stripe_unit=self.STRIPE_UNIT,
            stripe_count=self.STRIPE_COUNT,
            object_size=self.OBJECT_SIZE)
        # mkfs-on-first-mount: root dirfrag + ino allocator
        try:
            self.io.stat(_META_OBJ)
        except KeyError:
            self.io.write_full(_META_OBJ, b"fsmeta")
            self.io.write_full(self._dir_obj(ROOT_INO), b"dirfrag")

    # -- naming --------------------------------------------------------------

    @staticmethod
    def _dir_obj(ino: int) -> str:
        return f".fs.dir.{ino}"

    @staticmethod
    def _data_obj(ino: int) -> str:
        return f".fs.data.{ino}"

    @staticmethod
    def _caps_obj(ino: int) -> str:
        # the per-inode capability anchor: one UNSTRIPED object whose
        # cls-lock KV is the caps ledger (the Locker's per-inode state)
        return f".fs.caps.{ino}"

    def _clock(self) -> float:
        import time
        return getattr(self.io.rados.cluster, "now", 0.0) or time.time()

    def _alloc_ino(self) -> int:
        out = self.io.execute(_META_OBJ, "fs_meta", "alloc_ino")
        return json.loads(out)["ino"]

    # -- path walk (MDCache::path_traverse) ----------------------------------

    @staticmethod
    def _split(path: str) -> list[str]:
        path = posixpath.normpath("/" + path)
        return [p for p in path.split("/") if p]

    def _walk(self, parts: list[str]) -> dict:
        """Resolve to the dentry of the LAST part; root pseudo-dentry
        for []. Raises FileNotFoundError / NotADir on the way."""
        cur = {"ino": ROOT_INO, "type": "dir", "size": 0, "mtime": 0.0}
        for i, name in enumerate(parts):
            if cur["type"] != "dir":
                raise NotADir("/" + "/".join(parts[:i]))
            try:
                raw = self.io.execute(self._dir_obj(cur["ino"]),
                                      "fs_dir", "lookup",
                                      json.dumps({"name": name}).encode())
            except ClsError:
                raise FileNotFoundError(
                    "/" + "/".join(parts[:i + 1])) from None
            cur = json.loads(raw)
        return cur

    def _parent_and_name(self, path: str) -> tuple[dict, str]:
        parts = self._split(path)
        if not parts:
            raise FsError("operation on /")
        parent = self._walk(parts[:-1])
        if parent["type"] != "dir":
            raise NotADir(posixpath.dirname("/" + "/".join(parts)))
        return parent, parts[-1]

    # -- metadata ops --------------------------------------------------------

    def mkdir(self, path: str) -> None:
        parent, name = self._parent_and_name(path)
        ino = self._alloc_ino()
        self.io.write_full(self._dir_obj(ino), b"dirfrag")
        ent = {"ino": ino, "type": "dir", "size": 0,
               "mtime": self._clock()}
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir", "link",
                        json.dumps({"name": name, "ent": ent}).encode())

    def create(self, path: str, data: bytes = b"") -> None:
        """create + write in one call (the O_CREAT|O_WRONLY shape)."""
        parent, name = self._parent_and_name(path)
        ino = self._alloc_ino()
        ent = {"ino": ino, "type": "file", "size": 0,
               "mtime": self._clock()}
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir", "link",
                        json.dumps({"name": name, "ent": ent}).encode())
        if data:
            self.write(path, data)

    def stat(self, path: str) -> dict:
        return dict(self._walk(self._split(path)))

    def readdir(self, path: str) -> dict[str, dict]:
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        raw = self.io.execute(self._dir_obj(ent["ino"]),
                              "fs_dir", "list")
        return json.loads(raw)

    def unlink(self, path: str) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] == "dir":
            raise IsADir(path)
        self._check_caps(ent["ino"], write=True, what=f"unlink {path}")
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir",
                        "unlink", json.dumps({"name": name}).encode())
        try:
            self._striper.remove(self._data_obj(ent["ino"]))
        except KeyError:
            pass                     # never written
        try:
            self.io.remove(self._caps_obj(ent["ino"]))
        except KeyError:
            pass                     # never opened

    def rmdir(self, path: str) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        if self.readdir(path):
            raise NotEmpty(path)
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir",
                        "unlink", json.dumps({"name": name}).encode())
        self.io.remove(self._dir_obj(ent["ino"]))

    def rename(self, src: str, dst: str) -> None:
        """Atomic-at-the-dentries rename: unlink src, link dst with
        the SAME inode — data never moves (the MDS rename property).
        An existing dst file is replaced (POSIX); a dst dir must not
        exist."""
        sparent, sname = self._parent_and_name(src)
        dparent, dname = self._parent_and_name(dst)
        ent = self._walk(self._split(src))
        if sparent["ino"] == dparent["ino"] and sname == dname:
            # POSIX: same-path rename is a no-op. Without this the
            # dst link rewrites the dentry and the src unlink then
            # REMOVES it — the file vanishes and its data orphans.
            return
        if ent["type"] == "file":
            # a held capability pins the NAME too: renaming a file
            # out from under an open handle would strand its caps
            # (the MDS takes the dentry lock before rename the same
            # way)
            self._check_caps(ent["ino"], write=True,
                             what=f"rename {src}")
        try:
            dent = self._walk(self._split(dst))
            if dent["type"] == "dir":
                raise FsError(f"EEXIST: {dst} is a directory")
            if ent["type"] == "dir":
                # replacing an existing FILE with a directory is
                # ENOTDIR in POSIX (rename(2)); silently swapping the
                # types would strand the file's data object
                raise NotADir(dst)
            self._check_caps(dent["ino"], write=True,
                             what=f"rename over {dst}")
            old_ino = dent["ino"]
        except FileNotFoundError:
            old_ino = None
        self.io.execute(self._dir_obj(dparent["ino"]), "fs_dir", "link",
                        json.dumps({"name": dname, "ent": ent,
                                    "replace": True}).encode())
        self.io.execute(self._dir_obj(sparent["ino"]), "fs_dir",
                        "unlink", json.dumps({"name": sname}).encode())
        if old_ino is not None and old_ino != ent["ino"]:
            for obj, rm in ((self._data_obj(old_ino),
                             self._striper.remove),
                            (self._caps_obj(old_ino), self.io.remove)):
                try:
                    rm(obj)
                except KeyError:
                    pass

    # -- data ops ------------------------------------------------------------

    # -- capabilities (Locker/caps-lite) -------------------------------------

    @staticmethod
    def _holder_mount(holder: str) -> str:
        """Holder strings are '{mount}#{handle-seq}' (the owner+cookie
        pairing of cls_lock in the reference — the cookie makes each
        handle its own locker, so closing one of a mount's two handles
        releases only its own cap)."""
        return holder.split("#", 1)[0]

    def _caps_state(self, ino: int) -> dict:
        caps = self._caps_obj(ino)
        try:
            self.io.stat(caps)   # get_info on a missing object would
        except KeyError:         # materialize its KV as a side effect
            return {"type": None, "holders": []}
        try:
            raw = self.io.execute(caps, "lock", "get_info")
        except (KeyError, ClsError):
            return {"type": None, "holders": []}
        return json.loads(raw)

    def _check_caps(self, ino: int, write: bool, what: str) -> None:
        """Fail-fast conflict check for capability-less ops: an op by
        this client is refused while ANOTHER mount holds conflicting
        caps (the reference would instead revoke asynchronously)."""
        st = self._caps_state(ino)
        others = [h for h in st["holders"]
                  if self._holder_mount(h) != self.name]
        if not others:
            return
        if write or st["type"] == "exclusive":
            raise FsBusy(f"{what}: caps held by {others} "
                         f"({st['type']})")

    def open(self, path: str, mode: str = "r") -> "FsFile":
        """Acquire caps and return a handle: "r" -> shared (Fr),
        "w"/"rw" -> exclusive (Fw, creating the file if absent).
        A conflicting holder raises FsBusy — the fail-fast analog of
        the MDS delaying the open until revoke completes."""
        if mode not in ("r", "w", "rw"):
            raise ValueError(f"bad mode {mode!r}")
        writable = "w" in mode
        try:
            ent = self._walk(self._split(path))
        except FileNotFoundError:
            if not writable:
                raise
            self.create(path)
            ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        caps = self._caps_obj(ent["ino"])
        try:
            self.io.stat(caps)
        except KeyError:
            self.io.write_full(caps, b"caps")
        # one locker PER HANDLE (owner#seq — the owner+cookie pairing):
        # closing one of this mount's two read handles must release
        # only its own cap, not the sibling's
        self._handle_seq = getattr(self, "_handle_seq", 0) + 1
        holder = f"{self.name}#{self._handle_seq}"
        try:
            self.io.execute(caps, "lock", "lock", json.dumps(
                {"owner": holder,
                 "type": "exclusive" if writable else "shared"}
            ).encode())
        except ClsError as e:
            raise FsBusy(f"open {path} ({mode}): {e}") from None
        return FsFile(self, path, ent["ino"], mode, holder)

    def caps_info(self, path: str) -> dict:
        """{'type', 'holders'} for the path's inode (session ls role)."""
        ent = self._walk(self._split(path))
        return self._caps_state(ent["ino"])

    def break_caps(self, path: str, holder: str) -> None:
        """Operator eviction of a dead holder's caps (ref: cls_lock
        break_lock; `ceph tell mds.N client evict` role). `holder` is
        a full '{mount}#{seq}' string as listed by caps_info; a bare
        mount name evicts every one of that mount's handles."""
        ent = self._walk(self._split(path))
        victims = [h for h in self._caps_state(ent["ino"])["holders"]
                   if h == holder or self._holder_mount(h) == holder]
        for v in victims:
            try:
                self.io.execute(self._caps_obj(ent["ino"]), "lock",
                                "break_lock",
                                json.dumps({"owner": v}).encode())
            except (KeyError, ClsError):
                pass                 # no caps object / already gone

    def _release_caps(self, ino: int, holder: str) -> None:
        try:
            self.io.execute(self._caps_obj(ino), "lock", "unlock",
                            json.dumps({"owner": holder}).encode())
        except (KeyError, ClsError):
            pass                     # already broken/unlinked

    @staticmethod
    def _expect(ent: dict, path: str, expect_ino: int | None) -> None:
        """Stale-handle guard, enforced on the SAME walked entry the
        I/O uses (no second resolve, no check-then-act window)."""
        if expect_ino is not None and ent["ino"] != expect_ino:
            raise FsError(
                f"{path}: stale handle (inode {expect_ino} -> "
                f"{ent['ino']}; the name was replaced underneath)")

    def write(self, path: str, data: bytes, offset: int = 0,
              _expect_ino: int | None = None) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=True, what=f"write {path}")
        self._striper.write(self._data_obj(ent["ino"]), bytes(data),
                            offset=offset)
        new_size = max(ent["size"], offset + len(data))
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir",
                        "update",
                        json.dumps({"name": name,
                                    "fields": {"size": new_size,
                                               "mtime": self._clock()}
                                    }).encode())

    def read(self, path: str, length: int | None = None,
             offset: int = 0, _expect_ino: int | None = None) -> bytes:
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=False, what=f"read {path}")
        if ent["size"] == 0:
            return b""
        if length is None:
            length = max(0, ent["size"] - offset)
        return self._striper.read(self._data_obj(ent["ino"]),
                                  length=length, offset=offset)

    def truncate(self, path: str, size: int,
                 _expect_ino: int | None = None) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=True,
                         what=f"truncate {path}")
        if ent["size"] == 0 and size > 0:
            # sparse grow of a never-written file: materialize zeros
            self._striper.write(self._data_obj(ent["ino"]), b"\x00")
        if ent["size"] > 0 or size > 0:
            self._striper.truncate(self._data_obj(ent["ino"]), size)
        self.io.execute(self._dir_obj(parent["ino"]), "fs_dir",
                        "update",
                        json.dumps({"name": name,
                                    "fields": {"size": size,
                                               "mtime": self._clock()}
                                    }).encode())


class FsFile:
    """An open file handle holding capabilities until close() — the
    Fh + caps pairing of the reference client. Read requires Fr
    (any mode), write/truncate require Fw (mode with "w"); close
    releases exactly this handle's cap (holder = mount#seq), never a
    sibling handle's. Context-manager friendly.

    Handles are PATH-pinned (a lite deviation from the reference's
    ino-addressed Fh): each I/O's single path resolve must still name
    the inode the caps were granted on (enforced on the same walked
    entry the I/O uses) — a rename or unlink+recreate underneath
    turns the handle stale and raises FsError instead of silently
    writing a DIFFERENT inode under the old inode's caps (which would
    let two exclusive writers coexist). Caps checks in rename/unlink
    make that impossible across mounts; the guard catches the same
    mount doing it to itself."""

    def __init__(self, client: FsClient, path: str, ino: int,
                 mode: str, holder: str):
        self.client, self.path, self.ino = client, path, ino
        self.mode, self.holder = mode, holder
        self._open = True

    def _alive(self) -> None:
        if not self._open:
            raise ValueError(f"I/O on closed file {self.path}")

    def read(self, length: int | None = None, offset: int = 0) -> bytes:
        self._alive()
        return self.client.read(self.path, length=length, offset=offset,
                                _expect_ino=self.ino)

    def write(self, data: bytes, offset: int = 0) -> None:
        self._alive()
        if "w" not in self.mode:
            raise PermissionError(
                f"{self.path}: opened read-only (no Fw cap)")
        self.client.write(self.path, data, offset=offset,
                          _expect_ino=self.ino)

    def truncate(self, size: int) -> None:
        self._alive()
        if "w" not in self.mode:
            raise PermissionError(
                f"{self.path}: opened read-only (no Fw cap)")
        self.client.truncate(self.path, size, _expect_ino=self.ino)

    def close(self) -> None:
        if self._open:
            self._open = False
            self.client._release_caps(self.ino, self.holder)

    def __enter__(self) -> "FsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
