"""CephFS-lite — the POSIX-shaped file layer over rados.

Rebuild of the reference's filesystem data/metadata split (ref:
src/mds/ — CInode/CDentry/MDCache; dirfrag omap objects holding
dentries with EMBEDDED inodes, src/mds/CDir.cc; file DATA addressed
by inode number through the file layout into plain rados objects,
src/osd + libcephfs read/write path; client ops shape ref:
src/client/Client.cc mkdir/create/unlink/rename/readdir).

Mapping onto this framework:

* DIRECTORIES are objects (`.fs.dir.{ino}`) whose dentries live in
  the object-class KV plane and mutate atomically AT the object via
  the `fs_dir` class below — exactly the dirfrag-omap role. Each
  dentry embeds its inode (type, size, mtime, ino), the reference's
  primary-dentry embedding.
* FILE DATA is striped at `.fs.data.{ino}` through the RadosStriper —
  the file-layout striping of {ino}.{index} objects, client-side.
* INODE NUMBERS come from an allocator object (`.fs.meta`) bumped via
  cls (the InoTable role).
* The MDS ITSELF — a metadata-caching server process — collapses to
  these object-class methods: metadata mutations are already atomic
  at the dirfrag object, so the sim needs no extra daemon between
  client and OSD.
* FILE CAPABILITIES (ref: src/mds/Locker.cc issue/revoke; client
  caps Fr/Fw in src/client/Client.cc) map onto the cls `lock` class
  on a per-inode caps anchor (`.fs.caps.{ino}`): `open(path, "r")`
  acquires a SHARED lock (the Fr cap), `open(path, "w"/"rw")` an
  EXCLUSIVE one (Fw); conflicting opens fail with FsBusy instead of
  the reference's asynchronous revoke (fail-fast-lite), bare
  write/truncate/unlink refuse while another client holds caps, and
  `break_caps` is the operator eviction path for a dead holder
  (`ceph tell mds.N client evict` role). Multiple-active-MDS stays
  out of scope.

Everything rides librados/striper: EC fan-out, snapshots' COW,
recovery, scrub, and PG splits apply to file data and dirfrags with
no special cases."""

from __future__ import annotations

import json
import posixpath

from ..client.rados import IoCtx, RadosStriper
from ..osd.objclass import ClsError, ClsHandle, register_cls

ROOT_INO = 1
_META_OBJ = ".fs.meta"


class FsError(Exception):
    pass


class NotADir(FsError, NotADirectoryError):
    pass


class IsADir(FsError, IsADirectoryError):
    pass


class NotEmpty(FsError, OSError):
    pass


class FsBusy(FsError, OSError):
    """A conflicting capability is held by another client."""


# -- dirfrag object class (CDir dentry ops) ----------------------------------

@register_cls("fs_dir", "link")
def _dir_link(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    dents = h.kv.setdefault("dentries", {})
    if req["name"] in dents and not req.get("replace", False):
        raise ClsError(f"EEXIST: {req['name']}")
    dents[req["name"]] = req["ent"]
    # the dentry count rides back so the client can decide to split
    # this frag (CDir::should_split checks size at the MDS the same
    # way — on the structure that just grew)
    return json.dumps({"count": len(dents)}).encode()


@register_cls("fs_dir", "unlink")
def _dir_unlink(h: ClsHandle, inp: bytes) -> bytes:
    name = json.loads(inp)["name"]
    dents = h.kv.setdefault("dentries", {})
    if name not in dents:
        raise ClsError(f"ENOENT: {name}")
    ent = dents.pop(name)
    return json.dumps({"ent": ent, "count": len(dents)}).encode()


@register_cls("fs_dir", "get_bits")
def _dir_get_bits(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps({"bits": h.kv.get("frag_bits", 0)}).encode()


@register_cls("fs_dir", "set_bits")
def _dir_set_bits(h: ClsHandle, inp: bytes) -> bytes:
    h.kv["frag_bits"] = json.loads(inp)["bits"]
    return b"{}"


@register_cls("fs_dir", "load")
def _dir_load(h: ClsHandle, inp: bytes) -> bytes:
    """Replace this frag's whole dentry table in one op (the bulk
    move of a split/merge; frag_bits in the same KV is untouched)."""
    h.kv["dentries"] = json.loads(inp)
    return b"{}"


@register_cls("fs_dir", "set_quota")
def _dir_set_quota(h: ClsHandle, inp: bytes) -> bytes:
    q = json.loads(inp)
    if q:
        h.kv["quota"] = q
    else:
        h.kv.pop("quota", None)
    return b"{}"


@register_cls("fs_dir", "get_quota")
def _dir_get_quota(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps(h.kv.get("quota", {})).encode()


@register_cls("fs_dir", "clear")
def _dir_clear(h: ClsHandle, inp: bytes) -> bytes:
    h.kv.pop("dentries", None)
    return b"{}"


@register_cls("fs_dir", "lookup")
def _dir_lookup(h: ClsHandle, inp: bytes) -> bytes:
    name = json.loads(inp)["name"]
    ent = h.kv.get("dentries", {}).get(name)
    if ent is None:
        raise ClsError(f"ENOENT: {name}")
    return json.dumps(ent).encode()


@register_cls("fs_dir", "route")
def _dir_route(h: ClsHandle, inp: bytes) -> bytes:
    """Combined bits+lookup on the BASE dirfrag: an unfragmented dir
    (the common case) answers the dentry in ONE round-trip; a
    fragmented one returns its bits so the client re-aims at the frag
    — the MDS client piggybacks the fragtree on traversal the same
    way instead of refetching it per hop."""
    name = json.loads(inp)["name"]
    bits = h.kv.get("frag_bits", 0)
    if bits:
        return json.dumps({"bits": bits}).encode()
    ent = h.kv.get("dentries", {}).get(name)
    return json.dumps({"bits": 0, "found": ent is not None,
                       "ent": ent}).encode()


@register_cls("fs_dir", "list")
def _dir_list(h: ClsHandle, inp: bytes) -> bytes:
    return json.dumps(h.kv.get("dentries", {})).encode()


@register_cls("fs_dir", "update")
def _dir_update(h: ClsHandle, inp: bytes) -> bytes:
    req = json.loads(inp)
    ent = h.kv.get("dentries", {}).get(req["name"])
    if ent is None:
        raise ClsError(f"ENOENT: {req['name']}")
    ent.update(req["fields"])
    return json.dumps(ent).encode()


@register_cls("fs_meta", "alloc_ino")
def _meta_alloc(h: ClsHandle, inp: bytes) -> bytes:
    nxt = h.kv.get("next_ino", ROOT_INO + 1)
    h.kv["next_ino"] = nxt + 1
    return json.dumps({"ino": nxt}).encode()


class FsClient:
    """A mounted filesystem handle (the libcephfs Client role).

    `name` identifies this mount as a capability owner (the client
    session id the MDS would track); two FsClients with different
    names contend for caps. Each open handle is its own locker
    ('{name}#{seq}'), so shared handles of one mount coexist and
    close independently; exclusive conflicts — including same-mount
    upgrades — fail fast with FsBusy."""

    STRIPE_UNIT = 1 << 16
    STRIPE_COUNT = 4
    OBJECT_SIZE = 1 << 20

    def __init__(self, ioctx: IoCtx, name: str = "fsclient",
                 frag_split_threshold: int = 128,
                 frag_merge_threshold: int | None = None,
                 max_frag_bits: int = 6,
                 full_stripe_writes: bool = False):
        self.io = ioctx
        self.name = name
        # directory fragmentation knobs (ref: mds_bal_split_size /
        # mds_bal_merge_size + fragtree_t). Simplification disclosed:
        # fragmentation is UNIFORM per directory (all frags at one
        # bit-depth), where the reference's fragtree can split frags
        # unevenly.
        self.frag_split_threshold = frag_split_threshold
        self.frag_merge_threshold = (frag_split_threshold // 8
                                     if frag_merge_threshold is None
                                     else frag_merge_threshold)
        self.max_frag_bits = max_frag_bits
        # r20: file data rides write_at (partial-stripe fast path on
        # EC pools) unless the full-stripe fallback knob is set
        self._striper = RadosStriper(
            ioctx, stripe_unit=self.STRIPE_UNIT,
            stripe_count=self.STRIPE_COUNT,
            object_size=self.OBJECT_SIZE,
            full_stripe_writes=full_stripe_writes)
        # mkfs-on-first-mount: root dirfrag + ino allocator
        try:
            self.io.stat(_META_OBJ)
        except KeyError:
            self.io.write_full(_META_OBJ, b"fsmeta")
            self.io.write_full(self._dir_obj(ROOT_INO), b"dirfrag")

    # -- naming --------------------------------------------------------------

    @staticmethod
    def _dir_obj(ino: int) -> str:
        return f".fs.dir.{ino}"

    @staticmethod
    def _data_obj(ino: int) -> str:
        return f".fs.data.{ino}"

    @staticmethod
    def _caps_obj(ino: int) -> str:
        # the per-inode capability anchor: one UNSTRIPED object whose
        # cls-lock KV is the caps ledger (the Locker's per-inode state)
        return f".fs.caps.{ino}"

    def _clock(self) -> float:
        from ..client.rados import sim_clock
        return sim_clock(self.io)

    def _alloc_ino(self) -> int:
        out = self.io.execute(_META_OBJ, "fs_meta", "alloc_ino")
        return json.loads(out)["ino"]

    # -- directory fragmentation (CDir::split/merge, fragtree_t) -------------

    def _frag_obj(self, ino: int, frag: int, bits: int) -> str:
        return f"{self._dir_obj(ino)}.f{frag:x}b{bits}"

    def _dir_bits(self, ino: int) -> int:
        raw = self.io.execute(self._dir_obj(ino), "fs_dir", "get_bits")
        return json.loads(raw)["bits"]

    @staticmethod
    def _frag_of(name: str, bits: int) -> int:
        import zlib
        return zlib.crc32(name.encode()) & ((1 << bits) - 1) \
            if bits else 0

    def _dentry_obj(self, ino: int, name: str,
                    bits: int | None = None) -> str:
        """The object holding `name`'s dentry under the dir's current
        fragmentation (bits 0 = the base dirfrag itself)."""
        if bits is None:
            bits = self._dir_bits(ino)
        if bits == 0:
            return self._dir_obj(ino)
        return self._frag_obj(ino, self._frag_of(name, bits), bits)

    def _frag_objs(self, ino: int, bits: int) -> list[str]:
        if bits == 0:
            return [self._dir_obj(ino)]
        return [self._frag_obj(ino, f, bits) for f in range(1 << bits)]

    def _list_all(self, ino: int, bits: int | None = None) -> dict:
        """Merged dentries across every frag (CDir::get_dentries over
        the fragtree)."""
        if bits is None:
            bits = self._dir_bits(ino)
        out: dict = {}
        for obj in self._frag_objs(ino, bits):
            try:
                out.update(json.loads(
                    self.io.execute(obj, "fs_dir", "list")))
            except (ClsError, KeyError):
                pass    # frag object missing: empty frag
        return out

    def _link(self, ino: int, name: str, ent: dict,
              replace: bool = False) -> None:
        obj = self._dentry_obj(ino, name)
        raw = self.io.execute(obj, "fs_dir", "link",
                              json.dumps({"name": name, "ent": ent,
                                          "replace": replace}).encode())
        if json.loads(raw)["count"] > self.frag_split_threshold:
            self._split_dir(ino)

    def _unlink(self, ino: int, name: str) -> None:
        obj = self._dentry_obj(ino, name)
        raw = self.io.execute(obj, "fs_dir", "unlink",
                              json.dumps({"name": name}).encode())
        # this frag's remaining count is a LOWER bound on the dir
        # total: above the merge threshold the full 2^bits listing in
        # _maybe_merge can't fire and is skipped at zero extra I/O
        if json.loads(raw)["count"] <= self.frag_merge_threshold:
            self._maybe_merge(ino)

    def _reload_level(self, ino: int, bits: int, dents: dict) -> None:
        """Write `dents` out as fragmentation level `bits` (bulk load
        per frag), without touching frag_bits."""
        groups: dict[int, dict] = {}
        for name, ent in dents.items():
            groups.setdefault(self._frag_of(name, bits), {})[name] = ent
        for f, obj in enumerate(self._frag_objs(ino, bits)):
            if bits:
                self.io.write_full(obj, b"dirfrag")
            self.io.execute(obj, "fs_dir", "load",
                            json.dumps(groups.get(f, {})).encode())

    def _split_dir(self, ino: int) -> None:
        """One level deeper (CDir::split). Crash ordering: new frags
        are fully materialized BEFORE frag_bits flips (readers keep
        the old layout until the single-object commit point), then the
        old level is cleared; a crash in between leaves unreachable
        stale copies that the next split/merge rewrites."""
        bits = self._dir_bits(ino)
        if bits >= self.max_frag_bits:
            return
        dents = self._list_all(ino, bits)
        self._reload_level(ino, bits + 1, dents)
        self.io.execute(self._dir_obj(ino), "fs_dir", "set_bits",
                        json.dumps({"bits": bits + 1}).encode())
        self._drop_level(ino, bits)

    def _maybe_merge(self, ino: int) -> None:
        """Shallower — as many levels as the shrink warrants — when
        the whole dir dropped below the merge threshold (CDir::merge;
        upstream's mds_bal_merge_size)."""
        while True:
            bits = self._dir_bits(ino)
            if bits == 0:
                return
            dents = self._list_all(ino, bits)
            if len(dents) > self.frag_merge_threshold:
                return
            self._reload_level(ino, bits - 1, dents)
            self.io.execute(self._dir_obj(ino), "fs_dir", "set_bits",
                            json.dumps({"bits": bits - 1}).encode())
            self._drop_level(ino, bits)

    def _drop_level(self, ino: int, bits: int) -> None:
        if bits == 0:
            self.io.execute(self._dir_obj(ino), "fs_dir", "clear")
            return
        for obj in self._frag_objs(ino, bits):
            try:
                self.io.remove(obj)
            except KeyError:
                pass

    def frag_info(self, path: str) -> dict:
        """Observability: the dir's fragmentation state (`ceph tell
        mds dirfrag ls` role)."""
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        bits = self._dir_bits(ent["ino"])
        per = {}
        for obj in self._frag_objs(ent["ino"], bits):
            try:
                per[obj] = len(json.loads(
                    self.io.execute(obj, "fs_dir", "list")))
            except (ClsError, KeyError):
                per[obj] = 0
        return {"bits": bits, "frags": 1 << bits if bits else 1,
                "dentries": sum(per.values()), "per_frag": per}

    # -- directory quotas (ref: the vxattrs ceph.quota.max_bytes /
    #    ceph.quota.max_files, enforced by Client::check_quota_condition
    #    against the quota realm's rstats) --------------------------------

    class QuotaExceeded(FsError, OSError):
        pass

    def set_quota(self, path: str, max_bytes: int | None = None,
                  max_files: int | None = None) -> None:
        """`setfattr -n ceph.quota.*`: attach (or clear, with both
        None) a quota to a directory."""
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        q = {}
        for name, v in (("max_bytes", max_bytes),
                        ("max_files", max_files)):
            if v is not None:
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 1:
                    raise FsError(f"quota {name} must be a positive "
                                  f"int, got {v!r}")
                q[name] = v
        self.io.execute(self._dir_obj(ent["ino"]), "fs_dir",
                        "set_quota", json.dumps(q).encode())

    def get_quota(self, path: str) -> dict:
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        return json.loads(self.io.execute(
            self._dir_obj(ent["ino"]), "fs_dir", "get_quota"))

    def du(self, path: str) -> dict:
        """{bytes, files} under a directory (recursive; the rstats
        role, computed on demand — disclosed simplification vs the
        MDS's incrementally-maintained rstats)."""
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        return self._du_ino(ent["ino"])

    def _du_ino(self, ino: int) -> dict:
        total = {"bytes": 0, "files": 0}
        for name, ent in self._list_all(ino).items():
            if ent["type"] == "dir":
                sub = self._du_ino(ent["ino"])
                total["bytes"] += sub["bytes"]
                # a directory IS an entry (rentries counts subdirs
                # toward max_files in the reference's rstats)
                total["files"] += sub["files"] + 1
            else:
                total["bytes"] += ent["size"]
                total["files"] += 1
        return total

    def _check_quota(self, chain: list[int], add_bytes: int = 0,
                     add_files: int = 0) -> None:
        """Check every quota realm on the (pre-collected) ancestor
        chain; any quota the growth would breach refuses with EDQUOT
        (Client::check_quota_condition walks realms upward the same
        way). The chain comes from the op's own _walk — no second
        path resolution."""
        if add_bytes <= 0 and add_files <= 0:
            return
        for ino in chain:
            q = json.loads(self.io.execute(
                self._dir_obj(ino), "fs_dir", "get_quota"))
            if not q:
                continue
            use = self._du_ino(ino)
            if "max_bytes" in q \
                    and use["bytes"] + add_bytes > q["max_bytes"]:
                raise self.QuotaExceeded(
                    f"EDQUOT: {use['bytes']} + {add_bytes} bytes "
                    f"exceeds max_bytes={q['max_bytes']}")
            if "max_files" in q \
                    and use["files"] + add_files > q["max_files"]:
                raise self.QuotaExceeded(
                    f"EDQUOT: {use['files']} + {add_files} files "
                    f"exceeds max_files={q['max_files']}")

    # -- path walk (MDCache::path_traverse) ----------------------------------

    @staticmethod
    def _split(path: str) -> list[str]:
        path = posixpath.normpath("/" + path)
        return [p for p in path.split("/") if p]

    def _walk(self, parts: list[str],
              chain: list[int] | None = None) -> dict:
        """Resolve to the dentry of the LAST part; root pseudo-dentry
        for []. Raises FileNotFoundError / NotADir on the way. When
        `chain` is given, the inos of every DIRECTORY on the path
        (root included, the target too if it is a dir) are appended —
        the quota realm chain, collected for free during the walk."""
        cur = {"ino": ROOT_INO, "type": "dir", "size": 0, "mtime": 0.0}
        if chain is not None:
            chain.append(ROOT_INO)
        for i, name in enumerate(parts):
            if cur["type"] != "dir":
                raise NotADir("/" + "/".join(parts[:i]))
            try:
                r = json.loads(self.io.execute(
                    self._dir_obj(cur["ino"]), "fs_dir", "route",
                    json.dumps({"name": name}).encode()))
                if r["bits"] == 0:
                    if not r["found"]:
                        raise ClsError("ENOENT")
                    cur = r["ent"]
                else:
                    raw = self.io.execute(
                        self._dentry_obj(cur["ino"], name,
                                         bits=r["bits"]),
                        "fs_dir", "lookup",
                        json.dumps({"name": name}).encode())
                    cur = json.loads(raw)
            except (ClsError, KeyError):
                raise FileNotFoundError(
                    "/" + "/".join(parts[:i + 1])) from None
            if chain is not None and cur["type"] == "dir":
                chain.append(cur["ino"])
        return cur

    def _parent_and_name(self, path: str,
                         chain: list[int] | None = None
                         ) -> tuple[dict, str]:
        parts = self._split(path)
        if not parts:
            raise FsError("operation on /")
        parent = self._walk(parts[:-1], chain=chain)
        if parent["type"] != "dir":
            raise NotADir(posixpath.dirname("/" + "/".join(parts)))
        return parent, parts[-1]

    # -- metadata ops --------------------------------------------------------

    def mkdir(self, path: str) -> None:
        chain: list[int] = []
        parent, name = self._parent_and_name(path, chain=chain)
        self._check_quota(chain, add_files=1)
        ino = self._alloc_ino()
        self.io.write_full(self._dir_obj(ino), b"dirfrag")
        ent = {"ino": ino, "type": "dir", "size": 0,
               "mtime": self._clock()}
        self._link(parent["ino"], name, ent)

    def create(self, path: str, data: bytes = b"") -> None:
        """create + write in one call (the O_CREAT|O_WRONLY shape)."""
        chain: list[int] = []
        parent, name = self._parent_and_name(path, chain=chain)
        self._check_quota(chain, add_files=1)
        ino = self._alloc_ino()
        ent = {"ino": ino, "type": "file", "size": 0,
               "mtime": self._clock()}
        self._link(parent["ino"], name, ent)
        if data:
            self.write(path, data)

    def stat(self, path: str) -> dict:
        return dict(self._walk(self._split(path)))

    def readdir(self, path: str) -> dict[str, dict]:
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        return self._list_all(ent["ino"])

    def unlink(self, path: str) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] == "dir":
            raise IsADir(path)
        self._check_caps(ent["ino"], write=True, what=f"unlink {path}")
        self._unlink(parent["ino"], name)
        try:
            self._striper.remove(self._data_obj(ent["ino"]))
        except KeyError:
            pass                     # never written
        try:
            self.io.remove(self._caps_obj(ent["ino"]))
        except KeyError:
            pass                     # never opened

    def rmdir(self, path: str) -> None:
        parent, name = self._parent_and_name(path)
        ent = self._walk(self._split(path))
        if ent["type"] != "dir":
            raise NotADir(path)
        if self.readdir(path):
            raise NotEmpty(path)
        bits = self._dir_bits(ent["ino"])
        self._unlink(parent["ino"], name)
        if bits:
            self._drop_level(ent["ino"], bits)
        self.io.remove(self._dir_obj(ent["ino"]))

    def rename(self, src: str, dst: str) -> None:
        """Atomic-at-the-dentries rename: unlink src, link dst with
        the SAME inode — data never moves (the MDS rename property).
        An existing dst file is replaced (POSIX); a dst dir must not
        exist."""
        schain: list[int] = []
        sparent, sname = self._parent_and_name(src, chain=schain)
        dchain: list[int] = []
        dparent, dname = self._parent_and_name(dst, chain=dchain)
        ent = self._walk(self._split(src))
        if sparent["ino"] == dparent["ino"] and sname == dname:
            # POSIX: same-path rename is a no-op. Without this the
            # dst link rewrites the dentry and the src unlink then
            # REMOVES it — the file vanishes and its data orphans.
            return
        # ONE dst resolution serves both the quota credit and the
        # replace/EEXIST checks below
        try:
            dent = self._walk(self._split(dst))
        except FileNotFoundError:
            dent = None
        if sparent["ino"] != dparent["ino"]:
            # a CROSS-directory move must satisfy the destination's
            # quota realms (the reference checks quota on cross-realm
            # rename) — a subtree brings its whole recursive usage
            if ent["type"] == "dir":
                use = self._du_ino(ent["ino"])
                mv_bytes, mv_files = use["bytes"], use["files"] + 1
            else:
                mv_bytes, mv_files = ent["size"], 1
            # a replace-rename frees the dst file it overwrites: the
            # NET growth is what quota enforces (POSIX replace into an
            # exactly-full realm must not spuriously EDQUOT)
            if dent is not None and dent["type"] == "file":
                mv_bytes -= dent["size"]
                mv_files -= 1
            # ancestors COMMON to src and dst see no net change from
            # the move — charging them would spuriously EDQUOT an
            # exactly-full shared realm
            common = set(schain)
            self._check_quota([i for i in dchain if i not in common],
                              add_bytes=mv_bytes, add_files=mv_files)
        if ent["type"] == "file":
            # a held capability pins the NAME too: renaming a file
            # out from under an open handle would strand its caps
            # (the MDS takes the dentry lock before rename the same
            # way)
            self._check_caps(ent["ino"], write=True,
                             what=f"rename {src}")
        if dent is not None:
            if dent["type"] == "dir":
                raise FsError(f"EEXIST: {dst} is a directory")
            if ent["type"] == "dir":
                # replacing an existing FILE with a directory is
                # ENOTDIR in POSIX (rename(2)); silently swapping the
                # types would strand the file's data object
                raise NotADir(dst)
            self._check_caps(dent["ino"], write=True,
                             what=f"rename over {dst}")
            old_ino = dent["ino"]
        else:
            old_ino = None
        self._link(dparent["ino"], dname, ent, replace=True)
        self._unlink(sparent["ino"], sname)
        if old_ino is not None and old_ino != ent["ino"]:
            for obj, rm in ((self._data_obj(old_ino),
                             self._striper.remove),
                            (self._caps_obj(old_ino), self.io.remove)):
                try:
                    rm(obj)
                except KeyError:
                    pass

    # -- data ops ------------------------------------------------------------

    # -- capabilities (Locker/caps-lite) -------------------------------------

    @staticmethod
    def _holder_mount(holder: str) -> str:
        """Holder strings are '{mount}#{handle-seq}' (the owner+cookie
        pairing of cls_lock in the reference — the cookie makes each
        handle its own locker, so closing one of a mount's two handles
        releases only its own cap)."""
        return holder.split("#", 1)[0]

    def _caps_state(self, ino: int) -> dict:
        caps = self._caps_obj(ino)
        try:
            self.io.stat(caps)   # get_info on a missing object would
        except KeyError:         # materialize its KV as a side effect
            return {"type": None, "holders": []}
        try:
            raw = self.io.execute(caps, "lock", "get_info")
        except (KeyError, ClsError):
            return {"type": None, "holders": []}
        return json.loads(raw)

    def _check_caps(self, ino: int, write: bool, what: str) -> None:
        """Fail-fast conflict check for capability-less ops: an op by
        this client is refused while ANOTHER mount holds conflicting
        caps (the reference would instead revoke asynchronously)."""
        st = self._caps_state(ino)
        others = [h for h in st["holders"]
                  if self._holder_mount(h) != self.name]
        if not others:
            return
        if write or st["type"] == "exclusive":
            raise FsBusy(f"{what}: caps held by {others} "
                         f"({st['type']})")

    def open(self, path: str, mode: str = "r") -> "FsFile":
        """Acquire caps and return a handle: "r" -> shared (Fr),
        "w"/"rw" -> exclusive (Fw, creating the file if absent).
        A conflicting holder raises FsBusy — the fail-fast analog of
        the MDS delaying the open until revoke completes."""
        if mode not in ("r", "w", "rw"):
            raise ValueError(f"bad mode {mode!r}")
        writable = "w" in mode
        try:
            ent = self._walk(self._split(path))
        except FileNotFoundError:
            if not writable:
                raise
            self.create(path)
            ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        caps = self._caps_obj(ent["ino"])
        try:
            self.io.stat(caps)
        except KeyError:
            self.io.write_full(caps, b"caps")
        # one locker PER HANDLE (owner#seq — the owner+cookie pairing):
        # closing one of this mount's two read handles must release
        # only its own cap, not the sibling's
        self._handle_seq = getattr(self, "_handle_seq", 0) + 1
        holder = f"{self.name}#{self._handle_seq}"
        try:
            self.io.execute(caps, "lock", "lock", json.dumps(
                {"owner": holder,
                 "type": "exclusive" if writable else "shared"}
            ).encode())
        except ClsError as e:
            raise FsBusy(f"open {path} ({mode}): {e}") from None
        return FsFile(self, path, ent["ino"], mode, holder)

    def caps_info(self, path: str) -> dict:
        """{'type', 'holders'} for the path's inode (session ls role)."""
        ent = self._walk(self._split(path))
        return self._caps_state(ent["ino"])

    def break_caps(self, path: str, holder: str) -> None:
        """Operator eviction of a dead holder's caps (ref: cls_lock
        break_lock; `ceph tell mds.N client evict` role). `holder` is
        a full '{mount}#{seq}' string as listed by caps_info; a bare
        mount name evicts every one of that mount's handles."""
        ent = self._walk(self._split(path))
        victims = [h for h in self._caps_state(ent["ino"])["holders"]
                   if h == holder or self._holder_mount(h) == holder]
        for v in victims:
            try:
                self.io.execute(self._caps_obj(ent["ino"]), "lock",
                                "break_lock",
                                json.dumps({"owner": v}).encode())
            except (KeyError, ClsError):
                pass                 # no caps object / already gone

    def _release_caps(self, ino: int, holder: str) -> None:
        try:
            self.io.execute(self._caps_obj(ino), "lock", "unlock",
                            json.dumps({"owner": holder}).encode())
        except (KeyError, ClsError):
            pass                     # already broken/unlinked

    @staticmethod
    def _expect(ent: dict, path: str, expect_ino: int | None) -> None:
        """Stale-handle guard, enforced on the SAME walked entry the
        I/O uses (no second resolve, no check-then-act window)."""
        if expect_ino is not None and ent["ino"] != expect_ino:
            raise FsError(
                f"{path}: stale handle (inode {expect_ino} -> "
                f"{ent['ino']}; the name was replaced underneath)")

    def write(self, path: str, data: bytes, offset: int = 0,
              _expect_ino: int | None = None) -> None:
        chain: list[int] = []
        parent, name = self._parent_and_name(path, chain=chain)
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=True, what=f"write {path}")
        self._check_quota(chain,
                          add_bytes=max(0, offset + len(data)
                                        - ent["size"]))
        self._striper.write(self._data_obj(ent["ino"]), bytes(data),
                            offset=offset)
        new_size = max(ent["size"], offset + len(data))
        self.io.execute(self._dentry_obj(parent["ino"], name),
                        "fs_dir", "update",
                        json.dumps({"name": name,
                                    "fields": {"size": new_size,
                                               "mtime": self._clock()}
                                    }).encode())

    def read(self, path: str, length: int | None = None,
             offset: int = 0, _expect_ino: int | None = None) -> bytes:
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=False, what=f"read {path}")
        if ent["size"] == 0:
            return b""
        if length is None:
            length = max(0, ent["size"] - offset)
        return self._striper.read(self._data_obj(ent["ino"]),
                                  length=length, offset=offset)

    def truncate(self, path: str, size: int,
                 _expect_ino: int | None = None) -> None:
        chain: list[int] = []
        parent, name = self._parent_and_name(path, chain=chain)
        ent = self._walk(self._split(path))
        if ent["type"] != "file":
            raise IsADir(path)
        self._expect(ent, path, _expect_ino)
        self._check_caps(ent["ino"], write=True,
                         what=f"truncate {path}")
        self._check_quota(chain,
                          add_bytes=max(0, size - ent["size"]))
        if ent["size"] == 0 and size > 0:
            # sparse grow of a never-written file: materialize zeros
            self._striper.write(self._data_obj(ent["ino"]), b"\x00")
        if ent["size"] > 0 or size > 0:
            self._striper.truncate(self._data_obj(ent["ino"]), size)
        self.io.execute(self._dentry_obj(parent["ino"], name),
                        "fs_dir", "update",
                        json.dumps({"name": name,
                                    "fields": {"size": size,
                                               "mtime": self._clock()}
                                    }).encode())


class FsFile:
    """An open file handle holding capabilities until close() — the
    Fh + caps pairing of the reference client. Read requires Fr
    (any mode), write/truncate require Fw (mode with "w"); close
    releases exactly this handle's cap (holder = mount#seq), never a
    sibling handle's. Context-manager friendly.

    Handles are PATH-pinned (a lite deviation from the reference's
    ino-addressed Fh): each I/O's single path resolve must still name
    the inode the caps were granted on (enforced on the same walked
    entry the I/O uses) — a rename or unlink+recreate underneath
    turns the handle stale and raises FsError instead of silently
    writing a DIFFERENT inode under the old inode's caps (which would
    let two exclusive writers coexist). Caps checks in rename/unlink
    make that impossible across mounts; the guard catches the same
    mount doing it to itself."""

    def __init__(self, client: FsClient, path: str, ino: int,
                 mode: str, holder: str):
        self.client, self.path, self.ino = client, path, ino
        self.mode, self.holder = mode, holder
        self._open = True

    def _alive(self) -> None:
        if not self._open:
            raise ValueError(f"I/O on closed file {self.path}")

    def read(self, length: int | None = None, offset: int = 0) -> bytes:
        self._alive()
        return self.client.read(self.path, length=length, offset=offset,
                                _expect_ino=self.ino)

    def write(self, data: bytes, offset: int = 0) -> None:
        self._alive()
        if "w" not in self.mode:
            raise PermissionError(
                f"{self.path}: opened read-only (no Fw cap)")
        self.client.write(self.path, data, offset=offset,
                          _expect_ino=self.ino)

    def truncate(self, size: int) -> None:
        self._alive()
        if "w" not in self.mode:
            raise PermissionError(
                f"{self.path}: opened read-only (no Fw cap)")
        self.client.truncate(self.path, size, _expect_ino=self.ino)

    def close(self) -> None:
        if self._open:
            self._open = False
            self.client._release_caps(self.ino, self.holder)

    def __enter__(self) -> "FsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
