"""MonitorCluster — quorum, leader election, replicated KV.

Rebuild of the reference's control plane shape (ref: src/mon/
Monitor.cc — rank-based election (Elector.cc: lowest reachable rank
wins), quorum = majority of the monmap; src/mon/Paxos.cc — proposals
commit only with quorum acks, each commit bumps a monotone version,
peons replicate the leader's transaction; src/mon/ConfigMonitor.cc —
the `ceph config set` KV; src/mon/OSDMonitor.cc — failure reports
become OSDMap updates only THROUGH a quorum commit).

Deliberately Paxos-lite: the sim is synchronous and partition-free
(a monitor is up or down, messages never reorder), so the full
prepare/promise/accept machinery collapses to: leader = lowest alive
rank; propose() commits iff a majority is alive; down monitors sync
the committed store on revive (the probing/synchronizing bootstrap
phases). What is kept faithfully is the OBSERVABLE contract the rest
of the system depends on:

* no quorum -> NO state changes anywhere (OSDMap epochs freeze, config
  stays, failure detection stalls) — the reference cluster's behavior
  when monitors lose majority;
* every commit carries a monotone version; a revived monitor replays
  to the committed version before voting again;
* reads are served only under quorum (the reference parks client
  sessions without it).

SimCluster routes every map mutation through propose(), so killing
monitors actually freezes the failure-handling pipeline — testable
elasticity the r01 sim lacked (its monitor logic was an infallible
singleton).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class NoQuorum(Exception):
    pass


@dataclass
class _Mon:
    rank: int
    alive: bool = True
    version: int = 0
    store: dict[str, object] = field(default_factory=dict)


class MonitorCluster:
    def __init__(self, n_mons: int = 3):
        if n_mons < 1:
            raise ValueError("need at least one monitor")
        self.mons = [_Mon(r) for r in range(n_mons)]
        self.commits = 0
        self.elections = 0
        self._last_leader: int | None = 0

    # -- membership ---------------------------------------------------------

    def kill(self, rank: int) -> None:
        self.mons[rank].alive = False

    def revive(self, rank: int) -> None:
        """Rejoin: sync the committed store before voting (the
        synchronizing phase). Syncing runs over the WHOLE quorum, not
        just the reviver: a quorum re-formed from monitors that came
        back during quorum loss may contain stale members, and a stale
        leader would fork history (reuse versions, lose commits)."""
        self.mons[rank].alive = True
        self._sync_quorum()

    def _sync_quorum(self) -> None:
        """Bring every quorum member to the committed (max) version —
        the probing/synchronizing phase every election runs before the
        quorum serves."""
        q = self.quorum()
        if q is None:
            return
        src = max((self.mons[r] for r in q), key=lambda m: m.version)
        for r in q:
            m = self.mons[r]
            if m.version < src.version:
                m.store = dict(src.store)
                m.version = src.version

    # -- election / quorum ---------------------------------------------------

    def quorum(self) -> list[int] | None:
        alive = [m.rank for m in self.mons if m.alive]
        if len(alive) * 2 > len(self.mons):
            return alive
        return None

    def leader(self) -> int | None:
        """Lowest rank in the quorum (Elector's winner)."""
        q = self.quorum()
        if q is None:
            return None
        lead = min(q)
        if lead != self._last_leader:
            self.elections += 1
            self._last_leader = lead
        return lead

    def _quorum_source(self) -> _Mon | None:
        q = self.quorum()
        if q is None:
            return None
        # any quorum member is at the committed version
        return max((self.mons[r] for r in q), key=lambda m: m.version)

    # -- paxos-lite commit ---------------------------------------------------

    def propose(self, key: str, value) -> int:
        """Commit key=value through the quorum; returns the new
        version. Raises NoQuorum when a majority is not alive — the
        caller's state change must NOT happen."""
        q = self.quorum()
        if q is None:
            raise NoQuorum(
                f"{sum(m.alive for m in self.mons)}/{len(self.mons)} "
                f"monitors alive; no majority")
        self._sync_quorum()  # a stale leader must never fork history
        leader = self.leader()
        v = self.mons[leader].version + 1
        for r in q:  # leader commits, peons replicate
            self.mons[r].store[key] = value
            self.mons[r].version = v
        self.commits += 1
        return v

    def get(self, key: str, default=None):
        """Read from the quorum (parked without one, like client
        sessions to a quorumless cluster)."""
        src = self._quorum_source()
        if src is None:
            raise NoQuorum("no majority; reads parked")
        return src.store.get(key, default)

    def version(self) -> int:
        src = self._quorum_source()
        if src is None:
            raise NoQuorum("no majority")
        return src.version

    # -- osd monitor role ----------------------------------------------------

    def record_up_thru(self, osd: int, epoch: int) -> int:
        """Commit an OSD's up_thru claim (the MOSDAlive handling, ref:
        OSDMonitor::prepare_alive -> osd_info_t::up_thru): the proof
        that an interval's primary was up at its start epoch rides the
        replicated store like any other map mutation — no quorum, no
        recorded up_thru, no PG activation. Monotone: a stale claim
        commits a no-op version bump but never regresses the value."""
        cur = int(self.get(f"osd/{osd}/up_thru", 0) or 0)
        return self.propose(f"osd/{osd}/up_thru", max(cur, int(epoch)))

    def up_thru(self, osd: int) -> int:
        """The committed up_thru for `osd` (0 = never recorded)."""
        return int(self.get(f"osd/{osd}/up_thru", 0) or 0)

    # -- config monitor role -------------------------------------------------

    def config_set(self, name: str, value) -> int:
        return self.propose(f"config/{name}", value)

    def config_dump(self) -> dict[str, object]:
        src = self._quorum_source()
        if src is None:
            raise NoQuorum("no majority")
        return {k[len("config/"):]: v for k, v in src.store.items()
                if k.startswith("config/")}
