"""Profile aggregation — cluster CPU flame profiles from per-daemon
sampling rings (r19).

The mgr half of the continuous-profiling plane (mgr/telemetry.py's
role for counters, played for folded stacks): daemons tick their
SamplingProfiler's interval-aligned stack deltas and ship fresh
entries in MgrReports (`profile` field); every monitor independently
folds them into

* a CUMULATIVE per-daemon flame profile (fold of every shipped delta
  — survives daemon ring eviction, horizon bounded only by monitor
  uptime),
* a CLUSTER flame profile that is the EXACT integer fold of the
  per-daemon ones (merge of merges == merge of all — the r18
  bit-exact-merge rule, pinned by tests), and
* a bounded per-interval series of category splits (attribution
  drift over time, aligned across daemons by the shared-clock bucket
  index like the telemetry plane).

Served as `profile cpu [daemon] [--collapsed|--speedscope]` (mon cmd
+ asok + `ceph_cli flame`): the default view is a category self-time
split + top stacks, `--collapsed` is folded-stack text (flamegraph.pl
/ speedscope import), `--speedscope` a complete speedscope JSON
document.
"""

from __future__ import annotations

import threading
import time

from ..utils.profiler import (PROFILE_CATEGORIES, category_split,
                              collapsed_lines, merge_stacks, speedscope,
                              top_stacks)

__all__ = ["ProfileAggregator"]

#: per-category distinct-stack cap per daemon: past it the smallest
#: counts fold into a "..." catch-all stack (disclosed via
#: stacks_folded, never silently dropped — sample totals are exact)
MAX_STACKS = 4096


class ProfileAggregator:
    def __init__(self, config=None, now_fn=time.time):
        self._config = config
        self._now = now_fn
        self._lock = threading.Lock()
        # name -> {"stacks", "samples", "busy_s", "hz", "last_t",
        #          "entries", "dropped_unshipped", "stacks_folded"}
        self._daemons: dict[str, dict] = {}
        # bucket -> {"t", "interval_s", "samples", "categories",
        #            "daemons": set}
        self._intervals: dict[int, dict] = {}

    def _opt(self, name: str, fallback):
        if self._config is not None:
            try:
                return self._config.get(name)
            except (KeyError, ValueError, TypeError):
                pass
        return fallback

    @property
    def max_intervals(self) -> int:
        return int(self._opt("mgr_history_len", 90))

    # -- ingestion (the MgrReport `profile` field) -------------------------

    def ingest(self, name: str, block: dict) -> None:
        """Fold one daemon's shipped profile block: interval entries
        (stack deltas) + the sampler's accounting stats."""
        if not isinstance(block, dict):
            return
        with self._lock:
            d = self._daemons.setdefault(name, {
                "stacks": {}, "samples": 0, "busy_s": 0.0,
                "hz": 0.0, "last_t": 0.0, "entries": 0,
                "dropped_unshipped": 0, "idle_samples": 0,
                "stacks_folded": 0})
            for ent in block.get("entries") or []:
                try:
                    stacks = ent.get("stacks") or {}
                    d["stacks"] = merge_stacks((d["stacks"], stacks))
                    d["samples"] += int(ent.get("samples", 0))
                    d["busy_s"] += float(ent.get("busy_s", 0.0))
                    d["hz"] = float(ent.get("hz", d["hz"]))
                    d["last_t"] = max(d["last_t"],
                                      float(ent.get("t", 0.0)))
                    d["entries"] += 1
                    self._fold_interval(name, ent, stacks)
                except (TypeError, ValueError):
                    continue     # one malformed entry never poisons
            self._trim_daemon(d)
            stats = block.get("stats")
            if isinstance(stats, dict):
                try:
                    d["dropped_unshipped"] = int(
                        stats.get("dropped_unshipped", 0))
                    d["idle_samples"] = int(
                        stats.get("idle_samples", 0))
                    d["hz"] = float(stats.get("hz", d["hz"]))
                except (TypeError, ValueError):
                    pass
            self._trim_intervals()

    def _fold_interval(self, name: str, ent: dict, stacks: dict) -> None:
        b = int(ent.get("bucket", 0))
        iv = self._intervals.setdefault(b, {
            "t": float(ent.get("t", 0.0)),
            "interval_s": float(ent.get("interval_s", 0.0)),
            "samples": 0,
            "categories": {c: 0 for c in PROFILE_CATEGORIES},
            "daemons": set()})
        iv["samples"] += int(ent.get("samples", 0))
        for cat, n in category_split(stacks).items():
            iv["categories"][cat] = iv["categories"].get(cat, 0) + n
        iv["daemons"].add(name)

    def _trim_daemon(self, d: dict) -> None:
        for bucket in d["stacks"].values():
            over = len(bucket) - MAX_STACKS
            if over <= 0:
                continue
            victims = sorted(bucket, key=lambda s: (bucket[s], s))
            folded = 0
            for stk in victims[:over]:
                folded += bucket.pop(stk)
            bucket["..."] = bucket.get("...", 0) + folded
            d["stacks_folded"] += over

    def _trim_intervals(self) -> None:
        over = len(self._intervals) - self.max_intervals
        if over > 0:
            for b in sorted(self._intervals,
                            key=lambda b: self._intervals[b]["t"])[:over]:
                del self._intervals[b]

    # -- views -------------------------------------------------------------

    def daemons(self) -> list[str]:
        with self._lock:
            return sorted(self._daemons)

    def flame(self, daemon: str | None = None) -> dict:
        """Merged {category: {stack: n}} — one daemon's cumulative
        profile, or the cluster fold of every daemon's (EXACT integer
        add, so cluster == merge of per-daemon merges)."""
        with self._lock:
            if daemon is not None:
                d = self._daemons.get(daemon)
                return {c: dict(s) for c, s in
                        (d["stacks"] if d else {}).items()}
            return merge_stacks(d["stacks"]
                                for d in self._daemons.values())

    def stats(self) -> dict:
        """Per-daemon sampler accounting (samples, hz, ring drops) —
        the `ceph_cli top` drop-gauge feed."""
        with self._lock:
            return {name: {"samples": d["samples"],
                           "idle_samples": d["idle_samples"],
                           "hz": d["hz"],
                           "entries": d["entries"],
                           "dropped_unshipped": d["dropped_unshipped"],
                           "stacks_folded": d["stacks_folded"],
                           "sampler_busy_s": round(d["busy_s"], 6)}
                    for name, d in sorted(self._daemons.items())}

    def intervals(self, limit: int = 16) -> list[dict]:
        """Newest-last per-interval category splits (the drift
        series), wall-time ordered like the telemetry plane."""
        with self._lock:
            bs = sorted(self._intervals,
                        key=lambda b: self._intervals[b]["t"])
            out = []
            for b in bs[-int(limit):]:
                iv = self._intervals[b]
                out.append({"bucket": b, "t": iv["t"],
                            "interval_s": iv["interval_s"],
                            "samples": iv["samples"],
                            "categories": dict(iv["categories"]),
                            "daemons": sorted(iv["daemons"])})
            return out

    def dump(self, daemon: str | None = None, top_n: int = 10) -> dict:
        """The `profile cpu [daemon]` body: category split + top
        stacks + per-daemon accounting + the drift series."""
        stacks = self.flame(daemon)
        split = category_split(stacks)
        total = sum(split.values())
        return {
            "daemon": daemon or "cluster",
            "daemons": self.daemons(),
            "samples": total,
            "categories": split,
            "category_share": {c: round(v / total, 4) if total else 0.0
                               for c, v in split.items()},
            "top_stacks": top_stacks(stacks, n=top_n),
            "stats": self.stats(),
            "intervals": self.intervals(),
        }

    # -- the command surface ----------------------------------------------

    def cpu_cmd(self, arg: str = "") -> dict:
        """`profile cpu [daemon] [--collapsed|--speedscope]` — ONE
        parser for the mon cmd, the asok, and ceph_cli flame."""
        daemon = None
        want = "summary"
        for word in (arg or "").split():
            if word == "--collapsed":
                want = "collapsed"
            elif word == "--speedscope":
                want = "speedscope"
            elif word.startswith("--"):
                raise ValueError(f"profile cpu: unknown flag {word!r}")
            else:
                daemon = word
        if daemon is not None and daemon not in self.daemons():
            return {"daemon": daemon, "found": False,
                    "daemons": self.daemons()}
        if want == "collapsed":
            return {"daemon": daemon or "cluster", "found": True,
                    "collapsed": collapsed_lines(self.flame(daemon))}
        if want == "speedscope":
            return {"daemon": daemon or "cluster", "found": True,
                    "speedscope": speedscope(
                        self.flame(daemon),
                        name=f"{daemon or 'cluster'} cpu")}
        return {"found": True, **self.dump(daemon)}
