"""Placement plane — the device-batched balancer/upmap loop (r12).

The scalar balancer (`balancer.calc_pg_upmaps`, kept as the parity
oracle) walks PGs one at a time in Python: per move it re-derives one
PG's raw mapping, rebuilds failure-domain sets, and scans targets —
fine at 128 PGs, hopeless at 1M. This module runs the same greedy
max-deviation optimization as array programs:

* ONE batched `pgs_to_raw` launch per optimize() call maps every PG
  of the pool through the vectorized CRUSH mapper (chunked so one
  compiled program shape serves arbitrarily large pools). The raw
  mapping is invariant under upmap edits, so rounds after the first
  re-score against a host-side effective view instead of relaunching.
* Candidate generation is vectorized: every (pg, src_osd) shard held
  by an overfull device crossed with the most-underfull target set.
* Scoring runs ON DEVICE (`_score_kernel`, jitted): legality (target
  not already a member, failure-domain separation at the pool rule's
  chooseleaf type) and gain (deviation transfer) for the whole
  (N candidates x U targets) block in one launch — millions of
  candidates per step.
* Selection is a cheap host greedy over the device-ranked survivors,
  bounded by a DATA-MOVEMENT BUDGET (each accepted move migrates one
  PG shard; rebalancing at scale is a wire-cost problem first —
  PAPERS.md, arxiv 1309.0186).

Objective and legality match the scalar oracle: weight-proportional
expected load over up+in devices, moves only from overfull to
strictly-better targets (gain = dev[src] - dev[dst] - 1 > 0), domain
membership derived from the RAW set plus redirect targets (a
down-but-in member still owns its slot). The bit-exactness guard in
tests/test_placement.py pins batched results against scalar
`pg_to_up_acting_osds` after application.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..crush.map import CRUSH_ITEM_NONE
from .balancer import _domain_of, _rule_domain_type

_NONE = np.int32(CRUSH_ITEM_NONE)


def osd_domains(crush, type_id: int, n_osds: int) -> np.ndarray:
    """Per-device failure-domain id at bucket level `type_id` — the
    dense form of balancer._domain_of for every OSD at once. Devices
    with no ancestor at that level get a unique negative id (they can
    never clash with anything)."""
    dom = np.empty(n_osds, dtype=np.int32)
    cache: dict = {}
    for o in range(n_osds):
        d = _domain_of(crush, o, type_id, cache)
        # no-ancestor devices get unique ids far below any bucket id
        # (bucket ids are small negatives) but above the kernel's
        # masked-slot sentinel
        dom[o] = d if d is not None else -(10 ** 7) - o
    return dom


def chunked_pgs_to_raw(osdmap, pool_id: int,
                       chunk: int = 1 << 16) -> np.ndarray:
    """Full-pool raw mapping through fixed-size device launches: one
    compiled program shape (`chunk` lanes) serves any pg_num — at 1M
    PGs a monolithic batch would compile its own program and hold
    every intermediate live."""
    pool = osdmap.pools[pool_id]
    B = pool.pg_num
    if B <= chunk:
        return osdmap.pgs_to_raw(pool_id)
    out = np.empty((B, pool.size), np.int32)
    for s in range(0, B, chunk):
        n = min(chunk, B - s)
        ps = np.arange(s, s + chunk, dtype=np.uint32)  # pad past pg_num
        ps[n:] = s  # padded lanes recompute a real pg; result sliced off
        out[s:s + n] = osdmap.pgs_to_raw(pool_id, ps)[:n]
    return out


def apply_upmaps_to_raw(raw: np.ndarray, pool_id: int,
                        pg_upmap_items: dict) -> np.ndarray:
    """Effective placement: raw with every pg_upmap_items redirect
    applied (same semantics as OSDMap._apply_upmap, vectorized over
    the dense raw array with a sparse host overlay — upmaps are rare
    relative to pg_num)."""
    eff = raw.copy()
    B = raw.shape[0]
    for (pid, ps), items in pg_upmap_items.items():
        if pid != pool_id or ps >= B:
            continue
        row = eff[ps]
        for frm, to in items:
            if (row == to).any():
                continue  # a duplicate target would break slot sets
            hits = np.nonzero(row == frm)[0]
            if hits.size:
                row[hits[0]] = to
    return eff


@functools.partial(jax.jit, static_argnums=(5,))
def _score_kernel(members, src, dsts, dev, dom, topk):
    """Device scoring of the (N, U) candidate block.

    members: (N, 2S) raw-set + effective-set of each candidate's PG
             (CRUSH_ITEM_NONE padding); src: (N,) the overfull device
             each candidate would move a shard off; dsts: (U,) target
             devices; dev: (n_osds,) load deviation; dom: (n_osds,)
             failure-domain ids at the rule's separation level.

    Returns (best (N, topk), score (N, topk)): per candidate the
    indices into dsts of the topk highest-gain LEGAL targets (score
    -inf past the legal count). Several ranked targets per candidate
    keep the host greedy moving when the globally-best targets
    saturate mid-round (at 10k OSDs a best-only kernel stalled every
    round at ~100 accepts). Legality mirrors the scalar oracle:
    target not already a member of the PG, and its failure domain
    serves no OTHER shard (the source device's own occurrences are
    masked out).
    """
    none = jnp.int32(CRUSH_ITEM_NONE)
    valid = (members != none) & (members != src[:, None])      # (N, 2S)
    midx = jnp.clip(members, 0, dom.shape[0] - 1)
    # masked-out slots get a sentinel no real domain id can hold
    # (bucket ids are small negatives; -1 is a REAL bucket, and
    # osd_domains' no-ancestor ids stay above -(10^7 + n_osds))
    mdom = jnp.where(valid, dom[midx],
                     jnp.int32(-(2 ** 31) + 1))                # (N, 2S)
    ddom = dom[dsts]                                           # (U,)
    # (N, U): domain clash / already-member / gain
    clash = (mdom[:, :, None] == ddom[None, None, :]).any(axis=1)
    member = (members[:, :, None] == dsts[None, None, :]).any(axis=1)
    gain = dev[src][:, None] - dev[dsts][None, :] - 1.0
    score = jnp.where(clash | member | (gain <= 0.0),
                      -jnp.inf, gain)
    vals, best = jax.lax.top_k(score, topk)
    return best, vals


def _pow2_pad(n: int) -> int:
    return 1 << max(6, (n - 1).bit_length())


@dataclass
class BalanceResult:
    """What one batched optimize() run did — the numbers scale_sim
    commits and the bench schema pins."""
    moves: list = field(default_factory=list)
    proposed: dict = field(default_factory=dict)
    rounds: int = 0
    candidates_scored: int = 0
    score_elapsed_s: float = 0.0
    elapsed_s: float = 0.0
    max_dev_before: float = 0.0
    max_dev_after: float = 0.0
    spread_before: int = 0
    spread_after: int = 0
    budget: int | None = None
    budget_used: int = 0
    converged: bool = False

    @property
    def candidates_per_s(self) -> float:
        if self.score_elapsed_s <= 0:
            return 0.0
        return self.candidates_scored / self.score_elapsed_s

    def to_dict(self) -> dict:
        return {
            "moves": len(self.moves), "rounds": self.rounds,
            "candidates_scored": self.candidates_scored,
            "candidates_per_s": round(self.candidates_per_s, 1),
            "score_elapsed_s": round(self.score_elapsed_s, 4),
            "elapsed_s": round(self.elapsed_s, 4),
            "max_dev_before": round(self.max_dev_before, 3),
            "max_dev_after": round(self.max_dev_after, 3),
            "spread_before": self.spread_before,
            "spread_after": self.spread_after,
            "budget": self.budget, "budget_used": self.budget_used,
            "converged": self.converged,
        }


def telemetry_movement_budget(telemetry, base_budget: int,
                              pool_id: int = 1,
                              p99_ceiling_s: float | None = None) -> int:
    """Movement budget derived from live client latency (r18 — the
    ROADMAP item 5 hook): the base budget shrinks linearly with the
    telemetry plane's hottest fast-window SLO burn rate (rebalancing
    yields to suffering traffic; a fully burning SLO stops movement
    entirely), and `p99_ceiling_s` adds a rule-free guard — when the
    observed_client_latency feed's p99 exceeds it, movement stops
    regardless of declared rules.

    telemetry is a mgr/telemetry.TelemetryAggregator (or None: the
    base budget passes through — offline tools without a live feed
    keep their old semantics)."""
    if telemetry is None or base_budget is None:
        return base_budget
    burn = float(telemetry.burn_rate())
    if p99_ceiling_s is not None:
        ocl = telemetry.observed_client_latency(pool_id)
        if ocl.get("count") and ocl.get("p99_ms", 0.0) / 1e3 \
                > p99_ceiling_s:
            burn = 1.0
    return max(0, int(base_budget * (1.0 - min(1.0, burn))))


def batch_calc_pg_upmaps(osdmap, pool_id: int, max_deviation: int = 1,
                         max_movement: int | None = None,
                         max_src: int = 64, max_dst: int = 64,
                         max_rounds: int = 256, chunk: int = 1 << 16,
                         apply: bool = True,
                         raw: np.ndarray | None = None,
                         telemetry=None,
                         p99_ceiling_s: float | None = None
                         ) -> BalanceResult:
    """One device-batched optimization run over a whole pool.

    max_movement is the data-movement budget in PG shards (each move
    migrates one shard's worth of data); None = unbounded. Pass a
    precomputed `raw` (chunked_pgs_to_raw) to skip the mapping launch
    — the scale sim reuses one launch across balancer calls on an
    unchanged topology.

    telemetry (r18): a TelemetryAggregator whose SLO burn rate /
    observed client latency SHRINKS the movement budget before the
    run (telemetry_movement_budget) — the live balancer's
    yield-to-traffic gate. Requires max_movement (an unbounded run
    has no budget to shrink).

    Returns a BalanceResult; with apply=True the winning upmap set is
    landed on the map as ONE epoch (set_pg_upmap_bulk).
    """
    if telemetry is not None and max_movement is not None:
        max_movement = telemetry_movement_budget(
            telemetry, max_movement, pool_id=pool_id,
            p99_ceiling_s=p99_ceiling_s)
    t_all = time.monotonic()
    crush = osdmap.crush
    pool = osdmap.pools[pool_id]
    n_osds = len(osdmap.osd_weight)
    dom = osd_domains(crush, _rule_domain_type(crush, pool.crush_rule),
                      n_osds)
    if raw is None:
        raw = chunked_pgs_to_raw(osdmap, pool_id, chunk)
    items_now = {pg: list(v) for pg, v in osdmap.pg_upmap_items.items()
                 if pg[0] == pool_id}
    eff = apply_upmaps_to_raw(raw, pool_id, items_now)

    res = BalanceResult(budget=max_movement)
    up_mask = np.asarray(osdmap.osd_up)
    usable = up_mask & (np.asarray(osdmap.osd_weight) > 0)
    if usable.sum() < 2:
        res.elapsed_s = time.monotonic() - t_all
        return res
    w = np.asarray(osdmap.osd_weight, dtype=np.float64) / 0x10000
    wsum = w[usable].sum()

    def histo():
        flat = eff[(eff != _NONE) & up_mask[np.clip(eff, 0, n_osds - 1)]
                   & (eff >= 0)]
        return np.bincount(flat, minlength=n_osds).astype(np.float64)

    load = histo()
    expected = np.zeros(n_osds)
    expected[usable] = load[usable].sum() * w[usable] / wsum
    dev = np.where(usable, load - expected, 0.0)

    def spread():
        d = dev[usable]
        return float(d.max() - d.min()), float(np.abs(d).max())

    res.spread_before = int(round(spread()[0]))
    res.max_dev_before = spread()[1]
    touched: dict = {}
    dom_host = dom  # int64 domain ids

    for _round in range(max_rounds):
        sp, _ = spread()
        if sp <= max_deviation:
            res.converged = True
            break
        if max_movement is not None and res.budget_used >= max_movement:
            break
        order = np.argsort(-dev)
        srcs = [int(o) for o in order[:max_src]
                if usable[o] and dev[o] > 0][:max_src]
        under = np.argsort(dev)
        dsts = np.asarray([int(o) for o in under[:max_dst]
                           if usable[o]], dtype=np.int32)
        if not srcs or dsts.size == 0:
            break
        t0 = time.monotonic()
        # every (pg, slot) shard currently on an overfull device
        src_of = np.full(n_osds, -1, dtype=np.int32)
        src_of[srcs] = np.arange(len(srcs))
        eff_c = np.clip(eff, 0, n_osds - 1)
        # NONE is a large POSITIVE sentinel: clip would alias it onto
        # the last device, minting phantom candidates
        hit = (eff != _NONE) & (eff >= 0) & (src_of[eff_c] >= 0)
        pg_idx, slot_idx = np.nonzero(hit)
        if pg_idx.size == 0:
            break
        src_arr = eff[pg_idx, slot_idx].astype(np.int32)
        members = np.concatenate([raw[pg_idx], eff[pg_idx]], axis=1)
        # pad N to a pow2 bucket so the device program recompiles
        # O(log N) times, not once per round
        N = pg_idx.size
        Np = _pow2_pad(N)
        if Np != N:
            members = np.concatenate(
                [members, np.full((Np - N, members.shape[1]), _NONE,
                                  np.int32)])
            src_arr = np.concatenate(
                [src_arr, np.zeros(Np - N, np.int32)])
        topk = int(min(8, dsts.size))
        best, score = _score_kernel(
            jnp.asarray(members), jnp.asarray(src_arr),
            jnp.asarray(dsts), jnp.asarray(dev, jnp.float32),
            jnp.asarray(dom_host), topk)
        best = np.asarray(best)[:N]                 # (N, topk)
        score = np.asarray(score)[:N]
        res.candidates_scored += N * int(dsts.size)
        res.score_elapsed_s += time.monotonic() - t0

        moved_pgs: set[int] = set()
        accepted = 0
        for ci in np.argsort(-score[:, 0]):
            if not np.isfinite(score[ci, 0]):
                break
            if max_movement is not None \
                    and res.budget_used >= max_movement:
                break
            ps = int(pg_idx[ci])
            if ps in moved_pgs:
                continue
            src = int(src_arr[ci])
            # devs moved under us this round: walk this candidate's
            # ranked legal targets for the first whose gain survives.
            # Sign guards keep the movement budget honest: a shard
            # must leave a device still ABOVE target for one still
            # BELOW it, so every accepted move shrinks sum|dev| —
            # without them, late-round moves onto targets that had
            # already crossed zero burned ~2x the budget for zero
            # convergence (observed at the 512-OSD 2x cell)
            if dev[src] <= 0:
                continue
            dst = -1
            for k in range(topk):
                if not np.isfinite(score[ci, k]):
                    break
                cand = int(dsts[best[ci, k]])
                if dev[cand] < 0 and dev[src] - dev[cand] > 1.0:
                    dst = cand
                    break
            if dst < 0:
                continue
            pg = (pool_id, ps)
            items = touched.get(pg, items_now.get(pg, []))
            raw_row = raw[ps]
            if (raw_row == src).any():
                new_items = list(items) + [(src, dst)]
            else:
                act = [f for f, t in items
                       if t == src and (raw_row == f).any()]
                if not act:
                    continue  # inactive redirect: wrong shard
                new_items = [(f, t) for f, t in items
                             if (f, t) != (act[0], src)]
                new_items.append((act[0], dst))
            slot = int(np.nonzero(eff[ps] == src)[0][0])
            eff[ps, slot] = dst
            touched[pg] = new_items
            res.moves.append((pg, (src, dst)))
            moved_pgs.add(ps)
            res.budget_used += 1
            accepted += 1
            load[src] -= 1
            load[dst] += 1
            dev[src] = load[src] - expected[src]
            dev[dst] = load[dst] - expected[dst]
        res.rounds += 1
        if accepted == 0:
            break

    sp, mx = spread()
    res.spread_after = int(round(sp))
    res.max_dev_after = mx
    res.converged = res.converged or sp <= max_deviation
    res.proposed = touched
    if apply and touched:
        osdmap.set_pg_upmap_bulk(touched)
    res.elapsed_s = time.monotonic() - t_all
    return res
