"""Health model — cluster checks from the aggregated daemon reports.

Rebuild of the reference's health_check_map_t surface (ref:
src/mon/health_check.h + the producers: OSDMap::check_health for
OSD_DOWN, PGMap health for PG_DEGRADED/PG_AVAILABILITY/SLOW_OPS,
Monitor::get_health_status for MON_DOWN): each check carries a code,
a severity, a one-line summary and detail lines, and the overall
status is the worst surviving severity. Everything here derives from
REAL state — the committed OSDMap, the monitor's own liveness view,
and MgrReport-aggregated daemon counters — never synthesized values.
"""

from __future__ import annotations

HEALTH_OK = "HEALTH_OK"
HEALTH_WARN = "HEALTH_WARN"
HEALTH_ERR = "HEALTH_ERR"


def _check(code: str, severity: str, summary: str,
           detail: list[str]) -> dict:
    return {"code": code, "severity": severity, "summary": summary,
            "detail": detail}


def health_checks(osdmap=None, quorum: list[int] | None = None,
                  mon_members: list[int] | None = None,
                  reports=None, stale_grace: float = 15.0,
                  pg_num: int | None = None,
                  telemetry=None, netobs=None) -> dict:
    """-> {"status", "checks": [check...]}. Any argument may be None
    (a monitor answering before its first map simply has fewer
    producers). `telemetry` (r18, a TelemetryAggregator) contributes
    SLO_BURN / LATENCY_REGRESSION / TRACE_RING_OVERFLOW from the
    retained metric history — quiet unless SLO rules are declared
    (mgr_slo_rules) or a flight ring persistently overflows.
    `netobs` (r22, a NetworkAggregator) contributes
    OSD_SLOW_PING_TIME naming the links whose heartbeat RTT ewma
    crossed the live slow-ping threshold."""
    checks: list[dict] = []

    if telemetry is not None:
        try:
            checks.extend(telemetry.health_checks())
        except Exception:   # noqa: BLE001 — a telemetry bug must not
            pass            # take down status/health itself

    if netobs is not None:
        try:
            checks.extend(netobs.health_checks())
        except Exception:   # noqa: BLE001 — same containment rule
            pass

    if osdmap is not None:
        down = [o for o, up in enumerate(osdmap.osd_up) if not up]
        if down:
            checks.append(_check(
                "OSD_DOWN", HEALTH_WARN,
                f"{len(down)} osds down",
                [f"osd.{o} is down" for o in down]))
        out = [o for o in range(len(osdmap.osd_weight))
               if osdmap.osd_weight[o] == 0]
        if out:
            checks.append(_check(
                "OSD_OUT", HEALTH_WARN,
                f"{len(out)} osds out",
                [f"osd.{o} is out (weight 0)" for o in out]))
        # r21 capacity ladder (ref: OSDMap::check_health OSD_NEARFULL/
        # OSD_BACKFILLFULL/OSD_FULL + PG_POOL_FULL): rendered straight
        # from the COMMITTED map's ladder state — health says exactly
        # what the mon decided, never a re-derivation from raw statfs
        full_state = getattr(osdmap, "osd_full_state", {}) or {}
        for state, code, sev, why in (
                (3, "OSD_FULL", HEALTH_ERR,
                 "at/over mon_osd_full_ratio — client writes parked"),
                (2, "OSD_BACKFILLFULL", HEALTH_WARN,
                 "at/over osd_backfillfull_ratio — recovery into it "
                 "parks"),
                (1, "OSD_NEARFULL", HEALTH_WARN,
                 "at/over mon_osd_nearfull_ratio")):
            osds = sorted(o for o, s in full_state.items()
                          if s == state)
            if osds:
                checks.append(_check(
                    code, sev,
                    f"{len(osds)} osd(s) {code[4:].lower()}",
                    [f"osd.{o} is {why}" for o in osds]))
        full_pools = getattr(osdmap, "full_pools", None) or set()
        if full_pools:
            checks.append(_check(
                "POOL_FULL", HEALTH_ERR,
                f"{len(full_pools)} pool(s) full",
                [f"pool {p} hit its quota "
                 f"(quota_max_bytes="
                 f"{osdmap.pools[p].quota_max_bytes}, "
                 f"quota_max_objects="
                 f"{osdmap.pools[p].quota_max_objects}) — client "
                 f"writes parked"
                 for p in sorted(full_pools) if p in osdmap.pools]))

    if quorum is not None and mon_members is not None:
        missing = sorted(set(mon_members) - set(quorum))
        if missing:
            sev = HEALTH_ERR if len(quorum) <= len(mon_members) // 2 \
                else HEALTH_WARN
            checks.append(_check(
                "MON_DOWN", sev,
                f"{len(missing)}/{len(mon_members)} monitors down",
                [f"mon.{r} is not in quorum" for r in missing]))

    if reports is not None:
        totals = reports.totals()
        if totals["slow_ops"]:
            slow = [f"{name}: {e.get('slow_ops', 0)} slow ops"
                    for name, e in sorted(reports.daemons().items())
                    if e.get("slow_ops")]
            checks.append(_check(
                "SLOW_OPS", HEALTH_WARN,
                f"{totals['slow_ops']} slow ops, oldest past "
                f"osd_op_complaint_time", slow))
        states = reports.pg_states()
        degraded = sorted(pg for pg, st in states.items()
                          if "degraded" in st or "undersized" in st
                          or "down" in st or "incomplete" in st)
        if degraded:
            checks.append(_check(
                "PG_DEGRADED", HEALTH_WARN,
                f"{len(degraded)} pgs degraded",
                [f"pg {pg} is {states[pg]}" for pg in degraded]))
        # PG_EXPOSED (r17): a PG at m-1 surviving redundancy — one
        # more failure loses data. Louder than plain degradation (the
        # repair policy's m-1 override is already rebuilding these
        # first; the check is the operator-visible exposure window)
        exposed = sorted(pg for pg, st in states.items()
                         if "exposed" in st)
        if exposed:
            checks.append(_check(
                "PG_EXPOSED", HEALTH_WARN,
                f"{len(exposed)} pgs at m-1 redundancy (one more "
                f"failure loses data)",
                [f"pg {pg} is {states[pg]}" for pg in exposed]))
        peering = sorted(pg for pg, st in states.items()
                         if "peering" in st or "needs_up_thru" in st)
        if peering:
            checks.append(_check(
                "PG_AVAILABILITY", HEALTH_WARN,
                f"{len(peering)} pgs peering",
                [f"pg {pg} is {states[pg]}" for pg in peering]))
        # PG_STALE: a PG nobody's fresh report claims — its primary
        # stopped reporting (daemon wedged/killed before the map
        # noticed) or no primary claims the pgid at all
        stale_names = [n for n, age in reports.report_ages().items()
                       if age > stale_grace]
        stale_pgs: list[str] = []
        if pg_num is not None:
            claimed = set(states)
            fresh_claimed = {
                pg for name, e in reports.daemons().items()
                if name not in stale_names
                for pg in (e.get("pgs") or {})}
            for ps in range(pg_num):
                pgid = f"1.{ps}"
                if pgid not in fresh_claimed:
                    stale_pgs.append(
                        f"pg {pgid} "
                        + ("last claimed by a stale daemon"
                           if pgid in claimed else "has no primary "
                           "report"))
        if stale_pgs:
            checks.append(_check(
                "PG_STALE", HEALTH_WARN,
                f"{len(stale_pgs)} pgs stale", stale_pgs))

    order = {HEALTH_OK: 0, HEALTH_WARN: 1, HEALTH_ERR: 2}
    status = HEALTH_OK
    for c in checks:
        if order[c["severity"]] > order[status]:
            status = c["severity"]
    return {"status": status, "checks": checks}
