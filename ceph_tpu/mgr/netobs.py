"""Network observability plane (r22) — per-link RTT + flow.

Every prior plane (r9 counters, r15 traces, r18 telemetry, r19
profiles) attributes time to DAEMONS; the wire between them was a
blind spot the trace assembler literally labels "wire". This module
closes it with the reference's answer (ref: OSD::dump_osd_network +
the OSD_SLOW_PING_TIME health check off mon_warn_on_slow_ping_time /
mon_warn_on_slow_ping_ratio in src/osd/OSD.cc): measure the heartbeat
frames we already exchange.

Two halves share this file because they share the link-key vocabulary:

* ``LinkTracker`` — the DAEMON half. Each OSD folds heartbeat
  ping→pong round trips (and store sub-op round trips) into per-link
  state keyed ``(peer, channel)``: an r18 ``lhist`` (log2-µs buckets,
  mergeable by exact bucket addition), a responsive EWMA, and a
  two-window min/max. Channels: ``hb`` (MOSDPing round trips — the
  pure wire+dispatch signal) and ``store`` (store sub-op round trips
  — wire plus service time). The tracker's dump rides the MgrReport
  pipe as a side-field (like ``statfs``/``mclock`` — per-peer keys
  are dynamic, so they must NOT be perf-counter names; the r9
  declared-names rule).

* ``NetworkAggregator`` — the MONITOR half. Folds every daemon's
  shipped links+flow claim into the cluster link matrix; serves
  ``dump_osd_network`` (asok + wire + ``ceph_cli netstat``), raises
  ``OSD_SLOW_PING_TIME`` naming the worst links, renders bounded-
  cardinality prometheus exposition (worst-N links by p99, real
  ``# TYPE histogram`` per the r18 rule), and answers the
  ``link_cost(a, b)`` feed the r14 helper ranking, r11 hedge ladder,
  and r17 DownClock evidence consume in place of op-latency-only
  inference.

A link key is DIRECTED: ``osd.0 → osd.3 (hb)`` is osd.0's measurement
of its own ping's round trip through osd.3's fast dispatch. A one-way
delay injected on osd.0's sends toward osd.3 inflates exactly this
key (the reply crosses undelayed — reactor threads never sleep), which
is what lets the health check name one direction of one link.
"""

from __future__ import annotations

import threading
import time

from ..utils import perf_counters as _pc
from ..utils.perf_counters import (LHIST_BUCKETS, lhist_bucket,
                                   lhist_bucket_le, lhist_merge,
                                   lhist_quantiles)

#: EWMA smoothing for the per-link round trip: deliberately MORE
#: responsive than the reference's 1/5/15-minute decaying averages —
#: the health check must flip within two heartbeat grace windows of a
#: real degrade (the thrasher pins this), and at test-scale intervals
#: a slow horizon would sit on stale air. 0.5 converges to >87% of a
#: step change in three pings.
EWMA_ALPHA = 0.5

#: min/max window length (seconds): the tracker keeps the current and
#: previous window, so dump's min/max always cover between one and two
#: windows of history — the reference's "last interval" framing
#: without per-sample memory.
WINDOW_S = 60.0

#: samples a link must carry before the aggregator will judge it slow
#: (one cold outlier during boot must not flip cluster health).
MIN_SAMPLES = 3


def link_key(peer: str, channel: str) -> str:
    """The wire/report encoding of one directed link's far end:
    ``"osd.3|hb"``. Kept flat (not a tuple) so the key survives JSON
    round trips through reports and bench artifacts unchanged."""
    return f"{peer}|{channel}"


def split_link_key(key: str) -> tuple[str, str]:
    peer, _, channel = key.partition("|")
    return peer, channel or "hb"


class LinkTracker:
    """Per-daemon fold of link round-trip samples (the OSD half).

    Thread-safe: ``note`` runs on reactor threads (pong fast
    dispatch) and store RPC completions concurrently; ``dump`` on the
    heartbeat thread. The lock is a leaf."""

    def __init__(self, now_fn=time.monotonic, window_s: float = WINDOW_S,
                 perf=None, perf_key: str = "hb_ping_rtt"):
        self._now = now_fn
        self._window = float(window_s)
        self._lock = threading.Lock()
        #: (peer, channel) -> link entry
        self._links: dict[tuple[str, str], dict] = {}
        # the DECLARED aggregate: every sample also tincs one
        # time_avg+lhist on the daemon's perf logger, so the r9
        # declared-names invariant holds while per-peer detail rides
        # the report side-field
        self._perf = perf
        self._perf_key = perf_key

    def note(self, peer: str, rtt_s: float,
             channel: str = "hb") -> None:
        """Fold one round-trip sample into the (peer, channel) link."""
        if rtt_s < 0:
            return                      # clock skew artifact: drop
        if self._perf is not None and channel == "hb":
            try:
                self._perf.tinc(self._perf_key, rtt_s)
            except KeyError:
                pass                    # harness perf without schema
        now = self._now()
        with self._lock:
            ent = self._links.get((peer, channel))
            if ent is None:
                ent = self._links[(peer, channel)] = {
                    "hist": {"buckets": [0] * LHIST_BUCKETS,
                             "sum": 0.0, "count": 0},
                    "ewma_s": rtt_s, "last_s": rtt_s, "count": 0,
                    "win_start": now, "win_min": rtt_s,
                    "win_max": rtt_s, "prev_min": None,
                    "prev_max": None,
                }
            if now - ent["win_start"] >= self._window:
                ent["prev_min"], ent["prev_max"] = \
                    ent["win_min"], ent["win_max"]
                ent["win_start"] = now
                ent["win_min"] = ent["win_max"] = rtt_s
            ent["count"] += 1
            ent["last_s"] = rtt_s
            ent["ewma_s"] = (EWMA_ALPHA * rtt_s
                             + (1.0 - EWMA_ALPHA) * ent["ewma_s"])
            ent["win_min"] = min(ent["win_min"], rtt_s)
            ent["win_max"] = max(ent["win_max"], rtt_s)
            # the module attribute, read at call time: the benches'
            # OFF arm flips it process-wide (r18 overhead guard)
            if _pc.LHIST_ENABLED:
                h = ent["hist"]
                h["buckets"][lhist_bucket(rtt_s)] += 1
                h["sum"] += rtt_s
                h["count"] += 1

    def ewma_s(self, peer: str) -> float:
        """Worst live EWMA toward `peer` across channels (seconds) —
        the link-cost feed's daemon-local edge (r14 helper blend)."""
        with self._lock:
            return max((e["ewma_s"] for (p, _c), e
                        in self._links.items() if p == peer),
                       default=0.0)

    def dump(self) -> dict:
        """Report/asok shape: {"osd.3|hb": {hist, ewma_ms, last_ms,
        min_ms, max_ms, count}}. min/max span the current + previous
        window. hist buckets are COPIED (the report pipe serializes
        after this returns)."""
        out: dict[str, dict] = {}
        with self._lock:
            for (peer, channel), e in self._links.items():
                lo = e["win_min"] if e["prev_min"] is None \
                    else min(e["win_min"], e["prev_min"])
                hi = e["win_max"] if e["prev_max"] is None \
                    else max(e["win_max"], e["prev_max"])
                out[link_key(peer, channel)] = {
                    "hist": {"buckets": list(e["hist"]["buckets"]),
                             "sum": e["hist"]["sum"],
                             "count": e["hist"]["count"]},
                    "ewma_ms": round(e["ewma_s"] * 1e3, 3),
                    "last_ms": round(e["last_s"] * 1e3, 3),
                    "min_ms": round(lo * 1e3, 3),
                    "max_ms": round(hi * 1e3, 3),
                    "count": e["count"],
                }
        return out

    def clear(self) -> None:
        with self._lock:
            self._links.clear()


class NetworkAggregator:
    """Per-monitor fold of every daemon's links+flow claims (the mon
    half). Constructed beside the TraceAssembler/TelemetryAggregator/
    ProfileAggregator; thread-safe; also driven standalone by the
    benches and unit tests."""

    def __init__(self, config=None, now_fn=time.monotonic):
        self._config = config
        self._now = now_fn
        self._lock = threading.Lock()
        #: daemon name -> {"links": {key: link}, "flow": {peer: flow},
        #:                 "stamp": monotonic}
        self._daemons: dict[str, dict] = {}

    # -- plumbing -------------------------------------------------------------

    def _cfg(self, key: str, default):
        if self._config is None:
            return default
        try:
            v = self._config[key] if not hasattr(self._config, "get") \
                else self._config.get(key)
            return default if v is None else v
        except (KeyError, TypeError):
            return default

    def threshold_ms(self) -> float:
        """The slow-link verdict line, resolved LIVE from config each
        call (a committed `config set` retunes health with no
        restart): mon_warn_on_slow_ping_time (ms) when > 0, else
        mon_warn_on_slow_ping_ratio x osd_heartbeat_grace — exactly
        the reference's fallback."""
        warn = float(self._cfg("mon_warn_on_slow_ping_time", 0.0))
        if warn > 0:
            return warn
        ratio = float(self._cfg("mon_warn_on_slow_ping_ratio", 0.05))
        grace = float(self._cfg("osd_heartbeat_grace", 20.0))
        return ratio * grace * 1e3

    def stale_after_s(self) -> float:
        """Claims older than this never feed verdicts: a dead daemon's
        last report must not pin a slow link (or hide a healed one)
        forever. Two grace windows, floored at 10s for report cadence."""
        grace = float(self._cfg("osd_heartbeat_grace", 20.0))
        return max(10.0, 2.0 * grace)

    # -- ingest ---------------------------------------------------------------

    def ingest(self, name: str, block: dict) -> None:
        """Fold one daemon's report side-field {"links", "flow"}.
        Newest claim per daemon wins (cumulative shapes, like the
        statfs claims)."""
        if not isinstance(block, dict):
            return
        with self._lock:
            self._daemons[name] = {
                "links": dict(block.get("links") or {}),
                "flow": dict(block.get("flow") or {}),
                "stamp": self._now(),
            }

    # -- the matrix -----------------------------------------------------------

    def links(self, fresh_only: bool = True) -> list[dict]:
        """The cluster link matrix as rows: one per directed
        (from, to, channel) with quantiles off the shipped lhist."""
        cutoff = (self._now() - self.stale_after_s()) if fresh_only \
            else float("-inf")
        rows: list[dict] = []
        with self._lock:
            claims = [(n, e) for n, e in self._daemons.items()
                      if e["stamp"] >= cutoff]
        for name, ent in claims:
            for key, link in ent["links"].items():
                peer, channel = split_link_key(key)
                hist = link.get("hist") or {}
                row = {
                    "from": name, "to": peer, "channel": channel,
                    "ewma_ms": float(link.get("ewma_ms", 0.0)),
                    "last_ms": float(link.get("last_ms", 0.0)),
                    "min_ms": float(link.get("min_ms", 0.0)),
                    "max_ms": float(link.get("max_ms", 0.0)),
                    "count": int(link.get("count", 0)),
                    "hist": hist,
                }
                row.update(lhist_quantiles(hist))
                rows.append(row)
        rows.sort(key=lambda r: (-r["ewma_ms"], r["from"], r["to"],
                                 r["channel"]))
        return rows

    def slow_links(self) -> list[dict]:
        """Rows over the live threshold (worst first), each stamped
        with the threshold it breached. Heartbeat channel ONLY: the
        check is OSD_SLOW_PING_TIME — a ping-RTT verdict, like the
        reference's (store sub-op latency rides the same matrix for
        the operator but feeds SLOW_OPS-shaped signals, not this
        one)."""
        thr = self.threshold_ms()
        out = []
        for row in self.links():
            if row["channel"] == "hb" and row["count"] >= MIN_SAMPLES \
                    and row["ewma_ms"] > thr:
                r = dict(row)
                r["threshold_ms"] = thr
                out.append(r)
        return out

    def link_cost(self, a, b) -> int:
        """The feed: directed cost of a→b in INTEGER MICROSECONDS
        (minimum_to_decode_with_cost units, same as _helper_costs) —
        the worst live EWMA `a` has measured toward `b` across
        channels, 0 when unmeasured. Accepts "osd.3" or 3."""
        a, b = _osd_name(a), _osd_name(b)
        with self._lock:
            ent = self._daemons.get(a)
            links = dict(ent["links"]) if ent is not None else {}
        worst = 0.0
        for key, link in links.items():
            peer, _channel = split_link_key(key)
            if peer == b:
                worst = max(worst, float(link.get("ewma_ms", 0.0)))
        return int(worst * 1e3)

    def worst_cost_per_osd(self) -> dict[int, int]:
        """Per-OSD worst cost (µs) over every live link TOUCHING it,
        either direction — the client hedge ladder's pull shape (a
        client reading from osd X pays X's bad links whichever end
        measured them)."""
        out: dict[int, int] = {}
        for row in self.links():
            cost = int(row["ewma_ms"] * 1e3)
            for end in (row["from"], row["to"]):
                osd = _osd_id(end)
                if osd is not None:
                    out[osd] = max(out.get(osd, 0), cost)
        return out

    def flow_totals(self) -> dict:
        """Cluster flow roll-up over every daemon's per-peer ledgers."""
        tot = {"bytes_tx": 0, "frames_tx": 0, "bytes_rx": 0,
               "frames_rx": 0, "stalls": 0, "stall_time_s": 0.0,
               "writeq_bytes": 0, "writeq_frames": 0}
        with self._lock:
            flows = [e["flow"] for e in self._daemons.values()]
        for flow in flows:
            for f in flow.values():
                for k in tot:
                    tot[k] += f.get(k, 0)
        tot["stall_time_s"] = round(tot["stall_time_s"], 6)
        return tot

    # -- operator views -------------------------------------------------------

    def dump(self, limit: int = 64) -> dict:
        """The `dump_osd_network` body (asok + wire + `ceph_cli
        netstat`): the matrix (worst-first, bounded), the slow-link
        verdicts, cluster flow totals, and the live threshold."""
        rows = self.links()
        dropped = max(0, len(rows) - int(limit))
        slim = []
        for row in rows[:int(limit)]:
            r = {k: v for k, v in row.items() if k != "hist"}
            slim.append(r)
        return {
            "threshold_ms": round(self.threshold_ms(), 3),
            "stale_after_s": round(self.stale_after_s(), 3),
            "links": slim,
            "links_total": len(rows),
            "links_dropped": dropped,
            "slow": [{k: v for k, v in r.items() if k != "hist"}
                     for r in self.slow_links()],
            "flow_totals": self.flow_totals(),
            "daemons_reporting": len(self._daemons),
        }

    def health_checks(self) -> list[dict]:
        """OSD_SLOW_PING_TIME in mgr/health.py's check shape, naming
        the worst links (the reference's detail lines name
        back-to-back pairs the same way)."""
        slow = self.slow_links()
        if not slow:
            return []
        thr = slow[0]["threshold_ms"]
        return [{
            "code": "OSD_SLOW_PING_TIME",
            "severity": "HEALTH_WARN",
            "summary": f"{len(slow)} slow heartbeat link(s) "
                       f"(rtt ewma over {round(thr, 1)}ms)",
            "detail": [
                f"{r['from']} -> {r['to']} ({r['channel']}): "
                f"ewma {round(r['ewma_ms'], 1)}ms > "
                f"{round(thr, 1)}ms "
                f"(p99 {r['p99_ms']}ms over {r['count']} pings)"
                for r in slow[:10]],
        }]

    # -- prometheus (bounded cardinality) -------------------------------------

    def prometheus_text(self, prefix: str = "ceph_tpu",
                        limit: int | None = None) -> str:
        """Worst-N links by p99 as REAL `# TYPE histogram` series
        (cumulative _bucket with le in seconds — the r18 rule) plus
        per-link flow counters. N defaults from
        mgr_netobs_prom_links; everything past it is DISCLOSED via
        the _links_dropped gauge, never silently truncated."""
        if limit is None:
            limit = int(self._cfg("mgr_netobs_prom_links", 8))
        rows = self.links()
        rows.sort(key=lambda r: (-r["p99_ms"], r["from"], r["to"],
                                 r["channel"]))
        keep = rows[:max(0, int(limit))]
        m_rtt = f"{prefix}_netobs_link_rtt_seconds"
        lines = [
            f"# HELP {m_rtt} heartbeat/store round trip per directed "
            f"link (worst {len(keep)} of {len(rows)} by p99)",
            f"# TYPE {m_rtt} histogram",
        ]
        for r in keep:
            lab = (f'daemon="{r["from"]}",peer="{r["to"]}",'
                   f'channel="{r["channel"]}"')
            buckets = (r["hist"] or {}).get("buckets") or []
            total = 0
            for i, b in enumerate(buckets[:-1]):
                total += b
                lines.append(f'{m_rtt}_bucket{{{lab},'
                             f'le="{lhist_bucket_le(i)!r}"}} {total}')
            total += buckets[-1] if buckets else 0
            lines.append(f'{m_rtt}_bucket{{{lab},le="+Inf"}} {total}')
            lines.append(f'{m_rtt}_sum{{{lab}}} '
                         f'{(r["hist"] or {}).get("sum", 0.0)!r}')
            lines.append(f'{m_rtt}_count{{{lab}}} {total}')
        m_drop = f"{prefix}_netobs_links_dropped"
        lines.append(f"# HELP {m_drop} links over the worst-N "
                     f"exposition cap (cardinality bound, disclosed)")
        lines.append(f"# TYPE {m_drop} gauge")
        lines.append(f"{m_drop} {max(0, len(rows) - len(keep))}")
        m_tx = f"{prefix}_netobs_peer_bytes_tx"
        m_rx = f"{prefix}_netobs_peer_bytes_rx"
        with self._lock:
            flows = {n: dict(e["flow"])
                     for n, e in self._daemons.items()}
        flow_lines: list[str] = []
        peers_of = {}
        for name, flow in sorted(flows.items()):
            # same cardinality bound: only peers on a kept link
            kept_peers = {r["to"] for r in keep if r["from"] == name}
            peers_of[name] = kept_peers
            for peer in sorted(kept_peers & set(flow)):
                f = flow[peer]
                lab = f'daemon="{name}",peer="{peer}"'
                flow_lines.append(
                    f'{m_tx}{{{lab}}} {int(f.get("bytes_tx", 0))}')
                flow_lines.append(
                    f'{m_rx}{{{lab}}} {int(f.get("bytes_rx", 0))}')
        if flow_lines:
            lines.append(f"# TYPE {m_tx} counter")
            lines.append(f"# TYPE {m_rx} counter")
            lines.extend(flow_lines)
        return "\n".join(lines) + "\n"


def _osd_name(x) -> str:
    return x if isinstance(x, str) else f"osd.{int(x)}"


def _osd_id(name: str) -> int | None:
    if isinstance(name, str) and name.startswith("osd."):
        try:
            return int(name[4:])
        except ValueError:
            return None
    return None


def merge_link_dumps(*dumps: dict) -> dict:
    """Exact merge of LinkTracker dumps by link key: lhist buckets add
    element-wise (the r18 merge), counts add, min/max fold, the ewma
    of the LAST claim wins (EWMAs don't merge; newest is freshest).
    What the bit-exactness test replays by hand against the
    aggregator's matrix."""
    out: dict[str, dict] = {}
    for d in dumps:
        for key, link in (d or {}).items():
            cur = out.get(key)
            if cur is None:
                out[key] = {**link,
                            "hist": lhist_merge(link.get("hist"))}
                continue
            cur["hist"] = lhist_merge(cur["hist"], link.get("hist"))
            cur["count"] = cur.get("count", 0) + link.get("count", 0)
            cur["min_ms"] = min(cur.get("min_ms", float("inf")),
                                link.get("min_ms", float("inf")))
            cur["max_ms"] = max(cur.get("max_ms", 0.0),
                                link.get("max_ms", 0.0))
            cur["ewma_ms"] = link.get("ewma_ms", cur.get("ewma_ms"))
            cur["last_ms"] = link.get("last_ms", cur.get("last_ms"))
    return out
