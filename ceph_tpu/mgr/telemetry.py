"""Telemetry time-series plane — cluster metric history, mergeable
latency quantiles, SLO burn-rate health, and the observed-client-
latency feed (r18).

The mgr half of the retained-history pipeline (the role the
reference's mgr plays as DaemonStateIndex time-series cache +
prometheus recording rules + the SRE multiwindow burn-rate alerts
layered on top): every daemon keeps a per-interval MetricsHistory
ring (utils/perf_counters.MetricsHistory) and ships freshly recorded
entries in its MgrReports; every monitor runs one TelemetryAggregator
folding those entries into

* CLUSTER time-series — per wall-clock-aligned interval, the folded
  counter deltas per (generic logger, key) plus the per-daemon
  breakdown, bounded to `max_intervals`;
* MERGED latency histograms — lhist deltas add bucket-wise, so the
  cluster p99 is EXACTLY the quantile of the per-daemon merge (no
  approximation stacking; pinned by the bit-exactness test);
* SLO verdicts — declared rules (`mgr_slo_rules`) evaluated per
  interval into a fast window (the newest 2 data intervals — a
  breach "flips within two evaluation intervals" by construction)
  and a slow window (every data interval inside the rule's `over`
  span). Both burn rates ship with each verdict; SLO_BURN fires on a
  hot fast window and clears the first clean interval;
* LATENCY_REGRESSION — drift detection on the same feeds: the newest
  interval's p99 against the median of the trailing baseline
  (arxiv 1709.05365's lesson that online-EC bottlenecks MIGRATE —
  a point-in-time perf dump can't see the drift, history can);
* the observed-client-latency feed — `observed_client_latency()`
  returns merged client-visible quantiles (client-shipped histograms
  when clients report them, the merged OSD op histograms otherwise),
  and `burn_rate()` feeds the balancer movement budget
  (mgr/placement.telemetry_movement_budget): rebalancing yields to
  traffic when the burn is hot (ROADMAP item 5's hook).

Dimensionality, disclosed: series are keyed per (logger, key) with
per-daemon breakdown retained; this harness runs ONE pool (id 1) and
its per-tenant split lives in the mClock dumps, so the pool/tenant
dimensions of `observed_client_latency(pool)` validate-and-collapse
rather than fan out (ARCHITECTURE "Telemetry plane (r18)").
"""

from __future__ import annotations

import re
import threading
import time

from ..utils.perf_counters import (fold_delta, lhist_merge,
                                   lhist_quantile, lhist_quantiles)
from .reports import _generic_logger

__all__ = ["SLORule", "parse_slo_rules", "TelemetryAggregator",
           "FEED_ALIASES"]

#: rule-feed aliases -> (logger, lhist key). The merged-OSD feeds are
#: service-time at the primary (op enter -> reply built); the
#: client_observed feed is the client's own submit->reply frame time
#: (includes wire + windowing), shipped with its trace flushes.
FEED_ALIASES = {
    "client_read": ("osd", "op_r_latency_hist"),
    "client_write": ("osd", "op_w_latency_hist"),
    "client_op": ("osd", "op_latency_hist"),
    "subop": ("osd", "subop_latency_hist"),
    "client_observed": ("client", "op_lat_hist"),
    # r21: wall time mutating ops sat parked behind FULL flags — a
    # COUNT/DURATION feed, not a latency feed: parked time never
    # enters the write-latency feeds (parked ops are not dispatched),
    # and the write-feed verdicts disclose backoff activity instead
    # of letting a capacity stall read as a latency regression
    "full_backoff": ("client", "full_backoff_time_hist"),
}

#: feeds whose verdicts carry the r21 full-backoff disclosure (write
#: paths a FULL flag parks; read feeds keep serving and stay quiet)
_WRITE_FEEDS = frozenset({"client_write", "client_op",
                          "client_observed"})


def _is_write_rule(rule: "SLORule") -> bool:
    """Does this rule watch a feed a FULL flag parks? Matched on the
    resolved (logger, key) so both the alias spelling and an explicit
    `osd.op_w_latency_hist` rule get the disclosure."""
    return any((rule.logger, rule.key) == FEED_ALIASES[f]
               for f in _WRITE_FEEDS)

_UNIT_S = {"us": 1e-6, "ms": 1e-3, "s": 1.0}
_WIN_S = {"s": 1.0, "m": 60.0, "h": 3600.0}

_RULE_RE = re.compile(
    r"^\s*(?P<metric>[A-Za-z0-9_.]+?)_p(?P<q>\d{1,3})\s*<\s*"
    r"(?P<val>\d+(?:\.\d+)?)\s*(?P<unit>us|ms|s)\s+over\s+"
    r"(?P<win>\d+(?:\.\d+)?)\s*(?P<wu>s|m|h)"
    r"(?:\s*\[\s*tenant\s*=\s*(?P<tenant>[A-Za-z0-9_.:@-]+)\s*\])?"
    r"\s*$")

#: the fast burn window, in data intervals: a rule breaches when the
#: newest FAST_INTERVALS intervals with samples all violate — so an
#: injected slowdown flips SLO_BURN within two evaluation intervals,
#: and one clean interval clears it (hysteresis = re-breach needs two
#: hot intervals again)
FAST_INTERVALS = 2


class SLORule:
    """One parsed rule: `client_read_p99 < 50ms over 5m`. r20 adds an
    optional tenant qualifier — `client_observed_p99 < 30ms over 2m
    [tenant=client.interactive]` — which evaluates the rule against
    that tenant's OWN observed-latency feed (the per-tenant snapshots
    the workload engine ships via ingest_client(tenant=...)) instead
    of the cluster merge."""

    __slots__ = ("name", "logger", "key", "q", "threshold_s",
                 "window_s", "tenant")

    def __init__(self, name: str, logger: str, key: str, q: float,
                 threshold_s: float, window_s: float,
                 tenant: str | None = None):
        self.name = name
        self.logger = logger
        self.key = key
        self.q = q
        self.threshold_s = threshold_s
        self.window_s = window_s
        self.tenant = tenant

    def to_dict(self) -> dict:
        out = {"name": self.name, "logger": self.logger,
               "key": self.key, "quantile": self.q,
               "threshold_ms": round(self.threshold_s * 1e3, 3),
               "window_s": self.window_s}
        if self.tenant is not None:
            out["tenant"] = self.tenant
        return out


def parse_slo_rules(text: str) -> list[SLORule]:
    """';'-separated rules; a malformed rule raises ValueError with
    the offending fragment (the config layer surfaces it to the
    operator instead of silently evaluating nothing)."""
    rules: list[SLORule] = []
    for frag in (text or "").split(";"):
        frag = frag.strip()
        if not frag:
            continue
        m = _RULE_RE.match(frag)
        if m is None:
            raise ValueError(f"bad SLO rule {frag!r} (want "
                             f"'<feed>_p<Q> < <val><us|ms|s> over "
                             f"<win><s|m|h>')")
        metric = m.group("metric")
        if metric in FEED_ALIASES:
            logger, key = FEED_ALIASES[metric]
        elif "." in metric:
            logger, _, key = metric.partition(".")
        else:
            raise ValueError(
                f"bad SLO rule {frag!r}: unknown feed {metric!r} "
                f"(aliases: {sorted(FEED_ALIASES)}; or use "
                f"<logger>.<lhist-key>)")
        q = int(m.group("q")) / 100.0
        if not 0.0 < q < 1.0:
            raise ValueError(f"bad SLO rule {frag!r}: quantile "
                             f"p{m.group('q')} out of (0, 100)")
        tenant = m.group("tenant")
        if tenant is not None and metric != "client_observed":
            raise ValueError(
                f"bad SLO rule {frag!r}: [tenant=...] only applies "
                f"to the client_observed feed (per-tenant data comes "
                f"from client-shipped snapshots)")
        name = f"{metric}_p{m.group('q')}"
        if tenant is not None:
            name += f"[{tenant}]"
        rules.append(SLORule(
            name=name, logger=logger, key=key,
            q=q,
            threshold_s=float(m.group("val"))
            * _UNIT_S[m.group("unit")],
            window_s=float(m.group("win")) * _WIN_S[m.group("wu")],
            tenant=tenant))
    return rules


class TelemetryAggregator:
    """Per-monitor fold of every daemon's shipped MetricsHistory
    entries into bounded cluster time-series (+ the client-shipped
    observed-latency histograms and the flight-ring overflow
    tracker). Thread-safe; also used standalone by the benches over
    in-process rings."""

    def __init__(self, config=None, max_intervals: int = 256,
                 now_fn=time.time):
        self._config = config
        self._max = int(max_intervals)
        self._now = now_fn
        self._lock = threading.Lock()
        #: bucket(int) -> {"t", "interval_s", "delta" (cluster fold,
        #: generic loggers), "daemons": {name: per-daemon delta}}
        self._intervals: dict[int, dict] = {}
        #: client name -> cumulative "client" logger dump (the
        #: observed-latency feed; cumulative, monitor computes deltas
        #: implicitly by replacing)
        self._clients: dict[str, dict] = {}
        #: daemon -> (last dropped_unshipped, consecutive growths)
        self._flight: dict[str, tuple[int, int]] = {}
        #: r20 per-tenant observed-latency feed: tenant -> last
        #: cumulative op_lat_hist, and tenant -> bounded ring of
        #: (t, interval-delta hist) points the tenant-qualified SLO
        #: rules evaluate over
        self._tenant_last: dict[str, dict] = {}
        self._tenant_points: dict[str, list] = {}
        #: r21 full-backoff tracking: client -> (last cumulative
        #: backoff count, wall stamp of the last observed GROWTH) —
        #: the write-feed verdicts' disclosure source
        self._backoff: dict[str, tuple[int, float]] = {}

    # -- ingest ---------------------------------------------------------------

    def ingest(self, name: str, entries: list[dict]) -> None:
        """Fold one daemon's shipped history entries (MetricsHistory
        drain shape). Idempotence rides the per-daemon replace: a
        re-shipped entry re-folds, but daemons drain each entry
        exactly once (the cursor), so dups only occur on report
        replay — tolerated as the counters they'd inflate are
        diagnostics, not billing."""
        if not entries:
            return
        with self._lock:
            for e in entries:
                if not isinstance(e, dict) or "bucket" not in e:
                    continue
                delta = _normalize_loggers(e.get("delta") or {})
                ent = self._intervals.get(e["bucket"])
                if ent is None:
                    ent = self._intervals[e["bucket"]] = {
                        "t": e.get("t", 0.0),
                        "interval_s": e.get("interval_s", 0.0),
                        "delta": {}, "daemons": {}}
                ent["delta"] = fold_delta(ent["delta"], delta)
                ent["daemons"][name] = fold_delta(
                    ent["daemons"].get(name, {}), delta)
            over = len(self._intervals) - self._max
            if over > 0:
                for b in sorted(self._intervals,
                                key=lambda b:
                                self._intervals[b]["t"])[:over]:
                    del self._intervals[b]

    def ingest_client(self, name: str, client_perf: dict,
                      tenant: str | None = None) -> None:
        """A client's CUMULATIVE "client" logger dump (ships with its
        trace flushes): newest snapshot wins per client. With
        `tenant=` (r20, the workload engine's per-tenant feed) the
        snapshot ALSO folds into that tenant's interval ring: each
        call appends the op_lat_hist delta vs the previous snapshot
        as one (t, hist) point, so tenant-qualified SLO rules get the
        same interval/burn-window semantics the cluster feeds have."""
        if not isinstance(client_perf, dict):
            return
        with self._lock:
            self._clients[name] = client_perf
            # r21: note full-backoff growth (cumulative time_avg
            # avgcount) — stamps the last interval a client was
            # observed parked, read by the write-feed verdicts
            fb = (client_perf.get("client") or client_perf
                  ).get("full_backoff_time")
            if isinstance(fb, dict):
                try:
                    cur = int(fb.get("avgcount", 0))
                except (TypeError, ValueError):
                    cur = 0
                last, stamp = self._backoff.get(name, (0, 0.0))
                if cur > last:
                    stamp = self._now()
                self._backoff[name] = (cur, stamp)
            if tenant is None:
                return
            hist = (client_perf.get("client") or client_perf
                    ).get("op_lat_hist")
            if not isinstance(hist, dict) or "buckets" not in hist:
                return
            delta = _lhist_sub(hist, self._tenant_last.get(tenant))
            self._tenant_last[tenant] = hist
            if not delta.get("count"):
                return
            ring = self._tenant_points.setdefault(tenant, [])
            ring.append((self._now(), delta))
            del ring[:-self._max]

    def full_backoff_active(self, window_s: float) -> bool:
        """r21: was ANY client observed growing its full-backoff
        counter within the trailing window? The disclosure gate the
        write-feed SLO verdicts and the regression probe consult."""
        cutoff = self._now() - window_s
        with self._lock:
            return any(stamp >= cutoff and cnt > 0
                       for cnt, stamp in self._backoff.values())

    def full_backoff(self) -> dict:
        """Per-client cumulative full-backoff accounting (count +
        total seconds parked) from the newest client snapshots —
        `ceph_cli slo`'s capacity-stall disclosure block."""
        out: dict[str, dict] = {}
        with self._lock:
            for name, perf in self._clients.items():
                fb = (perf.get("client") or perf
                      ).get("full_backoff_time")
                if isinstance(fb, dict) and fb.get("avgcount"):
                    out[name] = {
                        "count": int(fb.get("avgcount", 0)),
                        "total_s": round(float(fb.get("sum", 0.0)), 3)}
        return out

    def note_flight(self, name: str, stats: dict) -> None:
        """Track a daemon's flight-ring `dropped_unshipped` across
        reports: N consecutive observed GROWTHS = persistent overflow
        (the TRACE_RING_OVERFLOW source). A report with no growth
        resets the streak."""
        try:
            cur = int((stats or {}).get("dropped_unshipped", 0))
        except (TypeError, ValueError):
            return
        with self._lock:
            last, streak = self._flight.get(name, (cur, 0))
            if cur > last:
                streak += 1
            elif cur < last:      # daemon restarted: ring reset
                streak = 0
            else:
                streak = 0
            self._flight[name] = (cur, streak)

    def flight_drops(self) -> dict[str, int]:
        """Per-daemon flight-ring dropped_unshipped gauges (newest
        reported value) — surfaced by `ceph_cli top` next to the r19
        sampler gauges so ring overflow is visible BEFORE the
        TRACE_RING_OVERFLOW streak trips."""
        with self._lock:
            return {name: last
                    for name, (last, _streak) in sorted(
                        self._flight.items())}

    # -- views ----------------------------------------------------------------

    def _buckets_locked(self, window_s: float | None = None
                        ) -> list[int]:
        # ordered by WALL TIME, not bucket index: a live
        # mgr_history_interval change rescales the index space, and
        # index-sorted "newest" would interleave the two scales
        bs = sorted(self._intervals,
                    key=lambda b: self._intervals[b]["t"])
        if window_s is not None and bs:
            cutoff = self._now() - window_s
            bs = [b for b in bs
                  if self._intervals[b]["t"] >= cutoff]
        return bs

    def series(self, logger: str, key: str,
               limit: int = 32) -> list[dict]:
        """Per-interval cluster values of one (logger, key), newest
        last. Numbers come back as-is; time_avg deltas as their dict;
        lhist deltas as their {buckets,sum,count} dict."""
        with self._lock:
            out = []
            for b in self._buckets_locked()[-limit:]:
                ent = self._intervals[b]
                val = (ent["delta"].get(logger) or {}).get(key)
                out.append({"bucket": b, "t": ent["t"],
                            "interval_s": ent["interval_s"],
                            "value": val})
            return out

    def per_daemon_hist(self, logger: str, key: str,
                        window_s: float | None = None) -> dict:
        """Per-daemon lhist merged over the window's intervals — the
        operand list of the cluster merge (the bit-exactness test
        re-merges these by hand and compares)."""
        with self._lock:
            out: dict[str, dict] = {}
            for b in self._buckets_locked(window_s):
                for name, d in self._intervals[b]["daemons"].items():
                    h = (d.get(logger) or {}).get(key)
                    if isinstance(h, dict) and "buckets" in h:
                        out[name] = lhist_merge(out.get(name), h)
            return out

    def merged_hist(self, logger: str, key: str,
                    window_s: float | None = None) -> dict:
        """Cluster lhist over the window = exact bucket-add over every
        daemon's entries."""
        with self._lock:
            out: dict = {}
            for b in self._buckets_locked(window_s):
                h = (self._intervals[b]["delta"].get(logger)
                     or {}).get(key)
                if isinstance(h, dict) and "buckets" in h:
                    out = lhist_merge(out, h)
            return out

    def quantiles(self, logger: str, key: str,
                  window_s: float | None = None) -> dict:
        return lhist_quantiles(self.merged_hist(logger, key,
                                                window_s))

    def observed_client_latency(self, pool: int | None = None) -> dict:
        """THE stable feed (ROADMAP item 5): merged client-visible
        latency quantiles. Prefers client-shipped histograms (true
        client-observed: submit -> reply, wire included); falls back
        to the merged OSD client-op service histograms when no client
        reports (source disclosed in the result). `pool` validates
        against this harness's single pool."""
        if pool is not None and int(pool) != 1:
            raise KeyError(f"no pool {pool} (this harness runs pool 1)")
        with self._lock:
            client_hists = [
                (d.get("client") or d).get("op_lat_hist")
                for d in self._clients.values()]
            client_hists = [h for h in client_hists
                            if isinstance(h, dict) and h.get("count")]
        if client_hists:
            merged = lhist_merge(*client_hists)
            return {"source": "client", "pool": 1,
                    **lhist_quantiles(merged)}
        merged = self.merged_hist("osd", "op_latency_hist")
        return {"source": "osd", "pool": 1,
                **lhist_quantiles(merged)}

    def tenant_latency(self) -> dict:
        """Per-tenant observed-latency quantiles merged over each
        tenant's interval ring (r20) — the per-tenant complement of
        observed_client_latency(), empty until the workload engine
        ships tenant-tagged snapshots."""
        with self._lock:
            rings = {t: [h for _t, h in pts]
                     for t, pts in self._tenant_points.items()}
        out = {}
        for tenant, hists in sorted(rings.items()):
            merged: dict = {}
            for h in hists:
                merged = lhist_merge(merged, h)
            out[tenant] = {"intervals": len(hists),
                           **lhist_quantiles(merged)}
        return out

    # -- SLO evaluation -------------------------------------------------------

    def _rules(self) -> list[SLORule]:
        text = ""
        if self._config is not None:
            try:
                text = self._config.get("mgr_slo_rules")
            except (KeyError, TypeError):
                text = ""
        try:
            return parse_slo_rules(text)
        except ValueError:
            return []            # malformed committed value: the
            #                    # config set path already rejected it

    def slo_status(self, rules: list[SLORule] | None = None) -> list[dict]:
        """One verdict per declared rule: per-interval quantiles over
        the rule window, fast/slow burn rates, and the breach flag
        (fast window = newest FAST_INTERVALS data intervals, all
        violating)."""
        out = []
        for rule in (self._rules() if rules is None else rules):
            with self._lock:
                points = []
                if rule.tenant is not None:
                    # tenant-qualified rule: evaluate over that
                    # tenant's own interval ring (r20)
                    cutoff = self._now() - rule.window_s
                    for i, (t, h) in enumerate(
                            self._tenant_points.get(rule.tenant, [])):
                        if t >= cutoff and h.get("count"):
                            points.append(
                                (i, lhist_quantile(h, rule.q),
                                 int(h["count"])))
                else:
                    for b in self._buckets_locked(rule.window_s):
                        ent = self._intervals[b]
                        h = (ent["delta"].get(rule.logger)
                             or {}).get(rule.key)
                        if isinstance(h, dict) and h.get("count"):
                            points.append(
                                (b, lhist_quantile(h, rule.q),
                                 int(h["count"])))
            violated = [q > rule.threshold_s for _b, q, _n in points]
            fast = violated[-FAST_INTERVALS:]
            burn_fast = (sum(fast) / len(fast)) if fast else 0.0
            burn_slow = (sum(violated) / len(violated)) \
                if violated else 0.0
            breach = len(fast) >= FAST_INTERVALS and all(fast)
            verdict = {
                **rule.to_dict(),
                "intervals": len(points),
                "samples": sum(n for _b, _q, n in points),
                "current_ms": round(points[-1][1] * 1e3, 3)
                if points else None,
                "burn_fast": round(burn_fast, 3),
                "burn_slow": round(burn_slow, 3),
                "breach": breach,
            }
            # r21 disclosure: a write-feed verdict evaluated while
            # clients sat in full-backoff says so — the operator reads
            # "capacity stall", not "the write path got slow"
            if _is_write_rule(rule) \
                    and self.full_backoff_active(rule.window_s):
                verdict["full_backoff_active"] = True
            out.append(verdict)
        return out

    def burn_rate(self) -> float:
        """Hottest fast-window burn across declared rules, in [0, 1]
        — what the balancer movement budget shrinks by
        (mgr/placement.telemetry_movement_budget). No rules declared
        -> 0.0 (budget passes through)."""
        return max((v["burn_fast"] for v in self.slo_status()),
                   default=0.0)

    def regressions(self) -> list[dict]:
        """LATENCY_REGRESSION probes over the declared rules' feeds:
        newest data interval's quantile vs the MEDIAN of the trailing
        baseline intervals. Needs >= 3 baseline intervals and >= 16
        samples in the newest (noise floor on a loaded 1-core box);
        factor from mgr_latency_regression_factor (0 disables)."""
        factor = 4.0
        if self._config is not None:
            try:
                factor = float(
                    self._config.get("mgr_latency_regression_factor"))
            except (KeyError, TypeError, ValueError):
                pass
        if factor <= 0:
            return []
        out = []
        for rule in self._rules():
            with self._lock:
                points = []
                for b in self._buckets_locked():
                    h = (self._intervals[b]["delta"]
                         .get(rule.logger) or {}).get(rule.key)
                    if isinstance(h, dict) and h.get("count"):
                        points.append((lhist_quantile(h, 0.99),
                                       int(h["count"])))
            if len(points) < 4 or points[-1][1] < 16:
                continue
            if _is_write_rule(rule) and self.full_backoff_active(
                    max(60.0, rule.window_s)):
                # r21: a capacity stall is not a latency regression —
                # the parked interval is disclosed on the SLO verdict
                # (full_backoff_active) and in `slo`'s full_backoff
                # block instead of tripping LATENCY_REGRESSION
                continue
            baseline = sorted(q for q, _n in points[:-1])
            median = baseline[len(baseline) // 2]
            current = points[-1][0]
            if median > 0 and current > factor * median:
                out.append({
                    "feed": rule.name, "logger": rule.logger,
                    "key": rule.key,
                    "baseline_p99_ms": round(median * 1e3, 3),
                    "current_p99_ms": round(current * 1e3, 3),
                    "factor": round(current / median, 2),
                })
        return out

    # -- health ---------------------------------------------------------------

    def health_checks(self) -> list[dict]:
        """The r18 checks, in mgr/health.py's check shape — folded
        into the monitor's health_checks() output."""
        checks: list[dict] = []
        breaches = [v for v in self.slo_status() if v["breach"]]
        if breaches:
            checks.append({
                "code": "SLO_BURN", "severity": "HEALTH_WARN",
                "summary": f"{len(breaches)} SLO rule(s) burning "
                           f"(fast window hot)",
                "detail": [
                    f"{v['name']}: current "
                    f"{v['current_ms']}ms > {v['threshold_ms']}ms, "
                    f"burn fast={v['burn_fast']} "
                    f"slow={v['burn_slow']} over {v['window_s']}s"
                    for v in breaches]})
        regs = self.regressions()
        if regs:
            checks.append({
                "code": "LATENCY_REGRESSION",
                "severity": "HEALTH_WARN",
                "summary": f"{len(regs)} latency feed(s) regressed "
                           f"vs trailing baseline",
                "detail": [
                    f"{r['feed']}: p99 {r['current_p99_ms']}ms = "
                    f"{r['factor']}x baseline "
                    f"{r['baseline_p99_ms']}ms" for r in regs]})
        with self._lock:
            overflowing = sorted(
                name for name, (_last, streak) in self._flight.items()
                if streak >= 2)
        if overflowing:
            checks.append({
                "code": "TRACE_RING_OVERFLOW",
                "severity": "HEALTH_WARN",
                "summary": f"{len(overflowing)} daemon(s) "
                           f"persistently dropping unshipped trace "
                           f"spans (flight ring too small or reports "
                           f"too slow)",
                "detail": [f"{n} dropped sampled spans before "
                           f"shipping in consecutive reports "
                           f"(raise osd_trace_ring_size or lower "
                           f"mgr_report_interval)"
                           for n in overflowing]})
        return checks

    # -- the operator views (`ceph_cli top / slo`, mon cmds) ------------------

    def dump(self, series_keys: list[tuple[str, str]] | None = None,
             limit: int = 32) -> dict:
        """The `telemetry` mon-command body: interval series for the
        headline keys + merged quantiles + the client feed + SLO
        verdicts. Bench JSON embeds this same shape (schema pinned by
        tests/test_bench_schema.py)."""
        keys = series_keys or [("osd", "op"), ("osd", "subop"),
                               ("ec", "recovered_bytes")]
        hists = [("osd", "op_latency_hist"),
                 ("osd", "subop_latency_hist")]
        return {
            "interval_buckets": len(self._intervals),
            "series": {f"{lg}.{k}": self.series(lg, k, limit)
                       for lg, k in keys},
            "quantiles": {f"{lg}.{k}": self.quantiles(lg, k)
                          for lg, k in hists},
            "observed_client_latency":
                self.observed_client_latency(),
            "slo": self.slo_status(),
        }

    def top(self, reports=None) -> dict:
        """The `ceph_cli top` body: per-daemon rates over the newest
        interval + cluster quantiles + in-flight totals (reports =
        the monitor's MgrReportAggregator, for ops_in_flight)."""
        with self._lock:
            bs = self._buckets_locked()
            newest = self._intervals[bs[-1]] if bs else None
            rows = {}
            if newest:
                iv = max(1e-9, newest["interval_s"])
                for name, d in sorted(newest["daemons"].items()):
                    osd = d.get("osd") or {}
                    lat = osd.get("op_latency") or {}
                    cnt = lat.get("avgcount") or 0
                    rows[name] = {
                        "ops_per_s": round(
                            (osd.get("op") or 0) / iv, 1),
                        "subops_per_s": round(
                            (osd.get("subop") or 0) / iv, 1),
                        "op_ms_avg": round(
                            1e3 * lat.get("sum", 0.0) / cnt, 3)
                        if cnt else 0.0,
                    }
        out = {"interval_s": newest["interval_s"] if newest else None,
               "daemons": rows,
               "cluster": self.quantiles("osd", "op_latency_hist"),
               "observed_client_latency":
                   self.observed_client_latency()}
        tl = self.tenant_latency()
        if tl:
            out["tenant_latency"] = tl
        if reports is not None:
            out["totals"] = reports.totals()
        return out


def _lhist_sub(cur: dict, prev: dict | None) -> dict:
    """Bucket-wise lhist subtraction cur - prev (both cumulative
    dumps). A fresh/reset snapshot (no prev, shorter buckets, or any
    bucket that went DOWN — client restart) deltas against zero, i.e.
    returns cur whole; never a negative histogram."""
    cb = list(cur.get("buckets") or [])
    if prev is None:
        return {"buckets": cb, "sum": float(cur.get("sum", 0.0)),
                "count": int(cur.get("count", 0))}
    pb = list(prev.get("buckets") or [])
    if len(pb) > len(cb):
        return {"buckets": cb, "sum": float(cur.get("sum", 0.0)),
                "count": int(cur.get("count", 0))}
    pb += [0] * (len(cb) - len(pb))
    if any(c < p for c, p in zip(cb, pb)):
        return {"buckets": cb, "sum": float(cur.get("sum", 0.0)),
                "count": int(cur.get("count", 0))}
    return {"buckets": [c - p for c, p in zip(cb, pb)],
            "sum": float(cur.get("sum", 0.0))
            - float(prev.get("sum", 0.0)),
            "count": int(cur.get("count", 0))
            - int(prev.get("count", 0))}


def _normalize_loggers(delta: dict) -> dict:
    """Per-daemon logger names ("osd.3") fold onto their generic
    logger ("osd") so cluster series don't mint one family per
    daemon (mgr/reports._normalized, applied to history deltas)."""
    return {_generic_logger(lg): counters
            for lg, counters in delta.items()}
