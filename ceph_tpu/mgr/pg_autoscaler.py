"""pg_autoscaler — PG-count recommendations.

Rebuild of the reference's autoscaler mgr module (ref: src/pybind/mgr/
pg_autoscaler/module.py — for each pool: ideal pg_num = in-OSD count *
mon_target_pg_per_osd * pool's capacity share / pool size, rounded to
a power of two; a change is only recommended when the current value is
off by more than the threshold factor (default 3.0), because pg_num
changes cause mass data movement and must not flap).

This module produces RECOMMENDATIONS (the reference's `warn` mode);
executing them is `SimCluster.apply_autoscale()`, which drives the
OSD-side split machinery (`split_pgs`: quorum-gated pg_num bump,
local collection split, pg_temp-protected child backfill — ref:
src/osd/PG.cc split) — the reference's autoscale `on` mode.
"""

from __future__ import annotations


def _pow2_round(x: float) -> int:
    """Nearest power of two (>= 1), the reference's nearest_power."""
    if x <= 1:
        return 1
    lo = 1 << (int(x).bit_length() - 1)
    hi = lo << 1
    return lo if x / lo < hi / x else hi


def _capacity_share(osdmap, pool_id: int,
                    pool_bytes: dict | None) -> float:
    """The fraction of cluster capacity this pool should size its PG
    count for. With real per-pool utilization (MgrReport-aggregated
    logical bytes) the share is the pool's byte fraction — the
    reference's capacity_ratio; a pool with no bytes yet keeps a
    one-PG-floor share. Without utilization data, an even split
    (the pre-r12 synthetic behavior, kept for offline tools)."""
    if not pool_bytes:
        return 1.0 / max(1, len(osdmap.pools))
    total = sum(int(pool_bytes.get(int(p), 0)) for p in osdmap.pools)
    if total <= 0:
        return 1.0 / max(1, len(osdmap.pools))
    return int(pool_bytes.get(int(pool_id), 0)) / total


def recommend_pg_num(osdmap, pool_id: int,
                     target_pg_per_osd: int = 100,
                     threshold: float = 3.0,
                     pool_bytes: dict | None = None) -> dict:
    """Autoscale advice for one pool. pool_bytes is the MgrReport
    pool-utilization aggregate ({pool_id: logical bytes}); absent, the
    capacity share is split evenly across pools."""
    if threshold < 1.0:
        raise ValueError(f"threshold {threshold} must be >= 1.0")
    pool = osdmap.pools[pool_id]
    n_in = int((osdmap.osd_weight > 0).sum())
    share = _capacity_share(osdmap, pool_id, pool_bytes)
    ideal = max(1.0, n_in * target_pg_per_osd * share / pool.size)
    recommended = _pow2_round(ideal)
    ratio = (pool.pg_num / recommended if pool.pg_num >= recommended
             else recommended / pool.pg_num)
    return {
        "pool_id": pool_id,
        "pg_num_current": pool.pg_num,
        "pg_num_ideal": round(ideal, 1),
        "pg_num_recommended": recommended,
        "would_adjust": ratio > threshold,
        "reason": (f"{n_in} in-osds x {target_pg_per_osd} target/osd "
                   f"x {share:.2f} share / size {pool.size}"),
    }


def autoscale_status(osdmap, target_pg_per_osd: int = 100,
                     threshold: float = 3.0,
                     pool_bytes: dict | None = None) -> list[dict]:
    return [recommend_pg_num(osdmap, pid, target_pg_per_osd, threshold,
                             pool_bytes)
            for pid in sorted(osdmap.pools)]


def autoscale_from_reports(aggregator, osdmap,
                           target_pg_per_osd: int = 100,
                           threshold: float = 3.0) -> list[dict]:
    """The live wiring (r12): capacity shares from the monitors'
    MgrReport aggregate (primaries report per-pool logical bytes)
    instead of synthetic even splits — what the `ceph autoscale
    status` monitor command serves."""
    return autoscale_status(osdmap, target_pg_per_osd, threshold,
                            pool_bytes=aggregator.pool_bytes())
