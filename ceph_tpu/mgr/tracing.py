"""Trace assembly — stitch per-daemon flight rings into causal
timelines with critical-path attribution.

The mgr half of the r15 distributed-tracing plane (the role of a
Jaeger collector against the reference's tracer spans): daemons drain
their flight-recorder rings into MgrReports (standalone.py ships a
bounded `spans` list per report; clients flush theirs after op
rounds), every monitor ingests them into a bounded per-trace store,
and `ceph_cli trace <id> / slow / list` renders one ASSEMBLED view —
spans ordered causally across daemons, a queue/crypto/encode/store/
wire attribution summary, and Chrome trace-event JSON for
chrome://tracing / Perfetto.

Gap semantics (disclosed; ARCHITECTURE "Distributed tracing (r15)",
updated r18): spans arrive best-effort — a ring may evict before
shipping, an unsampled hop records nothing. The assembler never
interpolates: time inside the root not covered by any recorded span
is reported as `wire` — which since r18 means WIRE SERIALIZATION plus
untraced host work only: retro traces now cover replica hops too
(sub-op service windows published from the daemons' retro rings as
retro.subop / retro.store.apply spans under the deterministic retro
root), so replica store time no longer masquerades as wire. A trace
whose root never arrived is summarized over its longest span instead.
Wall-clock ordering across daemons leans on the single-host shared
clock.

r18 additionally folds sampled traces into CONTINUOUS critical-path
profiles: per wall-clock interval, the summed per-category self time
across every trace whose root started in that interval — attribution
drift (queue share creeping up, store share exploding after a device
change) becomes a first-class time-series instead of a one-off
`trace <id>` (the 1709.05365 bottleneck-migration lesson). Evicted
traces fold into the profile PERMANENTLY before leaving the LRU, so
the profile's horizon outlives the trace store's.
"""

from __future__ import annotations

import threading

__all__ = ["TraceAssembler", "critical_path", "chrome_trace_events",
           "CATEGORY_OF"]

#: span name -> attribution category. Names not listed fall into
#: "other" (their self-time is still accounted, never silently
#: dropped). The retro.* family maps the OpTracker stage marks onto
#: the same buckets: initiated->reached_pg is queue+dispatch wait,
#: reached_pg->commit_sent is the execute window (encode + store
#: fan-out, indistinguishable retroactively).
CATEGORY_OF = {
    "osd.queue": "queue",
    "rpc.window": "queue",
    "msgr.seal": "crypto",
    "msgr.open": "crypto",
    "ecbackend.write.encode": "encode",
    "ecbackend.read.decode": "encode",
    "ecbackend.recover.stage": "encode",
    "ecbackend.recover.launch": "encode",
    "ecbackend.recover.fetch": "encode",
    "ecbackend.recover.batch": "encode",
    "ecbackend.recover.writeback": "store",
    "store.apply": "store",
    "osd.subop": "store",
    "retro.reached_pg": "queue",
    "retro.commit_sent": "other",
    "retro.done": "other",
    # r18: replica-published retro sub-op spans (the subop retro ring)
    "retro.subop": "store",
    "retro.store.apply": "store",
}

#: every summary carries exactly these keys (schema-pinned by
#: tests/test_bench_schema.py for the bench "trace" block)
CATEGORIES = ("queue", "crypto", "encode", "store", "wire", "other")


def _union_len(intervals: list[tuple[float, float]],
               lo: float, hi: float) -> float:
    """Total length of the union of [start, end) intervals clipped to
    [lo, hi] — robust to overlap from concurrent children (parallel
    sub-op fan-out, hedged duplicates)."""
    clipped = sorted((max(lo, s), min(hi, e)) for s, e in intervals
                     if e > lo and s < hi)
    total, cur_s, cur_e = 0.0, None, None
    for s, e in clipped:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _iv(span: dict) -> tuple[float, float]:
    return (span["start"], span["start"] + span["dur"])


def critical_path(spans: list[dict]) -> dict:
    """Attribution summary over one trace's spans.

    Per-span SELF time = duration minus the union of its direct
    children's intervals (concurrent children never double-subtract);
    self times sum into categories by span name. `wire` = root
    duration minus the union of every NON-root span's interval inside
    the root — the time the op spent between recorded hops (wire
    serialization + any untraced host work; see module docstring)."""
    out = {c: 0.0 for c in CATEGORIES}
    out["total"] = 0.0
    if not spans:
        return {k: round(v, 6) for k, v in out.items()}
    by_id = {s["span_id"]: s for s in spans}
    kids: dict[str, list[dict]] = {}
    roots = []
    for s in spans:
        if s["parent_id"] in by_id:
            kids.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)
    # the root: prefer a client-origin span, else the longest orphan
    root = max(roots or spans,
               key=lambda s: (s["name"].startswith("client."),
                              s["name"] == "retro.op", s["dur"]))
    r_lo, r_hi = _iv(root)
    out["total"] = root["dur"]
    for s in spans:
        if s is root:
            continue
        lo, hi = _iv(s)
        child_ivs = [_iv(c) for c in kids.get(s["span_id"], ())]
        self_t = max(0.0, s["dur"] - _union_len(child_ivs, lo, hi))
        out[CATEGORY_OF.get(s["name"], "other")] += self_t
    covered = _union_len([_iv(s) for s in spans if s is not root],
                         r_lo, r_hi)
    out["wire"] = max(0.0, root["dur"] - covered)
    return {k: round(v, 6) for k, v in out.items()}


def chrome_trace_events(spans: list[dict]) -> list[dict]:
    """Chrome trace-event JSON (the `traceEvents` list): one complete
    "X" event per span, daemons as processes (named via "M" metadata
    events), timestamps in microseconds."""
    daemons = sorted({s["daemon"] for s in spans})
    pid_of = {d: i + 1 for i, d in enumerate(daemons)}
    events = [{"name": "process_name", "ph": "M", "pid": pid_of[d],
               "tid": 0, "args": {"name": d}} for d in daemons]
    for s in sorted(spans, key=lambda s: s["start"]):
        ev = {
            "name": s["name"], "ph": "X", "cat": "ceph_tpu",
            "pid": pid_of[s["daemon"]], "tid": 0,
            "ts": round(s["start"] * 1e6, 3),
            "dur": round(s["dur"] * 1e6, 3),
            "args": {"trace_id": s["trace_id"],
                     "span_id": s["span_id"],
                     "parent_id": s["parent_id"],
                     **(s.get("tags") or {})},
        }
        events.append(ev)
    return events


class TraceAssembler:
    """Bounded per-trace span store + assembled views (one instance
    per monitor, fed from the MgrReport pipe; also used standalone by
    the benches to assemble in-process rings)."""

    def __init__(self, max_traces: int = 512,
                 max_spans_per_trace: int = 4096,
                 config=None, profile_interval: float = 10.0,
                 max_profile_intervals: int = 256):
        self._max_traces = int(max_traces)
        self._max_spans = int(max_spans_per_trace)
        #: trace_id(hex) -> {"spans": [..], "stamp": monotone counter}
        self._traces: dict[str, dict] = {}
        self._tick = 0
        self._lock = threading.Lock()
        # r18 continuous critical-path profile: interval bucket ->
        # settled per-category self-time sums (traces fold here
        # PERMANENTLY on LRU eviction; live traces fold on demand in
        # profile()). Interval tracks mgr_history_interval when a
        # config is given so the profile series aligns with the
        # telemetry plane's metric series.
        self._config = config
        self._profile_interval = float(profile_interval)
        self._max_profile = int(max_profile_intervals)
        self._settled: dict[int, dict] = {}

    def ingest(self, spans: list[dict]) -> None:
        """Fold a daemon's drained spans (dicts in FlightRecorder
        shape). Dedup by (daemon, span_id) so re-shipped spans fold
        idempotently; LRU-evict whole traces past the cap."""
        with self._lock:
            self._tick += 1
            for s in spans:
                if not isinstance(s, dict) or "trace_id" not in s:
                    continue
                ent = self._traces.get(s["trace_id"])
                if ent is None:
                    ent = self._traces[s["trace_id"]] = {
                        "spans": [], "seen": set(), "stamp": 0}
                key = (s.get("daemon"), s.get("span_id"))
                if key in ent["seen"] \
                        or len(ent["spans"]) >= self._max_spans:
                    continue
                ent["seen"].add(key)
                ent["spans"].append(dict(s))
                ent["stamp"] = self._tick
            over = len(self._traces) - self._max_traces
            if over > 0:
                for tid in sorted(self._traces,
                                  key=lambda t:
                                  self._traces[t]["stamp"])[:over]:
                    # settle the evicted trace into the continuous
                    # profile first — the rollup's horizon must
                    # outlive the LRU
                    self._settle_profile_locked(
                        self._traces[tid]["spans"])
                    del self._traces[tid]

    # -- continuous critical-path profile (r18) -------------------------------

    def _iv(self) -> float:
        if self._config is not None:
            try:
                iv = float(self._config.get("mgr_history_interval"))
                if iv > 0:
                    return iv
            except (KeyError, TypeError, ValueError):
                pass
        return self._profile_interval

    def _settle_profile_locked(self, spans: list[dict]) -> None:
        if not spans:
            return
        cp = critical_path(spans)
        bucket = int(min(s["start"] for s in spans) / self._iv())
        row = self._settled.setdefault(
            bucket, {c: 0.0 for c in CATEGORIES}
            | {"total": 0.0, "traces": 0})
        for c in CATEGORIES:
            row[c] += cp.get(c, 0.0)
        row["total"] += cp.get("total", 0.0)
        row["traces"] += 1
        over = len(self._settled) - self._max_profile
        if over > 0:
            for b in sorted(self._settled)[:over]:
                del self._settled[b]

    def profile(self, limit: int = 32) -> dict:
        """Per-interval critical-path attribution series (the
        `profile` mon command / `ceph_cli profile` body): settled
        (evicted) traces + an on-demand fold of every trace still in
        the store. Shares are per-category self time over the
        interval's summed root time — the drift signal."""
        iv = self._iv()
        with self._lock:
            rows = {b: dict(r) for b, r in self._settled.items()}
            live = [list(e["spans"]) for e in self._traces.values()]
        for spans in live:
            if not spans:
                continue
            cp = critical_path(spans)
            bucket = int(min(s["start"] for s in spans) / iv)
            row = rows.setdefault(
                bucket, {c: 0.0 for c in CATEGORIES}
                | {"total": 0.0, "traces": 0})
            for c in CATEGORIES:
                row[c] += cp.get(c, 0.0)
            row["total"] += cp.get("total", 0.0)
            row["traces"] += 1
        out = []
        for b in sorted(rows)[-int(limit):]:
            row = rows[b]
            total = row["total"] or 1e-12
            out.append({
                "bucket": b,
                "t": round(b * iv, 3),
                "traces": row["traces"],
                "self_s": {c: round(row[c], 6) for c in CATEGORIES},
                "total_s": round(row["total"], 6),
                "share": {c: round(row[c] / total, 4)
                          for c in CATEGORIES},
            })
        return {"interval_s": iv, "intervals": out}

    # -- views ----------------------------------------------------------------

    def _spans(self, trace_id: str) -> list[dict]:
        tid = str(trace_id).lower().removeprefix("0x").rjust(16, "0")
        with self._lock:
            ent = self._traces.get(tid)
            return [dict(s) for s in ent["spans"]] if ent else []

    def _summary_locked(self, tid: str) -> dict:
        spans = self._traces[tid]["spans"]
        daemons = sorted({s["daemon"] for s in spans})
        root_dur = max((s["dur"] for s in spans), default=0.0)
        return {"trace_id": tid, "spans": len(spans),
                "daemons": daemons, "duration_s": round(root_dur, 6)}

    def list_traces(self) -> list[dict]:
        with self._lock:
            return sorted((self._summary_locked(t)
                           for t in self._traces),
                          key=lambda e: -e["duration_s"])

    def slow(self, threshold_s: float = 0.0, limit: int = 16) -> list[dict]:
        """Traces ordered slowest-first (the `trace slow` view), with
        their attribution summaries — the cross-daemon complement of
        the per-daemon slow_ops dump."""
        out = []
        for ent in self.list_traces():
            if ent["duration_s"] < threshold_s:
                continue
            spans = self._spans(ent["trace_id"])
            out.append({**ent, "critical_path": critical_path(spans)})
            if len(out) >= limit:
                break
        return out

    def assemble(self, trace_id: str) -> dict:
        """One trace, fully assembled: causally ordered spans, the
        critical-path summary, and Chrome trace-event JSON."""
        spans = self._spans(trace_id)
        spans.sort(key=lambda s: (s["start"], -s["dur"]))
        return {
            "trace_id": str(trace_id).lower().removeprefix("0x")
            .rjust(16, "0"),
            "found": bool(spans),
            "daemons": sorted({s["daemon"] for s in spans}),
            "critical_path": critical_path(spans),
            "spans": spans,
            "chrome": {"traceEvents": chrome_trace_events(spans)},
        }
