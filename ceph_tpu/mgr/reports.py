"""MgrReport aggregation — daemon counters to cluster view.

Rebuild of the reference's daemon->mgr stats pipe (ref: MMgrReport in
src/messages/MMgrReport.h + src/mgr/DaemonServer.cc handle_report:
daemons periodically ship their PerfCounters as DELTAS after an
initial full declaration, the mgr folds them into DaemonStateIndex,
and the prometheus module renders the aggregate as text exposition).

Here the aggregation lives in each monitor (this tier has no separate
mgr daemon — disclosed in ARCHITECTURE.md): daemons broadcast reports
to every monitor, each folds independently, and any one of them can
answer `ceph status` / `prometheus`. Wire shape per report:

    {"name": "osd.0", "seq": N, "kind": "full"|"delta",
     "perf": <dump or delta over nested loggers>,
     "schema": {logger: {key: {kind, description}}}   (full only),
     "ops_in_flight": n, "slow_ops": n,
     "pgs": {"1.0": "active+clean", ...}, "epoch": e}

Deltas fold only when seq == last_seq + 1; any gap (monitor restart,
lost report, daemon restart) marks the daemon stale until the next
FULL report re-bases it — daemons interleave a full every
FULL_EVERY reports, so staleness self-heals without acks.
"""

from __future__ import annotations

import threading
import time

from ..utils.perf_counters import fold_delta

#: a daemon re-ships its full dump every Nth report; deltas ride the
#: reports in between (the bounded-delta discipline the PG metadata
#: plane already uses)
FULL_EVERY = 8


class MgrReportAggregator:
    """Per-monitor fold of every daemon's report stream."""

    def __init__(self, now_fn=time.monotonic):
        self._now = now_fn
        self._lock = threading.Lock()
        #: name -> {"perf", "schema", "seq", "stamp", "ops_in_flight",
        #:          "slow_ops", "pgs", "epoch", "synced"}
        self._daemons: dict[str, dict] = {}

    def ingest(self, report: dict) -> None:
        name = report.get("name")
        if not name:
            return
        now = self._now()
        with self._lock:
            ent = self._daemons.setdefault(
                name, {"perf": {}, "schema": {}, "seq": -1,
                       "synced": False, "pgs": {}, "epoch": 0,
                       "pool_bytes": {}, "ops_in_flight": 0,
                       "slow_ops": 0, "stamp": now})
            seq = int(report.get("seq", 0))
            if report.get("kind") == "full":
                ent["perf"] = report.get("perf", {})
                if report.get("schema"):
                    ent["schema"] = report["schema"]
                ent["synced"] = True
            elif ent["synced"] and seq == ent["seq"] + 1:
                ent["perf"] = fold_delta(ent["perf"],
                                         report.get("perf", {}))
            else:
                # gap: this delta extends a base we never saw — wait
                # for the next interleaved full instead of folding
                # garbage (self-heals within FULL_EVERY reports)
                ent["synced"] = False
            ent["seq"] = seq
            ent["stamp"] = now
            for key in ("ops_in_flight", "slow_ops", "pgs", "epoch",
                        "pool_bytes", "pool_objects", "mclock",
                        "statfs", "network"):
                if key in report:
                    ent[key] = report[key]

    # -- views ---------------------------------------------------------------

    def daemons(self) -> dict:
        with self._lock:
            return {n: dict(e) for n, e in self._daemons.items()}

    def report_ages(self) -> dict[str, float]:
        now = self._now()
        with self._lock:
            return {n: now - e["stamp"] for n, e in self._daemons.items()}

    def pg_states(self) -> dict[str, str]:
        """Latest primary-reported state per pgid (the report carrying
        the newest epoch wins a contested pgid — two daemons can both
        claim a PG across an interval change)."""
        with self._lock:
            ents = sorted(self._daemons.values(),
                          key=lambda e: e["epoch"])
        out: dict[str, str] = {}
        for ent in ents:
            out.update(ent.get("pgs") or {})
        return out

    def pool_bytes(self) -> dict[int, int]:
        """Logical bytes per pool summed over every reporting
        primary's claim — the pool-utilization input the
        pg_autoscaler's capacity shares derive from (role of
        pg_stat_t num_bytes aggregation in the mgr)."""
        out: dict[int, int] = {}
        with self._lock:
            claims = [e.get("pool_bytes") or {}
                      for e in self._daemons.values()]
        for claim in claims:
            for pid, b in claim.items():
                pid = int(pid)
                out[pid] = out.get(pid, 0) + int(b)
        return out

    def pool_objects(self) -> dict[int, int]:
        """Object count per pool summed over every reporting primary's
        claim — what quota_max_objects is enforced against (role of
        pg_stat_t num_objects aggregation in the mgr)."""
        out: dict[int, int] = {}
        with self._lock:
            claims = [e.get("pool_objects") or {}
                      for e in self._daemons.values()]
        for claim in claims:
            for pid, n in claim.items():
                pid = int(pid)
                out[pid] = out.get(pid, 0) + int(n)
        return out

    def statfs(self) -> dict[str, dict]:
        """Latest raw statfs claim per reporting OSD ("osd.N" ->
        {"total","used","avail"}) — the r21 capacity ladder's only
        input (the mon never guesses at space it wasn't told about).
        Daemons with no claim (mons, unbounded stores reporting
        total=0) simply appear without a usable ratio."""
        with self._lock:
            return {n: dict(e["statfs"])
                    for n, e in self._daemons.items()
                    if e.get("statfs")}

    def network(self) -> dict[str, dict]:
        """Latest links+flow claim per reporting daemon (r22, the
        NetworkAggregator's raw input — kept here too so a bench or
        test can replay the fold from the same aggregator state)."""
        with self._lock:
            return {n: dict(e["network"])
                    for n, e in self._daemons.items()
                    if e.get("network")}

    def tenants(self) -> dict:
        """Per-tenant mClock accounting summed over every daemon's
        latest `mclock` claim (r20): class "tenant:<entity>" rows fold
        into one row per entity — served/served_cost (grants),
        throttled (limit-bound dequeue passes) and queued depth, plus
        the (ρ, w, λ) profile the class last ran under. The view
        `ceph_cli top` and the workload bench use to say WHICH tenant
        mClock is holding back."""
        out: dict[str, dict] = {}
        with self._lock:
            claims = [e.get("mclock") or {}
                      for e in self._daemons.values()]
        for claim in claims:
            for cls, row in claim.items():
                if not cls.startswith("tenant:"):
                    continue
                entity = cls[len("tenant:"):]
                cur = out.setdefault(
                    entity, {"queued": 0, "served": 0,
                             "served_cost": 0.0, "throttled": 0,
                             "profile": row.get("profile")})
                cur["queued"] += int(row.get("queued", 0))
                cur["served"] += int(row.get("served", 0))
                cur["served_cost"] = round(
                    cur["served_cost"]
                    + float(row.get("served_cost", 0.0)), 3)
                cur["throttled"] += int(row.get("throttled", 0))
                if row.get("profile"):
                    cur["profile"] = row["profile"]
        return out

    def totals(self) -> dict:
        with self._lock:
            return {
                "slow_ops": sum(e.get("slow_ops", 0)
                                for e in self._daemons.values()),
                "ops_in_flight": sum(e.get("ops_in_flight", 0)
                                     for e in self._daemons.values()),
                "daemons_reporting": len(self._daemons),
            }

    def cluster_perf(self) -> dict:
        """Counters summed across daemons per (logger, key) — the
        `perf dump` a monitor can answer for the whole cluster."""
        out: dict = {}
        with self._lock:
            dumps = [e["perf"] for e in self._daemons.values()]
        for dump in dumps:
            out = fold_delta(out, _normalized(dump))
        return out


def _normalized(perf: dict) -> dict:
    """Fold per-daemon logger names ("osd.3") onto their generic
    logger ("osd") so cluster aggregation and exposition don't mint
    one metric family per daemon."""
    out = {}
    for logger, counters in perf.items():
        out[_generic_logger(logger)] = counters
    return out


def _generic_logger(logger: str) -> str:
    head, _, tail = logger.partition(".")
    return head if tail.isdigit() else logger


def _clean(s: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in s)


def prometheus_text(agg: MgrReportAggregator,
                    prefix: str = "ceph_tpu") -> str:
    """Text exposition over the aggregated REAL daemon counters (ref:
    src/pybind/mgr/prometheus/module.py): one series per (logger, key,
    daemon) with a `daemon` label, typed from the schema the daemons
    declared in their full reports. time_avg renders as a summary's
    _sum/_count; histograms as cumulative power-of-two buckets (the
    PerfCountersCollection.prometheus_text convention)."""
    daemons = agg.daemons()
    lines: list[str] = []
    seen_header: set[str] = set()
    for dname in sorted(daemons):
        ent = daemons[dname]
        schema = ent.get("schema") or {}
        for logger in sorted(ent.get("perf") or {}):
            counters = ent["perf"][logger]
            lschema = schema.get(logger, {})
            glogger = _generic_logger(logger)
            for key in sorted(counters):
                val = counters[key]
                ks = lschema.get(key, {})
                kind = ks.get("kind") or _guess_kind(val)
                metric = f"{_clean(prefix)}_{_clean(glogger)}_{_clean(key)}"
                label = f'{{daemon="{dname}"}}'
                if metric not in seen_header:
                    seen_header.add(metric)
                    if ks.get("description"):
                        lines.append(f"# HELP {metric} "
                                     f"{ks['description']}")
                    lines.append(f"# TYPE {metric} "
                                 f"{_prom_type(kind)}")
                if kind == "time_avg":
                    lines.append(f"{metric}_sum{label} "
                                 f"{val.get('sum', 0)!r}")
                    lines.append(f"{metric}_count{label} "
                                 f"{val.get('avgcount', 0)}")
                elif kind == "lhist":
                    # r18: REAL `# TYPE ... histogram` exposition for
                    # the mergeable latency histograms — cumulative
                    # _bucket/_sum/_count with le in SECONDS, never
                    # flattened to gauges
                    from ..utils.perf_counters import lhist_bucket_le
                    buckets = (val or {}).get("buckets") or []
                    total = 0
                    for i, b in enumerate(buckets[:-1]):
                        total += b
                        lines.append(
                            f'{metric}_bucket{{daemon="{dname}",'
                            f'le="{lhist_bucket_le(i)!r}"}} {total}')
                    total += buckets[-1] if buckets else 0
                    lines.append(f'{metric}_bucket{{daemon="{dname}",'
                                 f'le="+Inf"}} {total}')
                    lines.append(f"{metric}_sum{label} "
                                 f"{(val or {}).get('sum', 0.0)!r}")
                    lines.append(f"{metric}_count{label} {total}")
                elif kind == "histogram":
                    total = 0
                    for i, b in enumerate(val[:-1]):
                        total += b
                        lines.append(
                            f'{metric}_bucket{{daemon="{dname}",'
                            f'le="{1 << (i + 1)}"}} {total}')
                    total += val[-1] if val else 0
                    lines.append(f'{metric}_bucket{{daemon="{dname}",'
                                 f'le="+Inf"}} {total}')
                    lines.append(f"{metric}_count{label} {total}")
                else:
                    v = (str(int(val)) if float(val).is_integer()
                         else repr(float(val)))
                    lines.append(f"{metric}{label} {v}")
    return "\n".join(lines) + "\n"


def _guess_kind(val) -> str:
    if isinstance(val, dict):
        return "lhist" if "buckets" in val else "time_avg"
    if isinstance(val, list):
        return "histogram"
    return "counter"


def _prom_type(kind: str) -> str:
    return {"counter": "counter", "gauge": "gauge",
            "time_avg": "summary", "histogram": "histogram",
            "lhist": "histogram"}[kind]
