"""Balancer — the upmap placement optimizer.

Rebuild of the reference's mgr balancer module in upmap mode (ref:
src/pybind/mgr/balancer/module.py `do_upmap`, which drives
OSDMap::calc_pg_upmaps — greedy moves of PGs from overfull to
underfull OSDs via pg_upmap_items entries, bounded per round by
max_optimizations, stopping at max_deviation).

TPU-first shaping: the expensive part of balancing is knowing where
every PG currently maps — here that is ONE batched `pgs_to_up` launch
per round (the vectorized CRUSH mapper) instead of the reference's
per-PG loop; the load histogram and the greedy move-selection derive
from that single array host-side.

Since r12 this scalar module is the PARITY ORACLE: the production
path is `mgr/placement.py` (device-batched candidate scoring, one
raw launch per optimize run, data-movement budgets), which pins its
legality rules and objective against this implementation in
tests/test_placement.py.

Failure-domain safety: a move is only legal if the target device does
not put two shards of the PG into one failure domain, at the SAME
bucket level the pool's CRUSH rule separates on (chooseleaf type) —
host rules separate hosts, rack rules separate racks.
"""

from __future__ import annotations

import numpy as np

from ..crush.map import (CRUSH_ITEM_NONE, STEP_CHOOSE_FIRSTN,
                         STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_FIRSTN,
                         STEP_CHOOSELEAF_INDEP)


def load_from_up(up: np.ndarray, n_osds: int) -> np.ndarray:
    """PG-shard count per OSD from a (B, size) up array."""
    flat = np.asarray(up)
    flat = flat[flat != CRUSH_ITEM_NONE]
    return np.bincount(flat, minlength=n_osds)


def device_load(osdmap, pool_id: int) -> np.ndarray:
    """Convenience: one vectorized mapping launch -> per-OSD load
    (the same histogram OSDMap.pg_stats exposes as pg_per_osd)."""
    return load_from_up(osdmap.pgs_to_up(pool_id),
                        len(osdmap.osd_weight))


def _rule_domain_type(crush, rule_id: int) -> int:
    """The bucket type the rule separates replicas on (the chooseleaf/
    choose step's type); 0 (osd) when the rule picks devices directly."""
    for step in crush.rules[rule_id].steps:
        if step.op in (STEP_CHOOSELEAF_FIRSTN, STEP_CHOOSELEAF_INDEP,
                       STEP_CHOOSE_FIRSTN, STEP_CHOOSE_INDEP):
            return step.type_id
    return 0


def _domain_of(crush, item: int, type_id: int,
               _parent_cache: dict | None = None) -> int | None:
    """The ancestor bucket of `item` at `type_id` (transitive walk —
    a rack-level domain is two levels above an osd)."""
    if type_id == 0:
        return item
    parents = _parent_cache if _parent_cache is not None else {}
    if not parents:
        for bid, b in crush.buckets.items():
            for it in b.items:
                parents[it] = bid
    cur = item
    for _ in range(len(crush.buckets) + 1):
        cur = parents.get(cur)
        if cur is None:
            return None
        if crush.buckets[cur].type_id == type_id:
            return cur
    return None


def calc_pg_upmaps(osdmap, pool_id: int, max_deviation: int = 1,
                   max_optimizations: int = 10) -> list[tuple]:
    """One optimization run: returns the applied
    [((pool, ps), (from_osd, to_osd)), ...] moves (already set on the
    map — one redirect pair per move).

    Greedy: move a shard from the most-loaded OSD to the least-loaded
    OSD that is up+in, doesn't already serve the PG, and lives in a
    failure domain serving no other shard of it. Stops when the
    max-min spread over up+in OSDs is within max_deviation or no legal
    move exists.
    """
    crush = osdmap.crush
    pool = osdmap.pools[pool_id]
    dom_type = _rule_domain_type(crush, pool.crush_rule)
    parent_cache: dict = {}
    applied: list[tuple] = []
    n_osds = len(osdmap.osd_weight)
    while len(applied) < max_optimizations:
        up_all = np.asarray(osdmap.pgs_to_up(pool_id))  # ONE launch
        load = load_from_up(up_all, n_osds).astype(np.float64)
        w = np.asarray(osdmap.osd_weight, dtype=np.float64) / 0x10000
        usable = (w > 0) & np.asarray(osdmap.osd_up)
        in_osds = [int(o) for o in np.nonzero(usable)[0]]
        if len(in_osds) < 2:
            break
        # deviation vs the WEIGHT-PROPORTIONAL target (a half-weight
        # device should carry half the PGs; equalizing raw counts
        # would fight CRUSH — the reference measures the same way)
        total = load[usable].sum()
        wsum = w[in_osds].sum()
        expected = {o: total * w[o] / wsum for o in in_osds}

        def dev(o):
            return load[o] - expected[o]

        # many moves per mapping launch: update the load histogram
        # incrementally and only re-launch when a full pass over the
        # candidates makes no further progress
        moved_pgs: set[int] = set()
        round_moves = 0
        progress = True
        while progress and len(applied) < max_optimizations:
            progress = False
            devs = sorted(in_osds, key=dev, reverse=True)
            if dev(devs[0]) - dev(devs[-1]) <= max_deviation:
                break
            for overfull in devs:
                if dev(overfull) <= 0:
                    break  # nothing left that is actually overfull
                targets = sorted((o for o in in_osds if o != overfull),
                                 key=dev)
                hit = self_move = None
                for ps in np.nonzero(
                        (up_all == overfull).any(axis=1))[0]:
                    ps = int(ps)
                    if ps in moved_pgs:
                        continue  # up_all is stale for moved pgs
                    pg = (pool_id, ps)
                    raw = osdmap._raw_pg_to_osds(pool, ps)
                    # domain safety derives from the RAW set: a
                    # down-but-in member still owns its slot, and
                    # stacking into its domain breaks separation the
                    # moment it rejoins
                    members = {int(o) for o in raw
                               if o != CRUSH_ITEM_NONE}
                    for _f, t in osdmap.pg_upmap_items.get(pg, []):
                        members.add(t)
                    doms = {_domain_of(crush, o, dom_type, parent_cache)
                            for o in members if o != overfull}
                    items = osdmap.pg_upmap_items.get(pg, [])
                    # who sources overfull here? Either overfull is in
                    # the raw mapping, or an ACTIVE redirect (f ->
                    # overfull, f in raw) produced it; rewriting an
                    # inactive redirect would move the wrong shard
                    if overfull in raw:
                        src_pair = None
                    else:
                        act = [f for f, t in items
                               if t == overfull and f in raw]
                        if not act:
                            continue
                        src_pair = act[0]
                    for to in targets:
                        if dev(to) >= dev(overfull) - 1:
                            break  # no target improves balance
                        if to in members:
                            continue
                        if _domain_of(crush, to, dom_type,
                                      parent_cache) in doms:
                            continue  # two shards in one domain
                        hit, self_move = (pg, ps, items, to), src_pair
                        break
                    if hit:
                        break
                if not hit:
                    continue  # this osd is stuck; try the next
                pg, ps, items, to = hit
                if self_move is None:
                    new_items = items + [(overfull, to)]
                else:
                    new_items = [(f, t) for f, t in items
                                 if (f, t) != (self_move, overfull)]
                    new_items.append((self_move, to))
                osdmap.set_pg_upmap_items(pg, new_items)
                applied.append((pg, (overfull, to)))
                moved_pgs.add(ps)
                load[overfull] -= 1
                load[to] += 1
                round_moves += 1
                progress = True
                break  # re-rank deviations after every move
        if round_moves == 0:
            break  # a full relaunch would see the same stuck state
    return applied
