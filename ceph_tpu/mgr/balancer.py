"""Balancer — the upmap placement optimizer.

Rebuild of the reference's mgr balancer module in upmap mode (ref:
src/pybind/mgr/balancer/module.py `do_upmap`, which drives
OSDMap::calc_pg_upmaps — greedy moves of PGs from overfull to
underfull OSDs via pg_upmap_items entries, bounded per round by
max_optimizations, stopping at max_deviation).

TPU-first shaping: the expensive part of balancing is knowing where
every PG currently maps — here that is ONE batched `pgs_to_up` launch
per round (the vectorized CRUSH mapper) instead of the reference's
per-PG loop; the load histogram and the greedy move-selection derive
from that single array host-side.

Failure-domain safety: a move is only legal if the target device does
not put two shards of the PG into one failure domain, at the SAME
bucket level the pool's CRUSH rule separates on (chooseleaf type) —
host rules separate hosts, rack rules separate racks.
"""

from __future__ import annotations

import numpy as np

from ..crush.map import (CRUSH_ITEM_NONE, STEP_CHOOSE_FIRSTN,
                         STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_FIRSTN,
                         STEP_CHOOSELEAF_INDEP)


def load_from_up(up: np.ndarray, n_osds: int) -> np.ndarray:
    """PG-shard count per OSD from a (B, size) up array."""
    flat = np.asarray(up)
    flat = flat[flat != CRUSH_ITEM_NONE]
    return np.bincount(flat, minlength=n_osds)


def device_load(osdmap, pool_id: int) -> np.ndarray:
    """Convenience: one vectorized mapping launch -> per-OSD load
    (the same histogram OSDMap.pg_stats exposes as pg_per_osd)."""
    return load_from_up(osdmap.pgs_to_up(pool_id),
                        len(osdmap.osd_weight))


def _rule_domain_type(crush, rule_id: int) -> int:
    """The bucket type the rule separates replicas on (the chooseleaf/
    choose step's type); 0 (osd) when the rule picks devices directly."""
    for step in crush.rules[rule_id].steps:
        if step.op in (STEP_CHOOSELEAF_FIRSTN, STEP_CHOOSELEAF_INDEP,
                       STEP_CHOOSE_FIRSTN, STEP_CHOOSE_INDEP):
            return step.type_id
    return 0


def _domain_of(crush, item: int, type_id: int,
               _parent_cache: dict | None = None) -> int | None:
    """The ancestor bucket of `item` at `type_id` (transitive walk —
    a rack-level domain is two levels above an osd)."""
    if type_id == 0:
        return item
    parents = _parent_cache if _parent_cache is not None else {}
    if not parents:
        for bid, b in crush.buckets.items():
            for it in b.items:
                parents[it] = bid
    cur = item
    for _ in range(len(crush.buckets) + 1):
        cur = parents.get(cur)
        if cur is None:
            return None
        if crush.buckets[cur].type_id == type_id:
            return cur
    return None


def calc_pg_upmaps(osdmap, pool_id: int, max_deviation: int = 1,
                   max_optimizations: int = 10) -> list[tuple]:
    """One optimization run: returns the applied
    [((pool, ps), (from_osd, to_osd)), ...] moves (already set on the
    map — one redirect pair per move).

    Greedy: move a shard from the most-loaded OSD to the least-loaded
    OSD that is up+in, doesn't already serve the PG, and lives in a
    failure domain serving no other shard of it. Stops when the
    max-min spread over up+in OSDs is within max_deviation or no legal
    move exists.
    """
    crush = osdmap.crush
    pool = osdmap.pools[pool_id]
    dom_type = _rule_domain_type(crush, pool.crush_rule)
    parent_cache: dict = {}
    applied: list[tuple] = []
    for _ in range(max_optimizations):
        up_all = np.asarray(osdmap.pgs_to_up(pool_id))  # ONE launch
        load = load_from_up(up_all, len(osdmap.osd_weight))
        usable = (np.asarray(osdmap.osd_weight) > 0) \
            & np.asarray(osdmap.osd_up)
        in_osds = np.nonzero(usable)[0]
        if len(in_osds) < 2:
            break
        sub = load[in_osds]
        if sub.max() - sub.min() <= max_deviation:
            break
        overfull = int(in_osds[np.argmax(sub)])
        targets = [int(o) for o in in_osds[np.argsort(sub, kind="stable")]
                   if int(o) != overfull]
        moved = False
        for ps in np.nonzero((up_all == overfull).any(axis=1))[0]:
            pg = (pool_id, int(ps))
            members = [int(o) for o in up_all[ps]
                       if o != CRUSH_ITEM_NONE]
            doms = {_domain_of(crush, o, dom_type, parent_cache)
                    for o in members if o != overfull}
            raw = osdmap._raw_pg_to_osds(pool, int(ps))
            items = osdmap.pg_upmap_items.get(pg, [])
            # who sources overfull in this PG? Either overfull itself
            # is in the raw mapping, or an ACTIVE redirect (f ->
            # overfull, f in raw) produced it; rewriting an INACTIVE
            # redirect would move the wrong OSD's shard
            if overfull in raw:
                src_pair = None
            else:
                act = [f for f, t in items
                       if t == overfull and f in raw]
                if not act:
                    continue  # can't attribute the shard; skip this pg
                src_pair = act[0]
            for to in targets:
                if to in members:
                    continue
                if _domain_of(crush, to, dom_type, parent_cache) in doms:
                    continue  # would stack two shards in one domain
                if src_pair is None:
                    new_items = items + [(overfull, to)]
                else:
                    new_items = [(f, t) for f, t in items
                                 if (f, t) != (src_pair, overfull)]
                    new_items.append((src_pair, to))
                osdmap.set_pg_upmap_items(pg, new_items)
                applied.append((pg, (overfull, to)))
                moved = True
                break
            if moved:
                break
        if not moved:
            break  # no legal move improves this round
    return applied
