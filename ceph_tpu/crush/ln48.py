"""Fixed-point log2 tables for the straw2 draw (crush_ln equivalent).

The reference's bucket_straw2_choose draws are s64 fixed point (ref:
src/crush/mapper.c crush_ln — a two-level __RH_LH_tbl/__LL_tbl lookup
pyramid returning ~2^44 * log2(x) — then
  ln   = crush_ln(u & 0xffff) - 0x1000000000000   (<= 0)
  draw = div64_s64(ln, item_weight)               (truncating)
and the FIRST strictly-greatest draw wins).

This module reproduces those semantics exactly, restructured for a
machine with no 64-bit integers on the device:

* `ln44(v)` computes floor(2^44 * log2(v)) with deterministic pure-
  integer arithmetic (msb + 44 fractional bits by the classic square-
  and-extract method at 96-bit working precision) — no float rounding,
  identical on every host. Upstream's table pyramid approximates the
  same quantity with its own interpolation error; its exact table bytes
  cannot be verified here (empty reference mount, same caveat as the
  rjenkins constants — see SURVEY.md), so we pin the mathematically
  exact value instead.
* `a48_table()` is A[u] = 2^48 - crush_ln(u) >= 0 for the 16-bit draw
  domain: since draw = ln/w = -(A // w) for w > 0, comparing draws
  descending is comparing q = A // w ascending, first index winning
  ties — integer semantics identical to the reference's.
* `quotient_tables(weights)` precomputes, per DISTINCT item weight w,
  the full 65536-entry q = A // w table split into u32 hi/lo halves
  (q < 2^48). The device then needs only gathers and u32 lexicographic
  compares — the whole s64 divide/compare pipeline becomes two table
  reads. Weights are static per CrushMap, so this is build-time work.
"""

from __future__ import annotations

import functools

import numpy as np

_PREC = 96          # working precision bits for the fractional part
_FRAC = 44          # fractional bits of crush_ln's fixed point


def ln44(v: int) -> int:
    """floor(2^44 * log2(v)) for integer v >= 1, exact integer math."""
    if v < 1:
        raise ValueError("ln44 domain is v >= 1")
    e = v.bit_length() - 1
    # r = v / 2^e in [1, 2) as a _PREC-bit fixed-point integer
    r = v << (_PREC - e)
    one = 1 << _PREC
    frac = 0
    for _ in range(_FRAC):
        r = (r * r) >> _PREC
        frac <<= 1
        if r >= (one << 1):
            frac |= 1
            r >>= 1
    return (e << _FRAC) | frac


_BASE = 24          # limb radix bits for the vectorized builder
_NLIMB = 5          # 5 x 24 = 120 bits >= _PREC + 2


def _ln44_table_vec() -> np.ndarray:
    """ln44(v) for v in [1, 65536] as uint64, vectorized.

    Same square-and-extract recurrence as ln44() at the same _PREC,
    bit-identical (pinned by tests), but the 44 iterations run as
    numpy limb arithmetic over the whole domain at once instead of
    65536 Python bigint loops (~50x faster; this builds at first
    mapper construction, so it must be cheap). Limbs are base 2^24 in
    uint64, so a 5x5 limb square's column sums stay < 2^53."""
    v = np.arange(1, 65537, dtype=np.uint64)
    e = np.zeros(65536, dtype=np.uint64)
    bl = np.zeros(65536, dtype=np.int64)   # bit_length(v) - 1
    tmp = v.copy()
    for _ in range(17):
        tmp >>= np.uint64(1)
        bl += (tmp > 0).astype(np.int64)
    e = bl.astype(np.uint64)
    # R = v << (_PREC - e), split into base-2^24 limbs (little-endian)
    mask = np.uint64((1 << _BASE) - 1)
    shift = (np.uint64(_PREC) - e).astype(np.uint64)
    limbs = np.zeros((_NLIMB, 65536), dtype=np.uint64)
    # R has at most _PREC+1 bits; fill limb l with bits [24l, 24l+24)
    for li in range(_NLIMB):
        lo = np.int64(li * _BASE)
        # bits of (v << shift) at offset lo = bits of v at lo - shift
        off = lo - shift.astype(np.int64)
        left = np.clip(off, -63, 63)
        part = np.where(left >= 0,
                        v >> left.clip(0).astype(np.uint64),
                        v << (-left).clip(0).astype(np.uint64))
        limbs[li] = part & mask
    one_hi = np.uint64(1 << (_PREC - (_NLIMB - 1) * _BASE))  # 2^96 top limb
    frac = np.zeros(65536, dtype=np.uint64)
    for _ in range(_FRAC):
        # S = (R * R) >> _PREC, computed in limbs
        cols = np.zeros((2 * _NLIMB, 65536), dtype=np.uint64)
        for i in range(_NLIMB):
            for j in range(_NLIMB):
                cols[i + j] += limbs[i] * limbs[j]
        # carry-propagate
        prod = np.zeros((2 * _NLIMB + 1, 65536), dtype=np.uint64)
        carry = np.zeros(65536, dtype=np.uint64)
        for c in range(2 * _NLIMB):
            s = cols[c] + carry
            prod[c] = s & mask
            carry = s >> np.uint64(_BASE)
        prod[2 * _NLIMB] = carry
        # shift right by _PREC = 4 limbs * 24 bits  (4*24 == 96 == _PREC)
        limbs = prod[4:4 + _NLIMB]
        # R >= 2 * 2^_PREC  <=>  top limb >= 2 * one_hi (R < 4*2^_PREC)
        top = limbs[_NLIMB - 1]
        ge2 = top >= (one_hi << np.uint64(1))
        frac = (frac << np.uint64(1)) | ge2.astype(np.uint64)
        # where ge2: R >>= 1 (across limbs)
        down = [(limbs[li] >> np.uint64(1))
                | ((limbs[li + 1] & np.uint64(1)) << np.uint64(_BASE - 1))
                for li in range(_NLIMB - 1)] + [limbs[_NLIMB - 1] >> np.uint64(1)]
        for li in range(_NLIMB):
            limbs[li] = np.where(ge2, down[li], limbs[li])
    return (e << np.uint64(_FRAC)) | frac


@functools.cache
def a48_table() -> np.ndarray:
    """A[u] = 2^48 - ln44(u + 1) for u in [0, 65536), uint64.

    Monotone decreasing; A[0xffff] == 0 (the best possible draw)."""
    return np.uint64(1 << 48) - _ln44_table_vec()


@functools.lru_cache(maxsize=64)
def _quotients_for(w: int) -> np.ndarray:
    # bounded: each entry is a 512 KiB table, and real maps can carry
    # per-OSD capacity-derived weights (many distinct values)
    if w < 1:
        raise ValueError("weight must be >= 1")
    return a48_table() // np.uint64(w)


def quotient_tables(weights) -> tuple[dict[int, int], np.ndarray, np.ndarray]:
    """For the distinct positive weights (16.16 ints), build q-tables.

    Returns (index_of_weight, q_hi, q_lo): q_hi/q_lo are
    (n_distinct, 65536) uint32 with q = A48 // w split at bit 32."""
    distinct = sorted({int(w) for w in weights if int(w) > 0})
    if not distinct:
        distinct = [0x10000]
    index = {w: i for i, w in enumerate(distinct)}
    q = np.stack([_quotients_for(w) for w in distinct])
    return index, (q >> np.uint64(32)).astype(np.uint32), \
        (q & np.uint64(0xFFFFFFFF)).astype(np.uint32)
