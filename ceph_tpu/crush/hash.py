"""rjenkins1 integer mixing hash — the randomness source of CRUSH.

Rebuild of the reference's crush_hash32_{1..5} (ref: src/crush/hash.c,
crush_hashmix / crush_hash_seed, CRUSH_HASH_RJENKINS1): every placement
draw in the mapper derives from these. Written once over generic array
ops so the same code runs as numpy uint32 (host oracle) and jax uint32
(vectorized mapper) — both wrap mod 2^32, so results agree bit-for-bit.

NOTE (see SURVEY.md citation notice): the reference mount was empty at
build time, so these formulas are reconstructed from the well-known
public rjenkins lookup3-style mix used by CRUSH; the parity tests pin
vectorized == oracle, and the constants are frozen here so placement is
stable forever within this framework.
"""

from __future__ import annotations

import numpy as np

CRUSH_HASH_SEED = 1315423911  # crush_hash_seed
_X = 231232
_Y = 1232


def _mix(a, b, c):
    """One crush_hashmix round; a/b/c are uint32 arrays (any backend).
    uint32 wraparound is the point — suppress numpy's scalar overflow
    warnings so host/oracle callers stay quiet."""
    a = (a - b) - c
    a = a ^ (c >> 13)
    b = (b - c) - a
    b = b ^ (a << 8)
    c = (c - a) - b
    c = c ^ (b >> 13)
    a = (a - b) - c
    a = a ^ (c >> 12)
    b = (b - c) - a
    b = b ^ (a << 16)
    c = (c - a) - b
    c = c ^ (b >> 5)
    a = (a - b) - c
    a = a ^ (c >> 3)
    b = (b - c) - a
    b = b ^ (a << 10)
    c = (c - a) - b
    c = c ^ (b >> 15)
    return a, b, c


def _u32(backend, v):
    return backend.asarray(v, dtype=backend.uint32)


def _quiet(fn):
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        with np.errstate(over="ignore"):
            return fn(*args, **kw)
    return wrapped


@_quiet
def hash32_1(a, np_like=np):
    a = _u32(np_like, a)
    seed = _u32(np_like, CRUSH_HASH_SEED)
    h = seed ^ a
    b = a
    x = _u32(np_like, _X)
    y = _u32(np_like, _Y)
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h



@_quiet
def hash32_2(a, b, np_like=np):
    a = _u32(np_like, a)
    b = _u32(np_like, b)
    h = _u32(np_like, CRUSH_HASH_SEED) ^ a ^ b
    x = _u32(np_like, _X)
    y = _u32(np_like, _Y)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h



@_quiet
def hash32_3(a, b, c, np_like=np):
    a = _u32(np_like, a)
    b = _u32(np_like, b)
    c = _u32(np_like, c)
    h = _u32(np_like, CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = _u32(np_like, _X)
    y = _u32(np_like, _Y)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h



@_quiet
def hash32_4(a, b, c, d, np_like=np):
    a = _u32(np_like, a)
    b = _u32(np_like, b)
    c = _u32(np_like, c)
    d = _u32(np_like, d)
    h = _u32(np_like, CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d
    x = _u32(np_like, _X)
    y = _u32(np_like, _Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h



@_quiet
def hash32_5(a, b, c, d, e, np_like=np):
    a = _u32(np_like, a)
    b = _u32(np_like, b)
    c = _u32(np_like, c)
    d = _u32(np_like, d)
    e = _u32(np_like, e)
    h = _u32(np_like, CRUSH_HASH_SEED) ^ a ^ b ^ c ^ d ^ e
    x = _u32(np_like, _X)
    y = _u32(np_like, _Y)
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h
