"""Vectorized CRUSH mapper — crush_do_rule over a batch of PGs at once.

The TPU rebuild of the reference's hot placement loop (ref:
src/crush/mapper.c crush_do_rule / crush_choose_{firstn,indep} /
bucket_straw2_choose — SURVEY.md §3.4): placement is pure integer math,
so the whole rule program is executed as fixed-shape array ops over a
(B,) batch of inputs. Data-dependent retry loops become a static unroll
(tunables.choose_total_tries) with lane masks; the bucket hierarchy
descent becomes max_depth gather steps; every draw stays uint32/float32
so results are bit-identical to the scalar oracle (oracle.py) — pinned
by parity tests.

Call shape: VectorMapper(map).do_rule(rule_id, xs, weights, result_max)
-> (B, R) int32 device ids with CRUSH_ITEM_NONE holes (indep) or
NONE-padded tails (firstn).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .hash import hash32_2, hash32_3, hash32_4
from .map import (ALG_LIST, ALG_STRAW, ALG_STRAW2, ALG_TREE, ALG_UNIFORM,
                  CRUSH_ITEM_NONE, CrushMap, STEP_CHOOSE_FIRSTN,
                  STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_FIRSTN,
                  STEP_CHOOSELEAF_INDEP, STEP_EMIT, STEP_TAKE)
from .oracle import ln16_table

_NONE = np.int32(CRUSH_ITEM_NONE)


def _mulhi32(h, w):
    """Exact (h * w) >> 32 for uint32 operands without 64-bit ints:
    16-bit split with carry tracking (the tree draw needs the high
    word of a 32x32 product, like mapper.c's __u64 shift)."""
    a, b = h >> 16, h & jnp.uint32(0xFFFF)
    c, d = w >> 16, w & jnp.uint32(0xFFFF)
    mid = a * d
    s = mid + b * c
    carry = (s < mid).astype(jnp.uint32)
    lo = b * d
    s2 = s + (lo >> 16)
    carry2 = (s2 < s).astype(jnp.uint32)
    return a * c + (s2 >> 16) + ((carry + carry2) << 16)


class VectorMapper:
    def __init__(self, m: CrushMap, draw: str = "fixed"):
        if draw not in ("fixed", "float"):
            raise ValueError(f"draw must be 'fixed' or 'float', got {draw!r}")
        self.m = m
        self.draw = draw
        p = m.pack()
        self.tries = m.tunables.choose_total_tries
        self.max_depth = p.max_depth
        self.S = p.max_size
        # device-resident map tables
        self.t_items = jnp.asarray(p.items)                    # (NB, S) i32
        self.t_w32 = jnp.asarray(
            (p.weights.astype(np.float64) / 65536.0).astype(np.float32))
        self.t_wzero = jnp.asarray(p.weights == 0)             # (NB, S)
        self.t_size = jnp.asarray(p.size)                      # (NB,)
        self.t_alg = jnp.asarray(p.alg)
        self.t_type = jnp.asarray(p.type_id)
        # list-bucket cumulative weights, split for 32-bit exact math
        sw = p.sum_weights.astype(np.uint64)
        self.t_sw_lo = jnp.asarray((sw & 0xFFFF).astype(np.uint32))
        self.t_sw_hi = jnp.asarray((sw >> 16).astype(np.uint32))
        self.t_iw_u32 = jnp.asarray(p.weights.astype(np.uint32))
        self.t_ln16 = jnp.asarray(ln16_table())
        if draw == "fixed":
            # per-distinct-weight q = A48 // w tables (ln48.py): the
            # whole s64 draw/divide/compare pipeline reduces to two u32
            # gathers + a lexicographic argmin, exact vs the oracle
            from .ln48 import quotient_tables
            widx_of, qhi, qlo = quotient_tables(p.weights.ravel())
            widx = np.zeros(p.weights.shape, dtype=np.int32)
            for w, i in widx_of.items():
                widx[p.weights == w] = i
            self.t_widx = jnp.asarray(widx)              # (NB, S)
            self.t_qhi = jnp.asarray(qhi.reshape(-1))    # (D * 65536,)
            self.t_qlo = jnp.asarray(qlo.reshape(-1))
        self.algs_used = set(int(a) for a in np.unique(p.alg) if a != 0)
        self.S_uniform = p.max_size_by_alg.get(ALG_UNIFORM, 1)
        if p.tree_nodes is not None:
            # calc_tree_nodes already wraps mod 2^32 (__u32 parity
            # with the oracle); the cast is lossless
            self.t_tree_nodes = jnp.asarray(
                p.tree_nodes.astype(np.uint32))
            self.t_tree_nn = jnp.asarray(p.tree_num_nodes)
            self.tree_depth = int(np.log2(p.tree_nodes.shape[1])) + 1
        if p.straws is not None:
            st = p.straws.astype(np.uint64)
            self.t_straw_hi = jnp.asarray((st >> 16).astype(np.uint32))
            self.t_straw_lo = jnp.asarray((st & 0xFFFF).astype(np.uint32))
            self.t_straw_zero = jnp.asarray(p.straws == 0)
        self._jitted = {}

    # -- bucket choose (batched over lanes) ---------------------------------

    def _rows(self, node):
        """bucket id (negative) -> packed row; invalid lanes -> row 0."""
        row = -1 - node
        return jnp.clip(row, 0, self.t_items.shape[0] - 1)

    def _straw2(self, row, x, r):
        items = self.t_items[row]                       # (B, S)
        slot_ok = (jnp.arange(self.S)[None, :] < self.t_size[row][:, None]) \
            & ~self.t_wzero[row]
        r_b = jnp.asarray(r, jnp.uint32)
        r_b = r_b[:, None] if r_b.ndim else r_b
        h = hash32_3(x[:, None], items.astype(jnp.uint32), r_b, np_like=jnp)
        h16 = (h & jnp.uint32(0xFFFF)).astype(jnp.int32)
        if self.draw == "fixed":
            best = self._straw2_best_fixed(row, h16, slot_ok)
        else:
            w32 = self.t_w32[row]
            draws = self.t_ln16[h16] / w32
            draws = jnp.where(slot_ok, draws, -jnp.inf)
            best = jnp.argmax(draws, axis=1)
        item = jnp.take_along_axis(items, best[:, None], axis=1)[:, 0]
        any_ok = slot_ok.any(axis=1)
        return jnp.where(any_ok, item, _NONE)

    def _straw2_best_fixed(self, row, h16, slot_ok):
        """Winning slot under reference integer draw semantics: first
        strictly-smallest q = A48 // w (48-bit, as u32 hi/lo pair) —
        lexicographic argmin with first-wins ties (mapper.c keeps the
        earlier item unless a later draw is STRICTLY greater)."""
        umax = jnp.uint32(0xFFFFFFFF)
        flat = self.t_widx[row] * 65536 + h16           # (B, S)
        qhi = jnp.where(slot_ok, self.t_qhi[flat], umax)
        qlo = jnp.where(slot_ok, self.t_qlo[flat], umax)
        m1 = qhi.min(axis=1, keepdims=True)
        cand = qhi == m1
        lo_m = jnp.where(cand, qlo, umax)
        m2 = lo_m.min(axis=1, keepdims=True)
        return jnp.argmax(cand & (lo_m == m2), axis=1)  # first winner

    def _uniform(self, row, x, r):
        size = self.t_size[row]                         # (B,)
        bid = (-1 - row).astype(jnp.uint32)
        B = row.shape[0]
        # unroll bound: largest UNIFORM bucket, not the global max size
        # (a big straw2 root must not bloat every uniform choose)
        SU = self.S_uniform
        perm = jnp.broadcast_to(jnp.arange(SU, dtype=jnp.int32), (B, SU))
        cols = jnp.arange(SU, dtype=jnp.int32)[None, :]
        for i in range(SU - 1):
            rem = jnp.maximum(size - i, 1)
            h = hash32_3(x, bid, jnp.uint32(i), np_like=jnp)
            j = i + (h % rem.astype(jnp.uint32)).astype(jnp.int32)
            vi = perm[:, i]
            vj = jnp.take_along_axis(perm, j[:, None], axis=1)[:, 0]
            active = (i < size)[:, None]
            swapped = jnp.where(cols == i, vj[:, None],
                                jnp.where(cols == j[:, None], vi[:, None],
                                          perm))
            perm = jnp.where(active, swapped, perm)
        r_arr = jnp.broadcast_to(jnp.asarray(r, jnp.int32), (B,)) \
            if jnp.ndim(r) == 0 else r.astype(jnp.int32)
        pr = r_arr % jnp.maximum(size, 1)
        slot = jnp.take_along_axis(perm, pr[:, None], axis=1)[:, 0]
        item = jnp.take_along_axis(self.t_items[row], slot[:, None],
                                   axis=1)[:, 0]
        return jnp.where(size > 0, item, _NONE)

    def _list(self, row, x, r):
        items = self.t_items[row]
        bid = (-1 - row).astype(jnp.uint32)
        r_b = jnp.asarray(r, jnp.uint32)
        r_b = r_b[:, None] if r_b.ndim else r_b
        h = hash32_4(x[:, None], items.astype(jnp.uint32), r_b,
                     bid[:, None], np_like=jnp)
        h16 = h & jnp.uint32(0xFFFF)
        # exact floor((h16 * sum_w) / 2^16) < item_w in 32-bit pieces
        p_lo = h16 * self.t_sw_lo[row]
        p_hi = h16 * self.t_sw_hi[row]
        lhs = p_hi + (p_lo >> 16)
        cond = lhs < self.t_iw_u32[row]
        slot_ok = jnp.arange(self.S)[None, :] < self.t_size[row][:, None]
        mask = cond & slot_ok
        rev = mask[:, ::-1]
        pos = jnp.argmax(rev, axis=1)
        idx = self.S - 1 - pos
        found = rev.any(axis=1)
        slot = jnp.where(found, idx, 0)
        item = jnp.take_along_axis(items, slot[:, None], axis=1)[:, 0]
        return jnp.where(self.t_size[row] > 0, item, _NONE)

    def _tree(self, row, x, r):
        """In-order binary-tree walk, all lanes in lockstep for
        tree_depth steps (ref: mapper.c bucket_tree_choose). Terminal
        (odd) nodes self-loop: half = lowest-set-bit(n) >> 1 is 0."""
        nodes_b = self.t_tree_nodes[row]              # (B, MN)
        nn = self.t_tree_nn[row]                      # (B,)
        n = (nn >> 1).astype(jnp.int32)
        bid = (-1 - row).astype(jnp.uint32)
        r_b = jnp.broadcast_to(jnp.asarray(r, jnp.uint32), n.shape) \
            if jnp.ndim(r) == 0 else r.astype(jnp.uint32)
        root_w = jnp.take_along_axis(nodes_b, n[:, None], axis=1)[:, 0]

        def walk(_i, n):
            half = (n & -n) >> 1                      # 0 when n is odd
            w = jnp.take_along_axis(nodes_b, n[:, None], axis=1)[:, 0]
            h = hash32_4(x, n.astype(jnp.uint32), r_b, bid, np_like=jnp)
            t = _mulhi32(h, w)
            left = n - half
            wl = jnp.take_along_axis(nodes_b, left[:, None],
                                     axis=1)[:, 0]
            return jnp.where(half > 0,
                             jnp.where(t < wl, left, n + half), n)
        # fori_loop keeps the traced program small: the walk body is
        # emitted once, not tree_depth times per descend level
        n = jax.lax.fori_loop(0, self.tree_depth, walk, n)
        item = jnp.take_along_axis(self.t_items[row], (n >> 1)[:, None],
                                   axis=1)[:, 0]
        ok = ((n & 1) == 1) & (root_w > 0)
        return jnp.where(ok, item, _NONE)

    def _straw(self, row, x, r):
        """Legacy straw: draw = h16 * straw (48-bit) with the replica
        rank hashed in, first-wins max, compared as (hi, lo16) u32
        pairs (ref: bucket_straw_choose hashes (x, item, r))."""
        items = self.t_items[row]
        r_b = jnp.asarray(r, jnp.uint32)
        r_b = r_b[:, None] if r_b.ndim else r_b
        h = hash32_3(x[:, None], items.astype(jnp.uint32), r_b,
                     np_like=jnp)
        h16 = h & jnp.uint32(0xFFFF)
        slot_ok = jnp.arange(self.S)[None, :] < self.t_size[row][:, None]
        hi = h16 * self.t_straw_hi[row] \
            + ((h16 * self.t_straw_lo[row]) >> 16)
        lo = (h16 * self.t_straw_lo[row]) & jnp.uint32(0xFFFF)
        hi = jnp.where(slot_ok, hi, 0)
        lo = jnp.where(slot_ok, lo, 0)
        m1 = hi.max(axis=1, keepdims=True)
        cand = hi == m1
        lo_m = jnp.where(cand, lo, 0)
        m2 = lo_m.max(axis=1, keepdims=True)
        best = jnp.argmax(cand & (lo_m == m2), axis=1)  # first winner
        item = jnp.take_along_axis(items, best[:, None], axis=1)[:, 0]
        dead = jnp.take_along_axis(self.t_straw_zero[row], best[:, None],
                                   axis=1)[:, 0]
        return jnp.where((self.t_size[row] > 0) & ~dead, item, _NONE)

    def _bucket_choose(self, node, x, r):
        """node (B,) bucket ids (negative) -> chosen child item (B,)."""
        row = self._rows(node)
        alg = self.t_alg[row]
        out = jnp.full(node.shape, _NONE, dtype=jnp.int32)
        if ALG_STRAW2 in self.algs_used:
            out = jnp.where(alg == ALG_STRAW2, self._straw2(row, x, r), out)
        if ALG_UNIFORM in self.algs_used:
            out = jnp.where(alg == ALG_UNIFORM, self._uniform(row, x, r), out)
        if ALG_LIST in self.algs_used:
            out = jnp.where(alg == ALG_LIST, self._list(row, x, r), out)
        if ALG_TREE in self.algs_used:
            out = jnp.where(alg == ALG_TREE, self._tree(row, x, r), out)
        if ALG_STRAW in self.algs_used:
            out = jnp.where(alg == ALG_STRAW, self._straw(row, x, r), out)
        return out

    # -- descent / rejection ------------------------------------------------

    def _item_type(self, item):
        row = self._rows(item)
        return jnp.where(item >= 0, 0, self.t_type[row])

    def _descend(self, node, x, r, want_type: int):
        cur = node
        for _ in range(self.max_depth + 1):
            t = self._item_type(cur)
            done = (t == want_type) | (cur == _NONE)
            dead_end = (cur >= 0) & (t != want_type)
            active = ~done & ~dead_end
            nxt = self._bucket_choose(jnp.where(active, cur, -1), x, r)
            cur = jnp.where(active, nxt, jnp.where(dead_end, _NONE, cur))
        final_ok = self._item_type(cur) == want_type
        return jnp.where(final_ok & (cur != _NONE), cur, _NONE)

    def _is_out(self, weights, item, x):
        """weights: (n_devices,) int32 16.16; item may be NONE/bucket."""
        dev = jnp.clip(item, 0, weights.shape[0] - 1)
        w = weights[dev]
        h16 = hash32_2(x, item.astype(jnp.uint32), np_like=jnp) \
            & jnp.uint32(0xFFFF)
        rejected = jnp.where(w >= 0x10000, False,
                             jnp.where(w == 0, True,
                                       h16.astype(jnp.int32) >= w))
        return jnp.where(item >= 0, rejected, False)

    # -- choose -------------------------------------------------------------

    def _choose_indep(self, take, x, numrep: int, want_type: int,
                      weights, to_leaf: bool):
        B = x.shape[0]
        out0 = jnp.full((B, numrep), _NONE, dtype=jnp.int32)
        leaves0 = jnp.full((B, numrep), _NONE, dtype=jnp.int32)

        # one retry round is traced once; lax.fori_loop runs `tries` of
        # them (the reference's data-dependent retry loop, made static)
        def round_body(rnd, carry):
            out, leaves = carry
            for rep in range(numrep):
                r = (jnp.uint32(rep) + rnd.astype(jnp.uint32)
                     * jnp.uint32(numrep))
                undecided = out[:, rep] == _NONE
                item = self._descend(take, x, r, want_type)
                valid = item != _NONE
                collide = (item[:, None] == out).any(axis=1)
                ok = undecided & valid & ~collide
                if to_leaf:
                    leaf = self._descend(jnp.where(valid, item, -1), x, r, 0)
                    lvalid = (leaf != _NONE) \
                        & ~(leaf[:, None] == leaves).any(axis=1) \
                        & ~self._is_out(weights, leaf, x)
                    ok = ok & lvalid
                    leaves = leaves.at[:, rep].set(
                        jnp.where(ok, leaf, leaves[:, rep]))
                else:
                    ok = ok & ~self._is_out(weights, item, x)
                out = out.at[:, rep].set(jnp.where(ok, item, out[:, rep]))
            return out, leaves

        def cond(state):
            rnd, (out, leaves) = state
            undecided = ((leaves if to_leaf else out) == _NONE).any()
            return (rnd < self.tries) & undecided

        def body(state):
            rnd, carry = state
            return rnd + 1, round_body(rnd, carry)

        # while_loop instead of a fixed unroll: nearly every lane
        # succeeds in round 0, so the retry rounds only run (for the
        # whole batch) while some slot is still NONE
        _, (out, leaves) = jax.lax.while_loop(
            cond, body, (jnp.int32(0), (out0, leaves0)))
        return leaves if to_leaf else out

    def _choose_firstn(self, take, x, numrep: int, want_type: int,
                       weights, to_leaf: bool):
        B = x.shape[0]
        out0 = jnp.full((B, numrep), _NONE, dtype=jnp.int32)
        leaves0 = jnp.full((B, numrep), _NONE, dtype=jnp.int32)
        ftotal0 = jnp.zeros((B,), dtype=jnp.int32)

        def make_attempt(rep):
            def attempt(_t, carry):
                out, leaves, ftotal, found = carry
                active = ~found & (ftotal < self.tries)
                r = (jnp.int32(rep) + ftotal).astype(jnp.uint32)
                item = self._descend(take, x, r, want_type)
                valid = item != _NONE
                collide = (item[:, None] == out).any(axis=1)
                ok = active & valid & ~collide
                if to_leaf:
                    leaf = self._descend(jnp.where(valid, item, -1), x, r, 0)
                    lvalid = (leaf != _NONE) \
                        & ~(leaf[:, None] == leaves).any(axis=1) \
                        & ~self._is_out(weights, leaf, x)
                    ok = ok & lvalid
                    leaves = leaves.at[:, rep].set(
                        jnp.where(ok, leaf, leaves[:, rep]))
                else:
                    ok = ok & ~self._is_out(weights, item, x)
                out = out.at[:, rep].set(jnp.where(ok, item, out[:, rep]))
                ftotal = jnp.where(active & ~ok, ftotal + 1, ftotal)
                found = found | ok
                return out, leaves, ftotal, found

            return attempt

        out, leaves, ftotal = out0, leaves0, ftotal0
        for rep in range(numrep):
            found = jnp.zeros((B,), dtype=bool)
            attempt = make_attempt(rep)

            def cond(carry):
                _out, _leaves, ft, fnd = carry
                return (~fnd & (ft < self.tries)).any()

            def body(carry):
                return attempt(0, carry)

            out, leaves, ftotal, found = jax.lax.while_loop(
                cond, body, (out, leaves, ftotal, found))
        return leaves if to_leaf else out

    # -- rule execution -----------------------------------------------------

    def _do_rule_impl(self, rule_id: int, result_max: int, xs, weights):
        rule = self.m.rules[rule_id]
        working = None
        results = []
        B = xs.shape[0]
        for step in rule.steps:
            if step.op == STEP_TAKE:
                working = jnp.full((B, 1), np.int32(step.arg), jnp.int32)
            elif step.op == STEP_EMIT:
                results.append(working)
                working = None
            else:
                numrep = step.arg if step.arg > 0 else result_max + step.arg
                indep = step.op in (STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_INDEP)
                to_leaf = step.op in (STEP_CHOOSELEAF_FIRSTN,
                                      STEP_CHOOSELEAF_INDEP)
                fn = self._choose_indep if indep else self._choose_firstn
                cols = []
                for w in range(working.shape[1]):
                    cols.append(fn(working[:, w], xs, numrep, step.type_id,
                                   weights, to_leaf))
                working = jnp.concatenate(cols, axis=1)
        return jnp.concatenate(results, axis=1)

    def do_rule(self, rule_id: int, xs, weights, result_max: int):
        """xs: (B,) int/uint32 PG seeds; weights: (n_devices,) 16.16
        int32 reweights. Returns (B, R) int32 items, CRUSH_ITEM_NONE
        for unfilled slots."""
        key = (rule_id, result_max)
        fn = self._jitted.get(key)
        if fn is None:
            def impl(tables, xs, weights,
                     _rid=rule_id, _rm=result_max, _self=self):
                # the map tables enter as RUNTIME inputs (a dict
                # pytree), NOT closed-over trace constants: closing
                # over the device arrays let XLA constant-fold the
                # bucket-table gathers at compile time — compile cost
                # scaled with lane count and capped the CPU fallback
                # at 100k-lane sub-batches (r3). A shallow view with
                # tracer-valued t_* attrs routes every method access
                # through the arguments instead.
                import copy as _copy
                view = _copy.copy(_self)
                view.__dict__.update(tables)
                return VectorMapper._do_rule_impl(view, _rid, _rm,
                                                  xs, weights)
            fn = jax.jit(impl)
            self._jitted[key] = fn
        xs = jnp.asarray(xs).astype(jnp.uint32)
        weights = jnp.asarray(weights, jnp.int32)
        return fn(self._table_args(), xs, weights)

    def _table_args(self) -> dict:
        """Every device-resident map table, keyed by attribute name —
        the runtime-input pytree for the jitted rule."""
        return {k: v for k, v in self.__dict__.items()
                if k.startswith("t_")}

    def scan_rule(self, rule_id: int, weights, result_max: int,
                  start: int, sub: int, n_batches: int):
        """Place n_batches consecutive sub-batches of `sub` PGs inside
        ONE device program (lax.scan), seeds generated on device.

        Per-dispatch round trips dominate do_rule on a tunneled TPU
        (~2s/dispatch observed 2026-07-31: a 1000-batch 10M run
        dispatched in 3s and drained for >30min), so throughput
        benching must put the whole loop on device — same shape as
        bench.py's digest-synced scan pipeline. Returns (digest, last)
        where digest is an int32 XOR fold over every placement (the
        data dependency that keeps all batches live) and last is the
        final (sub, result_max) placement batch for spot validation.
        """
        key = ("scan", rule_id, result_max, sub, n_batches)
        fn = self._jitted.get(key)
        if fn is None:
            def impl(tables, weights, start, _rid=rule_id,
                     _rm=result_max, _sub=sub, _nb=n_batches,
                     _self=self):
                import copy as _copy
                view = _copy.copy(_self)
                view.__dict__.update(tables)

                def body(carry, i):
                    acc, _last = carry
                    xs = (jnp.arange(_sub, dtype=jnp.uint32)
                          + (start + i * _sub).astype(jnp.uint32))
                    res = VectorMapper._do_rule_impl(
                        view, _rid, _rm, xs, weights)
                    d = jnp.bitwise_xor.reduce(
                        jnp.bitwise_xor.reduce(res, axis=0))
                    return (acc ^ d, res), None
                init = (jnp.int32(0),
                        jnp.zeros((_sub, _rm), jnp.int32))
                (acc, last), _ = jax.lax.scan(
                    body, init, jnp.arange(_nb, dtype=jnp.int32))
                return acc, last
            fn = jax.jit(impl)
            self._jitted[key] = fn
        weights = jnp.asarray(weights, jnp.int32)
        acc, last = fn(self._table_args(), weights, jnp.int32(start))
        return int(jax.device_get(acc)), last


def full_weights(n_devices: int) -> np.ndarray:
    return np.full(n_devices, 0x10000, dtype=np.int32)
