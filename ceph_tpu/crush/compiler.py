"""CRUSH text compiler/decompiler.

Rebuild of the reference's map text tooling (ref: src/crush/
CrushCompiler.{h,cc} — `crushtool -d` decompiles a map to the editable
text form, `crushtool -c` compiles it back; the canonical grammar is
the one in the upstream docs: tunable lines, `device N osd.N`,
`type N <name>`, bucket blocks `<typename> <name> { id/alg/hash/item }`,
and rule blocks with `step take/choose/chooseleaf/emit`).

Round-trip property: compile(decompile(m)) places identically to m —
pinned by tests/test_crushtext.py.
"""

from __future__ import annotations

from .map import (ALG_NAMES, _SUPPORTED_ALGS, CrushMap, Rule, Step,
                  STEP_CHOOSE_FIRSTN, STEP_CHOOSE_INDEP,
                  STEP_CHOOSELEAF_FIRSTN, STEP_CHOOSELEAF_INDEP,
                  STEP_EMIT, STEP_TAKE, Tunables)

_CHOOSE_OPS = {
    ("choose", "firstn"): STEP_CHOOSE_FIRSTN,
    ("choose", "indep"): STEP_CHOOSE_INDEP,
    ("chooseleaf", "firstn"): STEP_CHOOSELEAF_FIRSTN,
    ("chooseleaf", "indep"): STEP_CHOOSELEAF_INDEP,
}
_OP_WORDS = {v: k for k, v in _CHOOSE_OPS.items()}


class CompileError(ValueError):
    pass


# ---------------------------------------------------------------- decompile

def decompile(m: CrushMap) -> str:
    """Map -> editable text (crushtool -d)."""
    lines: list[str] = ["# begin crush map",
                        f"tunable choose_total_tries "
                        f"{m.tunables.choose_total_tries}", ""]
    lines.append("# devices")
    for d in range(m.n_devices):
        lines.append(f"device {d} osd.{d}")
    lines.append("")
    lines.append("# types")
    for tid in sorted(m.types):
        lines.append(f"type {tid} {m.types[tid]}")
    lines.append("")
    lines.append("# buckets")

    def item_name(it: int) -> str:
        return f"osd.{it}" if it >= 0 else m.buckets[it].name

    # children before parents so every reference is already defined
    for bid in sorted(m.buckets, key=lambda b: (m.depth_below(b), -b)):
        b = m.buckets[bid]
        tname = m.types.get(b.type_id, f"type{b.type_id}")
        lines.append(f"{tname} {b.name} {{")
        lines.append(f"\tid {b.id}")
        lines.append(f"\talg {ALG_NAMES[b.alg]}")
        lines.append(f"\thash {b.hash_id}\t# rjenkins1")
        for it, w in zip(b.items, b.weights):
            # .5f makes text->map exact for any 16.16 weight
            # (0.00001 * 65536 < 1), matching the reference's precision
            lines.append(f"\titem {item_name(it)} "
                         f"weight {w / 65536.0:.5f}")
        lines.append("}")
    lines.append("")
    lines.append("# rules")
    for rid in sorted(m.rules):
        r = m.rules[rid]
        indep = any(s.op in (STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_INDEP)
                    for s in r.steps)
        lines.append(f"rule {r.name} {{")
        lines.append(f"\tid {r.id}")
        lines.append(f"\ttype {'erasure' if indep else 'replicated'}")
        for s in r.steps:
            if s.op == STEP_TAKE:
                lines.append(f"\tstep take {item_name(s.arg)}")
            elif s.op == STEP_EMIT:
                lines.append("\tstep emit")
            else:
                kw, mode = _OP_WORDS[s.op]
                tname = m.types.get(s.type_id, f"type{s.type_id}")
                lines.append(f"\tstep {kw} {mode} {s.arg} type {tname}")
        lines.append("}")
    lines.append("# end crush map")
    return "\n".join(lines) + "\n"


# ------------------------------------------------------------------ compile

def _tokens(text: str):
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        yield ln, line.replace("{", " { ").replace("}", " } ").split()


def compile_text(text: str) -> CrushMap:
    """Text -> map (crushtool -c). Grammar errors raise CompileError
    with the line number."""
    m = CrushMap()
    type_by_name: dict[str, int] = {}
    bucket_by_name: dict[str, int] = {}
    toks = list(_tokens(text))
    i = 0

    def err(ln: int, msg: str):
        raise CompileError(f"line {ln}: {msg}")

    def resolve_item(ln: int, name: str) -> int:
        if name.startswith("osd."):
            try:
                return int(name[4:])
            except ValueError:
                err(ln, f"bad device name {name!r}")
        if name in bucket_by_name:
            return bucket_by_name[name]
        err(ln, f"unknown item {name!r} (buckets must be defined "
                f"before use)")

    def parse_block(start: int) -> tuple[list[tuple], int]:
        """Collect lines until the matching '}' (flat blocks only)."""
        body = []
        j = start
        while j < len(toks):
            ln, words = toks[j]
            if words == ["}"]:
                return body, j + 1
            body.append((ln, words))
            j += 1
        err(toks[start - 1][0], "unterminated block")

    while i < len(toks):
        ln, words = toks[i]
        head = words[0]
        if head == "tunable":
            if len(words) != 3:
                err(ln, "tunable <name> <value>")
            if words[1] == "choose_total_tries":
                m.tunables = Tunables(choose_total_tries=int(words[2]))
            i += 1
        elif head == "device":
            if len(words) != 3 or not words[2].startswith("osd."):
                err(ln, "device <id> osd.<id>")
            m.max_device = max(m.max_device, int(words[1]))
            i += 1
        elif head == "type":
            if len(words) != 3:
                err(ln, "type <id> <name>")
            tid = int(words[1])
            m.add_type(tid, words[2])
            type_by_name[words[2]] = tid
            i += 1
        elif head == "rule":
            if len(words) != 3 or words[2] != "{":
                err(ln, "rule <name> {")
            rname = words[1]
            body, i = parse_block(i + 1)
            rid = None
            steps: list[Step] = []
            for bln, bw in body:
                if bw[0] == "id":
                    rid = int(bw[1])
                elif bw[0] == "type":
                    pass  # replicated/erasure is derived from steps
                elif bw[0] in ("min_size", "max_size"):
                    pass  # legacy fields, accepted and ignored
                elif bw[0] == "step":
                    if len(bw) < 2:
                        err(bln, "bare 'step'")
                    if bw[1] == "take":
                        # reject qualifiers we don't implement (e.g.
                        # 'class ssd') rather than silently dropping a
                        # placement constraint
                        if len(bw) != 3:
                            err(bln, "step take <bucketname> (device-"
                                     "class qualifiers unsupported)")
                        steps.append(Step(STEP_TAKE,
                                          arg=resolve_item(bln, bw[2])))
                    elif bw[1] == "emit":
                        steps.append(Step(STEP_EMIT))
                    elif len(bw) >= 3 and (bw[1], bw[2]) in _CHOOSE_OPS:
                        if len(bw) != 6 or bw[4] != "type":
                            err(bln, "step choose* <firstn|indep> <n> "
                                     "type <typename>")
                        if bw[5] not in type_by_name:
                            err(bln, f"unknown type {bw[5]!r}")
                        steps.append(Step(_CHOOSE_OPS[(bw[1], bw[2])],
                                          arg=int(bw[3]),
                                          type_id=type_by_name[bw[5]]))
                    else:
                        err(bln, f"unknown step {bw[1]!r}")
                else:
                    err(bln, f"unknown rule field {bw[0]!r}")
            if rid is None:
                err(ln, f"rule {rname!r} has no id")
            m.add_rule(rid, steps, name=rname)
        elif head in type_by_name:
            # bucket block: <typename> <name> {
            if len(words) != 3 or words[2] != "{":
                err(ln, f"{head} <name> {{")
            bname = words[1]
            body, i = parse_block(i + 1)
            bid = alg = None
            hash_id = 0
            items: list[int] = []
            weights: list[float] = []
            for bln, bw in body:
                if bw[0] == "id":
                    bid = int(bw[1])
                elif bw[0] == "alg":
                    if bw[1] not in _SUPPORTED_ALGS:
                        err(bln, f"unknown alg {bw[1]!r}")
                    alg = bw[1]
                elif bw[0] == "hash":
                    hash_id = int(bw[1])
                elif bw[0] == "item":
                    w = 1.0
                    if len(bw) >= 4 and bw[2] == "weight":
                        w = float(bw[3])
                    items.append(resolve_item(bln, bw[1]))
                    weights.append(w)
                else:
                    err(bln, f"unknown bucket field {bw[0]!r}")
            if bid is None:
                err(ln, f"bucket {bname!r} has no id")
            if alg is None:
                err(ln, f"bucket {bname!r} has no alg")
            b = m.add_bucket(bid, type_by_name[head], alg, items,
                             weights, name=bname)
            b.hash_id = hash_id
            bucket_by_name[bname] = bid
        else:
            err(ln, f"unknown directive {head!r}")

    # topmost bucket (referenced by nothing) becomes the default root
    referenced = {it for b in m.buckets.values() for it in b.items
                  if it < 0}
    roots = [bid for bid in m.buckets if bid not in referenced]
    if len(roots) == 1:
        m.root_id = roots[0]
    m.validate()
    return m
