"""CRUSH map model: buckets, rules, tunables, and the packed SoA form.

Rebuild of the reference's map structures (ref: src/crush/crush.h —
crush_map / crush_bucket_{uniform,list,straw2} / crush_rule with
CRUSH_RULE_TAKE / CHOOSE* / EMIT step programs; builder API ref:
src/crush/builder.c, C++ facade ref: src/crush/CrushWrapper.h).

Here the map is a small Python object graph with a `pack()` method that
lowers everything to dense int32/float32 arrays (items matrix padded to
max bucket size, per-bucket alg/size/type vectors) — the form the
vectorized JAX mapper consumes. Bucket ids are negative (devices are
non-negative), exactly the reference's convention; internally a bucket
id b maps to row (-1 - b).

Supported bucket algs: uniform, list, straw2 (the modern default),
plus the legacy tree and original-straw buckets (straw2 replaced straw
in Hammer) — calc_tree_nodes/calc_straws below hold their build-time
aux tables, and both mappers implement their draws with pinned
oracle==vector parity.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

CRUSH_ITEM_NONE = 0x7FFFFFFF

# bucket algs (crush.h values)
ALG_UNIFORM = 1
ALG_LIST = 2
ALG_TREE = 3
ALG_STRAW = 4
ALG_STRAW2 = 5
_SUPPORTED_ALGS = {"uniform": ALG_UNIFORM, "list": ALG_LIST,
                   "tree": ALG_TREE, "straw": ALG_STRAW,
                   "straw2": ALG_STRAW2}
ALG_NAMES = {v: k for k, v in _SUPPORTED_ALGS.items()}


def calc_tree_nodes(weights: list[int]) -> list[int]:
    """Tree-bucket node weights (ref: src/crush/builder.c
    crush_make_tree_bucket / crush_calc_tree_node): items live at odd
    node indices (item i -> node 2i+1) of an in-order-labelled binary
    tree of num_nodes = next_pow2(2*size); internal node weight = sum
    of its subtree. Missing leaves weigh 0 so they are never drawn."""
    size = len(weights)
    if size == 0:
        return [0, 0]
    depth = 1
    while (1 << depth) < 2 * size:
        depth += 1
    num_nodes = 1 << depth
    nodes = [0] * num_nodes
    for i, w in enumerate(weights):
        nodes[2 * i + 1] = int(w) & 0xFFFFFFFF
    # fill internal nodes bottom-up: node n at height h spans
    # [n - 2^h + 1, n + 2^h - 1]. Sums wrap mod 2^32 — the reference
    # stores node_weights as __u32, so both mappers must share the
    # same wraparound or oracle==vector parity breaks on huge buckets.
    for h in range(1, depth):
        step = 1 << (h + 1)
        first = 1 << h
        for n in range(first, num_nodes, step):
            nodes[n] = (nodes[n - (1 << (h - 1))] +
                        (nodes[n + (1 << (h - 1))]
                         if n + (1 << (h - 1)) < num_nodes else 0)) \
                & 0xFFFFFFFF
    return nodes


def calc_straws(weights: list[int]) -> list[int]:
    """Legacy-straw lengths (ref: src/crush/builder.c crush_calc_straw:
    items ascending by weight; each weight tier's straw is scaled so
    the win probability tracks the weight ratio — the approximation
    whose known bias led to straw2). 16.16 fixed-point outputs.

    Models straw_calc_version=1 semantics: zero-weight items get a
    zero straw AND are excluded from the tier accounting (numleft
    decrements) — the v1 fix for the v0 bug where zero weights skewed
    every later tier. The all-zero-draw winner diverges knowingly:
    both mapper impls return ITEM_NONE (a failed draw that retries/
    rejects), where the reference's bucket_straw_choose returns
    items[0] — i.e. an all-zero-weight straw bucket here places
    nothing instead of always its first item.

    NOTE: internally pinned (oracle==vector parity + monotonicity
    tests), not byte-verified against the reference (empty mount —
    SURVEY.md citation notice). First action if the mount populates:
    pin calc_straws + zero-straw winner semantics against crushtool
    output for maps with zero and duplicate weights."""
    size = len(weights)
    straws = [0] * size
    order = sorted(range(size), key=lambda i: (weights[i], i))
    straw = 1.0
    numleft = size
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        idx = order[i]
        if weights[idx] == 0:
            straws[idx] = 0
            i += 1
            numleft -= 1
            continue
        straws[idx] = int(straw * 0x10000)
        i += 1
        if i == size:
            break
        if weights[order[i]] == weights[order[i - 1]]:
            continue  # same tier shares the straw length
        wbelow += (float(weights[order[i - 1]]) - lastw) * numleft
        numleft = sum(1 for j in range(i, size)
                      if weights[order[j]] >= weights[order[i]])
        wnext = numleft * (weights[order[i]] - weights[order[i - 1]])
        pbelow = wbelow / (wbelow + wnext)
        straw *= (1.0 / pbelow) ** (1.0 / numleft)
        lastw = float(weights[order[i - 1]])
    return straws

# rule step opcodes (crush.h CRUSH_RULE_*)
STEP_TAKE = "take"
STEP_CHOOSE_FIRSTN = "choose_firstn"
STEP_CHOOSE_INDEP = "choose_indep"
STEP_CHOOSELEAF_FIRSTN = "chooseleaf_firstn"
STEP_CHOOSELEAF_INDEP = "chooseleaf_indep"
STEP_EMIT = "emit"


@dataclass
class Tunables:
    """Retry knobs (ref: crush_map tunables in crush.h; the 'optimal'
    profile). choose_total_tries is honored as the vectorized unroll
    bound, so both mapper impls use the same value."""
    choose_total_tries: int = 7


@dataclass
class Bucket:
    id: int                      # negative
    type_id: int                 # hierarchy level (host=1, rack=2, ...)
    alg: int
    items: list[int] = field(default_factory=list)
    weights: list[int] = field(default_factory=list)  # 16.16 fixed point
    hash_id: int = 0             # rjenkins1
    name: str = ""

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)


@dataclass
class Step:
    op: str
    arg: int = 0        # take: bucket id; choose*: numrep (0 = result_max)
    type_id: int = 0    # choose*: bucket type to select


@dataclass
class Rule:
    id: int
    steps: list[Step]
    name: str = ""


class CrushMap:
    """Builder + container; `pack()` freezes it for the mappers."""

    def __init__(self, tunables: Tunables | None = None):
        self.buckets: dict[int, Bucket] = {}
        self.rules: dict[int, Rule] = {}
        self.types: dict[int, str] = {0: "osd"}
        self.max_device: int = -1
        self.tunables = tunables or Tunables()
        self.root_id: int | None = None  # default take target for rules
        self._packed = None

    # -- building ----------------------------------------------------------

    def add_type(self, type_id: int, name: str) -> None:
        self.types[type_id] = name

    def add_bucket(self, bucket_id: int, type_id: int, alg: str,
                   items: list[int], weights: list[float] | None = None,
                   name: str = "") -> Bucket:
        """weights are in 'crush weight' units (1.0 ~ one disk); stored
        16.16 fixed like the reference."""
        if bucket_id >= 0:
            raise ValueError(f"bucket ids are negative, got {bucket_id}")
        if bucket_id in self.buckets:
            raise ValueError(f"duplicate bucket id {bucket_id}")
        if alg not in _SUPPORTED_ALGS:
            raise ValueError(
                f"bucket alg {alg!r} unsupported (supported: "
                f"{sorted(_SUPPORTED_ALGS)})")
        if weights is None:
            weights = [1.0] * len(items)
        if len(weights) != len(items):
            raise ValueError("items/weights length mismatch")
        b = Bucket(bucket_id, type_id, _SUPPORTED_ALGS[alg],
                   list(items), [int(round(w * 0x10000)) for w in weights],
                   name=name or f"bucket{bucket_id}")
        self.buckets[bucket_id] = b
        for it in items:
            if it >= 0:
                self.max_device = max(self.max_device, it)
        self._packed = None
        return b

    def add_rule(self, rule_id: int, steps: list[Step], name: str = "") -> Rule:
        r = Rule(rule_id, steps, name or f"rule{rule_id}")
        self.rules[rule_id] = r
        self._packed = None
        return r

    def item_type(self, item: int) -> int:
        if item >= 0:
            return 0
        return self.buckets[item].type_id

    def parent_of(self, item: int) -> int | None:
        """The bucket directly containing `item` (device or bucket),
        or None at the root. Reverse map built lazily and rebuilt
        whenever the bucket set changed — topology edits are rare,
        lookups ride every repair-budget grant."""
        cache = getattr(self, "_parent_cache", None)
        if cache is None or cache[0] != len(self.buckets):
            parents: dict[int, int] = {}
            for bid, b in self.buckets.items():
                for it in b.items:
                    parents[it] = bid
            cache = (len(self.buckets), parents)
            self._parent_cache = cache
        return cache[1].get(item)

    def domain_of(self, item: int, type_id: int = 2) -> int:
        """The ancestor bucket of `type_id` (rack by default — the
        failure-domain key the repair bandwidth budgets bucket by).
        Falls back to the highest ancestor found when the hierarchy
        has no bucket of that type (flat test maps: everything shares
        one domain, budgets degrade to a single global bucket)."""
        cur = item
        seen = 0
        while seen < 64:                # cycle guard
            parent = self.parent_of(cur)
            if parent is None:
                return cur if cur < 0 else 0
            if self.buckets[parent].type_id == type_id:
                return parent
            cur = parent
            seen += 1
        return cur

    @property
    def n_devices(self) -> int:
        return self.max_device + 1

    def validate(self) -> None:
        for b in self.buckets.values():
            for it in b.items:
                if it < 0 and it not in self.buckets:
                    raise ValueError(f"bucket {b.id} references missing {it}")
        for r in self.rules.values():
            if not r.steps or r.steps[0].op != STEP_TAKE:
                raise ValueError(f"rule {r.id} must start with take")
            if r.steps[-1].op != STEP_EMIT:
                raise ValueError(f"rule {r.id} must end with emit")

    def depth_below(self, item: int, _seen=None) -> int:
        """Max descent depth from item to a device (0 for a device)."""
        if item >= 0:
            return 0
        seen = _seen or set()
        if item in seen:
            raise ValueError(f"bucket cycle at {item}")
        b = self.buckets[item]
        if not b.items:
            return 1
        return 1 + max(self.depth_below(i, seen | {item}) for i in b.items)

    # -- wire form (ref: CrushWrapper::encode/decode) -----------------------

    def encode(self) -> bytes:
        """Versioned wire form (ref: src/crush/CrushWrapper encode —
        buckets, rules, types, tunables; here via the repo's
        utils/encoding.py section protocol)."""
        from ..utils.encoding import Encoder
        e = Encoder().start(1, 1)
        e.i32(self.max_device)
        e.boolean(self.root_id is not None)
        if self.root_id is not None:
            e.i32(self.root_id)
        e.u32(self.tunables.choose_total_tries)
        e.mapping(self.types, lambda en, k: en.i32(k),
                  lambda en, v: en.string(v))
        def enc_bucket(en, b: Bucket):
            en.start(1, 1)
            en.i32(b.id).i32(b.type_id).u8(b.alg).u8(b.hash_id)
            en.string(b.name)
            en.list(b.items, lambda e2, it: e2.i32(it))
            en.list(b.weights, lambda e2, w: e2.i64(w))
            en.finish()
        e.list(sorted(self.buckets.values(), key=lambda b: -b.id),
               enc_bucket)
        def enc_rule(en, r: Rule):
            en.start(1, 1)
            en.i32(r.id).string(r.name)
            def enc_step(e2, s: Step):
                e2.string(s.op).i64(s.arg).i32(s.type_id)
            en.list(r.steps, enc_step)
            en.finish()
        e.list(sorted(self.rules.values(), key=lambda r: r.id), enc_rule)
        return e.finish().bytes()

    @classmethod
    def decode(cls, data: bytes) -> "CrushMap":
        from ..utils.encoding import Decoder
        d = Decoder(data)
        d.start(1)
        m = cls()
        m.max_device = d.i32()
        if d.boolean():
            m.root_id = d.i32()
        m.tunables = Tunables(choose_total_tries=d.u32())
        m.types = d.mapping(lambda dd: dd.i32(), lambda dd: dd.string())
        def dec_bucket(dd) -> Bucket:
            dd.start(1)
            b = Bucket(dd.i32(), dd.i32(), dd.u8(), hash_id=0)
            b.hash_id = dd.u8()
            b.name = dd.string()
            b.items = dd.list(lambda e2: e2.i32())
            b.weights = dd.list(lambda e2: e2.i64())
            dd.finish()
            return b
        for b in d.list(dec_bucket):
            m.buckets[b.id] = b
        def dec_rule(dd) -> Rule:
            dd.start(1)
            rid, name = dd.i32(), dd.string()
            steps = dd.list(lambda e2: Step(e2.string(), e2.i64(),
                                            e2.i32()))
            dd.finish()
            return Rule(rid, steps, name)
        for r in d.list(dec_rule):
            m.rules[r.id] = r
        d.finish()
        m.validate()
        return m

    # -- packing -----------------------------------------------------------

    def pack(self) -> "PackedMap":
        if self._packed is None:
            self.validate()
            self._packed = PackedMap(self)
        return self._packed


class PackedMap:
    """Dense array view of a CrushMap for the vectorized mapper.

    Bucket row r holds bucket id -(r+1). Item/weight matrices are padded
    with CRUSH_ITEM_NONE / 0 to the max bucket size.
    """

    def __init__(self, m: CrushMap):
        self.map = m
        ids = sorted(m.buckets, reverse=True)  # -1, -2, ...
        nrows = (-min(ids)) if ids else 0
        self.n_buckets = nrows
        maxsz = max((b.size for b in m.buckets.values()), default=1)
        self.max_size = max(maxsz, 1)
        self.items = np.full((nrows, self.max_size), CRUSH_ITEM_NONE,
                             dtype=np.int32)
        self.weights = np.zeros((nrows, self.max_size), dtype=np.int64)
        self.size = np.zeros(nrows, dtype=np.int32)
        self.alg = np.zeros(nrows, dtype=np.int32)
        self.type_id = np.zeros(nrows, dtype=np.int32)
        self.bucket_weight = np.zeros(nrows, dtype=np.int64)
        # per-slot cumulative weights head..i (list buckets)
        self.sum_weights = np.zeros((nrows, self.max_size), dtype=np.int64)
        for bid, b in m.buckets.items():
            r = -1 - bid
            self.size[r] = b.size
            self.alg[r] = b.alg
            self.type_id[r] = b.type_id
            self.items[r, :b.size] = b.items
            self.weights[r, :b.size] = b.weights
            self.bucket_weight[r] = b.weight
            self.sum_weights[r, :b.size] = np.cumsum(b.weights)
        # legacy-alg aux tables, only materialized when used:
        # tree node-weight rows (padded to the largest num_nodes) and
        # straw lengths (16.16)
        algs = set(int(a) for a in self.alg)
        self.tree_nodes = None
        self.tree_num_nodes = None
        if ALG_TREE in algs:
            rows = {(-1 - bid): calc_tree_nodes(b.weights)
                    for bid, b in m.buckets.items() if b.alg == ALG_TREE}
            mn = max(len(v) for v in rows.values())
            self.tree_nodes = np.zeros((nrows, mn), dtype=np.int64)
            self.tree_num_nodes = np.ones(nrows, dtype=np.int32)
            for r, v in rows.items():
                self.tree_nodes[r, :len(v)] = v
                self.tree_num_nodes[r] = len(v)
        self.straws = None
        if ALG_STRAW in algs:
            self.straws = np.zeros((nrows, self.max_size), dtype=np.int64)
            for bid, b in m.buckets.items():
                if b.alg == ALG_STRAW:
                    r = -1 - bid
                    self.straws[r, :b.size] = calc_straws(b.weights)
        self.max_depth = max((m.depth_below(bid) for bid in m.buckets), default=0)
        # per-alg max sizes so the mapper can bound its unrolls tightly
        self.max_size_by_alg = {}
        for b in m.buckets.values():
            cur = self.max_size_by_alg.get(b.alg, 1)
            self.max_size_by_alg[b.alg] = max(cur, b.size)


# -- convenience map builders (test/bench topologies) ----------------------

def build_hierarchy(n_osds: int, osds_per_host: int = 8,
                    hosts_per_rack: int = 16, alg: str = "straw2",
                    osd_weight: float = 1.0) -> CrushMap:
    """root -> racks -> hosts -> osds, the standard test topology
    (what crushtool --build produces for layered maps)."""
    m = CrushMap()
    m.add_type(1, "host")
    m.add_type(2, "rack")
    m.add_type(3, "root")
    n_hosts = -(-n_osds // osds_per_host)
    n_racks = -(-n_hosts // hosts_per_rack)
    next_id = -1
    host_ids = []
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host,
                          min((h + 1) * osds_per_host, n_osds)))
        hid = next_id
        next_id -= 1
        m.add_bucket(hid, 1, alg, osds, [osd_weight] * len(osds),
                     name=f"host{h}")
        host_ids.append(hid)
    rack_ids = []
    for rck in range(n_racks):
        hs = host_ids[rck * hosts_per_rack:(rck + 1) * hosts_per_rack]
        rid = next_id
        next_id -= 1
        m.add_bucket(rid, 2, alg, hs,
                     [m.buckets[h].weight / 0x10000 for h in hs],
                     name=f"rack{rck}")
        rack_ids.append(rid)
    root_id = next_id
    m.add_bucket(root_id, 3, alg, rack_ids,
                 [m.buckets[r].weight / 0x10000 for r in rack_ids],
                 name="root")
    m.root_id = root_id
    return m


def _resolve_root(m: CrushMap, root: int | None) -> int:
    if root is None:
        root = m.root_id
    if root is None:
        raise ValueError(
            "no take target: pass root= or set map.root_id "
            "(build_hierarchy sets it automatically)")
    return root


def replicated_rule(m: CrushMap, rule_id: int = 0, choose_type: int = 1,
                    firstn: bool = True, root: int | None = None) -> Rule:
    """take root -> chooseleaf (host) -> emit, the default pool rule."""
    op = STEP_CHOOSELEAF_FIRSTN if firstn else STEP_CHOOSELEAF_INDEP
    return m.add_rule(rule_id, [
        Step(STEP_TAKE, arg=_resolve_root(m, root)),
        Step(op, arg=0, type_id=choose_type),
        Step(STEP_EMIT),
    ], name="replicated_rule")


def ec_rule(m: CrushMap, rule_id: int = 1, choose_type: int = 1,
            root: int | None = None) -> Rule:
    """take root -> chooseleaf_indep (host) -> emit: EC pool placement."""
    return m.add_rule(rule_id, [
        Step(STEP_TAKE, arg=_resolve_root(m, root)),
        Step(STEP_CHOOSELEAF_INDEP, arg=0, type_id=choose_type),
        Step(STEP_EMIT),
    ], name="ec_rule")
