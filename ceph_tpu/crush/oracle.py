"""Scalar CRUSH mapper — the host-side oracle.

Semantic rebuild of the reference's mapper (ref: src/crush/mapper.c —
crush_do_rule, crush_choose_firstn, crush_choose_indep,
crush_bucket_choose, bucket_straw2_choose, bucket_perm_choose,
bucket_list_choose, is_out weight rejection). Slow Python loops,
obviously correct; the vectorized JAX mapper in mapper.py must match it
bit-for-bit (parity tests pin that).

Divergences from upstream, frozen deliberately (reference unverifiable
at build time — see SURVEY.md):
  * straw2 draws default to FIXED-POINT crush_ln semantics (draw=
    "fixed"): q = (2^48 - crush_ln(u)) // weight compared ascending,
    first index winning ties — exactly the reference's truncating s64
    division compare (see ln48.py; table values are the exact
    mathematical log2 rather than upstream's two-level interpolation,
    whose byte-exact tables cannot be verified against the empty
    mount). The r01 float32 ln-table draw is kept as draw="float" for
    comparison.
  * retry schedule: `choose_total_tries` rounds with r' = rep +
    round*numrep (indep) or r' = rep + ftotal (firstn); modern-profile
    behaviors (vary_r/stable) are the only semantics (no legacy modes).
"""

from __future__ import annotations

import functools

import numpy as np

from .hash import hash32_2, hash32_3, hash32_4
from .map import (ALG_LIST, ALG_STRAW, ALG_STRAW2, ALG_TREE, ALG_UNIFORM,
                  CRUSH_ITEM_NONE, CrushMap, Rule, Step,
                  STEP_CHOOSE_FIRSTN, STEP_CHOOSE_INDEP,
                  STEP_CHOOSELEAF_FIRSTN, STEP_CHOOSELEAF_INDEP,
                  STEP_EMIT, STEP_TAKE, calc_straws, calc_tree_nodes)


@functools.cache
def ln16_table() -> np.ndarray:
    """float32 ln((h+1)/65536) for the 16-bit straw2 hash domain —
    the role of crush_ln's __RH_LH_tbl/__LL_tbl lookup pyramid."""
    h = np.arange(65536, dtype=np.float64)
    return np.log((h + 1.0) / 65536.0).astype(np.float32)


def _u32(v: int) -> np.uint32:
    return np.uint32(v & 0xFFFFFFFF)


class OracleMapper:
    def __init__(self, m: CrushMap, draw: str = "fixed"):
        if draw not in ("fixed", "float"):
            raise ValueError(f"draw must be 'fixed' or 'float', got {draw!r}")
        self.m = m
        self.draw = draw
        self.tries = m.tunables.choose_total_tries
        self._tree_cache: dict[int, list[int]] = {}
        self._straw_cache: dict[int, list[int]] = {}

    # -- bucket choose ------------------------------------------------------

    def bucket_choose(self, bucket_id: int, x: int, r: int) -> int:
        b = self.m.buckets[bucket_id]
        if b.size == 0:
            return CRUSH_ITEM_NONE
        with np.errstate(over="ignore"):
            if b.alg == ALG_STRAW2:
                return self._straw2_choose(b, x, r)
            if b.alg == ALG_UNIFORM:
                return self._perm_choose(b, x, r)
            if b.alg == ALG_LIST:
                return self._list_choose(b, x, r)
            if b.alg == ALG_TREE:
                return self._tree_choose(b, x, r)
            if b.alg == ALG_STRAW:
                return self._straw_choose(b, x, r)
        raise ValueError(f"unsupported bucket alg {b.alg}")

    def _tree_choose(self, b, x: int, r: int) -> int:
        """In-order binary tree walk (ref: mapper.c bucket_tree_choose):
        at internal node n (height h = lowest set bit), draw
        t = (hash32_4(x, n, r, id) * node_weight(n)) >> 32 and descend
        left iff t < weight(left subtree). Leaves are odd nodes; leaf
        2i+1 holds item i."""
        nodes = self._tree_cache.get(b.id)
        if nodes is None:
            nodes = calc_tree_nodes(b.weights)
            self._tree_cache[b.id] = nodes
        n = len(nodes) >> 1
        if nodes[n] == 0:
            return CRUSH_ITEM_NONE
        while not (n & 1):
            h = 1
            while not (n >> h) & 1:
                h += 1
            half = 1 << (h - 1)
            w = nodes[n]
            t = (int(hash32_4(_u32(x), _u32(n), _u32(r), _u32(b.id)))
                 * w) >> 32
            left = n - half
            n = left if t < nodes[left] else n + half
        return b.items[n >> 1]

    def _straw_choose(self, b, x: int, r: int) -> int:
        """Legacy straw draw (ref: mapper.c bucket_straw_choose):
        draw = (hash32_3(x, item, r) & 0xffff) * straws[i], max wins,
        first index on ties. The replica rank r MUST be hashed in or
        every rank would draw the same winner and multi-replica straw
        placement could never fill >1 slot."""
        straws = self._straw_cache.get(b.id)
        if straws is None:
            straws = calc_straws(b.weights)
            self._straw_cache[b.id] = straws
        best_i = -1
        best = -1
        for i, item in enumerate(b.items):
            h = int(hash32_3(_u32(x), _u32(item), _u32(r))) & 0xFFFF
            draw = h * straws[i]
            if draw > best:
                best = draw
                best_i = i
        if best_i < 0 or straws[best_i] == 0:
            return CRUSH_ITEM_NONE
        return b.items[best_i]

    def _straw2_choose(self, b, x: int, r: int) -> int:
        if self.draw == "fixed":
            return self._straw2_choose_fixed(b, x, r)
        ln = ln16_table()
        best_i = -1
        best_draw = None
        for i, (item, w) in enumerate(zip(b.items, b.weights)):
            if w == 0:
                continue  # zero crush weight never places (all-zero
                # buckets yield NONE so the retry loop moves on)
            h = int(hash32_3(_u32(x), _u32(item), _u32(r))) & 0xFFFF
            draw = ln[h] / (np.float32(w) / np.float32(65536.0))
            if best_draw is None or draw > best_draw:
                best_draw = draw
                best_i = i
        if best_i < 0:
            return CRUSH_ITEM_NONE
        return b.items[best_i]

    def _straw2_choose_fixed(self, b, x: int, r: int) -> int:
        """Reference integer semantics: draw = (crush_ln(u) - 2^48)/w,
        truncating s64 division, first strictly-greatest draw wins —
        equivalently first strictly-smallest q = A48 // w (ln48.py)."""
        from .ln48 import a48_table
        A = a48_table()
        best_i = -1
        best_q = None
        for i, (item, w) in enumerate(zip(b.items, b.weights)):
            if w == 0:
                continue
            h = int(hash32_3(_u32(x), _u32(item), _u32(r))) & 0xFFFF
            q = int(A[h]) // int(w)
            if best_q is None or q < best_q:
                best_q = q
                best_i = i
        if best_i < 0:
            return CRUSH_ITEM_NONE
        return b.items[best_i]

    def _perm_choose(self, b, x: int, r: int) -> int:
        pr = r % b.size
        perm = list(range(b.size))
        for i in range(pr + 1):
            rem = b.size - i
            j = i + int(hash32_3(_u32(x), _u32(b.id), _u32(i))) % rem
            perm[i], perm[j] = perm[j], perm[i]
        return b.items[perm[pr]]

    def _list_choose(self, b, x: int, r: int) -> int:
        csum = np.cumsum(b.weights)
        for i in range(b.size - 1, -1, -1):
            w = int(hash32_4(_u32(x), _u32(b.items[i]), _u32(r),
                             _u32(b.id))) & 0xFFFF
            w = (w * int(csum[i])) >> 16
            if w < b.weights[i]:
                return b.items[i]
        return b.items[0]

    # -- device rejection ---------------------------------------------------

    def is_out(self, weights: np.ndarray, item: int, x: int) -> bool:
        """weights: (n_devices,) 16.16 reweight vector (OSDMap's
        osd_weight); full weight never rejects, zero always does."""
        w = int(weights[item])
        if w >= 0x10000:
            return False
        if w == 0:
            return True
        return (int(hash32_2(_u32(x), _u32(item))) & 0xFFFF) >= w

    # -- descent ------------------------------------------------------------

    def descend(self, node: int, x: int, r: int, want_type: int) -> int:
        """bucket_choose down the hierarchy until an item of want_type."""
        for _ in range(self.m.pack().max_depth + 1):
            if self.m.item_type(node) == want_type:
                return node
            if node >= 0:
                return CRUSH_ITEM_NONE  # hit a device above wanted type
            node = self.bucket_choose(node, x, r)
            if node == CRUSH_ITEM_NONE:
                return CRUSH_ITEM_NONE
        return CRUSH_ITEM_NONE

    # -- choose -------------------------------------------------------------

    def choose_indep(self, take: int, x: int, numrep: int, want_type: int,
                     weights: np.ndarray, to_leaf: bool) -> list[int]:
        out = [CRUSH_ITEM_NONE] * numrep
        leaves = [CRUSH_ITEM_NONE] * numrep
        for rnd in range(self.tries):
            for rep in range(numrep):
                if out[rep] != CRUSH_ITEM_NONE:
                    continue
                r = rep + rnd * numrep
                item = self.descend(take, x, r, want_type)
                if item == CRUSH_ITEM_NONE:
                    continue
                if item in out:
                    continue
                if to_leaf:
                    leaf = self.descend(item, x, r, 0)
                    if leaf == CRUSH_ITEM_NONE or leaf in leaves:
                        continue
                    if self.is_out(weights, leaf, x):
                        continue
                    leaves[rep] = leaf
                elif item >= 0 and self.is_out(weights, item, x):
                    continue
                out[rep] = item
        return leaves if to_leaf else out

    def choose_firstn(self, take: int, x: int, numrep: int, want_type: int,
                      weights: np.ndarray, to_leaf: bool) -> list[int]:
        out: list[int] = []
        leaves: list[int] = []
        ftotal = 0
        for rep in range(numrep):
            while ftotal < self.tries:
                r = rep + ftotal
                item = self.descend(take, x, r, want_type)
                bad = (item == CRUSH_ITEM_NONE or item in out)
                leaf = CRUSH_ITEM_NONE
                if not bad and to_leaf:
                    leaf = self.descend(item, x, r, 0)
                    bad = (leaf == CRUSH_ITEM_NONE or leaf in leaves
                           or self.is_out(weights, leaf, x))
                elif not bad and item >= 0:
                    bad = self.is_out(weights, item, x)
                if bad:
                    ftotal += 1
                    continue
                out.append(item)
                leaves.append(leaf)
                break
        return leaves if to_leaf else out

    # -- rule execution -----------------------------------------------------

    def do_rule(self, rule: Rule | int, x: int, weights: np.ndarray,
                result_max: int) -> list[int]:
        """Execute a rule for input x (the PG seed); returns item ids
        (devices for chooseleaf/choose-to-osd rules). Mirrors
        crush_do_rule's working-vector semantics."""
        if isinstance(rule, int):
            rule = self.m.rules[rule]
        working: list[int] = []
        result: list[int] = []
        for step in rule.steps:
            if step.op == STEP_TAKE:
                working = [step.arg]
            elif step.op == STEP_EMIT:
                result.extend(working)
                working = []
            else:
                numrep = step.arg if step.arg > 0 else result_max + step.arg
                indep = step.op in (STEP_CHOOSE_INDEP, STEP_CHOOSELEAF_INDEP)
                to_leaf = step.op in (STEP_CHOOSELEAF_FIRSTN,
                                      STEP_CHOOSELEAF_INDEP)
                nxt: list[int] = []
                for parent in working:
                    if indep:
                        nxt.extend(self.choose_indep(
                            parent, x, numrep, step.type_id, weights, to_leaf))
                    else:
                        nxt.extend(self.choose_firstn(
                            parent, x, numrep, step.type_id, weights, to_leaf))
                working = nxt
        return result
