"""Structured logging with per-subsystem gates and a crash ring.

Rebuild of the reference's logging core (ref: src/log/Log.cc — a
dedicated writer keeps an in-memory ring of MORE entries than are
written out, dumped on crash; gating ref: src/common/dout.h `dout(N)`
macros against per-subsystem levels from src/common/subsys.h).

Two levels per subsystem, like the reference: `log_level` (what goes to
the sink) and `gather_level` (what is kept in the ring for dump_recent
— typically higher, so a crash report contains debug detail that was
never printed).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from dataclasses import dataclass

# subsystem table (role of src/common/subsys.h): name -> (log, gather)
SUBSYS: dict[str, tuple[int, int]] = {
    "": (1, 5),          # default
    "ec": (1, 5),
    "crush": (1, 5),
    "osd": (1, 5),
    "recovery": (1, 5),
    "csum": (1, 5),
    "mon": (1, 5),
    "bench": (1, 5),
    "msgr": (0, 5),
    "mgr": (1, 5),
    # chaos events gather into the ring (reconstructable over `log
    # dump` on the admin socket) without printing: the Thrasher keeps
    # its own verbose switch for stdout
    "chaos": (0, 5),
}


@dataclass
class Entry:
    stamp: float
    subsys: str
    level: int
    message: str

    def format(self) -> str:
        t = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(self.stamp))
        frac = int((self.stamp % 1) * 1e6)
        return f"{t}.{frac:06d} {self.subsys or 'none'} {self.level} {self.message}"


class Log:
    def __init__(self, max_recent: int = 1000, sink=None):
        self._ring: collections.deque[Entry] = collections.deque(
            maxlen=max_recent)
        self._lock = threading.Lock()
        self._sink = sink if sink is not None else sys.stderr
        self.levels = dict(SUBSYS)

    def set_level(self, subsys: str, log: int, gather: int | None = None):
        cur = self.levels.get(subsys, self.levels[""])
        self.levels[subsys] = (log, gather if gather is not None
                               else max(log, cur[1]))

    def should_gather(self, subsys: str, level: int) -> bool:
        log_lv, gather_lv = self.levels.get(subsys, self.levels[""])
        return level <= max(log_lv, gather_lv)

    def dout(self, subsys: str, level: int, message: str) -> None:
        """The dout(N) path: cheap when gated off."""
        log_lv, gather_lv = self.levels.get(subsys, self.levels[""])
        if level > log_lv and level > gather_lv:
            return
        e = Entry(time.time(), subsys, level, message)
        with self._lock:
            if level <= gather_lv:
                self._ring.append(e)
            if level <= log_lv and self._sink is not None:
                print(e.format(), file=self._sink)

    def error(self, subsys: str, message: str) -> None:
        self.dout(subsys, -1, message)

    def dump_recent(self, file=None) -> list[str]:
        """Crash-dump the gathered ring (most recent last) — the
        'dump_recent' behavior the reference triggers from its crash
        handler."""
        with self._lock:
            lines = [e.format() for e in self._ring]
        if file is not None:
            print("--- begin dump of recent events ---", file=file)
            for ln in lines:
                print(ln, file=file)
            print("--- end dump of recent events ---", file=file)
        return lines


g_log = Log()


def dout(subsys: str, level: int, message: str) -> None:
    g_log.dout(subsys, level, message)
