"""Flight recorder — per-daemon span ring + wire-propagated trace context.

Rebuild of the reference's distributed tracing (ref: src/common/
tracer.cc Jaeger/OpenTelemetry spans carried across the wire in
MOSDOp::otel_trace, plus the blkin/babeltrace lineage): a compact
trace context (trace id, parent span id, sampled flag) rides every
client op as an OPTIONAL, version-gated frame field, every hop appends
its finished spans to a bounded in-memory ring, and a mgr-side
assembler (mgr/tracing.py) stitches the rings into one causal timeline
per trace.

Design points, in the r9 observability plane's idiom:

* SAME instrumentation points — utils/tracing.span() (the jax.profiler
  + PerfCounters double-duty spans) additionally records into the
  flight ring whenever a SAMPLED context is active, so the trace plane
  cannot drift from the counters (one list of span sites, three
  consumers).
* DECLARED span names — like PerfCountersBuilder's counter registry,
  every span name the recorder may emit is declared up front
  (declare_span_names) and the observability smoke test asserts no
  ring ever carries an undeclared name.
* OFF-SAMPLE near-zero cost — with no active sampled context,
  trace_span() is one contextvar read; an UNSAMPLED context (the
  common case: the id travels so slow ops can be retroactively
  assembled, but nothing records eagerly) costs ~17 bytes on the wire
  and nothing else.
* RETROACTIVE slow-op capture — an op that crosses
  osd_op_complaint_time after the sampling decision said no is
  converted from its OpTracker event marks into `retro.*` spans
  (record_tracked), keyed by the trace id the context carried — so
  `ceph_cli trace` can assemble a timeline for an op nobody chose to
  sample. Hops that keep no OpTracker state (store sub-ops) leave
  gaps; the assembler reports them as wire/untraced time (documented
  assembler gap semantics, ARCHITECTURE "Distributed tracing (r15)").
* CLIENT COST FEED — a sampled context from a client carries that
  client's per-target latency EWMAs + complaint set (client_lat /
  client_suspects), which the serving daemon folds into the helper
  cost table the repair-locality planner ranks by (the r14 follow-up:
  cost ranking sees client-observed slowness, not only the daemon's
  own store-op EWMAs).

Timestamps are wall-clock (time.time()): every daemon of this
single-host harness shares the clock, which is what lets the
assembler order spans ACROSS daemons without clock-skew correction
(disclosed in the architecture notes).
"""

from __future__ import annotations

import contextlib
import contextvars
import random
import struct
import threading
import time

from . import profiler as _prof

__all__ = [
    "TraceContext", "FlightRecorder", "trace_span", "activate",
    "current", "current_sampled", "declare_span_names",
    "is_span_declared", "declared_span_names", "new_trace_id",
    "retro_root_id",
]

#: every span name the flight recorder may record — the span-name
#: mirror of perf_counters.declared_counters (the r9 no-undeclared-
#: names invariant, extended to the trace plane per the r15 CI
#: satellite). Call sites declare theirs at import time.
declared_span_names: set[str] = set()
_declared_lock = threading.Lock()


def declare_span_names(*names: str) -> None:
    with _declared_lock:
        declared_span_names.update(names)


def is_span_declared(name: str) -> bool:
    with _declared_lock:
        return name in declared_span_names


# names this module itself emits (the retro.* family from
# record_tracked; retro event names outside the allowlist fold into
# the root span's tags instead of minting undeclared span names).
# retro.subop / retro.store.apply are the r18 replica-hop spans: a
# primary crossing the complaint threshold asks its acting set to
# publish them from their sub-op retro rings (standalone's
# retro_publish store op), closing the r15 gap where replica time
# retro-assembled as "wire".
_RETRO_EVENTS = ("reached_pg", "commit_sent", "done")
declare_span_names("retro.op", "retro.subop", "retro.store.apply",
                   *(f"retro.{e}" for e in _RETRO_EVENTS))


def retro_root_id(trace_id: int) -> int:
    """The DETERMINISTIC span id of a trace's retro.op root: derived
    from the trace id alone, so replicas publishing retro.subop spans
    (which never saw the primary's retro conversion) parent them
    under the same root the primary minted — the assembler then
    subtracts sub-op time from the root's self time instead of
    double-counting it."""
    return ((int(trace_id) ^ 0x9E3779B97F4A7C15)
            & 0x7FFFFFFFFFFFFFFF) | 1


#: ids come from a module-level RNG seeded from the OS, never the
#: global `random` stream — seeded thrash replays must not be
#: perturbed by trace-id draws interleaving into their schedule
_id_rng = random.Random()
_id_lock = threading.Lock()


def new_trace_id() -> int:
    with _id_lock:
        return _id_rng.getrandbits(63) | 1   # never 0 (0 = "no id")


def coin(p: float) -> bool:
    """One sampling draw from the module RNG (never the global
    `random` stream — see _id_rng)."""
    if p <= 0.0:
        return False
    if p >= 1.0:
        return True
    with _id_lock:
        return _id_rng.random() < p


class TraceContext:
    """The compact wire context: (trace_id, parent_span_id, sampled)
    plus the optional client cost snapshot a first-hop sampled op
    carries. parent_span_id is the span id new child spans attach
    under (the caller's active span)."""

    __slots__ = ("trace_id", "parent_span_id", "sampled",
                 "client_lat", "client_suspects")

    def __init__(self, trace_id: int, parent_span_id: int = 0,
                 sampled: bool = False,
                 client_lat: dict[int, float] | None = None,
                 client_suspects: tuple[int, ...] = ()):
        self.trace_id = int(trace_id)
        self.parent_span_id = int(parent_span_id)
        self.sampled = bool(sampled)
        #: osd id -> client-observed read latency EWMA (seconds)
        self.client_lat = client_lat
        self.client_suspects = tuple(client_suspects)

    def child(self, span_id: int) -> "TraceContext":
        """The context a span's body runs under: same trace, this span
        as the parent of whatever records next. The cost snapshot does
        NOT propagate — it is a first-hop payload, folded once."""
        return TraceContext(self.trace_id, span_id, self.sampled)

    # -- wire form (the optional _Blob v2 tail field) -------------------------

    _FLAG_SAMPLED = 0x01
    _FLAG_LAT = 0x02

    def encode(self) -> bytes:
        flags = (self._FLAG_SAMPLED if self.sampled else 0)
        lat = self.client_lat if self.sampled else None
        sus = self.client_suspects if self.sampled else ()
        if lat or sus:
            flags |= self._FLAG_LAT
        out = struct.pack("<QQB", self.trace_id,
                          self.parent_span_id, flags)
        if flags & self._FLAG_LAT:
            lat = lat or {}
            out += struct.pack("<H", len(lat))
            for osd in sorted(lat):
                out += struct.pack("<if", int(osd), float(lat[osd]))
            out += struct.pack("<H", len(sus))
            for osd in sus:
                out += struct.pack("<i", int(osd))
        return out

    @classmethod
    def decode(cls, blob) -> "TraceContext | None":
        """Tolerant decode: a malformed context never kills the op —
        the op executes untraced (the field is advisory metadata)."""
        try:
            tid, parent, flags = struct.unpack_from("<QQB", blob, 0)
            off = 17
            lat = None
            sus: tuple[int, ...] = ()
            if flags & cls._FLAG_LAT:
                (n,) = struct.unpack_from("<H", blob, off)
                off += 2
                lat = {}
                for _ in range(n):
                    osd, v = struct.unpack_from("<if", blob, off)
                    off += 8
                    lat[int(osd)] = float(v)
                (n,) = struct.unpack_from("<H", blob, off)
                off += 2
                sus = struct.unpack_from(f"<{n}i", blob, off) \
                    if n else ()
            if not tid:
                return None
            return cls(tid, parent, bool(flags & cls._FLAG_SAMPLED),
                       client_lat=lat, client_suspects=sus)
        except (struct.error, ValueError, TypeError):
            return None


class FlightRecorder:
    """Bounded ring of finished spans for ONE daemon (the per-daemon
    flight recorder: in-RAM, dies with the process, dumped via the
    `trace dump` asok/wire command and drained incrementally into
    MgrReports for the mgr-side assembler).

    Capacity resolves LIVE through the daemon config
    (osd_trace_ring_size) when one is provided — a committed
    `config set` resizes a running ring on the next record."""

    def __init__(self, daemon: str, capacity: int = 2048, config=None):
        self.daemon = daemon
        self._capacity = int(capacity)
        self._config = config
        self._ring: list[dict] = []
        self._seq = 0            # monotone per-span sequence
        self._shipped = 0        # drain() cursor (MgrReport shipping)
        self._dropped = 0        # evictions total
        self._dropped_unshipped = 0
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        if self._config is not None:
            try:
                return int(self._config.get("osd_trace_ring_size"))
            except (KeyError, ValueError, TypeError):
                pass
        return self._capacity

    def record(self, trace_id: int, span_id: int, parent_id: int,
               name: str, start: float, duration: float,
               tags: dict | None = None) -> None:
        """Append one FINISHED span. `start` is wall-clock seconds,
        `duration` in seconds."""
        span = {
            "trace_id": f"{int(trace_id):016x}",
            "span_id": f"{int(span_id):016x}",
            "parent_id": f"{int(parent_id):016x}",
            "name": name,
            "daemon": self.daemon,
            "start": round(float(start), 6),
            "dur": round(float(duration), 9),
        }
        if tags:
            span["tags"] = tags
        cap = self.capacity
        with self._lock:
            self._seq += 1
            span["seq"] = self._seq
            self._ring.append(span)
            over = len(self._ring) - cap
            if over > 0:
                for s in self._ring[:over]:
                    if s["seq"] > self._shipped:
                        self._dropped_unshipped += 1
                self._dropped += over
                del self._ring[:over]

    def record_tracked(self, op, ctx: TraceContext,
                       desc: str | None = None) -> None:
        """Retroactive capture: convert a FINISHED TrackedOp's event
        marks into spans under the op's carried trace id (the
        complaint-threshold path — the op was never sampled, but its
        OpTracker history exists anyway). One `retro.op` root spanning
        the whole op, one `retro.<event>` child per allowlisted
        inter-event gap; other events fold into the root's tags."""
        if not getattr(op, "done", False):
            return
        dur = op.duration
        end_wall = getattr(op, "t_end_wall", time.time())
        start_wall = end_wall - dur
        # deterministic root id: replica-published retro.subop spans
        # parent under this same id without any coordination
        root = retro_root_id(ctx.trace_id)
        extra = []
        prev_t = 0.0
        for t_rel, ev in op.events:
            if ev == "initiated":
                prev_t = t_rel
                continue
            if ev in _RETRO_EVENTS:
                self.record(ctx.trace_id, new_trace_id(), root,
                            f"retro.{ev}", start_wall + prev_t,
                            max(0.0, t_rel - prev_t))
            else:
                extra.append(f"{ev}@{t_rel:.6f}")
            prev_t = t_rel
        tags = {"desc": desc or getattr(op, "desc", ""),
                "retro": True}
        if extra:
            tags["events"] = extra
        self.record(ctx.trace_id, root, ctx.parent_span_id,
                    "retro.op", start_wall, dur, tags)

    # -- views ----------------------------------------------------------------

    def dump(self, trace_id: str | int | None = None,
             limit: int | None = None) -> dict:
        """The `trace dump` admin command body. `trace_id` filters to
        one trace (hex string or int)."""
        want = None
        if trace_id is not None:
            want = trace_id if isinstance(trace_id, str) \
                else f"{int(trace_id):016x}"
            want = want.lower().removeprefix("0x").rjust(16, "0")
        with self._lock:
            spans = [s for s in self._ring
                     if want is None or s["trace_id"] == want]
            if limit is not None:
                spans = spans[-int(limit):]
            return {"daemon": self.daemon,
                    "capacity": self.capacity,
                    "recorded": self._seq,
                    "dropped": self._dropped,
                    "dropped_unshipped": self._dropped_unshipped,
                    "spans": list(spans)}

    def drain(self, limit: int = 512) -> list[dict]:
        """Spans recorded since the last drain (the MgrReport shipping
        cursor). Bounded per call; evicted-before-shipped spans are
        counted in dropped_unshipped (the gap self-reports)."""
        with self._lock:
            out = [s for s in self._ring if s["seq"] > self._shipped]
            out = out[:int(limit)]
            if out:
                self._shipped = out[-1]["seq"]
            return out

    def pending_ship(self) -> int:
        with self._lock:
            return sum(1 for s in self._ring
                       if s["seq"] > self._shipped)

    def stats(self) -> dict:
        """Ring accounting without the spans (what every MgrReport
        carries so the monitor-side overflow tracker never scrapes
        ring internals)."""
        with self._lock:
            return {"recorded": self._seq,
                    "dropped": self._dropped,
                    "dropped_unshipped": self._dropped_unshipped,
                    "pending": sum(1 for s in self._ring
                                   if s["seq"] > self._shipped)}


# -- ambient context (what makes span() sites trace-aware) --------------------

_CUR: contextvars.ContextVar[TraceContext | None] = \
    contextvars.ContextVar("trace_ctx", default=None)
_REC: contextvars.ContextVar[FlightRecorder | None] = \
    contextvars.ContextVar("trace_rec", default=None)


def current() -> TraceContext | None:
    return _CUR.get()


def current_sampled() -> TraceContext | None:
    """The active context IFF it is sampled and a recorder is bound —
    the one-read fast path every span site checks."""
    ctx = _CUR.get()
    if ctx is not None and ctx.sampled and _REC.get() is not None:
        return ctx
    return None


@contextlib.contextmanager
def activate(ctx: TraceContext | None, recorder: FlightRecorder | None):
    """Install a decoded wire context + the executing daemon's
    recorder for the dynamic extent of op handling. None ctx = no-op
    (the op is untraced)."""
    if ctx is None or recorder is None:
        yield
        return
    t1 = _CUR.set(ctx)
    t2 = _REC.set(recorder)
    try:
        yield
    finally:
        _CUR.reset(t1)
        _REC.reset(t2)


@contextlib.contextmanager
def trace_span(name: str, **tags):
    """Record `name` as a span under the active SAMPLED context (else
    a no-op costing one contextvar read). The body runs under a child
    context so nested spans parent correctly. Sampled or not, the
    name's attribution category tags the executing thread for the r19
    CPU sampler (utils/profiler) — unsampled sub-ops still burn CPU,
    and the flame profile must see store/crypto time the trace plane
    skipped."""
    ctx = _CUR.get()
    if ctx is None or not ctx.sampled:
        tagged = _prof.push_span(name)
        try:
            yield None
        finally:
            if tagged:
                _prof.pop_span()
        return
    rec = _REC.get()
    if rec is None:
        tagged = _prof.push_span(name)
        try:
            yield None
        finally:
            if tagged:
                _prof.pop_span()
        return
    sid = new_trace_id()
    tok = _CUR.set(ctx.child(sid))
    tagged = _prof.push_span(name)
    t0w = time.time()
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        if tagged:
            _prof.pop_span()
        _CUR.reset(tok)
        rec.record(ctx.trace_id, sid, ctx.parent_span_id, name,
                   t0w, time.perf_counter() - t0, tags or None)
