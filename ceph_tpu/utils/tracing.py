"""Tracing — named spans bridging to jax.profiler.

Rebuild of the reference's tracepoint layer (ref: src/tracing/*.tp
LTTng tracepoints + src/common/tracer.cc Jaeger/OpenTelemetry spans,
compiled in behind WITH_LTTNG/WITH_JAEGER and cheap no-ops otherwise).
Here the trace sink is the XLA profiler: a `span("name")` shows up in
a jax.profiler trace (TensorBoard / xprof) alongside the device
timeline, which is the TPU-native way to answer "which host stage
stalled the launch pipeline" — the question LTTng answers for the
reference's op path.

Spans degrade to near-zero-cost no-ops when profiling is off, exactly
like compiled-out tracepoints; they also time into an optional
PerfCounters time_avg key so production counters and profiler traces
come from the SAME instrumentation points (the reference does this
double-duty with OpTracker + tracepoints).

Usage:
    with span("ecbackend.recover.batch"):
        ...
    with span("osd.op", counters=perf, key="op_latency"):
        ...
    start_trace("/tmp/trace")   # capture; view in tensorboard/xprof
    ...
    stop_trace()
"""

from __future__ import annotations

import contextlib
import time

from . import flight_recorder as _fr
from . import profiler as _prof


#: memoized jax.profiler.TraceAnnotation class (False = unresolved):
#: the old per-span() try/import ran the import machinery on EVERY
#: entry — sys.modules lookup + exception plumbing on the msgr hot
#: path. Resolved once, lazily, so pure-host users still never pay
#: for the jax import and disabled spans are near-zero-cost.
_TRACE_ANNOTATION = False


def _annotation(name: str):
    """jax.profiler.TraceAnnotation(name) when jax is importable,
    else None. The import result is memoized at module level."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is False:
        try:
            from jax.profiler import TraceAnnotation
            _TRACE_ANNOTATION = TraceAnnotation
        except Exception:  # pragma: no cover - jax is baked in here
            _TRACE_ANNOTATION = None
    if _TRACE_ANNOTATION is None:
        return None
    return _TRACE_ANNOTATION(name)


@contextlib.contextmanager
def span(name: str, counters=None, key: str | None = None):
    """Named span: visible in jax.profiler traces; optionally tincs
    `counters[key]` (a time_avg) with the wall duration; when a
    SAMPLED trace context is active (utils/flight_recorder) — recorded
    into the executing daemon's flight ring under that trace; and —
    when the r19 CPU sampler is on — tags this thread with the span's
    attribution category so wall-clock samples land in the same
    queue/crypto/encode/store buckets the trace critical-path uses.
    One instrumentation point, four consumers (profiler timeline,
    production counters, per-op distributed trace, CPU flame
    attribution), so none of them can drift from the others.
    Off-trace with sampling off the extra cost is a contextvar read
    plus one int compare."""
    ann = _annotation(name)
    t0 = time.perf_counter() if counters is not None else 0.0
    fspan = _fr.trace_span(name) \
        if _fr.current_sampled() is not None else None
    if fspan is not None:
        fspan.__enter__()
    tagged = _prof.push_span(name)
    try:
        if ann is not None:
            with ann:
                yield
        else:
            yield
    finally:
        # record even when the body raises — failing/slow-error ops are
        # exactly the ones worth timing (PerfCounters.time() semantics)
        if tagged:
            _prof.pop_span()
        if fspan is not None:
            fspan.__exit__(None, None, None)
        if counters is not None and key is not None:
            counters.tinc(key, time.perf_counter() - t0)


_session: list = [None, None]        # [ProfilerSession, log_dir]


def start_trace(log_dir: str) -> bool:
    """Begin a jax.profiler capture (the 'enable tracing' admin-socket
    toggle). Returns False when the profiler is unavailable.

    Drives an XLA ProfilerSession directly with the PYTHON TRACER OFF
    when the binding allows: the per-python-call events of the default
    tracer flood the profiler's ~1M-event buffer within the first
    compile, silently dropping the very span/device events the trace
    is for. Falls back to the plain jax.profiler API otherwise."""
    try:
        import jax
        jax.devices()                # backend init before the session
        from jax._src.lib import xla_client
        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        _session[0] = xla_client.profiler.ProfilerSession(opts)
        _session[1] = log_dir
        return True
    except Exception:
        _session[0] = None
        try:
            import jax
            jax.profiler.start_trace(log_dir)
            return True
        except Exception:
            return False


def stop_trace() -> bool:
    try:
        if _session[0] is not None:
            sess, log_dir = _session
            _session[0] = None
            sess.export(sess.stop(), str(log_dir))
            return True
        import jax
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a whole block: `with trace("/tmp/tr"): run_workload()`."""
    ok = start_trace(log_dir)
    try:
        yield ok
    finally:
        if ok:
            stop_trace()
