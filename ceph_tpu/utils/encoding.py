"""Wire encoding — the bufferlist encode/decode layer.

Rebuild of the reference's serialization core (ref: src/include/
encoding.h — `encode()`/`decode()` over bufferlists, little-endian
primitives, length-prefixed strings/containers, and the
ENCODE_START(v, compat)/ENCODE_FINISH versioned-section protocol that
gives every structure forward AND backward compatibility: a section
carries (version, compat_version, length); an old reader meeting a
newer section checks `compat <= my_version` and skips the bytes past
what it understands; a new reader meeting an old section sees the low
version and decodes only the fields that existed then).

Everything is explicit little-endian bytes — no pickle, no struct-
by-reflection — so the format is stable across Python versions and
auditable on the wire, the same property the reference's hand-rolled
encoders guarantee.
"""

from __future__ import annotations

import struct


class EncodingError(ValueError):
    pass


class Encoder:
    """Append-only byte builder (the `bufferlist& bl` role)."""

    def __init__(self):
        self._buf = bytearray()
        self._sections: list[int] = []  # offsets of open length slots

    # -- primitives ---------------------------------------------------------

    def u8(self, v: int) -> "Encoder":
        self._buf += struct.pack("<B", v)
        return self

    def u16(self, v: int) -> "Encoder":
        self._buf += struct.pack("<H", v)
        return self

    def u32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<I", v)
        return self

    def u64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<Q", v)
        return self

    def i32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<i", v)
        return self

    def i64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self._buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def blob(self, b: bytes) -> "Encoder":
        self.u32(len(b))
        self._buf += b
        return self

    def string(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    def list(self, items, fn) -> "Encoder":
        """u32 count + fn(self, item) each (container convention)."""
        self.u32(len(items))
        for it in items:
            fn(self, it)
        return self

    def mapping(self, d: dict, kfn, vfn) -> "Encoder":
        self.u32(len(d))
        for k in d:
            kfn(self, k)
            vfn(self, d[k])
        return self

    # -- versioned sections (ENCODE_START / ENCODE_FINISH) ------------------

    def start(self, version: int, compat: int) -> "Encoder":
        if compat > version:
            raise EncodingError(f"compat {compat} > version {version}")
        self.u8(version).u8(compat)
        self._sections.append(len(self._buf))
        self.u32(0)  # length slot, patched by finish()
        return self

    def finish(self) -> "Encoder":
        if not self._sections:
            raise EncodingError("finish() without start()")
        at = self._sections.pop()
        body_len = len(self._buf) - at - 4
        self._buf[at:at + 4] = struct.pack("<I", body_len)
        return self

    def bytes(self) -> bytes:
        if self._sections:
            raise EncodingError(f"{len(self._sections)} unfinished "
                                f"section(s)")
        return bytes(self._buf)


class Decoder:
    """Cursor over bytes (the `bufferlist::const_iterator` role)."""

    def __init__(self, data: bytes):
        self._buf = memoryview(bytes(data))
        self._off = 0
        self._ends: list[int] = []  # section end offsets

    def _take(self, n: int) -> memoryview:
        if self._off + n > len(self._buf):
            raise EncodingError(
                f"decode past end: need {n} at {self._off}, "
                f"have {len(self._buf)}")
        if self._ends and self._off + n > self._ends[-1]:
            raise EncodingError(
                f"decode past section end {self._ends[-1]}")
        v = self._buf[self._off:self._off + n]
        self._off += n
        return v

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def list(self, fn) -> list:
        return [fn(self) for _ in range(self.u32())]

    def mapping(self, kfn, vfn) -> dict:
        return {kfn(self): vfn(self) for _ in range(self.u32())}

    # -- versioned sections (DECODE_START / DECODE_FINISH) ------------------

    def start(self, supported: int) -> int:
        """Open a section; returns its encoded version. Raises when the
        writer declared we're too old to read it at all."""
        v = self.u8()
        compat = self.u8()
        if compat > supported:
            raise EncodingError(
                f"section compat {compat} > supported {supported}: "
                f"written by an incompatible future version")
        length = self.u32()
        end = self._off + length
        if end > len(self._buf) or (self._ends and end > self._ends[-1]):
            raise EncodingError("section length overruns buffer")
        self._ends.append(end)
        return v

    def finish(self) -> None:
        """Skip any trailing fields a newer writer appended."""
        if not self._ends:
            raise EncodingError("finish() without start()")
        self._off = self._ends.pop()

    def remaining_in_section(self) -> int:
        if not self._ends:
            return len(self._buf) - self._off
        return self._ends[-1] - self._off

    @property
    def offset(self) -> int:
        return self._off
