"""Wire encoding — the bufferlist encode/decode layer.

Rebuild of the reference's serialization core (ref: src/include/
encoding.h — `encode()`/`decode()` over bufferlists, little-endian
primitives, length-prefixed strings/containers, and the
ENCODE_START(v, compat)/ENCODE_FINISH versioned-section protocol that
gives every structure forward AND backward compatibility: a section
carries (version, compat_version, length); an old reader meeting a
newer section checks `compat <= my_version` and skips the bytes past
what it understands; a new reader meeting an old section sees the low
version and decodes only the fields that existed then).

Everything is explicit little-endian bytes — no pickle, no struct-
by-reflection — so the format is stable across Python versions and
auditable on the wire, the same property the reference's hand-rolled
encoders guarantee.
"""

from __future__ import annotations

import struct


class EncodingError(ValueError):
    pass


class Encoder:
    """Append-only byte builder (the `bufferlist& bl` role).

    Scatter-gather aware: `blob_ref` appends a caller buffer BY
    REFERENCE (the bufferlist::append(bufferptr) role — no copy), so a
    message carrying a large data payload encodes as a list of
    segments: small bytearray chunks of framing fields interleaved
    with zero-copy views of the payload. `bytes()` still joins to one
    contiguous buffer for callers that need it; `segments()` hands the
    raw part list to the messenger's sendmsg path. Buffers appended by
    reference must stay unmodified until the encoded message is fully
    sent (and, on the lossless messenger, acked) — the same aliasing
    contract a bufferlist imposes."""

    def __init__(self):
        self._buf = bytearray()
        self._parts: list = []          # finalized parts (bytearray/mv)
        self._starts: list[int] = []    # absolute offset of each part
        self._base = 0                  # total bytes in finalized parts
        self._sections: list[int] = []  # ABS offsets of open length slots

    # -- primitives ---------------------------------------------------------

    def u8(self, v: int) -> "Encoder":
        self._buf += struct.pack("<B", v)
        return self

    def u16(self, v: int) -> "Encoder":
        self._buf += struct.pack("<H", v)
        return self

    def u32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<I", v)
        return self

    def u64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<Q", v)
        return self

    def i32(self, v: int) -> "Encoder":
        self._buf += struct.pack("<i", v)
        return self

    def i64(self, v: int) -> "Encoder":
        self._buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self._buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def blob(self, b: bytes) -> "Encoder":
        self.u32(len(b))
        self._buf += b
        return self

    def blob_ref(self, b) -> "Encoder":
        """Length-prefixed blob appended BY REFERENCE: `b` is one
        buffer (bytes/bytearray/memoryview) or a list of them. Wire
        bytes are identical to `blob(joined)`; no payload copy is
        made. The caller must keep the buffers unmodified until the
        encoded message has been transmitted (and acked on lossless
        transports)."""
        parts = b if isinstance(b, (list, tuple)) else (b,)
        self.u32(sum(len(p) for p in parts))
        for p in parts:
            if len(p) == 0:
                continue
            if self._buf:
                self._parts.append(self._buf)
                self._starts.append(self._base)
                self._base += len(self._buf)
                self._buf = bytearray()
            mv = p if isinstance(p, memoryview) else memoryview(p)
            self._parts.append(mv)
            self._starts.append(self._base)
            self._base += len(mv)
        return self

    def string(self, s: str) -> "Encoder":
        return self.blob(s.encode("utf-8"))

    def list(self, items, fn) -> "Encoder":
        """u32 count + fn(self, item) each (container convention)."""
        self.u32(len(items))
        for it in items:
            fn(self, it)
        return self

    def mapping(self, d: dict, kfn, vfn) -> "Encoder":
        self.u32(len(d))
        for k in d:
            kfn(self, k)
            vfn(self, d[k])
        return self

    # -- versioned sections (ENCODE_START / ENCODE_FINISH) ------------------

    def start(self, version: int, compat: int) -> "Encoder":
        if compat > version:
            raise EncodingError(f"compat {compat} > version {version}")
        self.u8(version).u8(compat)
        self._sections.append(self._base + len(self._buf))
        self.u32(0)  # length slot, patched by finish()
        return self

    def finish(self) -> "Encoder":
        if not self._sections:
            raise EncodingError("finish() without start()")
        at = self._sections.pop()
        body_len = self._base + len(self._buf) - at - 4
        self._patch_u32(at, body_len)
        return self

    def _patch_u32(self, at: int, value: int) -> None:
        """Patch 4 bytes at absolute offset `at`. The slot is always
        inside a bytearray part: blob_ref only flushes the current
        chunk AFTER writing the length prefix, and the 4-byte slot is
        written contiguously into one chunk."""
        packed = struct.pack("<I", value)
        if at >= self._base:
            self._buf[at - self._base:at - self._base + 4] = packed
            return
        import bisect
        i = bisect.bisect_right(self._starts, at) - 1
        part = self._parts[i]
        off = at - self._starts[i]
        part[off:off + 4] = packed

    def __len__(self) -> int:
        return self._base + len(self._buf)

    def bytes(self) -> bytes:
        if self._sections:
            raise EncodingError(f"{len(self._sections)} unfinished "
                                f"section(s)")
        if not self._parts:
            return bytes(self._buf)
        return b"".join(self._parts) + bytes(self._buf)

    def segments(self) -> list:
        """The encoded message as its raw part list (zero-copy where
        blob_ref was used). Joining the parts equals bytes() exactly.
        The encoder must not be appended to afterwards."""
        if self._sections:
            raise EncodingError(f"{len(self._sections)} unfinished "
                                f"section(s)")
        if self._buf:
            self._parts.append(self._buf)
            self._starts.append(self._base)
            self._base += len(self._buf)
            self._buf = bytearray()
        return list(self._parts)


class Decoder:
    """Cursor over bytes (the `bufferlist::const_iterator` role)."""

    def __init__(self, data):
        # bytes/bytearray/memoryview wrap zero-copy (the receive path
        # hands in a view over the frame body); anything else (numpy,
        # etc.) materializes once
        if isinstance(data, (bytes, bytearray, memoryview)):
            self._buf = memoryview(data)
        else:
            self._buf = memoryview(bytes(data))
        self._off = 0
        self._ends: list[int] = []  # section end offsets

    def _take(self, n: int) -> memoryview:
        if self._off + n > len(self._buf):
            raise EncodingError(
                f"decode past end: need {n} at {self._off}, "
                f"have {len(self._buf)}")
        if self._ends and self._off + n > self._ends[-1]:
            raise EncodingError(
                f"decode past section end {self._ends[-1]}")
        v = self._buf[self._off:self._off + n]
        self._off += n
        return v

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return bytes(self._take(self.u32()))

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def list(self, fn) -> list:
        return [fn(self) for _ in range(self.u32())]

    def mapping(self, kfn, vfn) -> dict:
        return {kfn(self): vfn(self) for _ in range(self.u32())}

    # -- versioned sections (DECODE_START / DECODE_FINISH) ------------------

    def start(self, supported: int) -> int:
        """Open a section; returns its encoded version. Raises when the
        writer declared we're too old to read it at all."""
        v = self.u8()
        compat = self.u8()
        if compat > supported:
            raise EncodingError(
                f"section compat {compat} > supported {supported}: "
                f"written by an incompatible future version")
        length = self.u32()
        end = self._off + length
        if end > len(self._buf) or (self._ends and end > self._ends[-1]):
            raise EncodingError("section length overruns buffer")
        self._ends.append(end)
        return v

    def finish(self) -> None:
        """Skip any trailing fields a newer writer appended."""
        if not self._ends:
            raise EncodingError("finish() without start()")
        self._off = self._ends.pop()

    def remaining_in_section(self) -> int:
        if not self._ends:
            return len(self._buf) - self._off
        return self._ends[-1] - self._off

    @property
    def offset(self) -> int:
        return self._off
