"""PerfCounters — metrics registry.

Rebuild of the reference's counter subsystem (ref:
src/common/perf_counters.{h,cc} — PerfCountersBuilder::add_u64_counter/
add_u64/add_time_avg, PerfCounters::{inc,dec,set,tinc},
PerfCountersCollection dumped over the admin socket as
`perf dump` / scraped by the mgr prometheus module).

Counter kinds:
  * counter   — monotonically increasing u64 (inc)
  * gauge     — settable value (set/inc/dec)
  * time_avg  — (sum_seconds, count) pair; tinc(seconds) adds a sample,
                dump reports sum + count + avg (latency counters)
  * histogram — fixed power-of-two-bucket latency/size histogram
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field


@dataclass
class _Counter:
    kind: str
    description: str = ""
    value: float = 0
    sum_s: float = 0.0
    count: int = 0
    buckets: list[int] = field(default_factory=list)


#: every (logger name, key) ever declared through PerfCountersBuilder —
#: the reference's "counters exist only if declared in a schema"
#: property, checkable from the outside: a dump/exposition emitting a
#: name absent here was assembled by hand (dynamic/typo'd counter
#: names, the failure mode the smoke test hunts).
declared_counters: dict[str, set] = {}
_declared_lock = threading.Lock()


def is_declared(logger: str, key: str) -> bool:
    with _declared_lock:
        return key in declared_counters.get(logger, ())


class PerfCountersBuilder:
    """Declare-then-freeze, like the reference's builder."""

    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}

    def _declare(self, key: str, counter: _Counter):
        self._counters[key] = counter
        with _declared_lock:
            declared_counters.setdefault(self.name, set()).add(key)
        return self

    def add_u64_counter(self, key: str, description: str = ""):
        return self._declare(key, _Counter("counter", description))

    def add_u64(self, key: str, description: str = ""):
        return self._declare(key, _Counter("gauge", description))

    def add_time_avg(self, key: str, description: str = ""):
        return self._declare(key, _Counter("time_avg", description))

    def add_histogram(self, key: str, description: str = "",
                      n_buckets: int = 32):
        return self._declare(key, _Counter("histogram", description,
                                           buckets=[0] * n_buckets))

    def create_perf_counters(self) -> "PerfCounters":
        return PerfCounters(self.name, self._counters)


class PerfCounters:
    def __init__(self, name: str, counters: dict[str, _Counter]):
        self.name = name
        self._c = counters
        self._lock = threading.Lock()

    def _get(self, key: str, kinds: tuple[str, ...]) -> _Counter:
        c = self._c[key]
        if c.kind not in kinds:
            raise TypeError(f"{self.name}.{key} is {c.kind}, not {kinds}")
        return c

    def inc(self, key: str, by: float = 1) -> None:
        with self._lock:
            self._get(key, ("counter", "gauge")).value += by

    def inc_many(self, pairs) -> None:
        """Batch inc: one lock acquisition for a hot path that bumps
        several counters per event (the msgr frame path)."""
        with self._lock:
            for key, by in pairs:
                self._get(key, ("counter", "gauge")).value += by

    def dec(self, key: str, by: float = 1) -> None:
        with self._lock:
            self._get(key, ("gauge",)).value -= by

    def set(self, key: str, value: float) -> None:
        with self._lock:
            self._get(key, ("gauge",)).value = value

    def tinc(self, key: str, seconds: float) -> None:
        with self._lock:
            c = self._get(key, ("time_avg",))
            c.sum_s += seconds
            c.count += 1

    def hinc(self, key: str, value: float) -> None:
        """Histogram sample: bucket = floor(log2(value)) clamped."""
        with self._lock:
            c = self._get(key, ("histogram",))
            b = max(0, min(len(c.buckets) - 1,
                           int(value).bit_length() - 1 if value >= 1 else 0))
            c.buckets[b] += 1
            c.sum_s += value  # powers the prometheus _sum series

    def get(self, key: str):
        with self._lock:
            c = self._c[key]
            if c.kind == "time_avg":
                return {"sum": c.sum_s, "count": c.count,
                        "avg": c.sum_s / c.count if c.count else 0.0}
            if c.kind == "histogram":
                return list(c.buckets)
            return c.value

    def time(self, key: str):
        """Context manager feeding a time_avg counter."""
        counters = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                counters.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def dump(self) -> dict:
        out = {}
        with self._lock:
            for key, c in self._c.items():
                if c.kind == "time_avg":
                    out[key] = {"avgcount": c.count, "sum": round(c.sum_s, 9)}
                elif c.kind == "histogram":
                    out[key] = list(c.buckets)
                else:
                    out[key] = c.value
        return out

    def schema(self) -> dict:
        """{key: {"kind", "description"}} — `perf schema` (ref: the
        admin socket's perf schema command); ships on full MgrReports
        so the aggregator can type metrics it never declared."""
        with self._lock:
            return {key: {"kind": c.kind, "description": c.description}
                    for key, c in self._c.items()}

    def reset(self) -> None:
        """`perf reset` (ref: admin_socket perf reset all): zero every
        counter, keeping the declarations."""
        with self._lock:
            for c in self._c.values():
                c.value = 0
                c.sum_s = 0.0
                c.count = 0
                c.buckets = [0] * len(c.buckets)


class PerfCountersCollection:
    """Process-wide registry; `perf dump` equivalent."""

    def __init__(self):
        self._loggers: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, counters: PerfCounters) -> PerfCounters:
        with self._lock:
            self._loggers[counters.name] = counters
        return counters

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def dump(self) -> dict:
        with self._lock:
            return {name: c.dump() for name, c in self._loggers.items()}

    def reset(self) -> None:
        with self._lock:
            loggers = list(self._loggers.values())
        for c in loggers:
            c.reset()

    def dump_json(self) -> str:
        return json.dumps(self.dump(), sort_keys=True)

    def prometheus_text(self, prefix: str = "ceph_tpu") -> str:
        """Prometheus exposition format over every registered logger —
        the role of the mgr prometheus module's scrape endpoint (ref:
        src/pybind/mgr/prometheus/module.py: counters become
        `<prefix>_<logger>_<key>` with HELP/TYPE headers; time_avg
        maps to a summary's _sum/_count pair; histograms emit one
        `_bucket{le=...}` series per slot)."""
        def clean(s: str) -> str:
            return "".join(ch if ch.isalnum() or ch == "_" else "_"
                           for ch in s)
        lines: list[str] = []
        with self._lock:
            loggers = dict(self._loggers)
        for lname in sorted(loggers):
            pc = loggers[lname]
            with pc._lock:
                items = {k: (c.kind, c.description, c.value, c.sum_s,
                             c.count, list(c.buckets))
                         for k, c in pc._c.items()}
            for key in sorted(items):
                kind, desc, value, sum_s, count, buckets = items[key]
                metric = f"{clean(prefix)}_{clean(lname)}_{clean(key)}"
                if desc:
                    lines.append(f"# HELP {metric} {desc}")
                # full precision: %g truncates to 6 significant digits,
                # which corrupts counters past ~1e6
                val = (str(int(value)) if float(value).is_integer()
                       else repr(float(value)))
                if kind == "counter":
                    lines.append(f"# TYPE {metric} counter")
                    lines.append(f"{metric} {val}")
                elif kind == "gauge":
                    lines.append(f"# TYPE {metric} gauge")
                    lines.append(f"{metric} {val}")
                elif kind == "time_avg":
                    lines.append(f"# TYPE {metric} summary")
                    lines.append(f"{metric}_sum {sum_s!r}")
                    lines.append(f"{metric}_count {count}")
                elif kind == "histogram":
                    # slot i holds samples in [2^i, 2^(i+1)), so the
                    # cumulative le bound is the slot's real upper
                    # value — histogram_quantile() then works in the
                    # sample's units, not bucket indices. The LAST slot
                    # is hinc's overflow clamp (values may exceed its
                    # nominal bound), so it folds into +Inf only.
                    lines.append(f"# TYPE {metric} histogram")
                    total = 0
                    for i, b in enumerate(buckets[:-1]):
                        total += b
                        lines.append(
                            f'{metric}_bucket{{le="{1 << (i + 1)}"}} '
                            f'{total}')
                    total += buckets[-1]
                    lines.append(f'{metric}_bucket{{le="+Inf"}} {total}')
                    lines.append(f"{metric}_sum {sum_s!r}")
                    lines.append(f"{metric}_count {total}")
        return "\n".join(lines) + "\n"


def dump_delta(before: dict, after: dict) -> dict:
    """Counter-delta attribution: `after - before` over two perf-dump
    shaped dicts (numbers subtract, time_avg dicts subtract
    field-wise, histogram lists subtract element-wise, nested logger
    dicts recurse). Keys new in `after` pass through whole. This is
    what rados_bench/recovery_bench emit so every BENCH_* number
    carries its own per-stage breakdown, and what a daemon ships in a
    delta MgrReport."""
    out: dict = {}
    for key, a in after.items():
        b = before.get(key)
        if b is None:
            out[key] = a
        elif isinstance(a, dict):
            out[key] = dump_delta(b, a)
        elif isinstance(a, list):
            out[key] = [x - y for x, y in zip(a, b)] \
                if len(a) == len(b) else a
        else:
            out[key] = a - b
    return out


def fold_delta(base: dict, delta: dict) -> dict:
    """The aggregation-side inverse of dump_delta: fold a delta dump
    onto an accumulated base (numbers add, dicts recurse, histogram
    lists add element-wise). Returns a NEW dict; inputs unchanged."""
    out = dict(base)
    for key, d in delta.items():
        b = out.get(key)
        if b is None:
            out[key] = d
        elif isinstance(d, dict):
            out[key] = fold_delta(b, d)
        elif isinstance(d, list):
            out[key] = [x + y for x, y in zip(b, d)] \
                if len(b) == len(d) else d
        else:
            out[key] = b + d
    return out


# the default process-wide collection (role of CephContext's collection)
g_perf_counters = PerfCountersCollection()
